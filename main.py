"""Entry point, role-compatible with the reference's ``main.py``:

    python main.py --id 0 --min_clients_federation 5 --model_type ctm   # server
    python main.py --id 1 --source corpus.parquet --data_type real      # client
    python main.py --source synthetic.npz                               # SPMD sim

See :mod:`gfedntm_tpu.cli` for the full surface.
"""

import sys

from gfedntm_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())
