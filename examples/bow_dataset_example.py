"""BoW data-prep walkthrough (script form of the reference's
`notebooks/tests/BoW dataset example.ipynb`): build a vocabulary, vectorize,
split, and inspect a BowDataset.

Run: python examples/bow_dataset_example.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from gfedntm_tpu.data.preparation import prepare_dataset
from gfedntm_tpu.data.synthetic import generate_synthetic_corpus

corpus = generate_synthetic_corpus(
    vocab_size=300, n_topics=5, n_docs=100, nwords=(20, 40), n_nodes=1,
    frozen_topics=2, seed=0,
)
docs = corpus.nodes[0].documents
print(f"{len(docs)} documents; first doc: {docs[0][:70]}...")

train_data, val_data, input_size, id2token, docs_train, vocab = (
    prepare_dataset(docs)
)
print(f"vocabulary: {input_size} terms (25% validation split, seed 42)")
print(f"train matrix: {train_data.X.shape}, val matrix: {val_data.X.shape}")
print("first 10 terms:", [id2token[i] for i in range(10)])
print("doc 0 active terms:", int((train_data.X[0] > 0).sum()))
