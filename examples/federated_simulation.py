"""Full federated run as one SPMD program: 3 clients, vocabulary consensus,
per-minibatch weighted FedAvg, per-client + global artifacts — the TPU-native
equivalent of the reference's docker-compose federation.

Run: python examples/federated_simulation.py
On a multi-device host each client maps to its own device; on one device the
clients batch into a single vmapped program.

On a machine whose TPU tunnel is down, jax backend init hangs
indefinitely — set FORCE_CPU=1 to pin the CPU backend first:

    FORCE_CPU=1 python examples/federated_simulation.py
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# FORCE_CPU=1 pins the CPU backend BEFORE any jax backend query -- on a
# machine whose TPU tunnel is down, backend init hangs indefinitely
# (same convention as experiments_scripts/).
if os.environ.get("FORCE_CPU"):
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

from gfedntm_tpu.data.loaders import RawCorpus
from gfedntm_tpu.data.synthetic import generate_synthetic_corpus
from gfedntm_tpu.eval.metrics import topic_diversity
from gfedntm_tpu.federated import run_vocab_consensus
from gfedntm_tpu.federated.trainer import FederatedTrainer
from gfedntm_tpu.models import AVITM

corpus = generate_synthetic_corpus(
    vocab_size=400, n_topics=6, n_docs=150, nwords=(25, 45), n_nodes=3,
    frozen_topics=2, seed=0,
)

# Phase 1: vocabulary consensus (sorted union of per-client vocabularies).
consensus = run_vocab_consensus(
    [RawCorpus(documents=list(n.documents)) for n in corpus.nodes]
)
print(f"global vocabulary: {len(consensus.global_vocab)} terms from "
      f"{len(consensus.datasets)} clients")

# Phase 2: federated training — the whole loop is one compiled program.
template = AVITM(
    input_size=len(consensus.global_vocab), n_components=6,
    hidden_sizes=(32, 32), batch_size=16, num_epochs=10,
)
trainer = FederatedTrainer(template, n_clients=3)
result = trainer.fit(consensus.datasets)
print(f"{result.losses.shape[0]} global steps; "
      f"final mean loss {float(result.losses[-1].mean()):.1f}")

# Shared parameters are identical across clients after the final exchange.
beta = np.asarray(result.client_params["beta"])
assert np.allclose(beta[0], beta[1]) and np.allclose(beta[0], beta[2])

global_model = trainer.make_global_model(result)
global_model.train_data = consensus.datasets[0]
topics = global_model.get_topics(8)
print(f"topic diversity: {topic_diversity(topics):.2f}")
for i, topic in enumerate(topics[:3]):
    print(f"topic {i}: {' '.join(topic)}")
