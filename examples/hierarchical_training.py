"""Hierarchical (second-level) topic modeling with TMWrapper — the
reference's `--hierarchical` workflow (`tm_wrapper.py:298-357`, HTM-WS /
HTM-DS) driven natively: train a father model, expand one of its topics
into a child model on the topic-restricted subcorpus.

Run: python examples/hierarchical_training.py

On a machine whose TPU tunnel is down, jax backend init hangs
indefinitely — set FORCE_CPU=1 to pin the CPU backend first:

    FORCE_CPU=1 python examples/hierarchical_training.py
"""

import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# FORCE_CPU=1 pins the CPU backend BEFORE any jax backend query -- on a
# machine whose TPU tunnel is down, backend init hangs indefinitely
# (same convention as experiments_scripts/).
import os

if os.environ.get("FORCE_CPU"):
    import jax

    jax.config.update("jax_platforms", "cpu")

from gfedntm_tpu.data.synthetic import generate_synthetic_corpus
from gfedntm_tpu.experiments.tm_wrapper import TMWrapper

corpus = generate_synthetic_corpus(
    vocab_size=400, n_topics=6, n_docs=200, nwords=(25, 45), n_nodes=1,
    frozen_topics=2, seed=0,
)
docs = corpus.nodes[0].documents

models_root = Path(tempfile.mkdtemp(prefix="htm_"))
wrapper = TMWrapper(models_root)

father, father_dir = wrapper.train_model(
    "father", docs, model_type="avitm", n_topics=6,
    model_kwargs=dict(hidden_sizes=(32, 32), num_epochs=5, batch_size=16),
)
print("father topics:")
for i, topic in enumerate(father.get_topics(6)):
    print(f"  {i}: {topic}")

for version in ("HTM-WS", "HTM-DS"):
    child, child_dir, child_corpus = wrapper.train_htm_submodel(
        version=version,
        father_model=father,
        father_dir=father_dir,
        corpus=docs,
        name=f"child_{version.lower().replace('-', '_')}",
        expansion_topic=0,
        model_type="avitm",
        n_topics=3,
        model_kwargs=dict(hidden_sizes=(16, 16), num_epochs=3, batch_size=8),
    )
    print(f"\n{version}: child trained on {len(child_corpus)} docs "
          f"-> {child_dir}")
    for i, topic in enumerate(child.get_topics(6)):
        print(f"  {i}: {topic}")
