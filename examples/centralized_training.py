"""Centralized ProdLDA on a synthetic corpus with ground-truth recovery
scoring — the reference's centralized-baseline workflow
(`experiments/dss_tss/run_simulation.py` single-iteration slice).

Run: python examples/centralized_training.py

On a machine whose TPU tunnel is down, jax backend init hangs
indefinitely — set FORCE_CPU=1 to pin the CPU backend first:

    FORCE_CPU=1 python examples/centralized_training.py
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# FORCE_CPU=1 pins the CPU backend BEFORE any jax backend query -- on a
# machine whose TPU tunnel is down, backend init hangs indefinitely
# (same convention as experiments_scripts/).
if os.environ.get("FORCE_CPU"):
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

from gfedntm_tpu.data.preparation import prepare_dataset
from gfedntm_tpu.data.synthetic import generate_synthetic_corpus
from gfedntm_tpu.eval.metrics import (
    convert_topic_word_to_init_size,
    random_baseline_tss,
    topic_similarity_score,
)
from gfedntm_tpu.models import AVITM

V, K = 500, 8
corpus = generate_synthetic_corpus(
    vocab_size=V, n_topics=K, n_docs=400, nwords=(30, 60), n_nodes=1,
    frozen_topics=3, seed=0,
)
docs = corpus.nodes[0].documents

train_data, val_data, input_size, id2token, _docs, _vocab = (
    prepare_dataset(docs)
)
model = AVITM(
    input_size=input_size, n_components=K, hidden_sizes=(64, 64),
    batch_size=32, num_epochs=15, verbose=True,
)
model.fit(train_data, val_data)

betas = model.get_topic_word_distribution()
betas_full = convert_topic_word_to_init_size(V, betas, id2token)
tss = topic_similarity_score(betas_full, corpus.topic_vectors)
print(f"TSS: {tss:.3f} (max {K}; random baseline "
      f"{random_baseline_tss(corpus.topic_vectors):.3f})")
for i, topic in enumerate(model.get_topics(8)[:3]):
    print(f"topic {i}: {' '.join(topic)}")
