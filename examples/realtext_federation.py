"""Real-text federation on the offline docstring corpus with the
local-steps FedAvg fix — the end-to-end flow of
``results/realtext_federated/``, scaled down to run in a couple of
minutes.

The corpus needs no downloads: it is extracted from the installed Python
libraries' docstrings, one client per package family (math, deep
learning, cloud RPC, NLP, data analysis) — a genuinely non-IID split in
the same sense as the reference's fieldsOfStudy partitioning
(`docker-compose.yaml:21-149`). ``local_steps`` controls the FedAvg
exchange period: 1 reproduces the reference's per-minibatch averaging
(and its topic-diversity collapse); a few local epochs between exchanges
recovers centralized-level coherence (see
results/realtext_federated/metrics.json).

Run: python examples/realtext_federation.py

On a machine whose TPU tunnel is down, jax backend init hangs
indefinitely — set FORCE_CPU=1 to pin the CPU backend first:

    FORCE_CPU=1 python examples/realtext_federation.py
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

if os.environ.get("FORCE_CPU"):
    import jax

    jax.config.update("jax_platforms", "cpu")

from gfedntm_tpu.presets import realtext_docstrings_5client

# scale=0.1 -> 300 docs/client, 10 epochs; local_steps = 2 local epochs
# between exchanges (at 300 docs and batch 64 that is 2 * 5 steps).
res = realtext_docstrings_5client(scale=0.1, n_components=10, local_steps=10)

print("clients:", res.summary["n_clients"],
      "vocab:", res.summary["vocab_size"],
      "steps:", res.summary["global_steps"])
print("metrics:", res.summary["metrics"])
for i, topic in enumerate(res.extras["topics"][:5]):
    print(f"topic {i}:", " ".join(topic))
print(
    "\nNOTE: scale=0.1 is a smoke demo (300 docs/client, 10 epochs) — "
    "coherence needs the full corpus. Full-scale evidence: "
    "results/realtext_federated/metrics.json (federated local_steps "
    "NPMI +0.21, centralized +0.20)."
)
