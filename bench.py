"""Benchmark: 5-client federated ProdLDA throughput on one TPU chip.

Regime: the reference's federated defaults — 5 clients, K=50 topics,
V=5000 synthetic vocabulary, hidden (50,50), batch 64, Adam(lr 2e-3,
betas=(0.99, 0.99)) — i.e. BASELINE.md's simulation/federation config.

Baseline: the reference's hot loop has a hard orchestration floor of
>= 3 s sleep per client per global step plus 2N fresh-channel gRPC
round-trips (``src/federation/server.py:417-420,449,472,515``), so with 5
clients one global step (5 x 64 = 320 documents) takes >= 15 s:
**<= 21.33 docs/s** before any model math. This framework runs the whole
federation as one compiled SPMD program, so its throughput is model-math
bound instead.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import numpy as np


def main() -> None:
    import jax

    from gfedntm_tpu.data.datasets import BowDataset
    from gfedntm_tpu.data.synthetic import generate_synthetic_corpus
    from gfedntm_tpu.federated.trainer import FederatedTrainer
    from gfedntm_tpu.models.avitm import AVITM

    n_clients, vocab, k, batch = 5, 5000, 50, 64
    docs_per_node = 2000
    corpus = generate_synthetic_corpus(
        vocab_size=vocab, n_topics=k, n_docs=docs_per_node, nwords=(150, 250),
        n_nodes=n_clients, frozen_topics=5, seed=0, materialize_docs=False,
    )
    idx2token = {i: f"wd{i}" for i in range(vocab)}
    datasets = [
        BowDataset(X=node.bow, idx2token=idx2token) for node in corpus.nodes
    ]

    epochs = 4
    template = AVITM(
        input_size=vocab, n_components=k, hidden_sizes=(50, 50),
        batch_size=batch, num_epochs=epochs, lr=2e-3, momentum=0.99,
        seed=0,
    )
    trainer = FederatedTrainer(template, n_clients=n_clients)

    # Warmup fit: compiles the whole-run program.
    warm = trainer.fit(datasets)
    assert np.isfinite(warm.losses).all()

    # Timed fit: same shapes -> jit cache hit; measures steady-state.
    t0 = time.perf_counter()
    result = trainer.fit(datasets)
    jax.block_until_ready(result.client_params)
    elapsed = time.perf_counter() - t0

    global_steps = result.losses.shape[0]
    docs_processed = float(global_steps) * n_clients * batch
    docs_per_sec = docs_processed / elapsed

    # Reference orchestration floor: >=3 s sleep x 5 clients per global step
    # (server.py:417-420,472) -> <= 320 docs / 15 s.
    baseline_docs_per_sec = n_clients * batch / (3.0 * n_clients)

    print(json.dumps({
        "metric": "federated_prodlda_5client_throughput",
        "value": round(docs_per_sec, 1),
        "unit": "docs/s",
        "vs_baseline": round(docs_per_sec / baseline_docs_per_sec, 1),
    }))


if __name__ == "__main__":
    main()
