"""Benchmark: 5-client federated ProdLDA throughput on one TPU chip.

Regime: the reference's federated defaults — 5 clients, K=50 topics,
V=5000 synthetic vocabulary, hidden (50,50), batch 64, Adam(lr 2e-3,
betas=(0.99, 0.99)) — i.e. BASELINE.md's simulation/federation config.

Baseline: the reference's hot loop has a hard orchestration floor of
>= 3 s sleep per client per global step plus 2N fresh-channel gRPC
round-trips (``src/federation/server.py:417-420,449,472,515``), so with 5
clients one global step (5 x 64 = 320 documents) takes >= 15 s:
**<= 21.33 docs/s** before any model math. This framework runs the whole
federation as one compiled SPMD program, so its throughput is model-math
bound instead.

Robustness: the TPU chip is single-tenant and reached through a tunnel, so
backend init can fail transiently. The backend is probed in a *subprocess*
(a failed in-process TPU init would poison this process's jax) with retries
and backoff; if the TPU never comes up the bench still produces a number on
CPU, clearly labeled ``"backend": "cpu"`` — a degraded result beats rc=1.

Budget: the whole bench honors ``BENCH_BUDGET_S`` (default 660 s) as a hard
wall-clock ceiling — every phase deadline is clamped to the remaining
budget and later phases are skipped rather than overrun, so the run always
emits its one JSON line inside the harness's 720 s deadline instead of
being SIGKILLed mid-phase (rc=124, BENCH_r05).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...} with
phase timings (compile vs steady-state) and per-step wall-clock as extra
keys.

MFU / throughput accounting (ISSUE 6 satellite): ``vs_baseline`` always
compares against the measured torch AVITM **on this host's CPU** — so its
meaning flips with ``"backend"``: on an accelerator backend (``"tpu"`` /
``"axon"``) a vs_baseline of 300x is accelerator-vs-CPU and MFU
(``mfu_vs_bf16_peak``, normalized to the v5e bf16 MXU peak) is the honest
utilization number; on ``"backend": "cpu"`` the run is the *fallback* —
vs_baseline ~1x means "our CPU path ties torch's CPU path" and the MFU
field is meaningless-by-construction (~1e-4: a CPU program measured
against a TPU peak), NOT an accelerator regression. Every emitted record
therefore names its backend, and any abandoned accelerator attempt is
recorded in ``accel_timeout_phase`` + ``accel_attempts`` (per-attempt
sub-deadline, reason, stderr tail) so a CPU number can never silently
pose as the chip's. ``run_phase_timings`` breaks the run phase down by
wall-clock (corpus synth, compile fit, steady fit, trace fit, torch
baseline, staging) — the diagnosis surface for the BENCH_r03-r05 run-
phase timeouts; set ``BENCH_PROFILE_DIR`` to additionally wrap a phase
window in the PR 4 ``RoundProfiler`` (``BENCH_PROFILE_ROUNDS``, default
``1:2`` = the compile fit, phase indices in ``_BENCH_PHASES``).

Staged run phase (ISSUE 12): the run phase is further split into
sub-phases — backend_init -> data_staging -> first_step_compile ->
steady_state (-> trace_fit) -> multichip -> torch_baseline — each with
its own sub-deadline (``_STAGE_DEADLINES_S`` /
``BENCH_STAGE_TIMEOUT_<NAME>``) enforced from OUTSIDE the subprocess by
``_watch_stages`` reading the fsync'd stage file, plus a partial-summary
flush after every completed stage. A hang therefore costs one stage's
deadline, the breadcrumb names the hung stage (``accel_timeout_phase``),
and the stages that completed still ship (``run_stages`` /
``provenance: partial``). The ``multichip`` stage data-shards the whole
corpus across the host/device mesh (``parallel.sharded.fit_data_sharded``;
``BENCH_MESH_DEVICES``, CPU default = one device per core) and becomes
the headline metric with MFU from live-measured FLOPs (``utils.flops``).
``--compile_cache DIR`` / ``BENCH_COMPILE_CACHE`` wires the persistent
XLA compilation cache; ``BENCH_TRY_BACKEND`` forces an honest
accelerator attempt even when the probe already degraded. The final
summary is schema-checked against ``scripts/bench_schema.py``.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

_PROBE_RETRIES = 3
_PROBE_BACKOFF_S = 20.0
_PROBE_TIMEOUT_S = 300.0
_REPO_ROOT = os.path.dirname(os.path.abspath(__file__))

# Whole-bench wall-clock budget. The harness runs `python bench.py` under a
# hard 720 s deadline; the old internal schedule (720 s phase + 1440 s
# escalation + CPU fallback + fused phase) could legally take ~65 minutes,
# so the harness SIGKILLed it (rc=124, no JSON — BENCH_r05). Every phase
# timeout below is clamped to the remaining budget, and phases that no
# longer fit are skipped in favor of emitting *some* parseable JSON.
_DEFAULT_BUDGET_S = 660.0
_BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", str(_DEFAULT_BUDGET_S)))
_T_START = time.monotonic()


def _reset_budget() -> None:
    """(Re)start the budget clock — called at main() entry so the budget
    measures the run, not the module import (tests import bench long
    before they drive main)."""
    global _BUDGET_S, _T_START
    _BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", str(_DEFAULT_BUDGET_S)))
    _T_START = time.monotonic()


def _remaining_s(reserve: float = 0.0) -> float:
    """Seconds left in the bench budget, minus a reserve for later phases."""
    return _BUDGET_S - (time.monotonic() - _T_START) - reserve


def _probe_backend() -> str:
    """Return the usable jax backend ('tpu'/'cpu'/...), probing in a
    subprocess with retries so a held chip or tunnel flake degrades to CPU
    instead of killing the bench. Probe attempts respect the bench budget:
    a dead tunnel must cost seconds of the budget, not all of it."""
    if os.environ.get("JAX_PLATFORMS"):
        return os.environ["JAX_PLATFORMS"].split(",")[0]
    code = "import jax; print(jax.default_backend())"
    for attempt in range(_PROBE_RETRIES):
        # Keep >=80% of the budget for the phases the probe exists to serve.
        probe_budget = _remaining_s(0.8 * _BUDGET_S)
        if probe_budget < 10.0:
            sys.stderr.write("bench: no budget left for backend probe\n")
            return "cpu"
        try:
            out = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True,
                timeout=min(_PROBE_TIMEOUT_S, probe_budget),
            )
            if out.returncode == 0 and out.stdout.strip():
                return out.stdout.strip().splitlines()[-1]
            sys.stderr.write(
                f"bench: backend probe attempt {attempt + 1} failed "
                f"(rc={out.returncode})\n"
            )
        except subprocess.TimeoutExpired:
            sys.stderr.write(
                f"bench: backend probe attempt {attempt + 1} timed out\n"
            )
        if attempt < _PROBE_RETRIES - 1:
            time.sleep(min(_PROBE_BACKOFF_S * (attempt + 1),
                           max(0.0, _remaining_s(0.9 * _BUDGET_S))))
    return "cpu"


# Phase indices for the run-phase RoundProfiler window (BENCH_PROFILE_DIR /
# BENCH_PROFILE_ROUNDS): the profiler treats each bench phase as one
# "round", so e.g. "2:3" captures a jax.profiler trace of the steady fit
# and "4:5" one of the multi-chip data-sharded fit.
_BENCH_PHASES = (
    "synthetic_corpus",        # 0
    "compile_and_first_run",   # 1
    "steady_state_fit",        # 2
    "trace_fit",               # 3
    "multichip",               # 4
    "torch_baseline",          # 5
)


# ---------------------------------------------------------------------------
# Staged run phase (ISSUE 12 tentpole): the monolithic 720 s "run" phase is
# split into sub-phases, each bracketed by begin/done records in a stage
# file the ORCHESTRATOR watches from outside the process. A tunnel hang is
# therefore killed at the hung STAGE's own sub-deadline, the breadcrumb
# names that stage, and the partial-summary flush after every completed
# stage means a timeout still ships the stages that finished — BENCH_r05's
# rc=124 with parsed:null (all evidence lost) cannot recur.
# ---------------------------------------------------------------------------

_RUN_STAGES = (
    "backend_init",        # jax platform pin + device enumeration (the hang site)
    "data_staging",        # synthetic corpus + dataset/trainer construction
    "first_step_compile",  # warmup fit: trace + XLA compile + first run
    "steady_state",        # timed fit over the compiled program
    "trace_fit",           # optional untimed profiler fit
    "multichip",           # data-sharded fit across the host/device mesh
    "torch_baseline",      # live torch CPU reference measurement
)

#: Per-stage sub-deadlines (seconds), overridable per stage with
#: BENCH_STAGE_TIMEOUT_<NAME> (e.g. BENCH_STAGE_TIMEOUT_BACKEND_INIT=60).
#: first_step_compile is the widest: an unbounded first-step compile was
#: the leading suspect for the 720 s wall this staging exists to diagnose.
_STAGE_DEADLINES_S = {
    "backend_init": 150.0,
    "data_staging": 120.0,
    "first_step_compile": 300.0,
    "steady_state": 240.0,
    "trace_fit": 120.0,
    "multichip": 240.0,
    "torch_baseline": 150.0,
}


def _stage_deadline(stage: str) -> float:
    env = os.environ.get(f"BENCH_STAGE_TIMEOUT_{stage.upper()}")
    if env:
        return float(env)
    return _STAGE_DEADLINES_S.get(stage, 240.0)


class StageLog:
    """Stage breadcrumbs + partial-summary flush for the staged run phase.

    Every stage transition is appended (fsync'd) as one JSON line to
    ``BENCH_STAGE_PATH`` so the watching orchestrator can enforce
    per-stage sub-deadlines and a SIGKILL still leaves each completed
    stage's timings/payload on disk; completed stages are also mirrored
    into ``BENCH_PARTIAL_PATH`` as a ready-to-ship partial summary JSON
    object (atomic replace). Both paths default to unset = disabled, so
    library use of :func:`run` is unaffected."""

    def __init__(self, backend: str, metrics=None):
        self.path = os.environ.get("BENCH_STAGE_PATH") or None
        self.partial_path = os.environ.get("BENCH_PARTIAL_PATH") or None
        self.backend = backend
        self.metrics = metrics
        self.stages: "dict[str, dict]" = {}
        self.order: "list[str]" = []

    def _append(self, rec: dict) -> None:
        if not self.path:
            return
        try:
            with open(self.path, "a") as f:
                f.write(json.dumps(rec) + "\n")
                f.flush()
                os.fsync(f.fileno())
        except OSError as err:
            sys.stderr.write(f"bench: stage log write failed: {err!r}\n")

    def stage(self, name: str):
        """Context manager bracketing one stage; yields a payload dict the
        stage body may fill (banked into the done record + partial)."""
        import contextlib

        @contextlib.contextmanager
        def _cm():
            # The test hook: BENCH_FAKE_HANG_STAGE=<name> turns this stage
            # into a deliberate hang so the watchdog path is testable with
            # the real kill/flush machinery (tests/test_bench_harness.py).
            self._append({
                "stage": name, "status": "begin", "wall_time": time.time(),
                "deadline_s": _stage_deadline(name),
            })
            if os.environ.get("BENCH_FAKE_HANG_STAGE") == name:
                time.sleep(3600.0)
            t0 = time.perf_counter()
            payload: dict = {}
            yield payload
            seconds = round(time.perf_counter() - t0, 3)
            self.done(name, seconds, **payload)

        return _cm()

    def done(self, name: str, seconds: float, **payload) -> None:
        rec = {"seconds": seconds, **payload}
        self.stages[name] = rec
        if name not in self.order:
            self.order.append(name)
        self._append({
            "stage": name, "status": "done", "wall_time": time.time(),
            **rec,
        })
        if self.metrics is not None:
            self.metrics.log("bench_stage", stage=name, seconds=seconds)
        self._flush_partial()

    def summary(self) -> dict:
        return {name: dict(self.stages[name]) for name in self.order}

    def _flush_partial(self) -> None:
        if not self.partial_path:
            return
        value = 0.0
        for rec in self.stages.values():
            if rec.get("docs_per_s"):
                value = rec["docs_per_s"]
        obj = {
            "metric": "bench_run_partial",
            "value": value,
            "unit": "docs/s",
            "vs_baseline": None,
            "backend": self.backend,
            "partial": True,
            "stage_order": list(self.order),
            "run_stages": self.summary(),
        }
        tmp = self.partial_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(obj, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.partial_path)
        except OSError as err:
            sys.stderr.write(f"bench: partial flush failed: {err!r}\n")


def _read_stage_file(path: str) -> "list[dict]":
    """Parse a stage JSONL file, tolerating a torn final line (the writer
    can be SIGKILLed mid-append)."""
    recs: "list[dict]" = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    recs.append(json.loads(line))
                except json.JSONDecodeError:
                    pass
    except OSError:
        pass
    return recs


def _stage_view(recs: "list[dict]"):
    """(completed stage names, in-flight ``(name, begin_wall_time)`` or
    None) from a stage file's records."""
    done = [r["stage"] for r in recs if r.get("status") == "done"]
    done_set = set(done)
    open_ = [
        (r["stage"], float(r.get("wall_time", 0.0)))
        for r in recs
        if r.get("status") == "begin" and r["stage"] not in done_set
    ]
    return done, (open_[-1] if open_ else None)


def _read_partial(path: str) -> "dict | None":
    try:
        with open(path) as f:
            obj = json.load(f)
        return obj if obj.get("run_stages") else None
    # graftlint: disable=exception-hygiene -- an absent/torn partial file
    # simply means "no partial evidence"; the caller reports None
    except (OSError, ValueError):
        return None


def run(backend: str) -> dict:
    stages = StageLog(backend=backend)
    with stages.stage("backend_init") as binfo:
        import jax

        if backend in ("cpu", "unavailable"):
            # Runtime env-var edits are invisible here: the TPU-tunnel
            # sitecustomize imports jax config at interpreter start,
            # snapshotting JAX_PLATFORMS. config.update is the override
            # that actually works.
            jax.config.update("jax_platforms", "cpu")
            backend = "cpu"
            # Partial summaries must name the backend the numbers were
            # actually measured on, not the pre-degradation request —
            # a shipped partial claiming "axon" for CPU numbers is the
            # exact misattribution accel_attempts exists to prevent.
            stages.backend = backend
        cache_dir = os.environ.get("BENCH_COMPILE_CACHE") or None
        if cache_dir:
            # Persistent XLA compilation cache (--compile_cache /
            # BENCH_COMPILE_CACHE): reruns replay compiles from disk, so
            # compile timings then measure cache DEserialization — the
            # summary records the dir so the reader knows which.
            jax.config.update("jax_compilation_cache_dir", cache_dir)
        # Multi-chip mesh sizing must happen BEFORE backend init on CPU
        # (XLA parses the forced-device flag exactly once).
        mesh_req = int(os.environ.get("BENCH_MESH_DEVICES", "0") or 0)
        if backend == "cpu":
            if mesh_req == 0:
                # Virtual devices beyond physical cores would only slice
                # the same silicon thinner — an honest CPU multi-chip
                # default is one device per core (cap 8, the test mesh).
                mesh_req = min(os.cpu_count() or 1, 8)
            if mesh_req > 1:
                from gfedntm_tpu.parallel.mesh import ensure_virtual_devices

                ensure_virtual_devices(mesh_req)
        # Device enumeration initializes the backend — THE historical
        # hang site on a dead tunnel, now bracketed by its own stage.
        n_devices = len(jax.devices())
        mesh_n = max(1, min(mesh_req or n_devices, n_devices))
        binfo.update(
            platform=jax.default_backend(), devices=n_devices,
            mesh_devices=mesh_n, compilation_cache_dir=cache_dir,
        )

    from gfedntm_tpu.data.datasets import BowDataset
    from gfedntm_tpu.data.synthetic import generate_synthetic_corpus
    from gfedntm_tpu.federated.trainer import FederatedTrainer
    from gfedntm_tpu.models.avitm import AVITM
    from gfedntm_tpu.utils.observability import (
        DeviceMemoryMonitor,
        MetricsLogger,
        RoundProfiler,
        phase_timer,
        trace,
        validate_record,
    )

    on_accel = backend not in ("cpu", "unavailable")
    n_clients, vocab, k, batch = 5, 5000, 50, 64
    # CPU fallback shrinks the corpus/epochs so a degraded environment still
    # reports a (labeled) number in minutes, not hours.
    docs_per_node = 2000 if on_accel else 640
    epochs = 20 if on_accel else 2

    # Bench telemetry rides the SAME JSONL schema as training runs (one
    # MetricsLogger, summarize-able with `gfedntm_tpu.cli summarize`), so
    # BENCH_r*.json and run telemetry are no longer two formats. Writes
    # results/bench_metrics.jsonl by default; BENCH_METRICS_PATH overrides.
    # mode="w": summarize aggregates one run per file (appending a second
    # bench run would smear wall_seconds across both and shadow the first
    # run's registry snapshot).
    # keep_records=True: the phase accounting below reads back its own
    # events in-process; a bench run is short, so retention is cheap.
    metrics = MetricsLogger(
        os.environ.get("BENCH_METRICS_PATH")
        or os.path.join(_REPO_ROOT, "results", "bench_metrics.jsonl"),
        mode="w",
        keep_records=True,
    )

    # PR 4 device-profiling hooks, aimed at the run phase itself: the
    # r03-r05 trajectory silently degraded to CPU because this phase hung
    # on the accelerator with no per-phase evidence. With BENCH_PROFILE_DIR
    # set, a jax.profiler window wraps the _BENCH_PHASES window named by
    # BENCH_PROFILE_ROUNDS (default "1:2": the compile fit).
    profiler = RoundProfiler(
        os.environ.get("BENCH_PROFILE_DIR") or None,
        rounds=os.environ.get("BENCH_PROFILE_ROUNDS", "1:2"),
        metrics=metrics,
    )
    stages.metrics = metrics

    profiler.observe(_BENCH_PHASES.index("synthetic_corpus"))
    with stages.stage("data_staging") as dinfo:
        with phase_timer(metrics, "synthetic_corpus"):
            corpus = generate_synthetic_corpus(
                vocab_size=vocab, n_topics=k, n_docs=docs_per_node,
                nwords=(150, 250), n_nodes=n_clients, frozen_topics=5,
                seed=0, materialize_docs=False,
            )
            idx2token = {i: f"wd{i}" for i in range(vocab)}
            datasets = [
                BowDataset(X=node.bow, idx2token=idx2token)
                for node in corpus.nodes
            ]

        template = AVITM(
            input_size=vocab, n_components=k, hidden_sizes=(50, 50),
            batch_size=batch, num_epochs=epochs, lr=2e-3, momentum=0.99,
            seed=0,
        )
        trainer = FederatedTrainer(template, n_clients=n_clients)
        dinfo.update(docs=n_clients * docs_per_node, vocab=vocab)

    # Warmup fit: stages the corpora once (cached in the trainer) and
    # compiles the whole-run program.
    # Device-memory gauges (device_bytes_in_use/<dev>; no-op on CPU):
    # sampled after the compile fit (peak includes compile scratch) and
    # after the steady fit, landing in the same registry snapshot.
    devmem = DeviceMemoryMonitor(metrics.registry)
    profiler.observe(_BENCH_PHASES.index("compile_and_first_run"))
    with stages.stage("first_step_compile") as cinfo:
        t0 = time.perf_counter()
        with phase_timer(metrics, "compile_and_first_run"):
            warm = trainer.fit(datasets, metrics=metrics)
            jax.block_until_ready(warm.client_params)
        compile_s = time.perf_counter() - t0
        devmem.sample()
        assert np.isfinite(warm.losses).all()
        stage_s = sum(
            r["seconds"] for r in metrics.events("phase")
            if r["phase"] == "stage_data"
        )
        cinfo.update(
            compile_and_first_run_s=round(compile_s, 2),
            one_time_stage_data_s=round(stage_s, 3),
            compilation_cache_dir=cache_dir,
        )

    # Timed fit: staged data + compiled program are reused, so this measures
    # the schedule build (host numpy) + the compiled whole-run scan — the
    # recurring cost of a training run. NO profiler here: tracing this fit
    # inflated the round-4 timed run ~5x (host instrumentation around every
    # np.asarray/tree_map), so the trace is captured on a separate,
    # untimed fit below.
    n_before = len(metrics.events("phase"))
    profiler.observe(_BENCH_PHASES.index("steady_state_fit"))
    with stages.stage("steady_state") as sinfo:
        t0 = time.perf_counter()
        with phase_timer(metrics, "steady_state_fit"):
            result = trainer.fit(datasets, metrics=metrics)
            jax.block_until_ready(result.client_params)
        steady_s = time.perf_counter() - t0
        devmem.sample()
        sinfo.update(
            docs_per_s=round(
                float(result.losses.shape[0]) * n_clients * batch
                / steady_s, 1,
            ),
        )
    # Phase accounting for the TIMED fit only (the traced fit below logs
    # its own program_segment events, which must not pollute this).
    phases = metrics.events("phase")[n_before:]
    schedule_s = sum(
        r["seconds"] for r in phases if r["phase"] == "build_schedules"
    )
    program_s = sum(
        r["seconds"] for r in phases if r["phase"] == "program_segment"
    )

    # Trace fit (untimed): same staged data + compiled program, captured
    # for the step-attribution README; its wall time is reported separately
    # so profiler overhead can never contaminate the headline.
    trace_dir = os.environ.get("BENCH_TRACE_DIR") or (
        os.path.join(_REPO_ROOT, "results", "profile_trace")
        if on_accel
        else None
    )
    traced_fit_s = None
    if trace_dir is not None:
        profiler.observe(_BENCH_PHASES.index("trace_fit"))
        with stages.stage("trace_fit") as tinfo:
            t0 = time.perf_counter()
            try:
                # metrics=None: profiler overhead inflates segment times
                # ~5x, and the registry's trainer_step_s histogram is
                # cumulative — a traced fit would skew the summarize
                # p50/p95/p99 the same way the phase slicing above guards
                # against.
                with trace(trace_dir):
                    traced = trainer.fit(datasets, metrics=None)
                    jax.block_until_ready(traced.client_params)
                traced_fit_s = round(time.perf_counter() - t0, 2)
            except Exception as err:
                # The failure is banked into the summary's trace_dir field
                # AND said out loud — a trace-less bench must name why.
                sys.stderr.write(f"bench: profiler trace failed: {err!r}\n")
                trace_dir = f"profiler-failed-on-{backend}"
            tinfo.update(trace_dir=trace_dir)

    # Multi-chip data-sharded fit (ISSUE 12 tentpole): the SAME total
    # corpus trains as one local dataset sharded over the mesh
    # (parallel.sharded.fit_data_sharded — bucketed padding, AOT compile
    # split, donated carried state), with MFU from live-measured
    # per-device FLOPs. This is the headline number when it runs; set
    # BENCH_MESH_DEVICES=1 to force single-device, 0/unset = one device
    # per core on CPU, all devices on an accelerator.
    multichip = None
    profiler.observe(_BENCH_PHASES.index("multichip"))
    with stages.stage("multichip") as minfo:
        from gfedntm_tpu.parallel.mesh import make_param_mesh
        from gfedntm_tpu.parallel.sharded import fit_data_sharded

        mc_ds = BowDataset(
            X=np.concatenate([node.bow for node in corpus.nodes]),
            idx2token=idx2token,
        )
        mc_model = AVITM(
            input_size=vocab, n_components=k, hidden_sizes=(50, 50),
            batch_size=batch, num_epochs=6 if on_accel else 3, lr=2e-3,
            momentum=0.99, seed=0, fused_decoder=False,
        )
        mc_mesh = make_param_mesh(axis_name="data", n_devices=mesh_n)
        multichip = fit_data_sharded(
            mc_model, mc_ds, mesh=mc_mesh, metrics=metrics,
        )
        assert np.isfinite(np.asarray(mc_model.epoch_losses)).all()
        minfo.update(**{
            mk: mv for mk, mv in multichip.items()
            if isinstance(mv, (int, float, str, type(None)))
        })

    global_steps = int(result.losses.shape[0])
    docs_processed = float(global_steps) * n_clients * batch
    docs_per_sec = docs_processed / steady_s
    step_ms = steady_s / global_steps * 1e3
    program_step_ms = program_s / global_steps * 1e3

    # Analytic matmul FLOPs per global step (fwd+bwd ~= 3x fwd), counting
    # the padded-client blocks the program actually computes: per client,
    # encoder V->50 + heads + decoder 50->V dominate at ~4*B*K*V fwd.
    c_pad = trainer.c_pad
    hidden = 50
    fwd_flops = 2.0 * batch * (
        vocab * hidden + hidden * hidden + 2 * hidden * k + k * vocab
    )
    flops_per_step = 3.0 * fwd_flops * c_pad
    mfu = flops_per_step / (program_step_ms / 1e3) / _V5E_PEAK_FLOPS

    # Reference orchestration floor: >=3 s sleep x 5 clients per global step
    # (server.py:417-420,472) -> <= 320 docs / 15 s.
    baseline_docs_per_sec = n_clients * batch / (3.0 * n_clients)

    # Measured compute baseline: the reference's own torch AVITM on this
    # host (imported from /root/reference, same regime, centralized =
    # its compute-only best case). Falls back to the committed artifact
    # if the live run is unavailable.
    torch_docs_per_sec, torch_src = None, None
    profiler.observe(_BENCH_PHASES.index("torch_baseline"))
    with stages.stage("torch_baseline") as binfo2:
        try:
            sys.path.insert(
                0, os.path.join(_REPO_ROOT, "experiments_scripts")
            )
            from torch_baseline import run_torch_baseline

            with phase_timer(metrics, "torch_baseline"):
                tb = run_torch_baseline(epochs=1)
            torch_docs_per_sec, torch_src = (
                tb["docs_per_s"], "measured-live",
            )
        except Exception as err:
            sys.stderr.write(
                f"bench: live torch baseline failed: {err!r}\n"
            )
            artifact = os.path.join(
                _REPO_ROOT, "results/torch_baseline.json"
            )
            if os.path.exists(artifact):
                with open(artifact) as f:
                    torch_docs_per_sec = json.load(f)["docs_per_s"]
                torch_src = "committed-artifact"
        binfo2.update(
            torch_docs_per_s=torch_docs_per_sec, source=torch_src,
        )

    metrics.log(
        "bench_summary", backend=backend, docs_per_sec=docs_per_sec,
        steps=global_steps, step_ms=step_ms, compile_s=compile_s,
        steady_s=steady_s, program_step_ms=program_step_ms,
    )

    # Headline ratio (VERDICT r3 Weak #5): vs_baseline is the measured
    # torch-AVITM compute baseline — beating the reference's >=3 s-sleep
    # orchestration floor is table stakes, not the story; it stays as
    # context under vs_orchestration_floor. If the torch baseline is
    # unavailable entirely, the floor ratio is reported with an explicit
    # label so the headline is never silently the easy comparison.
    vs_torch = (
        round(docs_per_sec / torch_docs_per_sec, 2)
        if torch_docs_per_sec else None
    )
    result = {
        "metric": "federated_prodlda_5client_throughput",
        "value": round(docs_per_sec, 1),
        "unit": "docs/s",
        "vs_baseline": (
            vs_torch if vs_torch is not None
            else round(docs_per_sec / baseline_docs_per_sec, 1)
        ),
        "baseline_definition": (
            "reference torch AVITM (same regime, this host, "
            f"{torch_src})" if vs_torch is not None
            else "reference >=3s-sleep orchestration floor (torch "
            "baseline unavailable)"
        ),
        "vs_torch_cpu": vs_torch,
        "vs_orchestration_floor": round(
            docs_per_sec / baseline_docs_per_sec, 1
        ),
        "torch_cpu_docs_per_s": torch_docs_per_sec,
        "torch_baseline_source": torch_src,
        "backend": backend,
        "global_steps": global_steps,
        "step_ms": round(step_ms, 3),
        "step_breakdown": {
            "program_ms_per_step": round(program_step_ms, 3),
            "schedule_build_s": round(schedule_s, 3),
            "program_s": round(program_s, 3),
            "one_time_stage_data_s": round(stage_s, 3),
            "note": (
                "round-2's 47.5 ms/step was ~98% one-time host staging "
                "(320 MB corpus upload) re-paid every fit; staging is now "
                "cached across fits"
            ),
        },
        "flops_per_global_step": flops_per_step,
        "program_gflops_per_s": round(
            flops_per_step / (program_step_ms / 1e3) / 1e9, 1
        ),
        "mfu_vs_bf16_peak": round(mfu, 4),
        # Regime-normalized trend metric (VERDICT r3 Weak #6): the CPU
        # fallback shrinks docs/epochs, so end-to-end docs/s is not
        # comparable across rounds with different backends. Per-step
        # program throughput has the same (V, K, B, C) work regardless of
        # corpus size or epochs — THIS is the cross-round trend line.
        "program_docs_per_s_normalized": round(
            n_clients * batch / (program_step_ms / 1e3), 1
        ),
        "profile_trace_dir": trace_dir,
        # The RoundProfiler window over the bench phases (BENCH_PROFILE_DIR
        # / BENCH_PROFILE_ROUNDS) — the staged-diagnosis trace the
        # acceptance evidence points at when the accelerator is
        # unreachable; None = no window requested.
        "profiler_window_dir": os.environ.get("BENCH_PROFILE_DIR") or None,
        # Wall time of the separate profiler-on fit (NOT the headline
        # measurement): the gap vs steady_state_s is profiler overhead.
        "traced_fit_s": traced_fit_s,
        # With a persistent XLA cache (the supervisor sets it so stall-kill
        # relaunches replay compiles from disk), this measures cache
        # deserialization, not compilation — the field below says which.
        "compile_and_first_run_s": round(compile_s, 1),
        "compilation_cache_dir": (
            os.environ.get("BENCH_COMPILE_CACHE")
            or os.environ.get("JAX_COMPILATION_CACHE_DIR")
        ),
        "steady_state_s": round(steady_s, 1),
        "regime": {
            "n_clients": n_clients, "vocab": vocab, "k": k, "batch": batch,
            "docs_per_node": docs_per_node, "epochs": epochs,
        },
    }
    profiler.close()
    # Per-phase wall-clock of THIS run phase (the r03-r05 timeout
    # diagnosis surface): every phase_timer event aggregated by name,
    # plus the untimed trace fit. When an accelerator attempt times out,
    # the partial JSONL at BENCH_METRICS_PATH still holds whatever phases
    # completed — the hang is bracketed by the first missing phase.
    timings: dict[str, float] = {}
    for r in metrics.events("phase"):
        timings[r["phase"]] = round(
            timings.get(r["phase"], 0.0) + r["seconds"], 3
        )
    if traced_fit_s is not None:
        timings["trace_fit"] = traced_fit_s
    result["run_phase_timings"] = timings
    # Staged sub-phase record (the ISSUE 12 diagnosis surface): per-stage
    # wall seconds + payloads, in execution order. The same records were
    # flushed incrementally to BENCH_STAGE_PATH/BENCH_PARTIAL_PATH, so a
    # stage that HANGS still leaves everything before it on disk.
    result["run_stages"] = stages.summary()
    if multichip is not None:
        result["multichip"] = multichip
        if multichip.get("docs_per_s"):
            # Headline (ISSUE 12): multi-chip data-sharded docs/s with
            # MFU from live-measured program FLOPs over a live-resolved
            # per-device peak (utils.flops — measured matmul probe on
            # CPU, nominal spec on accelerators). The 5-client federated
            # number stays on the record under federated_docs_per_s.
            result["federated_docs_per_s"] = result["value"]
            result["federated_vs_torch_cpu"] = result["vs_torch_cpu"]
            result["metric"] = "multichip_sharded_prodlda_throughput"
            result["value"] = multichip["docs_per_s"]
            result["mesh_devices"] = multichip["devices"]
            result["mfu"] = multichip["mfu"]
            result["mfu_peak_source"] = multichip["peak_flops_source"]
            result["multichip_compile_s"] = multichip["compile_s"]
            # Every ratio on the record must share the NEW numerator —
            # leaving a federated-numerator ratio next to a multichip
            # value would let a reader pair them. No torch baseline =>
            # vs_baseline is the floor ratio recomputed for this
            # numerator, with the definition labeled accordingly.
            if torch_docs_per_sec:
                result["vs_baseline"] = round(
                    multichip["docs_per_s"] / torch_docs_per_sec, 2
                )
                result["vs_torch_cpu"] = result["vs_baseline"]
            else:
                result["vs_baseline"] = round(
                    multichip["docs_per_s"] / baseline_docs_per_sec, 1
                )
                result["vs_torch_cpu"] = None
                result["baseline_definition"] = (
                    "reference >=3s-sleep orchestration floor (torch "
                    "baseline unavailable)"
                )
            result["vs_orchestration_floor"] = round(
                multichip["docs_per_s"] / baseline_docs_per_sec, 1
            )
    # The full bench record goes into the telemetry stream too, schema-
    # linted so the documented event contract can't silently drift.
    validate_record(metrics.log("bench_result", **result))
    metrics.snapshot_registry()
    metrics.close()
    return result


# TPU v5e (v5 lite) nominal peaks, used only to contextualize the soak
# numbers (the chip behind the tunnel reports "TPU v5 lite"):
#   MXU:  197 TFLOP/s bf16 (f32 matmuls run well below this — the soak runs
#         f32, so "mfu" here is conservative by construction)
#   HBM:  819 GB/s
_V5E_PEAK_FLOPS = 197.0e12
_V5E_PEAK_HBM_GBS = 819.0


def _grad_oracle_f64(theta, beta, x, mask, eps=1e-5, floor=1e-10):
    """float64 numpy gradients of ``sum(mask * rl)`` for the prodLDA
    reconstruction loss (training-mode batch statistics) — the accuracy
    oracle both f32 paths are measured against."""
    th = theta.astype(np.float64)
    bt = beta.astype(np.float64)
    xx = x.astype(np.float64)
    m = mask.astype(np.float64)[:, None]
    cnt = max(float(m.sum()), 1.0)
    z = th @ bt
    mean = (z * m).sum(axis=0) / cnt
    var = (np.square(z - mean) * m).sum(axis=0) / cnt
    inv_std = 1.0 / np.sqrt(var + eps)
    n = (z - mean) * inv_std
    e = np.exp(n - n.max(axis=1, keepdims=True))
    p = e / e.sum(axis=1, keepdims=True)
    g = m  # d loss / d rl = mask
    gp = -(xx / (p + floor)) * g
    gn = p * (gp - (gp * p).sum(axis=-1, keepdims=True))
    sum_gn = (gn * m).sum(axis=0, keepdims=True)
    sum_gnn = (gn * n * m).sum(axis=0, keepdims=True)
    gz = inv_std * (gn - m * (sum_gn / cnt) - n * m * (sum_gnn / cnt))
    return gz @ bt.T, th.T @ gz


# K for all fused-kernel soak cases. ONE constant: the tile-label paths
# (error rows, sweep live-case filter in soak_fused_kernel.py) resolve
# geometry with it and must agree with the K the cases actually run.
SOAK_K = 50


def bench_fused_largev(
    backend: str,
    v_list=(16384, 50_000, 100_000),
    batch_list=(64, 256),
    cases=None,
    storage: str = "float32",
) -> dict:
    """Soak the compiled Pallas fused decode+loss kernel at large V: on-device
    parity vs the unfused XLA oracle (values + grads) and fwd+bwd step time
    for both, per (V, B). This is the regime the kernel exists for (the
    reference preprocesses to V up to 100k, ``text_preproc.py:49``); the main
    bench's V=5000 federation sits below the auto-enable threshold.

    Timing runs N optimizer-coupled steps inside a single jitted
    ``lax.scan`` — the same shape the real trainer uses — because per-call
    timing through the tunnel is floored at several ms of dispatch latency,
    which flattens any compute difference (this is exactly what made the
    round-2 per-call numbers meaningless).
    """
    from gfedntm_tpu.ops.fused_decoder import resolve_tile_v

    interpret = backend == "cpu"  # CPU fallback: interpret mode (tiny V only)
    out = {}
    if cases is None:
        cases = [(V, B) for V in v_list for B in batch_list]
    if interpret:
        cases = [(2048, 64)]
    for V, B in cases:
        # A failing case must not lose the rows already measured — the
        # round-4 soak died at the tile-4096 x (V=100k, B=256) sweep case
        # (Mosaic scoped-VMEM overflow) and dropped the whole artifact.
        # Error rows carry the resolved tile too: the geometry that failed
        # is exactly the diagnostic the artifact exists to preserve.
        try:
            out[f"V{V}_B{B}"] = _fused_case(V, B, interpret, storage)
        except Exception as err:  # noqa: BLE001 — record, keep sweeping
            out[f"V{V}_B{B}"] = {
                "tile_v": resolve_tile_v(V, B, SOAK_K, storage),
                "storage_dtype": storage,
                "parity": False,
                "error": f"{type(err).__name__}: {err}"[:600],
            }
    return out


def _fused_case(
    V: int, B: int, interpret: bool, storage: str = "float32"
) -> dict:
    """Parity + timing for one (V, B) soak case; see bench_fused_largev.

    ``storage="bfloat16"`` soaks the bf16-stored kernel (beta/x streamed
    bf16, f32 accumulation). Parity is then judged AT THE QUANTIZED POINT:
    the unfused comparator and the f64 oracle both receive bf16-quantized
    beta/x, so the bands measure the kernel's accumulation error — storage
    quantization (~4e-3 on beta, exact on BoW counts < 256) is a modeling
    choice reported by ``quantization_grad_delta``, not a kernel defect.
    """
    import jax
    import jax.numpy as jnp

    from gfedntm_tpu.ops.fused_decoder import (
        prodlda_recon_loss,
        prodlda_recon_loss_reference,
        resolve_tile_v,
    )

    K = SOAK_K
    # The tile width the kernel will actually use for this case: the
    # VMEM-frontier clamp can silently shrink an operator-requested
    # GFEDNTM_FUSED_TILE_V at large B, so sweep rows must record the
    # resolved geometry or wider-tile labels would report baseline-tile
    # numbers as sweep results. K matters: small-K cases resolve the
    # widened (8192-cap) tiling.
    resolved_tile_v = resolve_tile_v(V, B, K, storage)
    rng = np.random.default_rng(0)
    theta = jnp.asarray(
        rng.dirichlet(np.ones(K), size=B).astype(np.float32)
    )
    beta = jnp.asarray(rng.normal(size=(K, V)).astype(np.float32))
    x = jnp.asarray(
        rng.integers(0, 3, size=(B, V)).astype(np.float32)
    )
    mask = jnp.ones((B,), jnp.float32)
    rm, rv = jnp.zeros((V,)), jnp.ones((V,))

    if storage == "bfloat16":
        beta_cmp = beta.astype(jnp.bfloat16).astype(jnp.float32)
        x_cmp = x.astype(jnp.bfloat16).astype(jnp.float32)
    else:
        beta_cmp, x_cmp = beta, x

    def loss_fused(theta, beta):
        rl, _, _ = prodlda_recon_loss(
            theta, beta, x, rm, rv, mask, True, 1e-5, 1e-10, interpret,
            storage,
        )
        return jnp.sum(rl * mask)

    def loss_ref(theta, beta):
        rl, _, _ = prodlda_recon_loss_reference(
            theta, beta, x_cmp, rm, rv, mask, True
        )
        return jnp.sum(rl * mask)

    # ---- parity (one call each) ----------------------------------------
    # Grad criterion: both f32 paths are compared against a float64
    # numpy oracle; the fused kernel passes if it is no farther from
    # the oracle than ~2x the unfused XLA path (plus an absolute floor
    # for when both are at f32 noise). A fused-vs-unfused bitwise-style
    # threshold instead measures f32 summation-order noise, which grows
    # with B*V and says nothing about which path is wrong.
    f_fused = jax.jit(jax.value_and_grad(loss_fused, argnums=(0, 1)))
    f_ref = jax.jit(jax.value_and_grad(loss_ref, argnums=(0, 1)))
    lf, gf = f_fused(theta, beta)
    # The unfused comparator evaluates at the same (possibly quantized)
    # point the kernel streams, so parity isolates accumulation error.
    lr, gr = f_ref(theta, beta_cmp)
    jax.block_until_ready((lf, gf, lr, gr))
    loss_rel = abs(float(lf) - float(lr)) / max(abs(float(lr)), 1e-9)
    grad_rel = max(
        float(jnp.max(jnp.abs(a - b)))
        / max(float(jnp.max(jnp.abs(b))), 1e-9)
        for a, b in zip(gf, gr)
    )
    g64 = _grad_oracle_f64(
        np.asarray(theta), np.asarray(beta_cmp), np.asarray(x_cmp),
        np.asarray(mask),
    )
    def _oracle_err(grads):
        return max(
            float(np.max(np.abs(np.asarray(a, np.float64) - o)))
            / max(float(np.max(np.abs(o))), 1e-9)
            for a, o in zip(grads, g64)
        )
    fused_vs_f64 = _oracle_err(gf)
    unfused_vs_f64 = _oracle_err(gr)
    grad_ok = fused_vs_f64 <= max(2.0 * unfused_vs_f64, 1e-4)

    # ---- timing (n steps inside one jitted scan) -----------------------
    n_steps = 200

    def make_loop(loss_fn):
        grad_fn = jax.value_and_grad(loss_fn, argnums=(0, 1))

        @jax.jit
        def run(theta, beta):
            def body(carry, _):
                th, bt = carry
                loss, (gt, gb) = grad_fn(th, bt)
                # SGD-coupled so no step can be folded away or reordered.
                return (th - 1e-6 * gt, bt - 1e-6 * gb), loss

            carry, losses = jax.lax.scan(
                body, (theta, beta), None, length=n_steps
            )
            return carry, losses

        return run

    def timeit_once(run):
        t0 = time.perf_counter()
        jax.block_until_ready(run(theta, beta))
        return (time.perf_counter() - t0) / n_steps * 1e3

    # Interleaved best-of-N: single-call timings through the tunnel show
    # multi-hundred-percent run-to-run drift, so fused/unfused strictly
    # alternate (F,R,F,R,...) and the minimum (the least-interfered
    # pass) is reported for each — consecutive blocks would let slow
    # drift systematically favor whichever path lands in the quiet
    # window.
    run_fused, run_ref = make_loop(loss_fused), make_loop(loss_ref)
    jax.block_until_ready(run_fused(theta, beta))  # compile + warm
    jax.block_until_ready(run_ref(theta, beta))
    fused_ms = unfused_ms = float("inf")
    for _ in range(7):
        fused_ms = min(fused_ms, timeit_once(run_fused))
        unfused_ms = min(unfused_ms, timeit_once(run_ref))

    # Analytic floors per step: matmul FLOPs and minimal HBM traffic.
    # Fused: z fwd (2BKV) + remat z, dtheta, dbeta in bwd (6BKV). Unfused
    # autodiff: no remat -> 6BKV, but it streams the [B, V] intermediates
    # through HBM. Traffic: beta read 3x + x read 2x at STORAGE width,
    # g_beta written once in f32.
    flops_fused = 8.0 * B * K * V
    sb = 2.0 if storage == "bfloat16" else 4.0
    bytes_fused = sb * (3 * K * V + 2 * B * V) + 4.0 * K * V
    step_s = fused_ms / 1e3

    # Context for bf16 rows: how far storage quantization alone moves the
    # gradient (fused grads vs the UNQUANTIZED f64 oracle). This is the
    # modeling cost of bf16 storage; the parity bands above measure the
    # kernel's own accumulation error at the quantized point.
    quant_delta = None
    if storage == "bfloat16":
        g64_unq = _grad_oracle_f64(
            np.asarray(theta), np.asarray(beta), np.asarray(x),
            np.asarray(mask),
        )
        quant_delta = max(
            float(np.max(np.abs(np.asarray(a, np.float64) - o)))
            / max(float(np.max(np.abs(o))), 1e-9)
            for a, o in zip(gf, g64_unq)
        )
    return {
        "tile_v": resolved_tile_v,
        "storage_dtype": storage,
        **(
            {"quantization_grad_delta": float(f"{quant_delta:.2e}")}
            if quant_delta is not None else {}
        ),
        "fused_ms": round(fused_ms, 3),
        "unfused_ms": round(unfused_ms, 3),
        "speedup": round(unfused_ms / fused_ms, 3),
        "loss_rel_err": float(f"{loss_rel:.2e}"),
        "grad_rel_err": float(f"{grad_rel:.2e}"),
        "grad_fused_vs_f64": float(f"{fused_vs_f64:.2e}"),
        "grad_unfused_vs_f64": float(f"{unfused_vs_f64:.2e}"),
        "parity": bool(loss_rel < 1e-4 and grad_ok),
        "fused_gflops_per_s": round(flops_fused / step_s / 1e9, 1),
        "fused_mfu_vs_bf16_peak": round(
            flops_fused / step_s / _V5E_PEAK_FLOPS, 4
        ),
        "fused_hbm_gb_per_s": round(bytes_fused / step_s / 1e9, 1),
        "fused_hbm_util": round(
            bytes_fused / step_s / 1e9 / _V5E_PEAK_HBM_GBS, 3
        ),
        "timing": f"{n_steps}-step jitted scan, per-step ms, best-of-interleaved",
    }


def _phase_main(phase: str, backend: str) -> None:
    """Run one bench phase in THIS process and print its JSON to stdout."""
    if backend in ("cpu", "unavailable"):
        # Every phase must pin the platform itself: a degraded-to-CPU phase
        # that still initializes the default axon backend would hang on the
        # exact tunnel failure that caused the degradation (the env var
        # alone is overridden by the image's sitecustomize).
        import jax

        jax.config.update("jax_platforms", "cpu")
    if phase == "run":
        out = run(backend)
    elif phase == "fused":
        # Three decision-relevant cases keep the bench bounded: the
        # auto-threshold regime, the saturating large-V/large-B one, and
        # the bf16-storage variant of the latter (the HBM headline). The
        # full (V, B) table is the committed soak artifact
        # (results/fused_kernel_soak.json via soak_fused_kernel.py).
        out = bench_fused_largev(backend, cases=[(16384, 64), (100_000, 256)])
        bf16 = bench_fused_largev(
            backend, cases=[(100_000, 256)], storage="bfloat16"
        )
        out["V100000_B256_bf16"] = bf16.get("V100000_B256", bf16)
    else:
        raise SystemExit(f"unknown phase {phase!r}")
    print("\n" + json.dumps(out), flush=True)


def _watch_stages(proc, stage_path: str, timeout_s: float):
    """Babysit a staged phase subprocess from OUTSIDE the process.

    Polls the stage file the subprocess appends begin/done records to
    (:class:`StageLog`); kills the process the moment the IN-FLIGHT
    stage exceeds its own sub-deadline (``_stage_deadline``), with the
    overall ``timeout_s`` as the backstop for un-staged phases and
    inter-stage gaps. Returns None on clean exit, else
    ``(hung_stage_or_None, waited_s)`` for the kill it performed —
    the named stage is exactly the evidence BENCH_r05 lost.
    """
    t0 = time.monotonic()
    while True:
        if proc.poll() is not None:
            return None
        _done, inflight = _stage_view(_read_stage_file(stage_path))
        if inflight is not None:
            stage, began = inflight
            waited = time.time() - began
            if waited > _stage_deadline(stage):
                proc.kill()
                return (stage, waited)
        elapsed = time.monotonic() - t0
        if elapsed > timeout_s:
            proc.kill()
            return ((inflight[0] if inflight else None), elapsed)
        time.sleep(0.25)


def _run_phase(
    phase: str, backend: str, timeout_s: float, retries: int = 1,
    failures: "list[dict] | None" = None,
):
    """Run a bench phase in a SUBPROCESS under staged watching.

    The TPU tunnel can hang any device call indefinitely (its client
    re-dials with unbounded sleeps; observed twice as a 20+-minute bench
    with ~20 s of CPU time). Phase isolation means a hang costs one
    sub-deadline + retry on a FRESH tunnel connection instead of the
    whole bench, and the orchestrator below stays stdlib-only so it
    cannot hang. The run phase additionally writes per-stage begin/done
    records (BENCH_STAGE_PATH) and a partial summary after every
    completed stage (BENCH_PARTIAL_PATH): :func:`_watch_stages` kills at
    the first stage whose own sub-deadline lapses, and the failure
    breadcrumb then carries the hung stage's NAME, the completed stages,
    and the partial summary — so a timeout ships evidence instead of
    rc=124 with parsed:null (BENCH_r05). Returns the parsed JSON or None.

    ``failures`` (if given) collects one machine-readable record per
    failed attempt — phase, backend, the sub-deadline it ran under, a
    reason code (``timeout`` / ``stage_timeout`` / ``rc`` /
    ``bad_json``), a stderr tail, and (for staged phases) ``stage`` /
    ``stages_completed`` / ``partial`` — so an abandoned accelerator
    attempt leaves evidence in the final JSON (``accel_attempts``)
    instead of silently shipping CPU numbers.
    """
    import tempfile

    def _note(reason: str, **extra) -> None:
        if failures is not None:
            failures.append(dict(
                phase=phase, backend=backend,
                timeout_s=round(timeout_s, 1), reason=reason, **extra,
            ))
    cmd = [
        sys.executable, os.path.abspath(__file__), "--phase", phase,
        "--backend", backend,
    ]
    env = dict(os.environ)
    if backend in ("cpu", "unavailable"):
        # A CPU phase must not even *import* the axon plugin: with the
        # tunnel down, the sitecustomize on PYTHONPATH blocks every
        # `import jax` at interpreter start (before any bench code runs),
        # so a "degraded to CPU" phase would hang exactly like the TPU one.
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in env.get("PYTHONPATH", "").split(os.pathsep)
            if p and "axon" not in p
        )
        env["JAX_PLATFORMS"] = "cpu"
    else:
        # An explicit accelerator attempt (including BENCH_TRY_BACKEND on
        # a host whose env already degraded to cpu) must actually aim the
        # subprocess at that platform.
        env["JAX_PLATFORMS"] = backend
    for attempt in range(retries + 1):
        fd, stage_path = tempfile.mkstemp(prefix=f"bench_{phase}_stages_")
        os.close(fd)
        fd, partial_path = tempfile.mkstemp(
            prefix=f"bench_{phase}_partial_"
        )
        os.close(fd)
        os.unlink(partial_path)  # StageLog creates it atomically on flush
        env["BENCH_STAGE_PATH"] = stage_path
        env["BENCH_PARTIAL_PATH"] = partial_path
        # stdout/stderr go to FILES, not pipes: the watcher below polls
        # without draining, and a chatty child (XLA warnings, a large
        # summary line) would fill a 64 KiB pipe and deadlock — blocked
        # on write(), making no stage progress, and get falsely killed
        # as a timeout.
        fd, out_path = tempfile.mkstemp(prefix=f"bench_{phase}_out_")
        os.close(fd)
        fd, err_path = tempfile.mkstemp(prefix=f"bench_{phase}_err_")
        os.close(fd)
        try:
            with open(out_path, "w") as out_f, open(err_path, "w") as err_f:
                proc = subprocess.Popen(
                    cmd, stdout=out_f, stderr=err_f, text=True, env=env,
                )
                hung = _watch_stages(proc, stage_path, timeout_s)
                try:
                    proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
            with open(out_path) as f:
                out = f.read()
            with open(err_path) as f:
                err = f.read()
            done, inflight = _stage_view(_read_stage_file(stage_path))
            partial = _read_partial(partial_path)
        finally:
            for p in (stage_path, partial_path, out_path, err_path):
                try:
                    os.unlink(p)
                except OSError:
                    pass
        if hung is not None:
            stage, waited = hung
            sys.stderr.write(
                f"bench: phase {phase!r} "
                + (f"hung in stage {stage!r} " if stage else "")
                + f"killed after {waited:.0f}s (attempt {attempt + 1}); "
                f"completed stages: {done}\n"
            )
            _note(
                "stage_timeout" if stage else "timeout",
                attempt=attempt + 1, stage=stage,
                waited_s=round(waited, 1), stages_completed=done,
                partial=partial, stderr_tail=(err or "")[-300:],
            )
            continue
        if proc.returncode == 0 and out.strip():
            try:
                return json.loads(out.strip().splitlines()[-1])
            except json.JSONDecodeError as jerr:
                sys.stderr.write(
                    f"bench: phase {phase!r} bad JSON ({jerr})\n"
                )
                _note(
                    "bad_json", attempt=attempt + 1, error=str(jerr),
                    stages_completed=done, partial=partial,
                )
        else:
            sys.stderr.write(
                f"bench: phase {phase!r} rc={proc.returncode} "
                f"(attempt {attempt + 1}); stderr tail: "
                f"{(err or '')[-500:]}\n"
            )
            _note(
                "rc", attempt=attempt + 1, rc=proc.returncode,
                stage=(inflight[0] if inflight else None),
                stages_completed=done, partial=partial,
                stderr_tail=(err or "")[-300:],
            )
    return None


def _hung_stage(failures: "list[dict] | None") -> "str | None":
    """The most recent named hung/in-flight stage across attempt
    breadcrumbs — what accel_timeout_phase should say instead of the
    undiagnostic 'run'."""
    for f in reversed(failures or []):
        if f.get("stage"):
            return f["stage"]
    return None


def _best_partial(failures: "list[dict] | None") -> "dict | None":
    """The richest partial summary any failed attempt flushed (most
    completed stages wins — later attempts tie-break by recency)."""
    best, best_n = None, -1
    for f in failures or []:
        p = f.get("partial")
        if p and len(p.get("run_stages", {})) >= best_n:
            best, best_n = p, len(p.get("run_stages", {}))
    return dict(best) if best else None


def _strip_partials(failures: "list[dict]") -> "list[dict]":
    """Attempt records for the shipped summary: the per-attempt partial
    copies stay out (the best one ships as the summary itself); the
    stage/reason/deadline evidence stays in."""
    return [
        {k: v for k, v in f.items() if k != "partial"} for f in failures
    ]


_TPU_ARTIFACT = os.path.join(_REPO_ROOT, "results", "bench_tpu", "bench_latest.json")


def _git(*args: str) -> "subprocess.CompletedProcess":
    return subprocess.run(
        ["git", "-C", _REPO_ROOT, *args], capture_output=True, text=True,
        timeout=60,
    )


def _persist_tpu_artifact(summary: dict) -> None:
    """Write a successful TPU bench to results/bench_tpu/ so the round's
    best live number survives as a falsifiable artifact even if a later
    driver-time run hits a dead tunnel (round 4's 86.5x existed only in
    prose because the driver's capture degraded to CPU). Write-only by
    default: committing repo history is a surprising side effect for a
    measurement tool (ADVICE r5), so the git commit requires an explicit
    ``BENCH_COMMIT=1`` opt-in (and ``BENCH_NO_GIT=1`` still force-disables
    it)."""
    try:
        os.makedirs(os.path.dirname(_TPU_ARTIFACT), exist_ok=True)
        head = _git("rev-parse", "HEAD").stdout.strip()
        record = dict(summary)
        record["captured_unix_time"] = round(time.time(), 1)
        record["captured_at_commit"] = head
        with open(_TPU_ARTIFACT, "w") as f:
            json.dump(record, f, indent=1)
        if not os.environ.get("BENCH_COMMIT") or os.environ.get(
            "BENCH_NO_GIT"
        ):
            return
        rel = os.path.relpath(_TPU_ARTIFACT, _REPO_ROOT)
        _git("add", rel)
        staged = _git("diff", "--cached", "--quiet", "--", rel)
        if staged.returncode != 0:  # artifact actually changed
            _git(
                "commit", "-m",
                "Bank live TPU bench artifact\n\n"
                "No-Verification-Needed: banked bench artifact only",
                "--only", "--", rel,
            )
    except Exception as err:  # noqa: BLE001 — never fail the bench over this
        sys.stderr.write(f"bench: artifact persist failed: {err!r}\n")


def _cached_tpu_summary() -> "dict | None":
    """Last committed (or banked) TPU bench, marked as cached provenance."""
    if not os.path.exists(_TPU_ARTIFACT):
        return None
    try:
        with open(_TPU_ARTIFACT) as f:
            summary = json.load(f)
    # graftlint: disable=exception-hygiene -- an unreadable/corrupt banked
    # artifact means "no cached summary"; the caller reports the miss
    except Exception:  # noqa: BLE001
        return None
    if summary.get("backend") != "tpu":
        return None
    summary["provenance"] = "cached"
    summary["provenance_note"] = (
        "live TPU unreachable at driver time (tunnel hang); this is the "
        "last banked live-TPU bench (results/bench_tpu/bench_latest.json, "
        f"captured at commit {summary.get('captured_at_commit', '?')[:12]}) "
        "rather than a silent CPU-degraded number"
    )
    return summary


def main() -> None:
    if "--phase" in sys.argv:
        phase = sys.argv[sys.argv.index("--phase") + 1]
        backend = sys.argv[sys.argv.index("--backend") + 1]
        _phase_main(phase, backend)
        return

    _reset_budget()
    if "--compile_cache" in sys.argv:
        # Persistent XLA compilation cache, applied in every phase
        # subprocess via the env (BENCH_COMPILE_CACHE is the env-only
        # spelling): reruns replay compiles from disk.
        idx = sys.argv.index("--compile_cache") + 1
        if idx >= len(sys.argv) or sys.argv[idx].startswith("--"):
            sys.stderr.write(
                "bench: --compile_cache needs a directory argument; "
                "ignoring\n"
            )
        else:
            os.environ["BENCH_COMPILE_CACHE"] = sys.argv[idx]
    backend = "cpu" if "--cpu" in sys.argv else _probe_backend()
    try_backend = os.environ.get("BENCH_TRY_BACKEND")
    if (
        try_backend and try_backend != "cpu" and backend == "cpu"
        and "--cpu" not in sys.argv
    ):
        # Force an honest accelerator ATTEMPT even when the probe already
        # degraded (e.g. this host pins JAX_PLATFORMS=cpu): the staged
        # run pins the failure to a named sub-phase — backend_init on a
        # dead tunnel or absent plugin — with per-attempt breadcrumbs,
        # instead of never having tried at all.
        backend = try_backend

    # Adaptive deadlines under a hard whole-bench budget (BENCH_BUDGET_S):
    # a contended chip can push the (compile + 3 fits + torch baseline)
    # phase past a fixed deadline — round 4 lost its official record that
    # way — so the TPU phase gets as much of the budget as fits while a
    # reserve is held back for the CPU fallback, which must ALWAYS get to
    # run: a degraded JSON line beats the harness's rc=124.
    base_timeout = float(os.environ.get("BENCH_PHASE_TIMEOUT_S", "720"))
    cpu_reserve = 240.0 if backend != "cpu" else 0.0
    main_timeout = min(base_timeout, max(60.0, _remaining_s(cpu_reserve)))
    # Per-attempt sub-deadline bookkeeping: every abandoned accelerator
    # attempt is recorded and surfaced on whatever summary ships, so a
    # degraded run is self-describing (no more silent CPU numbers).
    accel_failures: "list[dict]" = []
    # Breadcrumbs are collected for EVERY backend (ISSUE 12 satellite): a
    # CPU-backend phase timeout must ship its completed stages + hung
    # stage name too, not only abandoned accelerator attempts.
    summary = _run_phase(
        "run", backend, timeout_s=main_timeout, retries=0,
        failures=accel_failures,
    )
    if summary is None and backend != "cpu":
        # Escalate only when the budget still holds a 2x attempt PLUS the
        # CPU-fallback reserve; otherwise go straight to the fallback.
        retry_timeout = min(2 * base_timeout, _remaining_s(cpu_reserve))
        if retry_timeout >= main_timeout:
            sys.stderr.write(
                f"bench: retrying main phase with escalated deadline "
                f"({retry_timeout:.0f}s)\n"
            )
            summary = _run_phase(
                "run", backend, timeout_s=retry_timeout, retries=0,
                failures=accel_failures,
            )
    if summary is not None:
        summary["provenance"] = "live"
        if accel_failures:
            # The escalated retry succeeded, but the abandoned first
            # attempt is still part of the round's story (each record
            # carries its phase/deadline/reason) — a live summary after
            # a timeout must not erase the timeout.
            summary["accel_attempts"] = _strip_partials(accel_failures)
        if summary.get("backend") == "tpu":
            _persist_tpu_artifact(summary)
    cpu_failures: "list[dict]" = []
    if summary is None and backend != "cpu":
        # Live TPU is unreachable: prefer the last banked live-TPU artifact
        # (explicitly marked cached) over presenting a CPU number as the
        # round's TPU result (VERDICT r4 weak #1).
        summary = _cached_tpu_summary()
        if summary is not None:
            sys.stderr.write(
                "bench: live TPU unreachable; emitting banked TPU artifact "
                "with provenance=cached\n"
            )
            summary["accel_timeout_phase"] = (
                _hung_stage(accel_failures) or "run"
            )
            summary["accel_attempts"] = _strip_partials(accel_failures)
            print(json.dumps(summary))
            return
        sys.stderr.write("bench: degrading main phase to CPU\n")
        backend = "cpu"
        summary = _run_phase(
            "run", "cpu", timeout_s=max(60.0, _remaining_s(10.0)),
            retries=0, failures=cpu_failures,
        )
        if summary is not None:
            summary["provenance"] = "live-cpu-degraded"
            # The accelerator attempt(s) that forced this fallback, with
            # their sub-deadlines and reasons: the headline below is a
            # CPU number BECAUSE of these. accel_timeout_phase names the
            # hung STAGE when the staged watcher identified one.
            summary["accel_timeout_phase"] = (
                _hung_stage(accel_failures) or "run"
            )
            summary["accel_attempts"] = _strip_partials(accel_failures)
            # No banked live-TPU bench exists to serve as the cached
            # fallback; point the record at the strongest COMMITTED TPU
            # evidence so a degraded capture is self-describing instead
            # of silently standing in for the chip's numbers.
            probe_path = os.path.join(
                _REPO_ROOT, "results", "step_time_probe.json"
            )
            try:
                with open(probe_path) as f:
                    probe = json.load(f)
                if probe.get("backend") == "tpu":
                    base = probe["variants"]["baseline"]
                    summary["strongest_committed_tpu_evidence"] = {
                        "artifact": "results/step_time_probe.json",
                        "backend": "tpu",
                        "docs_per_s": base.get("docs_per_s"),
                        "program_ms_per_step": base.get(
                            "program_ms_per_step"
                        ),
                        "note": (
                            "same federated bench regime, measured on "
                            "live TPU in a prior round; see also "
                            "results/profile_trace/README.md"
                        ),
                    }
            except (OSError, ValueError, KeyError):
                pass
    if summary is None:
        # Every live attempt failed — but the staged partial flush means
        # the stages that DID complete can still ship (BENCH_r05's rc=124
        # lost everything; this is the fix's last line of defense).
        partial = _best_partial(cpu_failures) or _best_partial(
            accel_failures
        )
        if partial is not None:
            summary = partial
            summary["provenance"] = "partial"
            summary["error"] = (
                "run phase killed at a stage sub-deadline; completed "
                "stages shipped, accel_timeout_phase names the hung one"
            )
        else:
            summary = {
                "metric": "federated_prodlda_5client_throughput",
                "value": 0.0,
                "unit": "docs/s",
                "vs_baseline": 0.0,
                "backend": backend,
                "error": (
                    "all bench phase attempts failed or hung (TPU tunnel)"
                ),
            }
        hung = _hung_stage(cpu_failures) or _hung_stage(accel_failures)
        if accel_failures or cpu_failures:
            summary["accel_timeout_phase"] = hung or "run"
            summary["accel_attempts"] = _strip_partials(
                accel_failures + cpu_failures
            )

    if "error" not in summary:
        # The fused soak is a bonus artifact — it only runs when the main
        # phase left real budget behind (a cached/degraded main result
        # usually spent it all hanging on the tunnel).
        fused_timeout = min(
            float(os.environ.get("BENCH_PHASE_TIMEOUT_S", "720")),
            _remaining_s(15.0),
        )
        if fused_timeout < 60.0:
            summary["fused_largev_error"] = (
                f"skipped: {_remaining_s():.0f}s of the "
                f"{_BUDGET_S:.0f}s bench budget (BENCH_BUDGET_S) left; "
                "see results/fused_kernel_soak.json for the committed soak"
            )
        else:
            fused = _run_phase(
                "fused", summary.get("backend", backend),
                timeout_s=fused_timeout,
            )
            if fused is not None:
                summary["fused_largev"] = fused
                if summary.get("backend") == "tpu":
                    _persist_tpu_artifact(summary)
            else:
                summary["fused_largev_error"] = (
                    "phase timed out or failed (TPU tunnel hang); "
                    "see results/fused_kernel_soak.json for the committed "
                    "soak"
                )

    # Shared artifact-shape contract (scripts/bench_schema.py): a bench
    # must ALWAYS emit its one JSON line, so violations ship in-band as
    # schema_errors instead of crashing the emitter.
    try:
        sys.path.insert(0, os.path.join(_REPO_ROOT, "scripts"))
        import bench_schema

        problems = bench_schema.validate(summary, "bench")
        if problems:
            sys.stderr.write(
                "bench: schema violations: " + "; ".join(problems) + "\n"
            )
            summary["schema_errors"] = problems
    except ImportError as err:  # pragma: no cover - repo layout drift
        sys.stderr.write(f"bench: schema validator unavailable: {err!r}\n")

    print(json.dumps(summary))


if __name__ == "__main__":
    main()
