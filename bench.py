"""Benchmark: 5-client federated ProdLDA throughput on one TPU chip.

Regime: the reference's federated defaults — 5 clients, K=50 topics,
V=5000 synthetic vocabulary, hidden (50,50), batch 64, Adam(lr 2e-3,
betas=(0.99, 0.99)) — i.e. BASELINE.md's simulation/federation config.

Baseline: the reference's hot loop has a hard orchestration floor of
>= 3 s sleep per client per global step plus 2N fresh-channel gRPC
round-trips (``src/federation/server.py:417-420,449,472,515``), so with 5
clients one global step (5 x 64 = 320 documents) takes >= 15 s:
**<= 21.33 docs/s** before any model math. This framework runs the whole
federation as one compiled SPMD program, so its throughput is model-math
bound instead.

Robustness: the TPU chip is single-tenant and reached through a tunnel, so
backend init can fail transiently. The backend is probed in a *subprocess*
(a failed in-process TPU init would poison this process's jax) with retries
and backoff; if the TPU never comes up the bench still produces a number on
CPU, clearly labeled ``"backend": "cpu"`` — a degraded result beats rc=1.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...} with
phase timings (compile vs steady-state) and per-step wall-clock as extra
keys.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

_PROBE_RETRIES = 3
_PROBE_BACKOFF_S = 20.0
_PROBE_TIMEOUT_S = 300.0


def _probe_backend() -> str:
    """Return the usable jax backend ('tpu'/'cpu'/...), probing in a
    subprocess with retries so a held chip or tunnel flake degrades to CPU
    instead of killing the bench."""
    if os.environ.get("JAX_PLATFORMS"):
        return os.environ["JAX_PLATFORMS"].split(",")[0]
    code = "import jax; print(jax.default_backend())"
    for attempt in range(_PROBE_RETRIES):
        try:
            out = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True, timeout=_PROBE_TIMEOUT_S,
            )
            if out.returncode == 0 and out.stdout.strip():
                return out.stdout.strip().splitlines()[-1]
            sys.stderr.write(
                f"bench: backend probe attempt {attempt + 1} failed "
                f"(rc={out.returncode})\n"
            )
        except subprocess.TimeoutExpired:
            sys.stderr.write(
                f"bench: backend probe attempt {attempt + 1} timed out "
                f"after {_PROBE_TIMEOUT_S:.0f}s\n"
            )
        if attempt < _PROBE_RETRIES - 1:
            time.sleep(_PROBE_BACKOFF_S * (attempt + 1))
    return "cpu"


def run(backend: str) -> dict:
    import jax

    if backend in ("cpu", "unavailable"):
        # Runtime env-var edits are invisible here: the TPU-tunnel
        # sitecustomize imports jax config at interpreter start, snapshotting
        # JAX_PLATFORMS. config.update is the override that actually works.
        jax.config.update("jax_platforms", "cpu")
        backend = "cpu"

    from gfedntm_tpu.data.datasets import BowDataset
    from gfedntm_tpu.data.synthetic import generate_synthetic_corpus
    from gfedntm_tpu.federated.trainer import FederatedTrainer
    from gfedntm_tpu.models.avitm import AVITM
    from gfedntm_tpu.utils.observability import MetricsLogger, phase_timer

    on_accel = backend not in ("cpu", "unavailable")
    n_clients, vocab, k, batch = 5, 5000, 50, 64
    # CPU fallback shrinks the corpus/epochs so a degraded environment still
    # reports a (labeled) number in minutes, not hours.
    docs_per_node = 2000 if on_accel else 640
    epochs = 4 if on_accel else 2

    metrics = MetricsLogger(os.environ.get("BENCH_METRICS_PATH"))

    with phase_timer(metrics, "synthetic_corpus"):
        corpus = generate_synthetic_corpus(
            vocab_size=vocab, n_topics=k, n_docs=docs_per_node,
            nwords=(150, 250), n_nodes=n_clients, frozen_topics=5, seed=0,
            materialize_docs=False,
        )
        idx2token = {i: f"wd{i}" for i in range(vocab)}
        datasets = [
            BowDataset(X=node.bow, idx2token=idx2token)
            for node in corpus.nodes
        ]

    template = AVITM(
        input_size=vocab, n_components=k, hidden_sizes=(50, 50),
        batch_size=batch, num_epochs=epochs, lr=2e-3, momentum=0.99,
        seed=0,
    )
    trainer = FederatedTrainer(template, n_clients=n_clients)

    # Warmup fit: compiles the whole-run program (compile + first run).
    t0 = time.perf_counter()
    with phase_timer(metrics, "compile_and_first_run"):
        warm = trainer.fit(datasets)
        jax.block_until_ready(warm.client_params)
    compile_s = time.perf_counter() - t0
    assert np.isfinite(warm.losses).all()

    # Timed fit: same shapes -> jit cache hit; measures steady-state.
    t0 = time.perf_counter()
    with phase_timer(metrics, "steady_state_fit"):
        result = trainer.fit(datasets)
        jax.block_until_ready(result.client_params)
    steady_s = time.perf_counter() - t0

    global_steps = int(result.losses.shape[0])
    docs_processed = float(global_steps) * n_clients * batch
    docs_per_sec = docs_processed / steady_s
    step_ms = steady_s / global_steps * 1e3

    # Reference orchestration floor: >=3 s sleep x 5 clients per global step
    # (server.py:417-420,472) -> <= 320 docs / 15 s.
    baseline_docs_per_sec = n_clients * batch / (3.0 * n_clients)

    metrics.log(
        "bench_summary", backend=backend, docs_per_sec=docs_per_sec,
        steps=global_steps, step_ms=step_ms, compile_s=compile_s,
        steady_s=steady_s,
    )
    metrics.close()

    return {
        "metric": "federated_prodlda_5client_throughput",
        "value": round(docs_per_sec, 1),
        "unit": "docs/s",
        "vs_baseline": round(docs_per_sec / baseline_docs_per_sec, 1),
        "backend": backend,
        "global_steps": global_steps,
        "step_ms": round(step_ms, 2),
        "compile_and_first_run_s": round(compile_s, 1),
        "steady_state_s": round(steady_s, 1),
        "regime": {
            "n_clients": n_clients, "vocab": vocab, "k": k, "batch": batch,
            "docs_per_node": docs_per_node, "epochs": epochs,
        },
    }


def bench_fused_largev(backend: str, v_list=(16384, 100_000)) -> dict:
    """Soak the compiled Pallas fused decode+loss kernel at large V: on-device
    parity vs the unfused XLA oracle (values + grads) and fwd+bwd step time
    for both, per V. This is the regime the kernel exists for (the reference
    preprocesses to V up to 100k, ``text_preproc.py:49``); the main bench's
    V=5000 federation sits below the auto-enable threshold."""
    import jax
    import jax.numpy as jnp

    from gfedntm_tpu.ops.fused_decoder import (
        prodlda_recon_loss,
        prodlda_recon_loss_reference,
    )

    interpret = backend == "cpu"  # CPU fallback: interpret mode (tiny V only)
    out = {}
    B, K = 64, 50
    for V in v_list if not interpret else (2048,):
        rng = np.random.default_rng(0)
        theta = jnp.asarray(
            rng.dirichlet(np.ones(K), size=B).astype(np.float32)
        )
        beta = jnp.asarray(rng.normal(size=(K, V)).astype(np.float32))
        x = jnp.asarray(
            rng.integers(0, 3, size=(B, V)).astype(np.float32)
        )
        mask = jnp.ones((B,), jnp.float32)
        rm, rv = jnp.zeros((V,)), jnp.ones((V,))

        def loss_fused(theta, beta):
            rl, _, _ = prodlda_recon_loss(
                theta, beta, x, rm, rv, mask, True, interpret=interpret
            )
            return jnp.sum(rl * mask)

        def loss_ref(theta, beta):
            rl, _, _ = prodlda_recon_loss_reference(
                theta, beta, x, rm, rv, mask, True
            )
            return jnp.sum(rl * mask)

        f_fused = jax.jit(jax.value_and_grad(loss_fused, argnums=(0, 1)))
        f_ref = jax.jit(jax.value_and_grad(loss_ref, argnums=(0, 1)))

        lf, gf = f_fused(theta, beta)
        lr, gr = f_ref(theta, beta)
        jax.block_until_ready((lf, gf, lr, gr))
        loss_rel = abs(float(lf) - float(lr)) / max(abs(float(lr)), 1e-9)
        grad_rel = max(
            float(jnp.max(jnp.abs(a - b)))
            / max(float(jnp.max(jnp.abs(b))), 1e-9)
            for a, b in zip(gf, gr)
        )

        def timeit(fn, n=10):
            fn(theta, beta)  # warm
            t0 = time.perf_counter()
            for _ in range(n):
                res = fn(theta, beta)
            jax.block_until_ready(res)
            return (time.perf_counter() - t0) / n * 1e3

        out[f"V{V}"] = {
            "fused_ms": round(timeit(f_fused), 3),
            "unfused_ms": round(timeit(f_ref), 3),
            "loss_rel_err": float(f"{loss_rel:.2e}"),
            "grad_rel_err": float(f"{grad_rel:.2e}"),
            "parity": bool(loss_rel < 1e-4 and grad_rel < 1e-3),
        }
    return out


def main() -> None:
    forced_cpu = "--cpu" in sys.argv
    backend = "cpu" if forced_cpu else _probe_backend()

    try:
        summary = run(backend)
        try:
            summary["fused_largev"] = bench_fused_largev(
                summary.get("backend", backend)
            )
        except Exception as exc:  # noqa: BLE001 - variant must not kill bench
            summary["fused_largev_error"] = repr(exc)
    except Exception as exc:  # noqa: BLE001 - any accel failure -> CPU rerun
        if backend == "cpu":
            raise
        sys.stderr.write(
            f"bench: run on backend={backend!r} failed ({exc!r}); "
            "re-running on CPU\n"
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--cpu"], env=env
        )
        sys.exit(out.returncode)

    print(json.dumps(summary))


if __name__ == "__main__":
    main()
