"""North-star time-to-quality: wall-clock to a TSS target, this framework's
federated TPU path vs the reference's own torch AVITM on the same corpus.

BASELINE.json's metric is "5-client federated ProdLDA reaches PyTorch NPMI
in <= 1/4 the wall-clock". Its corpus (20Newsgroups) needs network egress
this environment lacks (no offline snapshot exists on the machine — checked
sklearn/HF caches), so per VERDICT r3 task 3 the committed substitute is
the reference's own published evaluation regime: the V=5000 / K=50 / 5-node
synthetic corpus (`experiments/dss_tss/config/eta_variable/config.json`,
scaled to 2000 train docs/node so the torch arm finishes on one CPU core),
with quality measured as ground-truth TSS — the reference's de-facto
correctness metric — under the CORRECT word mapping and a single softmax
(the envelope's double-softmax + off-by-one replication exists only for
comparability with the reference's published pickles; a quality target
should not inherit scoring bugs).

Arms (same generated corpus, same TSS scorer):

- **torch**: the unmodified reference AVITM (imported from /root/reference)
  trained centrally on the union corpus — its compute-only best case (its
  real federated path adds >=3 s sleeps per round on top of exactly this
  compute, `src/federation/server.py:417-420`). Betas are snapshotted per
  epoch with their wall-clock timestamps by wrapping _train_epoch.
- **gfedntm_tpu**: the flagship 5-client federated SPMD trainer, betas
  snapshotted per global epoch via fit(segment_callback=...) between
  compiled segments. Wall-clock for this arm is the in-fit time (staging +
  compile excluded the same way the torch arm's dataset prep is excluded;
  compile time is reported separately in the artifact).

Both arms then report time_to(T) for a ladder of absolute TSS targets
derived from the joint plateau, and the headline ratio
torch_time / tpu_time at the 95%-of-joint-plateau target.

Usage: python experiments_scripts/time_to_quality.py [out_json]
Writes results/time_to_quality/metrics.json (default).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time

REFERENCE_ROOT = "/root/reference"
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N_NODES, VOCAB, K, DOCS_PER_NODE = 5, 5000, 50, 2000
ETA, ALPHA, FROZEN = 0.01, 0.1, 5
# TTQ_EPOCHS shrinks the run for harness smoke tests ONLY — artifacts
# committed as evidence use the default 100.
EPOCHS = int(os.environ.get("TTQ_EPOCHS", "100"))
SEED = 0


def softmax_rows(a):
    import numpy as np

    e = np.exp(a - a.max(axis=1, keepdims=True))
    return e / e.sum(axis=1, keepdims=True)


def main(out_path: str | None = None) -> dict:
    logging.basicConfig(level=logging.INFO, force=True)
    sys.path.insert(0, REPO_ROOT)
    sys.path.insert(0, REFERENCE_ROOT)
    import numpy as np

    if not hasattr(np, "Inf"):
        np.Inf = np.inf

    import jax

    # FORCE_CPU must be honoured BEFORE any backend query:
    # jax.default_backend() initializes the platform, and on a dead TPU
    # tunnel that call blocks forever in the plugin's re-dial loop.
    if os.environ.get("FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")
    backend = jax.default_backend()

    from gfedntm_tpu.data.synthetic import generate_synthetic_corpus
    from gfedntm_tpu.eval.metrics import (
        convert_topic_word_to_init_size,
        topic_similarity_score,
    )

    t0 = time.perf_counter()
    corpus = generate_synthetic_corpus(
        vocab_size=VOCAB, n_topics=K, beta=ETA, alpha=ALPHA,
        n_docs=DOCS_PER_NODE, nwords=(150, 250), n_nodes=N_NODES,
        frozen_topics=FROZEN, seed=SEED,
    )
    topic_vectors = corpus.topic_vectors
    gen_s = time.perf_counter() - t0

    def tss_of(beta_logits_or_dist, id2token, already_dist=False):
        b = (
            np.asarray(beta_logits_or_dist)
            if already_dist
            else softmax_rows(np.asarray(beta_logits_or_dist))
        )
        full = convert_topic_word_to_init_size(VOCAB, b, id2token)
        return topic_similarity_score(full, topic_vectors)

    # ---- torch arm -------------------------------------------------------
    import torch

    from torch_baseline import make_reference_avitm

    from src.models.base.pytorchavitm.avitm_network.avitm import (
        AVITM as TorchAVITM,
    )
    from src.models.base.pytorchavitm.utils.data_preparation import (
        prepare_dataset as torch_prepare_dataset,
    )

    torch.manual_seed(SEED)
    union_docs = [
        d.split() for node in corpus.nodes for d in node.documents
    ]
    train_data, val_data, input_size, t_id2token, _docs, _cv = (
        torch_prepare_dataset(union_docs)
    )
    model = make_reference_avitm(
        input_size=input_size, n_components=K, num_epochs=EPOCHS,
    )
    torch_snaps: list[tuple[float, np.ndarray]] = []
    orig_train_epoch = TorchAVITM._train_epoch

    def snap_train_epoch(self, loader):
        out = orig_train_epoch(self, loader)
        torch_snaps.append(
            (time.perf_counter(),
             self.model.beta.detach().cpu().numpy().copy())
        )
        return out

    TorchAVITM._train_epoch = snap_train_epoch
    try:
        t_start = time.perf_counter()
        # No validation set -> no early stopping: the curve must extend to
        # the plateau so targets near it are reachable by both arms.
        model.fit(train_data, None)
    finally:
        TorchAVITM._train_epoch = orig_train_epoch
    torch_curve = [
        {"wall_s": round(ts - t_start, 2),
         "tss": round(tss_of(beta, t_id2token), 4)}
        for ts, beta in torch_snaps
    ]
    print(f"torch arm: {len(torch_curve)} epochs, "
          f"final TSS {torch_curve[-1]['tss']}", flush=True)

    # ---- torch FEDERATED arm (the north-star's own algorithm) -----------
    # The reference's federated semantics without its orchestration: per
    # global step each client runs one minibatch fwd/bwd/Adam step
    # (federated_avitm.py:51-83, driven through the reference AVITM's own
    # model/_loss/optimizer), then the full state dict is averaged across
    # clients and written back (server.py:476-487 + dft_params.cf:50 names
    # the entire state dict). This is the reference's COMPUTE floor — its
    # shipped stack adds >=3 s sleep x N clients + 2N fresh-channel gRPC
    # round-trips per step on top (BASELINE.md).
    from src.models.base.pytorchavitm.datasets.bow_dataset import BOWDataset
    from torch.utils.data import DataLoader

    torch.manual_seed(SEED + 1)
    idx2tok_arr = np.array([f"wd{i}" for i in range(VOCAB)])
    fed_models, fed_iters, fed_loaders = [], [], []
    for node in corpus.nodes:
        m = make_reference_avitm(
            input_size=VOCAB, n_components=K, num_epochs=EPOCHS,
            logger_name="torch-fed",
        )
        loader = DataLoader(
            BOWDataset(node.bow.astype(np.float32), idx2tok_arr),
            batch_size=64, shuffle=True, num_workers=0,
        )
        fed_models.append(m)
        fed_loaders.append(loader)
        fed_iters.append(iter(loader))
    steps_per_epoch_t = -(-DOCS_PER_NODE // 64)
    total_fed_steps = EPOCHS * steps_per_epoch_t
    torch_fed_snaps: list[tuple[float, np.ndarray]] = []
    tf_start = time.perf_counter()
    for step in range(total_fed_steps):
        for c, m in enumerate(fed_models):
            try:
                batch = next(fed_iters[c])
            except StopIteration:
                fed_iters[c] = iter(fed_loaders[c])
                batch = next(fed_iters[c])
            X = batch["X"].float()
            m.model.zero_grad()
            pm, pv, qm, qv, qlv, wd = m.model(X)
            loss = m._loss(X, wd, pm, pv, qm, qv, qlv)
            loss.backward()
            m.optimizer.step()
        sds = [m.model.state_dict() for m in fed_models]
        avg = {
            k: (
                torch.stack([sd[k].float() for sd in sds]).mean(0)
                if torch.is_floating_point(sds[0][k]) else sds[0][k]
            )
            for k in sds[0]
        }
        for m in fed_models:
            m.model.load_state_dict(avg)
        if (step + 1) % steps_per_epoch_t == 0:
            torch_fed_snaps.append(
                (time.perf_counter(),
                 fed_models[0].model.beta.detach().cpu().numpy().copy())
            )
    t_id2tok_full = {i: f"wd{i}" for i in range(VOCAB)}
    torch_fed_curve = [
        {"wall_s": round(ts - tf_start, 2),
         "tss": round(tss_of(beta, t_id2tok_full), 4)}
        for ts, beta in torch_fed_snaps
    ]
    print(f"torch federated arm: {len(torch_fed_curve)} epochs, "
          f"final TSS {torch_fed_curve[-1]['tss']}", flush=True)

    # ---- gfedntm_tpu federated arm --------------------------------------
    from gfedntm_tpu.data.datasets import BowDataset
    from gfedntm_tpu.federated.trainer import FederatedTrainer
    from gfedntm_tpu.models.avitm import AVITM

    idx2token = {i: f"wd{i}" for i in range(VOCAB)}
    datasets = [
        BowDataset(X=node.bow, idx2token=idx2token) for node in corpus.nodes
    ]
    template = AVITM(
        input_size=VOCAB, n_components=K, hidden_sizes=(100, 100),
        batch_size=64, num_epochs=EPOCHS, lr=2e-3, momentum=0.99, seed=SEED,
    )
    trainer = FederatedTrainer(template, n_clients=N_NODES)
    steps_per_epoch = max(1, -(-DOCS_PER_NODE // template.batch_size))

    jax_snaps: list[tuple[float, np.ndarray]] = []

    def snap_segment(step, params, batch_stats):
        jax_snaps.append(
            (time.perf_counter(), np.asarray(params["beta"][0]).copy())
        )

    # Warmup fit (1 segment): stages data + compiles both segment shapes.
    warm_template_epochs = template.num_epochs
    template.num_epochs = 1
    t0 = time.perf_counter()
    trainer.fit(datasets)
    compile_s = time.perf_counter() - t0
    template.num_epochs = warm_template_epochs

    j_start = time.perf_counter()
    trainer.fit(
        datasets, checkpoint_every=steps_per_epoch,
        segment_callback=snap_segment,
    )
    jax_curve = [
        {"wall_s": round(ts - j_start, 2),
         "tss": round(tss_of(beta, idx2token), 4)}
        for ts, beta in jax_snaps
    ]
    print(f"jax arm ({backend}): {len(jax_curve)} epochs, "
          f"final TSS {jax_curve[-1]['tss']}", flush=True)

    # ---- local-steps arms (VERDICT r4 #4: the opt-in FedAvg-proper fix) -
    # Same corpus/model/optimizer, but clients run E local minibatches
    # between exchanges instead of the reference's per-minibatch
    # averaging. Two periods: one local epoch and five (the realtext
    # artifact shows diversity recovery grows with the period). Segment
    # boundaries are epoch boundaries; with E a multiple of
    # steps_per_epoch every snapshot is a post-exchange global beta or a
    # client-0 local beta between exchanges — the curve is client 0's
    # view either way, like the torch federated arm's.
    local_arms: dict[str, dict] = {}
    for arm_key, local_E in (
        ("E_1epoch", steps_per_epoch),
        ("E_5epoch", 5 * steps_per_epoch),
    ):
        template_E = AVITM(
            input_size=VOCAB, n_components=K, hidden_sizes=(100, 100),
            batch_size=64, num_epochs=EPOCHS, lr=2e-3, momentum=0.99,
            seed=SEED,
        )
        trainer_E = FederatedTrainer(
            template_E, n_clients=N_NODES, local_steps=local_E
        )
        e_snaps: list[tuple[float, np.ndarray]] = []

        def snap_segment_e(step, params, batch_stats, _snaps=e_snaps):
            _snaps.append(
                (time.perf_counter(), np.asarray(params["beta"][0]).copy())
            )

        template_E.num_epochs = 1
        trainer_E.fit(datasets)  # warmup: stage + compile (untimed)
        template_E.num_epochs = EPOCHS
        e_start = time.perf_counter()
        trainer_E.fit(
            datasets, checkpoint_every=steps_per_epoch,
            segment_callback=snap_segment_e,
        )
        # Keep only the curve + final beta: both arms' full per-epoch
        # snapshot lists would hold ~200 MB of betas to end of run.
        local_arms[arm_key] = {
            "E": local_E,
            "final_beta": e_snaps[-1][1],
            "curve": [
                {"wall_s": round(ts - e_start, 2),
                 "tss": round(tss_of(beta, idx2token), 4)}
                for ts, beta in e_snaps
            ],
        }
        e_snaps.clear()
        print(f"local-steps arm {arm_key} (E={local_E}): "
              f"final TSS {local_arms[arm_key]['curve'][-1]['tss']}",
              flush=True)

    # ---- final topic quality, all three arms ----------------------------
    # Answers whether the federated arm's lower topic diversity (seen in
    # parity_vs_torch) is an implementation artifact or a property of the
    # reference's per-minibatch FedAvg itself: the torch-federated arm
    # runs the reference's own model/loss/optimizer under the same
    # averaging, so matching diversity/NPMI here pins it on the algorithm.
    from gfedntm_tpu.eval.metrics import npmi_coherence, topic_diversity

    def topics_of(beta, id2tok):
        top = np.argsort(-np.asarray(beta), axis=1)[:, :10]
        return [[id2tok[int(i)] for i in row] for row in top]

    final_topic_quality = {}
    quality_arms = {
        "torch_centralized": (torch_snaps[-1][1], t_id2token),
        "torch_federated": (torch_fed_snaps[-1][1], t_id2tok_full),
        "gfedntm_tpu_federated": (jax_snaps[-1][1], idx2token),
    }
    for arm_key, arm in local_arms.items():
        quality_arms[f"gfedntm_tpu_local_steps_{arm_key}"] = (
            arm["final_beta"], idx2token,
        )
    for arm, (beta, idt) in quality_arms.items():
        tops = topics_of(beta, idt)
        final_topic_quality[arm] = {
            "topic_diversity_top10": round(topic_diversity(tops, 10), 4),
            "npmi": round(npmi_coherence(tops, union_docs), 4),
        }
    print("final topic quality:", json.dumps(final_topic_quality),
          flush=True)

    # ---- time-to-target ladder ------------------------------------------
    # The north star compares like with like: the reference's federated
    # algorithm (its compute floor) vs this framework's federated SPMD run
    # — same FedAvg semantics, so the arms share a quality plateau and
    # time-to-target is well-posed. The centralized torch curve stays in
    # the artifact as context (centralized reaches a higher plateau than
    # any FedAvg run on this non-IID split; that gap is the algorithm's,
    # not the framework's).
    plateau = min(torch_fed_curve[-1]["tss"], jax_curve[-1]["tss"])
    baseline_tss = float(
        topic_similarity_score(
            np.random.default_rng(SEED + 9).dirichlet(
                np.full(VOCAB, ETA), K
            ),
            topic_vectors,
        )
    )

    def time_to(curve, target):
        for p in curve:
            if p["tss"] >= target:
                return p["wall_s"]
        return None

    ladder = {}
    for frac in (0.80, 0.90, 0.95, 0.99):
        target = baseline_tss + frac * (plateau - baseline_tss)
        ladder[f"{int(frac * 100)}pct"] = {
            "target_tss": round(target, 4),
            "torch_federated_s": time_to(torch_fed_curve, target),
            "torch_centralized_s": time_to(torch_curve, target),
            "gfedntm_tpu_s": time_to(jax_curve, target),
            **{
                f"gfedntm_tpu_local_steps_{k}_s": time_to(v["curve"], target)
                for k, v in local_arms.items()
            },
        }
    head = ladder["95pct"]
    speedup = (
        round(head["torch_federated_s"] / head["gfedntm_tpu_s"], 2)
        if head["torch_federated_s"] and head["gfedntm_tpu_s"] else None
    )
    # The reference's SHIPPED federated stack pays >=3 s x N clients of
    # sleeps + 2N fresh-channel gRPC round-trips per global step before any
    # math (server.py:417-420,449,472,515) — the orchestration-inclusive
    # wall-clock its users actually experience.
    fed_95_steps = (
        None if head["torch_federated_s"] is None else
        int(round(head["torch_federated_s"] / max(
            (torch_fed_curve[-1]["wall_s"]) / total_fed_steps, 1e-9)))
    )
    shipped_floor_s = (
        None if fed_95_steps is None else round(fed_95_steps * 3.0 * N_NODES)
    )

    # ---- cold-start honesty (VERDICT r4 #7) -----------------------------
    # The headline excludes this framework's one-time compile+stage (the
    # torch arm's dataset prep is likewise excluded). Report the
    # amortization-free comparison too: a user running ONE fit from a cold
    # process pays compile_s up front. With the persistent XLA compile
    # cache warm (the supervisor sets JAX_COMPILATION_CACHE_DIR), a cold
    # PROCESS replays compiles from disk — measured below in a fresh
    # subprocess so the number is a real end-to-end cold start, not this
    # process's warm-jit state.
    cold_95 = (
        None if head["gfedntm_tpu_s"] is None
        else round(compile_s + head["gfedntm_tpu_s"], 2)
    )
    speedup_cold = (
        round(head["torch_federated_s"] / cold_95, 2)
        if head["torch_federated_s"] and cold_95 else None
    )
    # The chip is single-tenant and THIS process holds it, so a subprocess
    # probe on TPU would hang in backend init (round-5 review finding). On
    # TPU the measurement runs as the separate --coldproc-only invocation
    # (supervisor job "ttqcold", chip free, this run's compile cache warm)
    # which patches the field below into the artifact in place.
    if backend == "cpu" and not os.environ.get("TTQ_SKIP_COLDPROC"):
        # No chip contention on CPU: measure in a fresh subprocess now.
        import subprocess
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--coldproc-measure"],
                capture_output=True, text=True, timeout=1200,
                env=dict(os.environ),
            )
            line = next(
                ln for ln in proc.stdout.splitlines()
                if ln.startswith("COLDPROC ")
            )
            cold_process = json.loads(line[len("COLDPROC "):])
        except Exception as err:  # noqa: BLE001 — context metric only
            cold_process = {"error": repr(err)[:300]}
    else:
        cold_process = {
            "skipped": (
                "single-tenant chip held by this process; measured by the "
                "separate --coldproc-only run (supervisor job ttqcold)"
            )
        }

    out = {
        "metric": "wall_clock_to_tss_target",
        "headline_speedup_at_95pct": speedup,
        "headline_definition": (
            "torch_federated_s / gfedntm_tpu_s at the 95%-of-joint-"
            "federated-plateau TSS target (both arms run the reference's "
            "FedAvg algorithm on the same corpus)"
        ),
        "north_star_target": (
            ">= 4.0 (BASELINE.json: quality in <= 1/4 the wall-clock)"
        ),
        "reference_shipped_stack_floor_s_at_95pct": shipped_floor_s,
        "backend": backend,
        "regime": {
            "n_nodes": N_NODES, "vocab": VOCAB, "k": K,
            "docs_per_node": DOCS_PER_NODE, "eta": ETA, "alpha": ALPHA,
            "frozen_topics": FROZEN, "epochs": EPOCHS, "seed": SEED,
            "substitute_for": "20Newsgroups (no offline snapshot; no "
                              "egress) — reference eval regime instead",
            "corpus_gen_s": round(gen_s, 1),
        },
        "quality_metric": (
            "TSS vs ground-truth topic_vectors, single softmax, correct "
            "word mapping (max=50; random-Dirichlet floor ~3.6)"
        ),
        "baseline_tss_random": round(baseline_tss, 4),
        "joint_plateau_tss": round(plateau, 4),
        "final_topic_quality": final_topic_quality,
        "targets": ladder,
        "torch_note": (
            "centralized fit = the reference's compute-only best case; its "
            "shipped federated path adds >=3 s sleep x N clients per "
            "global step on top (server.py:417-420,472)"
        ),
        "gfedntm_compile_and_stage_s": round(compile_s, 1),
        # Measures cache deserialization, not compilation, when the
        # supervisor's persistent XLA cache is active:
        "compilation_cache_dir": os.environ.get("JAX_COMPILATION_CACHE_DIR"),
        "cold_start": {
            "gfedntm_cold_s_at_95pct": cold_95,
            "headline_speedup_at_95pct_cold": speedup_cold,
            "note": (
                "cold = compile+stage paid up front (amortization-free "
                "single-fit user); the headline above amortizes it, as the "
                "torch arm's dataset prep is likewise excluded"
            ),
            "cold_process_warm_cache": cold_process,
        },
        "local_steps_fix": {
            "definition": (
                "opt-in FederatedTrainer(local_steps=E): clients run E "
                "local minibatches between FedAvg exchanges; parity "
                "default E=1 unchanged. Diversity recovery grows with "
                "the period (see final_topic_quality and the realtext "
                "artifact)"
            ),
            "arms": {
                k: {"E": v["E"], "final_tss": v["curve"][-1]["tss"]}
                for k, v in local_arms.items()
            },
        },
        "torch_federated_curve": torch_fed_curve,
        "torch_curve": torch_curve,
        "gfedntm_curve": jax_curve,
        "gfedntm_local_steps_curves": {
            k: v["curve"] for k, v in local_arms.items()
        },
    }
    out_path = out_path or os.path.join(
        REPO_ROOT, "results", "time_to_quality", "metrics.json"
    )
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w", encoding="utf8") as f:
        json.dump(out, f, indent=2)
    print(json.dumps({k: v for k, v in out.items()
                      if not k.endswith("_curve")}, indent=2))
    return out


def measure_cold_process() -> dict:
    """Time a COLD process's corpus-gen + (stage + compile + 1-epoch fit)
    at the ttq regime. Only meaningful when this process is fresh — called
    via --coldproc-measure / --coldproc-only, never from a warm parent.
    With JAX_COMPILATION_CACHE_DIR warm (e.g. right after the main ttq
    run) the compile component is cache deserialization — the number the
    VERDICT r4 #7 asks for."""
    import jax

    if os.environ.get("FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")
    import numpy as np  # noqa: F401 — keep import cost inside the timing

    from gfedntm_tpu.data.datasets import BowDataset
    from gfedntm_tpu.data.synthetic import generate_synthetic_corpus
    from gfedntm_tpu.federated.trainer import FederatedTrainer
    from gfedntm_tpu.models.avitm import AVITM

    t0 = time.perf_counter()
    corpus = generate_synthetic_corpus(
        vocab_size=VOCAB, n_topics=K, beta=ETA, alpha=ALPHA,
        n_docs=DOCS_PER_NODE, nwords=(150, 250), n_nodes=N_NODES,
        frozen_topics=FROZEN, seed=SEED,
    )
    gen_s = time.perf_counter() - t0
    i2t = {i: f"wd{i}" for i in range(VOCAB)}
    datasets = [
        BowDataset(X=n.bow, idx2token=i2t) for n in corpus.nodes
    ]
    template = AVITM(
        input_size=VOCAB, n_components=K, hidden_sizes=(100, 100),
        batch_size=64, num_epochs=1, lr=2e-3, momentum=0.99, seed=SEED,
    )
    trainer = FederatedTrainer(template, n_clients=N_NODES)
    t0 = time.perf_counter()
    trainer.fit(datasets)
    fit_s = time.perf_counter() - t0
    return {
        "backend": jax.default_backend(),
        "corpus_gen_s": round(gen_s, 1),
        "stage_compile_and_one_epoch_fit_s": round(fit_s, 1),
        "compile_cache_dir": os.environ.get("JAX_COMPILATION_CACHE_DIR"),
    }


def coldproc_only(out_path: str | None = None) -> None:
    """Standalone cold-process measurement; patches the existing ttq
    artifact's cold_start.cold_process_warm_cache field in place."""
    result = measure_cold_process()
    out_path = out_path or os.path.join(
        REPO_ROOT, "results", "time_to_quality", "metrics.json"
    )
    try:
        with open(out_path, encoding="utf8") as f:
            artifact = json.load(f)
    except (OSError, ValueError):
        artifact = {"note": "coldproc ran before the main ttq artifact"}
    artifact.setdefault("cold_start", {})["cold_process_warm_cache"] = result
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w", encoding="utf8") as f:
        json.dump(artifact, f, indent=2)
    print(json.dumps(result, indent=2))


if __name__ == "__main__":
    sys.path.insert(0, REPO_ROOT)
    if "--coldproc-measure" in sys.argv:
        print("COLDPROC " + json.dumps(measure_cold_process()), flush=True)
    elif "--coldproc-only" in sys.argv:
        args = [a for a in sys.argv[1:] if not a.startswith("--")]
        coldproc_only(args[0] if args else None)
    else:
        main(sys.argv[1] if len(sys.argv) > 1 else None)
