"""Reproduce the BASELINE.md quality envelope (reference regime).

Runs the DSS/TSS simulation at the reference's published evaluation point:
eta=0.01, V=5000, K=50, 5 nodes, 10k train + 1k inference docs/node
(``experiments/dss_tss/config/eta_variable/config.json``), whose committed
envelope is centralized TSS 8.679 +/- 0.042 vs non-collaborative 7.571 vs
random 3.564 (BASELINE.md / ``results/eta_variable/results.pickle``).

Usage: python experiments_scripts/run_dss_tss_envelope.py [iters] [out_dir]

Writes ``results.json`` (+ ``results.pickle``) under ``out_dir`` (default
``results/dss_tss_eta001``). Runs on whatever backend jax selects; pass
FORCE_CPU=1 to pin CPU.
"""

from __future__ import annotations

import logging
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> None:
    iters = int(sys.argv[1]) if len(sys.argv) > 1 else 5
    out_dir = sys.argv[2] if len(sys.argv) > 2 else "results/dss_tss_eta001"
    frozen_dir = (
        sys.argv[3] if len(sys.argv) > 3 else "results/dss_tss_frozen40"
    )

    import jax

    if os.environ.get("FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")

    from gfedntm_tpu.experiments.dss_tss import SimulationConfig, run_simulation

    # force=True: jax's import-time warning already configured the root
    # logger at WARNING, which would silently swallow the simulation's
    # per-arm INFO progress lines.
    logging.basicConfig(level=logging.INFO, force=True)
    # The reference's full committed eta sweep (eta_variable/results.pickle):
    # 0.01 is the headline envelope, 1.0 the arms-converge regime
    # (44.302/44.302/39.660). Completed iterations are checkpointed under
    # the results dir and skipped on re-run, so re-invocations only compute
    # missing points.
    cfg = SimulationConfig(
        experiment=1, eta_list=(0.01, 0.02, 0.03, 0.04, 0.08, 1.0),
        iters=iters, seed=0,
    )
    t0 = time.perf_counter()
    out = run_simulation(cfg, results_dir=out_dir)
    elapsed = time.perf_counter() - t0
    cols = out["columns"]
    print(
        f"backend={jax.default_backend()} iters={iters} "
        f"elapsed={elapsed:.0f}s\n"
        f"centralized TSS {cols['centralized_betas_mean'][0]:.3f} "
        f"+/- {cols['centralized_betas_std'][0]:.3f} (ref 8.679+/-0.042)\n"
        f"non-collab  TSS {cols['non_colab_betas_mean'][0]:.3f} "
        f"+/- {cols['non_colab_betas_std'][0]:.3f} (ref 7.571+/-0.048)\n"
        f"random      TSS {cols['baseline_betas_mean'][0]:.3f} "
        f"+/- {cols['baseline_betas_std'][0]:.3f} (ref 3.564+/-0.098)\n"
        f"centralized DSS {cols['centralized_thetas_mean'][0]:.1f} "
        f"(ref 2555.5)\n"
        f"non-collab  DSS {cols['non_colab_thetas_mean'][0]:.1f} "
        f"(ref 3066.7)"
    )

    # Frozen-sweep points with published reference values: 40 (arms nearly
    # meet, centralized 8.664 +/- 0.037 vs non-collab 8.475 +/- 0.046) and
    # 5 (max collaboration gap, 8.676 +/- 0.049 vs 7.207 +/- 0.058).
    fcfg = SimulationConfig(
        experiment=0, frozen_topics_list=(40, 5), iters=iters, seed=0,
    )
    fout = run_simulation(fcfg, results_dir=frozen_dir)
    fcols = fout["columns"]
    print(
        f"frozen=40 centralized TSS {fcols['centralized_betas_mean'][0]:.3f} "
        f"+/- {fcols['centralized_betas_std'][0]:.3f} (ref 8.664+/-0.037)\n"
        f"frozen=40 non-collab  TSS {fcols['non_colab_betas_mean'][0]:.3f} "
        f"+/- {fcols['non_colab_betas_std'][0]:.3f} (ref 8.475+/-0.046)"
    )


if __name__ == "__main__":
    main()
