"""Reproduce the BASELINE.md quality envelope (reference regime).

Runs the DSS/TSS simulation at the reference's published evaluation point:
eta=0.01, V=5000, K=50, 5 nodes, 10k train + 1k inference docs/node
(``experiments/dss_tss/config/eta_variable/config.json``), whose committed
envelope is centralized TSS 8.679 +/- 0.042 vs non-collaborative 7.571 vs
random 3.564 (BASELINE.md / ``results/eta_variable/results.pickle``).

Usage: python experiments_scripts/run_dss_tss_envelope.py \
    [iters_eta] [iters_frozen] [out_dir] [frozen_dir]

Runs the frozen sweep first (default 10 iters into
``results/dss_tss_frozen40``), then the eta sweep (default 5 iters into
``results/dss_tss_eta001``); each writes ``results.json`` (+
``results.pickle``). Runs on whatever backend jax selects; pass FORCE_CPU=1
to pin CPU.
"""

from __future__ import annotations

import logging
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> None:
    iters_eta = int(sys.argv[1]) if len(sys.argv) > 1 else 5
    iters_frozen = int(sys.argv[2]) if len(sys.argv) > 2 else 10
    out_dir = sys.argv[3] if len(sys.argv) > 3 else "results/dss_tss_eta001"
    frozen_dir = (
        sys.argv[4] if len(sys.argv) > 4 else "results/dss_tss_frozen40"
    )

    import jax

    if os.environ.get("FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")

    from gfedntm_tpu.experiments.dss_tss import SimulationConfig, run_simulation

    # force=True: jax's import-time warning already configured the root
    # logger at WARNING, which would silently swallow the simulation's
    # per-arm INFO progress lines.
    logging.basicConfig(level=logging.INFO, force=True)

    # Frozen sweep FIRST (shorter: banked pre-refmap iterations resume from
    # checkpoints; only iterations beyond the banked depth compute fresh and
    # carry the betas_refmap stat). Published points: 40 (reference-map arms
    # nearly meet, centralized 8.664 +/- 0.037 vs non-collab 8.475 +/-
    # 0.046) and 5 (max collaboration gap, 8.676 +/- 0.049 vs 7.207 +/-
    # 0.058). The committed frozen=40 "ordering inversion" vs the reference
    # is a mapping artifact — this repo's primary TSS uses the correct word
    # mapping while the reference's pickles use its shifted one (see
    # refmap_project in gfedntm_tpu/experiments/dss_tss.py); the refmap
    # columns are the comparable ones.
    fcfg = SimulationConfig(
        experiment=0, frozen_topics_list=(40, 5), iters=iters_frozen, seed=0,
    )
    t0 = time.perf_counter()
    fout = run_simulation(fcfg, results_dir=frozen_dir)
    fcols = fout["columns"]
    print(
        f"frozen sweep done in {time.perf_counter() - t0:.0f}s\n"
        f"frozen=40 centralized TSS {fcols['centralized_betas_mean'][0]:.3f} "
        f"+/- {fcols['centralized_betas_std'][0]:.3f} "
        f"(refmap {fcols['centralized_betas_refmap_mean'][0]}, "
        f"ref-published 8.664+/-0.037)\n"
        f"frozen=40 non-collab  TSS {fcols['non_colab_betas_mean'][0]:.3f} "
        f"+/- {fcols['non_colab_betas_std'][0]:.3f} "
        f"(refmap {fcols['non_colab_betas_refmap_mean'][0]}, "
        f"ref-published 8.475+/-0.046)",
        flush=True,
    )

    # Eta sweep at the reference's ACTUAL regime — frozen_topics_list[1]=10,
    # applied inside run_simulation (`run_simulation.py:694-696`); the
    # config digest changed with the regime, so pre-correction (frozen=5)
    # checkpoints cannot be aggregated here. 0.01 is the headline envelope,
    # 1.0 the arms-converge regime (44.302/44.302/39.660).
    cfg = SimulationConfig(
        experiment=1, eta_list=(0.01, 0.02, 0.03, 0.04, 0.08, 1.0),
        iters=iters_eta, seed=0,
    )
    t0 = time.perf_counter()
    out = run_simulation(cfg, results_dir=out_dir)
    elapsed = time.perf_counter() - t0
    cols = out["columns"]
    print(
        f"backend={jax.default_backend()} iters={iters_eta} "
        f"elapsed={elapsed:.0f}s\n"
        f"centralized TSS {cols['centralized_betas_mean'][0]:.3f} "
        f"+/- {cols['centralized_betas_std'][0]:.3f} "
        f"(refmap {cols['centralized_betas_refmap_mean'][0]}, "
        f"ref-published 8.679+/-0.042)\n"
        f"non-collab  TSS {cols['non_colab_betas_mean'][0]:.3f} "
        f"+/- {cols['non_colab_betas_std'][0]:.3f} "
        f"(refmap {cols['non_colab_betas_refmap_mean'][0]}, "
        f"ref-published 7.571+/-0.048)\n"
        f"random      TSS {cols['baseline_betas_mean'][0]:.3f} "
        f"+/- {cols['baseline_betas_std'][0]:.3f} (ref 3.564+/-0.098)\n"
        f"centralized DSS {cols['centralized_thetas_mean'][0]:.1f} "
        f"(ref 2555.5)\n"
        f"non-collab  DSS {cols['non_colab_thetas_mean'][0]:.1f} "
        f"(ref 3066.7)"
    )


if __name__ == "__main__":
    main()
