"""TPU job supervisor: waits for the (single-tenant, tunnel-backed) chip
to answer, then runs a serial job queue, each job under an I/O-stall
watchdog.

Why this exists: the axon TPU tunnel can hang any device call
indefinitely — observed as a training process with /proc/<pid>/io counters
flat for 30+ minutes while its main thread sleeps in the plugin's re-dial
loop — and a killed client appears to hold the chip's lease for a while.
Recovery therefore needs (a) kill-on-I/O-stall rather than wall-clock
timeouts alone (a healthy long run also looks quiet on CPU), (b) probe
with long backoff before relaunching, and (c) jobs that are cheap to
relaunch — run_simulation checkpoints per iteration for exactly this
(gfedntm_tpu/experiments/dss_tss.py).

Usage: python experiments_scripts/tpu_job_supervisor.py  (edit `jobs`).
"""
import os
import signal
import subprocess
import sys
import time

REPO = "/root/repo"
LOG = open("/tmp/supervisor.log", "a", buffering=1)
STALL_S = 600
# Persistent XLA compilation cache: a relaunched job (stall kill, tunnel
# flake) replays its compiles from disk instead of re-paying 20-40 s per
# program over the tunnel.
CACHE_DIR = "/tmp/jax_compile_cache"
JOB_ENV = dict(os.environ, JAX_COMPILATION_CACHE_DIR=CACHE_DIR)
PROBE_CMD = [sys.executable, "-c", "import jax; print(jax.default_backend())"]


def log(msg):
    LOG.write(f"[{time.strftime('%H:%M:%S')}] {msg}\n")


def probe_tpu(timeout=150):
    try:
        out = subprocess.run(
            PROBE_CMD, capture_output=True, text=True, timeout=timeout,
            cwd=REPO,
        )
        # "axon" is this image's tunnel plugin name; a standard TPU VM
        # reports "tpu".
        return out.returncode == 0 and (
            "axon" in out.stdout or "tpu" in out.stdout
        )
    except subprocess.TimeoutExpired:
        return False


def wait_for_tpu(max_wait_s=3 * 3600):
    t0 = time.time()
    attempt = 0
    while time.time() - t0 < max_wait_s:
        attempt += 1
        if probe_tpu():
            log(f"tunnel up after {time.time() - t0:.0f}s "
                f"({attempt} probes)")
            return True
        log(f"probe {attempt} failed ({time.time() - t0:.0f}s elapsed)")
        time.sleep(180)
    return False


def _kill_group(proc):
    """Kill the job's whole process group (see start_new_session below),
    then reap it."""
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        proc.kill()
    proc.wait()


def io_bytes(pid):
    """Sum rchar+wchar across the job's whole process group.

    The job runs in its own session (start_new_session), so its pgid ==
    the direct child's pid; bench.py and the sweep runners do their real
    work in grandchildren, whose I/O is not reflected in the parent's
    counters until reaped — a parent blocked in wait() for >STALL_S would
    otherwise be killed as stalled while its child works (ADVICE r3).

    Returns (io_total, cpu_ticks): a Mosaic compile of a large-V kernel
    geometry is minutes of pure in-process CPU with zero read/write
    syscalls (round 4 watched a live soak, cputime growing, get killed at
    600 s of flat I/O mid-compile), so CPU-time growth must count as
    liveness too. A true tunnel hang is flat on BOTH counters — the
    plugin's re-dial loop sleeps."""
    total, cpu, found = 0, 0, False
    for entry in os.listdir("/proc"):
        if not entry.isdigit():
            continue
        try:
            with open(f"/proc/{entry}/stat") as f:
                # comm may contain spaces: split after the closing paren.
                # pgrp is index 2 of the remainder; utime/stime are
                # indices 11/12 (cutime at 13 is deliberately excluded —
                # it jumps when children are reaped).
                rest = f.read().rsplit(")", 1)[1].split()
            if int(rest[2]) != pid:
                continue
            cpu += int(rest[11]) + int(rest[12])
            with open(f"/proc/{entry}/io") as f:
                d = dict(
                    line.strip().split(": ") for line in f if ": " in line
                )
            total += int(d["rchar"]) + int(d["wchar"])
            found = True
        except (OSError, ValueError, IndexError):
            continue  # raced a process exit or unreadable entry
    return (total, cpu) if found else None


def run_watched(name, cmd, job_timeout, attempts=6, extra_env=None):
    for att in range(1, attempts + 1):
        log(f"{name}: attempt {att}: {' '.join(cmd)}")
        with open(f"/tmp/q_{name}.log", "ab") as out:
            # Own session/process group: a stall kill must also take down
            # the job's own subprocesses (bench.py probes the backend and
            # runs its phases in children; a killed parent would otherwise
            # leave a child holding the single-tenant chip).
            proc = subprocess.Popen(
                cmd, stdout=out, stderr=out, cwd=REPO,
                start_new_session=True,
                env=dict(JOB_ENV, **(extra_env or {})),
            )
        t0 = time.time()
        last_io, last_change = io_bytes(proc.pid), time.time()
        while True:
            rc = proc.poll()
            if rc is not None:
                if rc == 0:
                    log(f"{name}: done in {time.time() - t0:.0f}s")
                    return True
                log(f"{name}: rc={rc} after {time.time() - t0:.0f}s")
                break
            now = time.time()
            cur = io_bytes(proc.pid)
            if cur is not None and last_io is not None:
                io_moved = cur[0] != last_io[0]
                # Require REAL CPU progress — >=5% average CPU since the
                # last liveness reset (a Mosaic compile runs near 100%),
                # not any tick: the plugin's re-dial loop burns a few
                # ticks per reconnect attempt, which must not keep a hung
                # job alive forever (USER_HZ=100 ticks/s).
                cpu_moved = (
                    cur[1] - last_io[1] > 0.05 * (now - last_change) * 100
                )
                if io_moved or cpu_moved:
                    last_io, last_change = cur, now
            elif cur is not None:
                last_io, last_change = cur, now
            if now - last_change > STALL_S:
                log(f"{name}: I/O+CPU flat {STALL_S}s -> kill (stall)")
                _kill_group(proc)
                break
            if now - t0 > job_timeout:
                log(f"{name}: exceeded {job_timeout}s -> kill")
                _kill_group(proc)
                break
            time.sleep(20)
        if att < attempts:
            if not wait_for_tpu():
                log(f"{name}: tunnel never recovered; giving up")
                return False
    log(f"{name}: FAILED after {attempts} attempts")
    return False


def main():
    log("=== supervisor start ===")
    if not wait_for_tpu():
        log("tunnel never came up; aborting")
        sys.exit(1)
    py = sys.executable
    # Round-5 priority order (VERDICT r4): (1) the bench FIRST — it now
    # banks+commits its live-TPU artifact (results/bench_tpu/), the round's
    # single highest-leverage deliverable; (2) the V=50k/100k end-to-end
    # federated fit with the fused kernel engaged; (3) the soak with the
    # bf16-storage table; then the quality artifacts (ttq grew the
    # local-steps arm + cold-start section; parity grew the NeuralLDA
    # arms). The CPU-bound envelope is NOT here — it runs independently of
    # the chip.
    # Under the supervisor, bench's internal phase deadline must sit BELOW
    # the 600 s flat-CPU stall kill: a hung phase child then times out
    # in-bench (parent stays live, escalates 1x->2x, reaches the
    # cached-artifact fallback) instead of the whole group being
    # stall-killed before the fallback can run. 300+600 phase budget +
    # fused phase fits inside the 2400 s job timeout.
    bench_env = {"BENCH_PHASE_TIMEOUT_S": "300"}
    jobs = [
        ("bench", [py, "bench.py"], 2400, 3, bench_env),
        ("largev", [py, "experiments_scripts/run_full_v100k.py"],
         3600, 3, None),
        ("soak", [py, "experiments_scripts/soak_fused_kernel.py"],
         3600, 4, None),
        ("ttq", [py, "experiments_scripts/time_to_quality.py"],
         4500, 3, None),
        # Cold-process probe: must run in its OWN process with the chip
        # free and the ttq run's compile cache warm — see
        # time_to_quality.py --coldproc-only.
        ("ttqcold",
         [py, "experiments_scripts/time_to_quality.py", "--coldproc-only"],
         1500, 2, None),
        ("parity", [py, "experiments_scripts/parity_vs_torch.py"],
         7200, 3, None),
        ("noniid", [py, "experiments_scripts/run_noniid_full.py"],
         3600, 3, None),
        ("realtext", [py, "experiments_scripts/run_realtext_federated.py"],
         5400, 2, None),
        ("presets24", [py, "experiments_scripts/run_presets_24.py"],
         3600, 3, None),
    ]
    results = {}
    for name, cmd, jt, attempts, extra_env in jobs:
        results[name] = run_watched(name, cmd, jt, attempts, extra_env)
    log(f"=== supervisor done: {results} ===")


if __name__ == "__main__":
    main()
