"""Rebuild a sweep's ``results.json`` (+ ``results.pickle``) from its
banked per-iteration checkpoints, without training anything.

Why: ``run_simulation`` writes the aggregated ``results.json`` only when
the WHOLE sweep reaches its target depth; a deepening run that is killed
mid-sweep (round end, tunnel loss) leaves the committed aggregate at its
old depth even though later points are fully banked on disk. This tool
re-aggregates whatever is banked — per-point sample counts land in
``meta.stat_counts`` and ``meta.iters`` records the MINIMUM depth across
points, so a mixed-depth artifact says exactly how deep each column is.

The digest directory is chosen as the most recently modified one under
``<results_dir>/iters`` (the one the active deepening run writes to),
then VERIFIED against the prior artifact's regime via its
``config_stamp.json`` (frozen_topics and corpus geometry must match —
the stamp exists precisely so wrong-regime checkpoints can never be
aggregated under the right-regime label, ``dss_tss.py:356-370``); the
digest is recorded in ``meta.checkpoint_digest``.

Column alignment matches ``run_simulation``: every column keeps one
entry per index point, with ``None`` for stats a point's banked files do
not carry (pre-refmap checkpoints, never-reached points).

Usage: python experiments_scripts/aggregate_banked_envelope.py \
    results/dss_tss_eta001 [more_results_dirs...]
"""

from __future__ import annotations

import collections
import json
import pickle
import sys
from pathlib import Path

import numpy as np


def _check_regime(ckpt_dir: Path, prior_meta: dict) -> None:
    """Refuse to aggregate a digest whose config stamp contradicts the
    prior artifact's recorded regime."""
    stamp_path = ckpt_dir / "config_stamp.json"
    regime = prior_meta.get("regime", {})
    if not stamp_path.exists() or not regime:
        return
    with open(stamp_path, encoding="utf8") as f:
        stamp = json.load(f)
    for key in ("frozen_topics", "vocab_size", "n_topics", "n_nodes"):
        want = regime.get(key)
        # Sweep-variable regimes record a list (e.g. the frozen sweep's
        # frozen_topics [40, 5]); the stamp carries only the base config
        # value there, so the comparison is meaningless — skip it.
        if want is None or isinstance(want, list) or key not in stamp:
            continue
        if stamp[key] != repr(want):
            raise SystemExit(
                f"digest {ckpt_dir.name} regime mismatch on {key}: "
                f"stamp={stamp[key]} vs results.json regime={want!r} — "
                "refusing to aggregate wrong-regime checkpoints"
            )


def aggregate(results_dir: str) -> dict:
    rd = Path(results_dir)
    with open(rd / "results.json", encoding="utf8") as f:
        prior = json.load(f)
    index = prior["index"]
    index_name = prior.get("index_name")
    digests = sorted(
        (p for p in (rd / "iters").iterdir() if p.is_dir()),
        key=lambda p: p.stat().st_mtime,
    )
    if not digests:
        raise SystemExit(f"no checkpoint digests under {rd}/iters")
    ckpt_dir = digests[-1]
    _check_regime(ckpt_dir, prior.get("meta", {}))

    # First pass: the union of (arm, stat) across every banked file, so
    # every column stays len(index)-aligned (None where a point lacks the
    # stat — mirroring run_simulation's placeholder behavior).
    all_stats: set[tuple[str, str]] = set()
    point_files: dict = {}
    for point in index:
        files = sorted(
            ckpt_dir.glob(f"point{point}_it*.json"),
            key=lambda p: int(p.stem.rsplit("_it", 1)[1]),
        )
        loaded = []
        for path in files:
            with open(path, encoding="utf8") as f:
                loaded.append(json.load(f))
        point_files[point] = loaded
        for res in loaded:
            for arm, stats in res.items():
                if arm.startswith("_"):
                    continue
                all_stats.update((arm, stat) for stat in stats)

    columns: dict[str, list] = collections.defaultdict(list)
    stat_counts: dict[str, list] = collections.defaultdict(list)
    iter_backends: list[str] = []
    depths: list[int] = []
    for point in index:
        loaded = point_files[point]
        depths.append(len(loaded))
        per_iter: dict[tuple[str, str], list] = collections.defaultdict(list)
        for res in loaded:
            iter_backends.append(res.get("_backend", "unknown"))
            for arm, stats in res.items():
                if arm.startswith("_"):
                    continue
                for stat, val in stats.items():
                    per_iter[(arm, stat)].append(val)
        for arm, stat in sorted(all_stats):
            vals = np.asarray(per_iter.get((arm, stat), []), dtype=float)
            columns[f"{arm}_{stat}_mean"].append(
                float(vals.mean()) if vals.size else None
            )
            columns[f"{arm}_{stat}_std"].append(
                float(vals.std()) if vals.size else None
            )
            stat_counts[f"{arm}_{stat}"].append(int(vals.size))

    meta = dict(prior.get("meta", {}))
    meta.update(
        {
            "backend": "checkpoint-aggregate",
            "iter_backends": iter_backends,
            "stat_counts": dict(stat_counts),
            "iters": min(depths) if depths else 0,
            "iters_per_point": dict(zip(map(str, index), depths)),
            "aggregated_from_checkpoints": True,
            "checkpoint_digest": ckpt_dir.name,
            # Aggregation itself is ~instant; keep the prior run's compute
            # cost if recorded (the banked iterations are what cost hours).
            "elapsed_s": meta.get("elapsed_s") or 0.1,
        }
    )
    out = {
        "index": index,
        "index_name": index_name,
        "columns": dict(columns),
        "meta": meta,
    }
    # Atomic replace: results.json is also this tool's own input — a crash
    # mid-write must not brick re-runs (same tmp+rename as dss_tss.py).
    tmp = rd / "results.json.tmp"
    with open(tmp, "w", encoding="utf8") as f:
        json.dump(out, f, indent=2)
    tmp.rename(rd / "results.json")
    try:
        import pandas as pd

        df = pd.DataFrame(
            out["columns"], index=pd.Index(index, name=index_name)
        )
        with open(rd / "results.pickle", "wb") as f:
            pickle.dump(df, f)
    except ImportError:
        pass
    return out


def main() -> None:
    for results_dir in sys.argv[1:] or ["results/dss_tss_eta001"]:
        out = aggregate(results_dir)
        print(
            json.dumps(
                {
                    "dir": results_dir,
                    "digest": out["meta"]["checkpoint_digest"],
                    "iters_per_point": out["meta"]["iters_per_point"],
                }
            )
        )


if __name__ == "__main__":
    main()
