"""Head-to-head quality + wall-clock parity vs the reference implementation.

VERDICT r2 task 4 (the BASELINE.json north star minus the unavailable GPU):
train on the SAME corpus with the SAME vectorization —

- ``torch_centralized``: the reference's own PyTorch AVITM
  (`/root/reference/src/models/base/pytorchavitm/avitm_network/avitm.py`,
  imported and run, not copied), CPU (torch has no TPU path);
- ``tpu_centralized``: this framework's AVITM, same hyperparameters;
- ``tpu_federated``: this framework's 5-client federated run, clients
  partitioned by ``fieldsOfStudy`` (the docker-compose regime,
  `/root/reference/docker-compose.yaml:21-157`).

Corpus: the reference's in-repo ``s2cs_tiny.parquet`` (334 Semantic Scholar
CS abstracts, 5 FOS categories — the runnable stand-in it ships for the full
S2 corpus). Both centralized arms consume the *identical* BoW matrix and
vocabulary from this framework's ``prepare_dataset`` (25%/seed-42 split,
sklearn-parity vectorizer), so every difference is the trainer, not the
prep. All arms are scored by the same native metric implementations
(NPMI coherence vs the pooled corpus, topic diversity, inverted RBO —
the ``collab_vs_non_collab/train.py:22-101`` metric set).

Usage: python experiments_scripts/parity_vs_torch.py [out_json]
Writes ``results/parity_vs_torch/metrics.json``.
"""

from __future__ import annotations

import json
import logging
import math
import os
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REFERENCE_ROOT = "/root/reference"
sys.path.insert(0, REPO_ROOT)

PARQUET = "/root/reference/static/datasets/s2cs_tiny.parquet"
TOPN_NPMI = 10


def load_pooled_corpus():
    import pandas as pd

    df = pd.read_parquet(PARQUET, columns=["lemmas", "fieldsOfStudy"])
    df = df.dropna(subset=["lemmas"])
    return list(df["lemmas"]), df


def score(topics, corpus_tokens):
    from gfedntm_tpu.eval.metrics import (
        inverted_rbo,
        npmi_coherence,
        topic_diversity,
    )

    return {
        "npmi": round(npmi_coherence(topics, corpus_tokens, topn=TOPN_NPMI), 4),
        "topic_diversity": round(topic_diversity(topics), 4),
        "inverted_rbo": round(inverted_rbo(topics), 4),
    }


def run_torch_arm(train_data, val_data, id2token, k, epochs):
    sys.path.insert(0, REFERENCE_ROOT)
    import numpy as np

    if not hasattr(np, "Inf"):  # reference targets numpy<2
        np.Inf = np.inf
    from src.models.base.pytorchavitm.avitm_network.avitm import AVITM as TorchAVITM
    from src.models.base.pytorchavitm.datasets.bow_dataset import BOWDataset

    t_train = BOWDataset(np.asarray(train_data.X, np.float32), id2token)
    t_val = BOWDataset(np.asarray(val_data.X, np.float32), id2token)
    model = TorchAVITM(
        logger=logging.getLogger("torch_arm"), input_size=t_train.X.shape[1],
        n_components=k, model_type="prodLDA", hidden_sizes=(50, 50),
        activation="softplus", dropout=0.2, learn_priors=True, batch_size=64,
        lr=2e-3, momentum=0.99, solver="adam", num_epochs=epochs,
        reduce_on_plateau=False, topic_prior_mean=0.0,
        topic_prior_variance=None, num_samples=20,
        num_data_loader_workers=0, verbose=False,
    )
    t0 = time.perf_counter()
    model.fit(t_train, t_val)
    wall = time.perf_counter() - t0
    topics = [list(t) for t in model.get_topics(TOPN_NPMI)]
    best = getattr(model, "best_loss_train", None)
    return topics, wall, (float(best) if best is not None else None)


def run_tpu_centralized_arm(train_data, val_data, k, epochs):
    from gfedntm_tpu.models.avitm import AVITM

    model = AVITM(
        input_size=train_data.X.shape[1], n_components=k,
        hidden_sizes=(50, 50), batch_size=64, num_epochs=epochs, lr=2e-3,
        momentum=0.99, seed=0, verbose=False,
    )
    t0 = time.perf_counter()
    model.fit(train_data, val_data)
    wall = time.perf_counter() - t0
    return model.get_topics(TOPN_NPMI), wall, float(min(model.epoch_losses))


def run_tpu_federated_arm(k, epochs_scale):
    from gfedntm_tpu.presets import noniid_fos_5client

    t0 = time.perf_counter()
    res = noniid_fos_5client(
        scale=epochs_scale, n_components=k, compute_metrics=False,
    )
    wall = time.perf_counter() - t0
    global_model = res.trainer.make_global_model(res.result)
    global_model.train_data = res.extras["consensus"].datasets[0]
    return global_model.get_topics(TOPN_NPMI), wall, res


def main() -> None:
    out_path = (
        sys.argv[1] if len(sys.argv) > 1
        else os.path.join(REPO_ROOT, "results/parity_vs_torch/metrics.json")
    )
    logging.basicConfig(level=logging.WARNING)

    from gfedntm_tpu.data.preparation import prepare_dataset

    import jax

    docs, _ = load_pooled_corpus()
    corpus_tokens = [d.split() for d in docs]
    train_data, val_data, input_size, id2token, _, _ = prepare_dataset(docs)

    epochs = 100  # reference default (dft_params.cf / train_avitm)
    report = {
        "corpus": {
            "path": PARQUET,
            "n_docs": len(docs),
            "vocab": input_size,
            "prep": "shared prepare_dataset (25%/seed-42 split); both "
                    "centralized arms consume the identical BoW matrix",
        },
        "backend": jax.default_backend(),
        "epochs": epochs,
        "arms": {},
    }
    for k in (10, 50):
        topics_t, wall_t, loss_t = run_torch_arm(
            train_data, val_data, id2token, k, epochs
        )
        arm_t = {
            "wall_s": round(wall_t, 2),
            # None unless finite: the reference's best_loss_train can stay
            # at its float('inf') sentinel, and json.dump would emit bare
            # `Infinity` — invalid JSON for strict consumers.
            "best_train_loss": (
                round(loss_t, 2)
                if loss_t is not None and math.isfinite(loss_t)
                else None
            ),
            "device": "cpu-1core", **score(topics_t, corpus_tokens),
        }

        topics_j, wall_j, loss_j = run_tpu_centralized_arm(
            train_data, val_data, k, epochs
        )
        arm_j = {
            "wall_s": round(wall_j, 2), "best_train_loss": round(loss_j, 2),
            "device": report["backend"], **score(topics_j, corpus_tokens),
        }

        topics_f, wall_f, _ = run_tpu_federated_arm(k, 1.0)
        arm_f = {
            "wall_s": round(wall_f, 2),
            "device": report["backend"],
            "note": "5 clients partitioned by fieldsOfStudy; wall includes "
                    "consensus + staging + compile",
            **score(topics_f, corpus_tokens),
        }

        report["arms"][f"k{k}"] = {
            "torch_centralized": arm_t,
            "tpu_centralized": arm_j,
            "tpu_federated": arm_f,
            "wall_speedup_tpu_vs_torch": round(wall_t / max(wall_j, 1e-9), 2),
        }

    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
