"""Head-to-head quality + wall-clock parity vs the reference implementation.

VERDICT r2 task 4 (the BASELINE.json north star minus the unavailable GPU):
train on the SAME corpus with the SAME vectorization —

- ``torch_centralized``: the reference's own PyTorch AVITM
  (`/root/reference/src/models/base/pytorchavitm/avitm_network/avitm.py`,
  imported and run, not copied), CPU (torch has no TPU path);
- ``tpu_centralized``: this framework's AVITM, same hyperparameters;
- ``tpu_federated``: this framework's 5-client federated run, clients
  partitioned by ``fieldsOfStudy`` (the docker-compose regime,
  `/root/reference/docker-compose.yaml:21-157`).

Corpus: the reference's in-repo ``s2cs_tiny.parquet`` (334 Semantic Scholar
CS abstracts, 5 FOS categories — the runnable stand-in it ships for the full
S2 corpus). Both centralized arms consume the *identical* BoW matrix and
vocabulary from this framework's ``prepare_dataset`` (25%/seed-42 split,
sklearn-parity vectorizer), so every difference is the trainer, not the
prep. All arms are scored by the same native metric implementations
(NPMI coherence vs the pooled corpus, topic diversity, inverted RBO —
the ``collab_vs_non_collab/train.py:22-101`` metric set).

Usage: python experiments_scripts/parity_vs_torch.py [out_json]
Writes ``results/parity_vs_torch/metrics.json``.
"""

from __future__ import annotations

import json
import logging
import math
import os
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REFERENCE_ROOT = "/root/reference"
sys.path.insert(0, REPO_ROOT)

PARQUET = "/root/reference/static/datasets/s2cs_tiny.parquet"
TOPN_NPMI = 10


def load_pooled_corpus():
    import pandas as pd

    df = pd.read_parquet(PARQUET, columns=["lemmas", "fieldsOfStudy"])
    df = df.dropna(subset=["lemmas"])
    return list(df["lemmas"]), df


def score(topics, corpus_tokens):
    from gfedntm_tpu.eval.metrics import (
        inverted_rbo,
        npmi_coherence,
        topic_diversity,
    )

    return {
        "npmi": round(npmi_coherence(topics, corpus_tokens, topn=TOPN_NPMI), 4),
        "topic_diversity": round(topic_diversity(topics), 4),
        "inverted_rbo": round(inverted_rbo(topics), 4),
    }


def run_torch_arm(train_data, val_data, id2token, k, epochs, **overrides):
    import numpy as np

    from torch_baseline import make_reference_avitm

    sys.path.insert(0, REFERENCE_ROOT)
    from src.models.base.pytorchavitm.datasets.bow_dataset import BOWDataset

    t_train = BOWDataset(np.asarray(train_data.X, np.float32), id2token)
    t_val = BOWDataset(np.asarray(val_data.X, np.float32), id2token)
    model = make_reference_avitm(
        input_size=t_train.X.shape[1], n_components=k, num_epochs=epochs,
        hidden_sizes=(50, 50), logger_name="torch_arm", **overrides,
    )
    t0 = time.perf_counter()
    model.fit(t_train, t_val)
    wall = time.perf_counter() - t0
    topics = [list(t) for t in model.get_topics(TOPN_NPMI)]
    best = getattr(model, "best_loss_train", None)
    betas = np.asarray(model.get_topic_word_distribution())
    return topics, wall, (float(best) if best is not None else None), betas


def run_tpu_centralized_arm(train_data, val_data, k, epochs):
    from gfedntm_tpu.models.avitm import AVITM

    model = AVITM(
        input_size=train_data.X.shape[1], n_components=k,
        hidden_sizes=(50, 50), batch_size=64, num_epochs=epochs, lr=2e-3,
        momentum=0.99, seed=0, verbose=False,
    )
    t0 = time.perf_counter()
    model.fit(train_data, val_data)
    wall = time.perf_counter() - t0
    return model.get_topics(TOPN_NPMI), wall, float(min(model.epoch_losses))


def run_tpu_federated_arm(k, epochs_scale):
    from gfedntm_tpu.presets import noniid_fos_5client

    t0 = time.perf_counter()
    res = noniid_fos_5client(
        scale=epochs_scale, n_components=k, compute_metrics=False,
    )
    wall = time.perf_counter() - t0
    global_model = res.trainer.make_global_model(res.result)
    global_model.train_data = res.extras["consensus"].datasets[0]
    return global_model.get_topics(TOPN_NPMI), wall, res


def run_synthetic_regime(epochs: int = 100, seed: int = 0) -> dict:
    """The 10k-doc synthetic regime (VERDICT r3 task 8): 5 nodes x 2000
    docs, V=5000, K=50, eta=0.01 — the reference's published evaluation
    regime scaled to this host's single core. Unlike the 334-doc s2cs_tiny
    fixture (where 66 docs/client starves every arm and federated NPMI
    collapses), this corpus is large enough that quality differences mean
    something — and ground truth exists, so TSS is scored too (single
    softmax, correct word mapping)."""
    import numpy as np

    import jax

    from gfedntm_tpu.data.datasets import BowDataset
    from gfedntm_tpu.data.preparation import prepare_dataset
    from gfedntm_tpu.data.synthetic import generate_synthetic_corpus
    from gfedntm_tpu.eval.metrics import (
        convert_topic_word_to_init_size,
        topic_similarity_score,
    )
    from gfedntm_tpu.federated.trainer import FederatedTrainer
    from gfedntm_tpu.models.avitm import AVITM

    n_nodes, vocab, k = 5, 5000, 50
    corpus = generate_synthetic_corpus(
        vocab_size=vocab, n_topics=k, beta=0.01, alpha=0.1, n_docs=2000,
        nwords=(150, 250), n_nodes=n_nodes, frozen_topics=5, seed=seed,
    )
    union_docs = [d for node in corpus.nodes for d in node.documents]
    corpus_tokens = [d.split() for d in union_docs]
    train_data, val_data, input_size, id2token, _, _ = prepare_dataset(
        union_docs
    )

    def tss_of(beta_dist, i2t):
        full = convert_topic_word_to_init_size(
            vocab, np.asarray(beta_dist), i2t
        )
        return round(
            float(topic_similarity_score(full, corpus.topic_vectors)), 4
        )

    def softmax_rows(a):
        e = np.exp(a - a.max(axis=1, keepdims=True))
        return e / e.sum(axis=1, keepdims=True)

    arms: dict = {}
    topics_t, wall_t, _, betas_t = run_torch_arm(
        train_data, val_data, id2token, k, epochs
    )
    arms["torch_centralized"] = {
        "wall_s": round(wall_t, 2), "device": "cpu-1core",
        **score(topics_t, corpus_tokens),
        "tss_vs_ground_truth": tss_of(betas_t, id2token),
    }

    model = AVITM(
        input_size=input_size, n_components=k, hidden_sizes=(50, 50),
        batch_size=64, num_epochs=epochs, lr=2e-3, momentum=0.99,
        seed=seed, verbose=False,
    )
    t0 = time.perf_counter()
    model.fit(train_data, val_data)
    wall_j = time.perf_counter() - t0
    arms["tpu_centralized"] = {
        "wall_s": round(wall_j, 2), "device": jax.default_backend(),
        **score(model.get_topics(TOPN_NPMI), corpus_tokens),
        "tss_vs_ground_truth": tss_of(
            softmax_rows(np.asarray(model.params["beta"])), id2token
        ),
    }

    idx2token = {i: f"wd{i}" for i in range(vocab)}
    datasets = [
        BowDataset(X=node.bow, idx2token=idx2token) for node in corpus.nodes
    ]
    template = AVITM(
        input_size=vocab, n_components=k, hidden_sizes=(50, 50),
        batch_size=64, num_epochs=epochs, lr=2e-3, momentum=0.99, seed=seed,
    )
    trainer = FederatedTrainer(template, n_clients=n_nodes)
    t0 = time.perf_counter()
    result = trainer.fit(datasets)
    wall_f = time.perf_counter() - t0
    gm = trainer.make_global_model(result, dataset=datasets[0])
    arms["tpu_federated"] = {
        "wall_s": round(wall_f, 2), "device": jax.default_backend(),
        "note": "5 clients = the 5 generator nodes (non-IID by "
                "construction: rotating own-topic priors); wall includes "
                "consensus-free direct staging + compile",
        **score(gm.get_topics(TOPN_NPMI), corpus_tokens),
        "tss_vs_ground_truth": tss_of(
            softmax_rows(np.asarray(gm.params["beta"])), idx2token
        ),
    }
    arms["wall_speedup_tpu_vs_torch"] = round(wall_t / max(wall_j, 1e-9), 2)

    # --- NeuralLDA (model_type="LDA") head-to-head (VERDICT r4 #5) ------
    # Config-2's TSS 2.97 needs an attribution: if the reference's own
    # NeuralLDA lands at the same level on the same corpus, the level is
    # the algorithm's (the LDA decode theta @ softmax(BN(beta)) mixes
    # topics through batch-norm, diluting recovery); if it scores well,
    # this framework's LDA branch has a decode bug. Both arms are scored
    # on get_topic_word_distribution() — each implementation's own
    # LDA-decode path (reference: decoder_network.py:128-135).
    topics_tl, wall_tl, _, betas_tl = run_torch_arm(
        train_data, val_data, id2token, k, epochs, model_type="LDA"
    )
    arms["torch_centralized_neurallda"] = {
        "wall_s": round(wall_tl, 2), "device": "cpu-1core",
        **score(topics_tl, corpus_tokens),
        "tss_vs_ground_truth": tss_of(betas_tl, id2token),
    }
    model_l = AVITM(
        input_size=input_size, n_components=k, hidden_sizes=(50, 50),
        batch_size=64, num_epochs=epochs, lr=2e-3, momentum=0.99,
        seed=seed, verbose=False, model_type="LDA",
    )
    t0 = time.perf_counter()
    model_l.fit(train_data, val_data)
    wall_jl = time.perf_counter() - t0
    arms["tpu_centralized_neurallda"] = {
        "wall_s": round(wall_jl, 2), "device": jax.default_backend(),
        **score(model_l.get_topics(TOPN_NPMI), corpus_tokens),
        "tss_vs_ground_truth": tss_of(
            model_l.get_topic_word_distribution(), id2token
        ),
    }
    return {
        "corpus": {
            "generator": "synthetic LDA, V=5000, K=50, 5 nodes x 2000 "
                         "docs, eta=0.01, alpha=0.1, frozen=5, seed 0",
            "n_docs": len(union_docs),
            "vocab_fitted": input_size,
        },
        "epochs": epochs,
        "arms": arms,
    }


def main() -> None:
    out_path = (
        sys.argv[1] if len(sys.argv) > 1
        else os.path.join(REPO_ROOT, "results/parity_vs_torch/metrics.json")
    )
    logging.basicConfig(level=logging.WARNING)

    from gfedntm_tpu.data.preparation import prepare_dataset

    import jax

    if os.environ.get("FORCE_CPU"):
        # Must precede any backend query: jax.default_backend() on a dead
        # TPU tunnel blocks forever in the plugin's re-dial loop.
        jax.config.update("jax_platforms", "cpu")

    # Headline section: the 10k-doc synthetic regime (meaningful corpus).
    synthetic = run_synthetic_regime()

    docs, _ = load_pooled_corpus()
    corpus_tokens = [d.split() for d in docs]
    train_data, val_data, input_size, id2token, _, _ = prepare_dataset(docs)

    epochs = 100  # reference default (dft_params.cf / train_avitm)
    report = {
        "synthetic_10k": synthetic,
        "corpus": {
            "path": PARQUET,
            "n_docs": len(docs),
            "vocab": input_size,
            "prep": "shared prepare_dataset (25%/seed-42 split); both "
                    "centralized arms consume the identical BoW matrix",
            "caveat": "334 docs split 5 ways starves every arm — kept only "
                      "as the in-repo real-text fixture; the synthetic_10k "
                      "section is the meaningful comparison",
        },
        "backend": jax.default_backend(),
        "epochs": epochs,
        "arms": {},
    }
    for k in (10, 50):
        topics_t, wall_t, loss_t, _betas_t = run_torch_arm(
            train_data, val_data, id2token, k, epochs
        )
        arm_t = {
            "wall_s": round(wall_t, 2),
            # None unless finite: the reference's best_loss_train can stay
            # at its float('inf') sentinel, and json.dump would emit bare
            # `Infinity` — invalid JSON for strict consumers.
            "best_train_loss": (
                round(loss_t, 2)
                if loss_t is not None and math.isfinite(loss_t)
                else None
            ),
            "device": "cpu-1core", **score(topics_t, corpus_tokens),
        }

        topics_j, wall_j, loss_j = run_tpu_centralized_arm(
            train_data, val_data, k, epochs
        )
        arm_j = {
            "wall_s": round(wall_j, 2), "best_train_loss": round(loss_j, 2),
            "device": report["backend"], **score(topics_j, corpus_tokens),
        }

        topics_f, wall_f, _ = run_tpu_federated_arm(k, 1.0)
        arm_f = {
            "wall_s": round(wall_f, 2),
            "device": report["backend"],
            "note": "5 clients partitioned by fieldsOfStudy; wall includes "
                    "consensus + staging + compile",
            **score(topics_f, corpus_tokens),
        }

        report["arms"][f"k{k}"] = {
            "torch_centralized": arm_t,
            "tpu_centralized": arm_j,
            "tpu_federated": arm_f,
            "wall_speedup_tpu_vs_torch": round(wall_t / max(wall_j, 1e-9), 2),
        }

    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
