"""Soak the Pallas fused decode+loss kernel compiled on TPU at large V.

VERDICT r1 item 2: run the kernel compiled (non-interpret) at
V in {16384, 50k, 100k}, assert parity vs ``prodlda_recon_loss_reference``
on-device, measure fused vs unfused step time, and derive the auto-enable
threshold from data instead of faith (``models/avitm.py:_resolve_fused``).

Usage: python experiments_scripts/soak_fused_kernel.py [out_json]
Writes a JSON report (default ``results/fused_kernel_soak.json``) with the
timing table and a recommended threshold = the smallest tested V where the
fused path wins.
"""

from __future__ import annotations

import json
import os
import sys


def _pick_tile_v_default(v: int, b: int) -> int:
    """Tile width the kernel resolves with NO operator override (the
    baseline geometry), independent of the current env state."""
    from bench import SOAK_K
    from gfedntm_tpu.ops.fused_decoder import resolve_tile_v

    saved = os.environ.pop("GFEDNTM_FUSED_TILE_V", None)
    try:
        return resolve_tile_v(v, b, SOAK_K)
    finally:
        if saved is not None:
            os.environ["GFEDNTM_FUSED_TILE_V"] = saved


def main() -> None:
    out_path = (
        sys.argv[1] if len(sys.argv) > 1 else "results/fused_kernel_soak.json"
    )
    import jax

    if os.environ.get("FORCE_CPU"):
        # Must precede any backend query: jax.default_backend() on a dead
        # TPU tunnel blocks forever in the plugin's re-dial loop. (A
        # CPU-forced soak runs interpret-mode only — useful as a harness
        # shakeout, never as kernel evidence.)
        jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from bench import bench_fused_largev

    backend = jax.default_backend()
    # Baseline rows must be measured (and labeled) at the default tiling:
    # clear any operator-set GFEDNTM_FUSED_TILE_V for the baseline, record
    # what was cleared, and restore the operator's value when done (ADVICE
    # r3: a pre-existing override would silently relabel the baseline).
    prior_tile = os.environ.pop("GFEDNTM_FUSED_TILE_V", None)
    try:
        table = bench_fused_largev(backend, v_list=(16384, 50_000, 100_000))

        # Tile-width sweep (GFEDNTM_FUSED_TILE_V) on the cases where the
        # default 2048-wide tile historically only broke even: wider tiles
        # amortize grid overhead at the cost of more VMEM per step.
        # bench_fused_largev builds fresh jitted closures per call, so the
        # env knob takes effect per run.
        tile_sweep: dict[str, dict] = {}
        sweep_cases = [(50_000, 64), (100_000, 256)]
        from gfedntm_tpu.ops.fused_decoder import resolve_tile_v

        for tile in (4096, 8192):
            os.environ["GFEDNTM_FUSED_TILE_V"] = str(tile)
            try:
                # Skip combos where the VMEM-frontier clamp resolves the
                # requested tile back to the default geometry (large B):
                # re-benching them would just duplicate the baseline row
                # under a wider-tile label.
                from bench import SOAK_K as _soak_k
                live_cases = [
                    (v, b) for v, b in sweep_cases
                    if resolve_tile_v(v, b, _soak_k)
                    != _pick_tile_v_default(v, b)
                ]
                if live_cases:
                    tile_sweep[f"tile{tile}"] = bench_fused_largev(
                        backend, cases=live_cases
                    )
                skipped = [c for c in sweep_cases if c not in live_cases]
                if skipped:
                    tile_sweep.setdefault(f"tile{tile}", {})[
                        "skipped_clamped"
                    ] = [f"V{v}_B{b}" for v, b in skipped]
            finally:
                del os.environ["GFEDNTM_FUSED_TILE_V"]
        # bf16-storage rows (VERDICT r4 #3): beta/x streamed bf16 with f32
        # accumulation — the HBM-traffic halver. Parity is judged at the
        # quantized point; quantization_grad_delta reports the storage
        # cost (see bench._fused_case).
        bf16_table = bench_fused_largev(
            backend,
            cases=[(50_000, 64), (50_000, 256), (100_000, 64), (100_000, 256)],
            storage="bfloat16",
        )
    finally:
        if prior_tile is not None:
            os.environ["GFEDNTM_FUSED_TILE_V"] = prior_tile

    def _parse(key: str) -> tuple[int, int]:
        v, b = key[1:].split("_B")
        return int(v), int(b)

    # The auto threshold keys off V alone (models/avitm.py:_resolve_fused),
    # so derive it from the reference's production batch size (64,
    # dft_params.cf:16): smallest tested V where the fused path wins there.
    wins_b64 = [
        _parse(k)[0] for k, row in table.items()
        if _parse(k)[1] == 64 and row["parity"]
        and row["fused_ms"] < row["unfused_ms"]
    ]
    report = {
        "backend": backend,
        "baseline_tile_v": 2048,
        "cleared_operator_tile_override": prior_tile,
        "table": table,
        "tile_sweep": tile_sweep,
        "bf16_storage_table": bf16_table,
        "all_parity": all(r["parity"] for r in table.values()),
        "bf16_all_parity": all(r["parity"] for r in bf16_table.values()),
        "recommended_threshold": min(wins_b64) if wins_b64 else None,
        "threshold_rule": "min V with fused win at B=64 (reference batch)",
    }
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
