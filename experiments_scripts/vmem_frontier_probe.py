"""Compile-only probe of the fused backward kernel's Mosaic scoped-VMEM
frontier on real TPU.

The round-4 soak crashed in its tile sweep at (V=100k, B=256,
**tile=4096**: the failing HLO's v_pad was 102400 = 25x4096): the
one-pass backward (`_grads_kernel`) exceeded the 16 MB scoped-VMEM limit
at 19.17 MB. All six default-tiling table cases — including (V=100k,
B=256) at tile 2048 — had compiled and run, so the limit scales with
B x TILE, not V. This probe compiles (never runs) the fused
value_and_grad across (V, B, tile) combos and records pass/fail + the
reported scoped size, giving the data for the batch-aware tile cap in
`_pick_tile_v` (`_VMEM_TILE_ELEMS`): every b_pad*tile = 2^19 combo
compiles; 256x4096 = 2^20 does not (it either VMEM-errors, as in the
soak, or exceeds the probe's compile timeout).

Usage: python experiments_scripts/vmem_frontier_probe.py [out_json]
"""

from __future__ import annotations

import json
import os
import re
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def probe_case(v: int, b: int, tile: int) -> dict:
    import subprocess

    # Each case in a fresh process: the tile knob is read at trace time and
    # a poisoned Mosaic cache or leaked compile state must not leak across
    # cases.
    code = f"""
import os
os.environ["GFEDNTM_FUSED_TILE_V"] = "{tile}"
# Probe the RAW requested geometry: with the production VMEM-frontier
# clamp active, over-frontier combos would silently compile the clamped
# tile and report ok for a geometry that never compiled.
os.environ["GFEDNTM_FUSED_TILE_UNCLAMPED"] = "1"
import jax, jax.numpy as jnp, numpy as np
import sys
sys.path.insert(0, "{_REPO}")
from gfedntm_tpu.ops.fused_decoder import prodlda_recon_loss
K = 50
rng = np.random.default_rng(0)
theta = jnp.asarray(rng.dirichlet(np.ones(K), size={b}).astype(np.float32))
beta = jnp.asarray(rng.normal(size=(K, {v})).astype(np.float32))
x = jnp.asarray(rng.integers(0, 3, size=({b}, {v})).astype(np.float32))
mask = jnp.ones(({b},), jnp.float32)
rm, rv = jnp.zeros(({v},)), jnp.ones(({v},))
def loss(theta, beta):
    rl, _, _ = prodlda_recon_loss(theta, beta, x, rm, rv, mask, True)
    return jnp.sum(rl * mask)
f = jax.jit(jax.value_and_grad(loss, argnums=(0, 1)))
f.lower(theta, beta).compile()
print("COMPILE_OK")
"""
    try:
        r = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=420, cwd=_REPO,
        )
    except subprocess.TimeoutExpired:
        return {"ok": False, "error": "timeout"}
    ok = "COMPILE_OK" in r.stdout
    out = {"ok": ok}
    if not ok:
        m = re.search(r"size ([0-9.]+)M and limit ([0-9.]+)M", r.stderr)
        if m:
            out["scoped_mb"] = float(m.group(1))
            out["limit_mb"] = float(m.group(2))
        else:
            out["error"] = r.stderr.strip()[-400:]
    return out


def main() -> None:
    out_path = (
        sys.argv[1]
        if len(sys.argv) > 1
        else "results/vmem_frontier_probe.json"
    )
    cases = [
        # the observed frontier around the tile-4096 sweep crash: all
        # default-tiling (2048) cases compiled and ran in the soak, so
        # these first rows pin the known-good side of the frontier
        (100_000, 256, 2048),   # compiled+ran in the soak (default tiling)
        (50_000, 256, 2048),    # compiled+ran in the soak
        (16_384, 256, 2048),    # compiled+ran in the soak
        (100_000, 256, 1536),
        (100_000, 256, 1024),
        # the tile-sweep combos the soak would try next. The committed
        # artifact covers exactly this list: B256_T8192 (2x the product
        # that already fails at T4096) and B64_T2048 (the default-tiling
        # geometry the soak itself exercises at length) were dropped from
        # the original run plan as adding no frontier information.
        (50_000, 64, 4096),
        (50_000, 64, 8192),
        (100_000, 256, 4096),
    ]
    report = {}
    for v, b, tile in cases:
        key = f"V{v}_B{b}_T{tile}"
        report[key] = probe_case(v, b, tile)
        print(f"{key}: {report[key]}", flush=True)
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(report, f, indent=1)
    print(json.dumps({"probe": "done", "out": out_path}))


if __name__ == "__main__":
    main()
