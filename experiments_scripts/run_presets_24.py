"""Committed artifacts for BASELINE configs 2 and 4 (VERDICT r3 task 5).

Configs 2 (NeuralLDA, 2-client IID) and 4 (CombinedTM + contextual
embeddings, 5-client) have run inside tests since round 2
(`tests/test_presets.py`, `tests/test_federation_net.py:192-231`) but had
no committed metrics artifact the way config 5 has
`results/noniid_fos_full/`. This runs both presets at scale=1.0 and
commits, per config: the federation summary (clients, vocab, steps, final
loss), ground-truth TSS of the aggregated global model (the corpora are
synthetic, so recovery against the generator's topic_vectors is the
honest quality metric — single softmax, correct word mapping), and
topic diversity. Reference regime: CTM 5-client is the shipped default
(`/root/reference/docker-compose.yaml:21-157`).

Usage: python experiments_scripts/run_presets_24.py [out_json]
Writes results/presets_24/metrics.json (default).
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def main(out_path: str | None = None) -> dict:
    import jax

    if os.environ.get("FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from gfedntm_tpu.eval.metrics import (
        convert_topic_word_to_init_size,
        topic_diversity,
        topic_similarity_score,
    )
    from gfedntm_tpu.presets import combinedtm_5client, neurallda_2client_iid

    def softmax_rows(a):
        e = np.exp(a - a.max(axis=1, keepdims=True))
        return e / e.sum(axis=1, keepdims=True)

    def quality(res) -> dict:
        gt = res.extras["ground_truth"]
        consensus = res.extras["consensus"]
        id2token = consensus.global_vocab.id2token
        model = res.trainer.make_global_model(
            res.result, dataset=consensus.datasets[0]
        )
        beta = softmax_rows(np.asarray(model.params["beta"]))
        beta_full = convert_topic_word_to_init_size(
            gt.topic_vectors.shape[1], beta, id2token
        )
        tss = topic_similarity_score(beta_full, gt.topic_vectors)
        k = beta.shape[0]
        rand_tss = float(
            topic_similarity_score(
                np.random.default_rng(99).dirichlet(
                    np.full(gt.topic_vectors.shape[1], 0.01), k
                ),
                gt.topic_vectors,
            )
        )
        topics = model.get_topics(10)
        return {
            "tss_vs_ground_truth": round(float(tss), 4),
            "tss_max": k,
            "tss_random_floor": round(rand_tss, 4),
            "topic_diversity": round(topic_diversity(topics, topn=10), 4),
            "topics_top10": topics,
        }

    report: dict = {"backend": None, "configs": {}}
    t0 = time.perf_counter()
    res2 = neurallda_2client_iid(scale=1.0)
    report["configs"]["config2_neurallda_2client_iid"] = {
        "wall_s": round(time.perf_counter() - t0, 1),
        "summary": res2.summary,
        **quality(res2),
    }
    print("config 2 done", flush=True)

    t0 = time.perf_counter()
    res4 = combinedtm_5client(scale=1.0)
    report["configs"]["config4_combinedtm_5client"] = {
        "wall_s": round(time.perf_counter() - t0, 1),
        "summary": res4.summary,
        "embedder": "deterministic hashing stand-in, 768-d (SBERT needs "
                    "network egress; the CTM contextual path is identical)",
        **quality(res4),
    }
    report["backend"] = jax.default_backend()

    out_path = out_path or os.path.join(
        REPO_ROOT, "results", "presets_24", "metrics.json"
    )
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w", encoding="utf8") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(
        {c: {k: v for k, v in d.items() if k != "topics_top10"}
         for c, d in report["configs"].items()}, indent=2))
    return report


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else None)
