"""Regenerate the paper's figures from saved experiment artifacts.

Native equivalent of the reference's ``notebooks/graphs_paper/``:

- ``DSS_TSS``: errorbar panels of TSS (betas) and DSS (thetas) per arm
  (centralized / non-collaborative / random) against the sweep variable
  (eta, log-x; and/or number of frozen topics), read from the
  ``results.json`` files written by
  :func:`gfedntm_tpu.experiments.dss_tss.run_simulation`.
- ``Federated``: per-client + server topic summary read from the ``.npz``
  model artifacts written at federation end (betas heatmap + top words),
  schema of ``gfedntm_tpu/utils/serialization.py``.

Usage:
  python experiments_scripts/plot_paper_figures.py dss_tss OUT.png \
      --eta results/dss_tss_eta001/results.json [--frozen .../results.json]
  python experiments_scripts/plot_paper_figures.py federated OUT.png \
      MODEL1.npz [MODEL2.npz ...]
"""

from __future__ import annotations

import argparse
import json

import numpy as np

ARMS = ("centralized", "non_colab", "baseline")
LABELS = {"centralized": "Centralized", "non_colab": "Non-collaborative",
          "baseline": "Random baseline"}
COLORS = {"centralized": "tab:green", "non_colab": "tab:blue",
          "baseline": "tab:red"}


# The reference's committed pickles, for visual overlay (its TSS values
# are refmap scores — see gfedntm_tpu/experiments/dss_tss.refmap_project —
# so they are drawn against this repo's *_betas_refmap_* columns).
REF_PUBLISHED = {
    "eta": {
        "index": [0.01, 0.02, 0.03, 0.04, 0.08, 1.0],
        "centralized_betas": [8.679, 12.205, 14.747, 16.812, 22.671, 44.302],
        "non_colab_betas": [7.571, None, None, None, None, 44.302],
        "baseline_betas": [3.564, None, None, None, None, 39.660],
    },
    "frozen": {
        "index": [40, 5],
        "centralized_betas": [8.664, 8.676],
        "non_colab_betas": [8.475, 7.207],
    },
}


def _panel(ax, results: dict, stat: str, logx: bool,
           ref: dict | None = None) -> None:
    index = results["index"]
    cols = results["columns"]
    for arm in ARMS:
        # Prefer the reference-comparable refmap column when overlaying
        # the published values; fall back to the correct-map column.
        mean_key, std_key = f"{arm}_{stat}_mean", f"{arm}_{stat}_std"
        if ref is not None and f"{arm}_{stat}_refmap_mean" in cols:
            rm = cols[f"{arm}_{stat}_refmap_mean"]
            if all(v is not None for v in rm):
                mean_key = f"{arm}_{stat}_refmap_mean"
                std_key = f"{arm}_{stat}_refmap_std"
        if mean_key not in cols:
            continue
        if stat == "thetas" and arm == "baseline":
            continue  # reference omits the random arm from DSS panels
        ax.errorbar(
            index, cols[mean_key], yerr=cols.get(std_key), fmt="x-",
            label=LABELS[arm], color=COLORS[arm], ecolor="gray",
            capsize=2, lw=1,
        )
        if ref is not None and stat == "betas":
            pub = ref.get(f"{arm}_{stat}")
            if pub:
                pts = [
                    (x, y) for x, y in zip(ref["index"], pub)
                    if y is not None and x in index
                ]
                if pts:
                    ax.plot(
                        [p[0] for p in pts], [p[1] for p in pts], "o",
                        mfc="none", color=COLORS[arm], ms=7,
                        label=f"{LABELS[arm]} (reference)",
                    )
    if logx:
        ax.set_xscale("log")
    ax.set_xlabel(results.get("index_name", ""))
    ax.set_ylabel(
        "Topic similarity score" if stat == "betas"
        else "Doc similarity score"
    )
    ax.grid(True, linestyle=":")


def plot_dss_tss(out: str, eta_json: str | None, frozen_json: str | None):
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    sweeps = [
        (name, json.load(open(path)))
        for name, path in (("eta", eta_json), ("frozen", frozen_json))
        if path
    ]
    if not sweeps:
        raise SystemExit("need at least one of --eta / --frozen")
    fig, axs = plt.subplots(
        nrows=len(sweeps), ncols=2, figsize=(8, 2.8 * len(sweeps)),
        squeeze=False,
    )
    for row, (name, results) in enumerate(sweeps):
        ref = REF_PUBLISHED.get(name)
        _panel(axs[row][0], results, "betas", logx=name == "eta", ref=ref)
        _panel(axs[row][1], results, "thetas", logx=name == "eta")
    axs[0][0].legend(fontsize=8)
    fig.tight_layout()
    fig.savefig(out, dpi=300, bbox_inches="tight")
    print(f"wrote {out}")


def plot_federated(out: str, model_paths: list[str]):
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, axs = plt.subplots(
        nrows=1, ncols=len(model_paths), figsize=(3 * len(model_paths), 3),
        squeeze=False,
    )
    for i, path in enumerate(model_paths):
        data = np.load(path, allow_pickle=True)
        betas = np.asarray(data["betas"], dtype=np.float32)
        ax = axs[0][i]
        ax.imshow(betas, aspect="auto", cmap="viridis")
        ax.set_title(path.rsplit("/", 1)[-1], fontsize=8)
        ax.set_xlabel("vocabulary")
        ax.set_ylabel("topic")
        if "topics" in data and data["topics"] is not None:
            topics = data["topics"]
            try:
                first = ", ".join(list(topics[0])[:4])
                ax.text(
                    0.02, -0.35, f"t0: {first}", transform=ax.transAxes,
                    fontsize=6,
                )
            except (TypeError, IndexError):
                pass
    fig.tight_layout()
    fig.savefig(out, dpi=300, bbox_inches="tight")
    print(f"wrote {out}")


def plot_e_sweep(out: str, sweep_jsons: list[str]):
    """Exchange-period sweep (results/realtext_federated/e_sweep*.json):
    NPMI and topic diversity vs local_steps E. Two measures on different
    scales -> two panels sharing x (never a dual axis); the centralized
    ceiling is a dashed reference line; identity is carried by color AND
    linestyle/markers plus direct labels."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    points: dict[int, dict] = {}
    centralized = None
    spe = None
    for path in sweep_jsons:
        data = json.load(open(path))
        for name, arm in data["arms"].items():
            if name == "centralized":
                centralized = arm
                continue
            e_val = int(arm.get("local_steps", 0))
            if e_val:
                points[e_val] = arm
    es = sorted(points)
    fig, axs = plt.subplots(1, 2, figsize=(8, 3), sharex=True)
    for ax, metric, label in (
        (axs[0], "npmi", "NPMI coherence"),
        (axs[1], "topic_diversity", "Topic diversity (top-10)"),
    ):
        ys = [points[e][metric] for e in es]
        ax.plot(es, ys, "o-", color="tab:blue", lw=2, ms=6,
                label="federated (local_steps=E)")
        if centralized is not None:
            ax.axhline(centralized[metric], color="tab:green", ls="--",
                       lw=2, label="centralized ceiling")
        ax.axvline(47, color="gray", ls=":", lw=1)
        ax.text(47, ax.get_ylim()[1], "1 local epoch ", fontsize=7,
                color="gray", va="top", ha="right", rotation=90)
        ax.set_xscale("log", base=2)
        ax.set_xlabel("exchange period E (minibatches, log2)")
        ax.set_ylabel(label)
        ax.grid(True, linestyle=":", alpha=0.6)
    # Direct-label the parity point (the reference's algorithm) once.
    axs[0].annotate(
        "reference parity (E=1)", (es[0], points[es[0]]["npmi"]),
        textcoords="offset points", xytext=(6, 8), fontsize=7,
    )
    axs[0].legend(fontsize=8, loc="center left")
    fig.suptitle(
        "Real-text federation: FedAvg exchange period vs topic quality",
        fontsize=10,
    )
    fig.tight_layout()
    fig.savefig(out, dpi=300, bbox_inches="tight")
    print(f"wrote {out}")


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("figure", choices=["dss_tss", "federated", "e_sweep"])
    p.add_argument("out")
    p.add_argument("models", nargs="*",
                   help="npz artifacts (federated) / sweep jsons (e_sweep)")
    p.add_argument("--eta", help="eta-sweep results.json")
    p.add_argument("--frozen", help="frozen-sweep results.json")
    args = p.parse_args()
    import os

    os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
    if args.figure == "dss_tss":
        plot_dss_tss(args.out, args.eta, args.frozen)
    elif args.figure == "e_sweep":
        if not args.models:
            raise SystemExit("e_sweep figure needs sweep json paths")
        plot_e_sweep(args.out, args.models)
    else:
        if not args.models:
            raise SystemExit("federated figure needs npz model paths")
        plot_federated(args.out, args.models)


if __name__ == "__main__":
    main()
