"""Regenerate the paper's figures from saved experiment artifacts.

Native equivalent of the reference's ``notebooks/graphs_paper/``:

- ``DSS_TSS``: errorbar panels of TSS (betas) and DSS (thetas) per arm
  (centralized / non-collaborative / random) against the sweep variable
  (eta, log-x; and/or number of frozen topics), read from the
  ``results.json`` files written by
  :func:`gfedntm_tpu.experiments.dss_tss.run_simulation`.
- ``Federated``: per-client + server topic summary read from the ``.npz``
  model artifacts written at federation end (betas heatmap + top words),
  schema of ``gfedntm_tpu/utils/serialization.py``.

Usage:
  python experiments_scripts/plot_paper_figures.py dss_tss OUT.png \
      --eta results/dss_tss_eta001/results.json [--frozen .../results.json]
  python experiments_scripts/plot_paper_figures.py federated OUT.png \
      MODEL1.npz [MODEL2.npz ...]
"""

from __future__ import annotations

import argparse
import json

import numpy as np

ARMS = ("centralized", "non_colab", "baseline")
LABELS = {"centralized": "Centralized", "non_colab": "Non-collaborative",
          "baseline": "Random baseline"}
COLORS = {"centralized": "tab:green", "non_colab": "tab:blue",
          "baseline": "tab:red"}


def _panel(ax, results: dict, stat: str, logx: bool) -> None:
    index = results["index"]
    cols = results["columns"]
    for arm in ARMS:
        mean_key, std_key = f"{arm}_{stat}_mean", f"{arm}_{stat}_std"
        if mean_key not in cols:
            continue
        if stat == "thetas" and arm == "baseline":
            continue  # reference omits the random arm from DSS panels
        ax.errorbar(
            index, cols[mean_key], yerr=cols[std_key], fmt="x-",
            label=LABELS[arm], color=COLORS[arm], ecolor="gray",
            capsize=2, lw=1,
        )
    if logx:
        ax.set_xscale("log")
    ax.set_xlabel(results.get("index_name", ""))
    ax.set_ylabel(
        "Topic similarity score" if stat == "betas"
        else "Doc similarity score"
    )
    ax.grid(True, linestyle=":")


def plot_dss_tss(out: str, eta_json: str | None, frozen_json: str | None):
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    sweeps = [
        (name, json.load(open(path)))
        for name, path in (("eta", eta_json), ("frozen", frozen_json))
        if path
    ]
    if not sweeps:
        raise SystemExit("need at least one of --eta / --frozen")
    fig, axs = plt.subplots(
        nrows=len(sweeps), ncols=2, figsize=(8, 2.8 * len(sweeps)),
        squeeze=False,
    )
    for row, (name, results) in enumerate(sweeps):
        _panel(axs[row][0], results, "betas", logx=name == "eta")
        _panel(axs[row][1], results, "thetas", logx=name == "eta")
    axs[0][0].legend(fontsize=8)
    fig.tight_layout()
    fig.savefig(out, dpi=300, bbox_inches="tight")
    print(f"wrote {out}")


def plot_federated(out: str, model_paths: list[str]):
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, axs = plt.subplots(
        nrows=1, ncols=len(model_paths), figsize=(3 * len(model_paths), 3),
        squeeze=False,
    )
    for i, path in enumerate(model_paths):
        data = np.load(path, allow_pickle=True)
        betas = np.asarray(data["betas"], dtype=np.float32)
        ax = axs[0][i]
        ax.imshow(betas, aspect="auto", cmap="viridis")
        ax.set_title(path.rsplit("/", 1)[-1], fontsize=8)
        ax.set_xlabel("vocabulary")
        ax.set_ylabel("topic")
        if "topics" in data and data["topics"] is not None:
            topics = data["topics"]
            try:
                first = ", ".join(list(topics[0])[:4])
                ax.text(
                    0.02, -0.35, f"t0: {first}", transform=ax.transAxes,
                    fontsize=6,
                )
            except (TypeError, IndexError):
                pass
    fig.tight_layout()
    fig.savefig(out, dpi=300, bbox_inches="tight")
    print(f"wrote {out}")


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("figure", choices=["dss_tss", "federated"])
    p.add_argument("out")
    p.add_argument("models", nargs="*", help="npz artifacts (federated)")
    p.add_argument("--eta", help="eta-sweep results.json")
    p.add_argument("--frozen", help="frozen-sweep results.json")
    args = p.parse_args()
    import os

    os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
    if args.figure == "dss_tss":
        plot_dss_tss(args.out, args.eta, args.frozen)
    else:
        if not args.models:
            raise SystemExit("federated figure needs npz model paths")
        plot_federated(args.out, args.models)


if __name__ == "__main__":
    main()
