"""Non-collab divergence probe: reference torch AVITM vs this framework's
AVITM on the SAME node corpus, scored under BOTH word mappings.

VERDICT r3 task 2: the committed DSS/TSS envelope's non-collaborative arm
sits +0.7-1.1 TSS above the reference's published pickles (~15 sigma) and the
frozen=40 ordering is inverted. Two candidate causes were identified by
code diff and this probe adjudicates them on live runs:

1. **Regime mismatch (eta sweep only)**: the reference's eta sweep runs
   with ``frozen_topics = frozen_topics_list[1] = 10``
   (`run_simulation.py:694-696`), not the config.json's ``frozen_topics=5``
   this repo's SimulationConfig defaulted to. A pure-numpy check already
   confirms this explains the baseline arm exactly (frozen=10 random-theta
   DSS = 833.7 vs the reference's published 834.6 +/- 4.5; frozen=5 gives
   765.2 vs this repo's committed 764.9).

2. **Reference scoring off-by-one**: the reference generates words
   ``'wd'+str(word)`` with ``word`` drawn in [0, V)
   (`run_simulation.py:170-179` -> wd0..wd4999) but scores against
   ``all_words = ['wd'+str(w) for w in arange(V+1) if w > 0]`` = wd1..wd5000
   (`run_simulation.py:433-436`), so its
   ``convert_topic_word_to_init_size`` (`run_simulation.py:225-268`) places
   word id N's probability in full-vocab column N-1 and silently drops
   wd0's mass before L1-renormalizing. Every reference TSS number is
   computed on betas misaligned by one column; the penalty grows as eta
   shrinks (sparser topics), matching the observed divergence profile
   (+0.195 at eta=0.01, +0.04 at 0.02, ~0 at 1.0 on the centralized arm).

This script trains one non-collab node model with the UNMODIFIED reference
implementation (imported from /root/reference, not copied) and one with
this framework, on the same node-0 corpus, and scores both with (a) the
correct 0-based mapping and (b) the reference's shifted mapping. If the
two implementations agree under each mapping while (a) vs (b) reproduces
the published gap, the divergence is fully attributed to the reference's
scoring bug + the regime mismatch, and the corrected-regime sweep can pin
non-collab bands against refmap scores.

Usage: python experiments_scripts/noncollab_probe.py [out_json]
Writes results/noncollab_probe/probe.json (default). Runtime: ~10-20 min
on one CPU core (two 7.5k-doc AVITM fits with early stopping).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time

REFERENCE_ROOT = "/root/reference"
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FROZEN = 5          # matched regime: reference frozen-sweep row 5
ETA = 0.01
SEED = 123


def double_softmax(betas):
    """The reference applies softmax on top of the already-softmaxed
    topic-word distribution (`run_simulation.py:428-429`)."""
    import numpy as np

    e = np.exp(betas - betas.max(axis=1, keepdims=True))
    return e / e.sum(axis=1, keepdims=True)


def score_both(betas_model_vocab, id2token, thetas_inf, cfg_vocab,
               topic_vectors, inf_doc_topics):
    # The probe validates the sweep's refmap numbers, so it must use the
    # sweep's own projection — not a private copy that could drift.
    from gfedntm_tpu.experiments.dss_tss import refmap_project
    from gfedntm_tpu.eval.metrics import (
        convert_topic_word_to_init_size,
        document_similarity_score,
        topic_similarity_score,
    )

    b = double_softmax(betas_model_vocab)
    correct = convert_topic_word_to_init_size(cfg_vocab, b, id2token)
    shifted = refmap_project(b, id2token, cfg_vocab)
    return {
        "tss_correct_map": topic_similarity_score(correct, topic_vectors),
        "tss_ref_map": topic_similarity_score(shifted, topic_vectors),
        "dss": document_similarity_score(thetas_inf, inf_doc_topics),
    }


def main(out_path: str | None = None) -> dict:
    logging.basicConfig(level=logging.INFO, force=True)
    sys.path.insert(0, REPO_ROOT)
    sys.path.insert(0, REFERENCE_ROOT)
    # Force the CPU backend: the axon TPU tunnel hangs device calls
    # indefinitely when down (JAX_PLATFORMS env alone is overridden by the
    # axon sitecustomize; the config update is authoritative). The probe
    # compares training *semantics*, so the backend is irrelevant.
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    if not hasattr(np, "Inf"):  # numpy-2 shim for reference pytorchtools
        np.Inf = np.inf

    from gfedntm_tpu.data.synthetic import generate_synthetic_corpus
    from gfedntm_tpu.experiments.dss_tss import (
        SimulationConfig,
        _train_avitm,
    )
    from gfedntm_tpu.data.datasets import BowDataset
    from gfedntm_tpu.data.vocab import vectorize

    cfg = SimulationConfig(frozen_topics=FROZEN, beta=ETA, seed=SEED)
    t0 = time.time()
    docs_per_node = cfg.n_docs + cfg.n_docs_global_inf
    corpus = generate_synthetic_corpus(
        vocab_size=cfg.vocab_size, n_topics=cfg.n_topics, beta=cfg.beta,
        alpha=cfg.alpha, n_docs=docs_per_node, nwords=cfg.nwords,
        n_nodes=cfg.n_nodes, frozen_topics=cfg.frozen_topics, seed=SEED,
    )
    node0_docs = corpus.nodes[0].documents[: cfg.n_docs]
    inf_docs = [
        d for node in corpus.nodes
        for d in node.documents[cfg.n_docs: docs_per_node]
    ]
    inf_doc_topics = np.concatenate(
        [n.doc_topics[cfg.n_docs: docs_per_node] for n in corpus.nodes]
    )
    gen_s = time.time() - t0
    out: dict = {
        "regime": {"frozen_topics": FROZEN, "eta": ETA, "seed": SEED,
                   "n_docs": cfg.n_docs, "vocab": cfg.vocab_size,
                   "k": cfg.n_topics, "gen_s": round(gen_s, 1)},
        "reference_published": {
            "noncollab_tss_frozen5": {"mean": 7.207, "std": 0.058},
            "noncollab_tss_eta001_frozen10": {"mean": 7.571, "std": 0.048},
            "source": "BASELINE.md rows frozen_variable/eta_variable",
        },
    }

    # --- Arm A: unmodified reference implementation -----------------------
    t0 = time.time()
    from torch_baseline import make_reference_avitm
    from src.models.base.pytorchavitm.datasets.bow_dataset import BOWDataset
    from src.models.base.pytorchavitm.utils.data_preparation import (
        prepare_dataset as torch_prepare_dataset,
    )
    import torch

    torch.manual_seed(SEED)
    docs_tok = [d.split() for d in node0_docs]
    train_data, val_data, input_size, id2token, _docs, cv = \
        torch_prepare_dataset(docs_tok)
    model = make_reference_avitm(
        input_size=input_size, n_components=cfg.n_topics, num_epochs=100,
    )
    model.fit(train_data, val_data)
    epochs_ran_torch = model.nn_epoch + 1
    betas_t = model.get_topic_word_distribution()

    docs_val_conv = [" ".join(d.split()) for d in inf_docs]
    val_bow = cv.transform(docs_val_conv).toarray()
    thetas_t = np.asarray(model.get_doc_topic_distribution(
        BOWDataset(val_bow, train_data.idx2token)))
    out["torch_reference"] = {
        **score_both(betas_t, id2token, thetas_t, cfg.vocab_size,
                     corpus.topic_vectors, inf_doc_topics),
        "epochs_ran": int(epochs_ran_torch),
        "fit_s": round(time.time() - t0, 1),
    }
    print("torch arm:", out["torch_reference"], flush=True)

    # --- Arm B: this framework --------------------------------------------
    t0 = time.time()
    jmodel, vocab, jid2token = _train_avitm(node0_docs, cfg, SEED + 1)
    inf_bow = vectorize(inf_docs, vocab)
    thetas_j = jmodel.get_doc_topic_distribution(
        BowDataset(X=inf_bow, idx2token=jid2token))
    betas_j = jmodel.get_topic_word_distribution()
    out["gfedntm_tpu"] = {
        **score_both(betas_j, jid2token, thetas_j, cfg.vocab_size,
                     corpus.topic_vectors, inf_doc_topics),
        "epochs_ran": int(jmodel.nn_epoch + 1)
        if jmodel.nn_epoch is not None else None,
        "fit_s": round(time.time() - t0, 1),
    }
    print("jax arm:", out["gfedntm_tpu"], flush=True)

    out_path = out_path or os.path.join(
        REPO_ROOT, "results", "noncollab_probe", "probe.json")
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w", encoding="utf8") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out, indent=2))
    return out


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else None)
