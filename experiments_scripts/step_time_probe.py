"""Attribute the federated bench-regime per-step cost (VERDICT r3 task 4).

The round-2 TPU bench measured ~47 ms per global step for ~1 GFLOP of
matmul — three orders of magnitude off the chip's peak, i.e. the step is
overhead-dominated, not math-dominated. The whole run is ONE jitted
``lax.scan`` (federated/trainer.py:142-146), so the overhead is *inside*
the compiled program: candidate costs are the threefry RNG streams
(3 fold_ins + dropout/reparam draws per client per step), the per-step
``jnp.take`` corpus gather, f32 (vs bf16) matmuls, and the FedAvg
psum/broadcast exchange.

This probe times the SAME bench regime (V=5000, K=50, B=64, C=5,
20 epochs) under ablations, each as its own freshly-compiled program:

- ``baseline``     bench configuration exactly;
- ``bf16``         compute_dtype="bfloat16" (MXU at 2x f32 rate);
- ``no_dropout``   dropout=0.0 (removes 2 dropout mask draws/client/step);
- ``no_exchange``  grads_to_share=() (FedAvg mix becomes identity: no
                   psum, no broadcast — isolates the exchange cost);
- ``bf16_nodrop``  both (the compounding check).

Timing discipline matches bench.py: warm fit to compile + stage, then a
timed fit whose ``program_segment`` phase isolates the compiled program
from host schedule building. Reference framing: the reference's per-step
cost is pure orchestration (server.py:417-420 sleeps); ours must be pure
compute — this artifact says what it actually is.

Usage: python experiments_scripts/step_time_probe.py [out_json]
"""

from __future__ import annotations

import json
import os
import sys
import time


def run_variant(name: str, *, dropout=0.2, compute_dtype="float32",
                grads_to_share=None) -> dict:
    import jax
    import numpy as np

    from gfedntm_tpu.config import SHARE_ALL
    from gfedntm_tpu.data.datasets import BowDataset
    from gfedntm_tpu.data.synthetic import generate_synthetic_corpus
    from gfedntm_tpu.federated.trainer import FederatedTrainer
    from gfedntm_tpu.models.avitm import AVITM
    from gfedntm_tpu.utils.observability import MetricsLogger

    n_clients, vocab, k, batch, epochs = 5, 5000, 50, 64, 20
    corpus = generate_synthetic_corpus(
        vocab_size=vocab, n_topics=k, n_docs=2000, nwords=(150, 250),
        n_nodes=n_clients, frozen_topics=5, seed=0, materialize_docs=False,
    )
    idx2token = {i: f"wd{i}" for i in range(vocab)}
    datasets = [
        BowDataset(X=node.bow, idx2token=idx2token) for node in corpus.nodes
    ]

    template = AVITM(
        input_size=vocab, n_components=k, hidden_sizes=(50, 50),
        batch_size=batch, num_epochs=epochs, lr=2e-3, momentum=0.99,
        seed=0, dropout=dropout, compute_dtype=compute_dtype,
    )
    trainer = FederatedTrainer(
        template, n_clients=n_clients,
        grads_to_share=tuple(grads_to_share)
        if grads_to_share is not None else SHARE_ALL,
    )

    metrics = MetricsLogger(None)
    t0 = time.perf_counter()
    warm = trainer.fit(datasets, metrics=metrics)
    jax.block_until_ready(warm.client_params)
    compile_s = time.perf_counter() - t0
    assert np.isfinite(warm.losses).all(), f"{name}: non-finite losses"

    n_before = len(metrics.events("phase"))
    t0 = time.perf_counter()
    result = trainer.fit(datasets, metrics=metrics)
    jax.block_until_ready(result.client_params)
    steady_s = time.perf_counter() - t0
    phases = metrics.events("phase")[n_before:]
    program_s = sum(
        r["seconds"] for r in phases if r["phase"] == "program_segment"
    )
    schedule_s = sum(
        r["seconds"] for r in phases if r["phase"] == "build_schedules"
    )
    steps = int(result.losses.shape[0])
    return {
        "steps": steps,
        # Cache-hit compiles (supervisor sets JAX_COMPILATION_CACHE_DIR)
        # measure deserialization, not compilation; see the report-level
        # compilation_cache_dir field.
        "compile_and_first_run_s": round(compile_s, 2),
        "steady_s": round(steady_s, 3),
        "program_ms_per_step": round(program_s / steps * 1e3, 3),
        "steady_ms_per_step": round(steady_s / steps * 1e3, 3),
        "schedule_s": round(schedule_s, 3),
        "docs_per_s": round(steps * n_clients * batch / steady_s, 1),
        "final_mean_loss": float(result.losses[-1].mean()),
    }


def main() -> None:
    out_path = (
        sys.argv[1] if len(sys.argv) > 1 else "results/step_time_probe.json"
    )
    import jax

    if os.environ.get("FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    backend = jax.default_backend()

    variants = {
        "baseline": {},
        "bf16": {"compute_dtype": "bfloat16"},
        "no_dropout": {"dropout": 0.0},
        "no_exchange": {"grads_to_share": ()},
        "bf16_nodrop": {"compute_dtype": "bfloat16", "dropout": 0.0},
    }
    report = {
        "backend": backend,
        "regime": "V=5000 K=50 B=64 C=5 epochs=20 (bench regime)",
        "compilation_cache_dir": os.environ.get("JAX_COMPILATION_CACHE_DIR"),
        "variants": {},
    }
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)

    def _flush():
        # Incremental write after every variant: a failing variant (or a
        # supervisor stall-kill) must not lose the measurements already
        # taken — same lesson as bench_fused_largev's per-case capture.
        base = report["variants"].get("baseline", {}).get(
            "program_ms_per_step"
        )
        if base is not None:
            report["attribution_ms"] = {
                name: round(base - v["program_ms_per_step"], 3)
                for name, v in report["variants"].items()
                if name != "baseline" and "program_ms_per_step" in v
            }
        with open(out_path, "w") as f:
            json.dump(report, f, indent=1)

    for name, kw in variants.items():
        print(f"[probe] {name} ...", flush=True)
        try:
            report["variants"][name] = run_variant(name, **kw)
        except Exception as err:  # noqa: BLE001 — record, keep probing
            report["variants"][name] = {
                "error": f"{type(err).__name__}: {err}"[:600]
            }
        print(f"[probe] {name}: {report['variants'][name]}", flush=True)
        _flush()
    print(json.dumps({"probe": "done", "out": out_path}))


if __name__ == "__main__":
    main()
