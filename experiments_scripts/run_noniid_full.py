"""Full-scale real-corpus federated run with committed metrics.

VERDICT r2 task 6: `noniid_fos_5client` at scale=1.0 end-to-end —
vocabulary consensus over the 5 fieldsOfStudy partitions of the
reference's in-repo ``s2cs_tiny.parquet``, SPMD federated fit (100
epochs, the reference's `dft_params.cf` regime), then NPMI coherence /
topic diversity / inverted RBO of the aggregated global model (the
`collab_vs_non_collab/train.py:22-101` metric set, computed natively).
Round 2 only ever ran this inside a test at scale=0.3 with no committed
artifact.

Usage: python experiments_scripts/run_noniid_full.py [out_json]
Writes ``results/noniid_fos_full/metrics.json``.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def main() -> None:
    out_path = (
        sys.argv[1] if len(sys.argv) > 1
        else os.path.join(REPO_ROOT, "results/noniid_fos_full/metrics.json")
    )
    import jax

    if os.environ.get("FORCE_CPU"):
        # Must precede any backend query: jax.default_backend() on a dead
        # TPU tunnel blocks forever in the plugin's re-dial loop.
        jax.config.update("jax_platforms", "cpu")

    from gfedntm_tpu.presets import noniid_fos_5client

    t0 = time.perf_counter()
    res = noniid_fos_5client(scale=1.0, compute_metrics=True)
    wall = time.perf_counter() - t0

    report = {
        "preset": "noniid_fos_5client",
        "scale": 1.0,
        "backend": jax.default_backend(),
        "wall_s": round(wall, 1),
        "summary": {
            k: v for k, v in res.summary.items() if k != "topics"
        },
        "topics_top10": res.extras.get("topics"),
    }
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2, default=float)
    print(json.dumps(report, indent=2, default=float))


if __name__ == "__main__":
    main()
