"""Healthy real-text federated run on the offline docstring corpus
(VERDICT r4 #6).

The only real-text corpus committed so far was the reference's 334-doc
s2cs_tiny fixture — starved (66 docs/client), NPMI -0.42, junk topics. This
run uses the site-packages docstring corpus
(``gfedntm_tpu/data/local_corpus.py``): ~15k real English technical
documents, 5 clients partitioned by package family (math / deep learning /
cloud RPC / NLP / data analysis) — the same one-client-per-field non-IID
shape as the reference's docker-compose federation
(``/root/reference/docker-compose.yaml:21-149``).

Arms: centralized (context ceiling), federated parity (per-minibatch
FedAvg, the reference algorithm), and federated local_steps at 1-epoch
and 5-epoch exchange periods (the opt-in FedAvg-proper fix) — all scored
with NPMI / topic diversity / inverted RBO against the pooled corpus,
plus top-10 topics in real words.

Usage: python experiments_scripts/run_realtext_federated.py [out_json]
Writes results/realtext_federated/metrics.json (default).
REALTEXT_SCALE=0.1 shrinks docs/epochs for a smoke run; REALTEXT_EPOCHS
overrides the epoch count independently of the corpus scale (the CPU
fallback uses full docs with fewer epochs).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

TOPN = 10


def main() -> None:
    out_path = (
        sys.argv[1] if len(sys.argv) > 1
        else os.path.join(REPO_ROOT, "results/realtext_federated/metrics.json")
    )
    logging.basicConfig(level=logging.WARNING)
    scale = float(os.environ.get("REALTEXT_SCALE", "1.0"))
    seed = int(os.environ.get("REALTEXT_SEED", "0"))

    import jax

    if os.environ.get("FORCE_CPU"):
        # Must precede any backend query (dead-tunnel hang; see bench.py).
        jax.config.update("jax_platforms", "cpu")
    backend = jax.default_backend()

    import numpy as np

    from gfedntm_tpu.data.loaders import RawCorpus
    from gfedntm_tpu.data.local_corpus import (
        DocstringCorpusConfig,
        build_docstring_corpus,
    )
    from gfedntm_tpu.data.preproc import (
        PreprocConfig,
        load_wordlist,
        preprocess_corpus,
    )
    from gfedntm_tpu.eval.metrics import (
        inverted_rbo,
        npmi_coherence,
        topic_diversity,
    )
    from gfedntm_tpu.federated.consensus import run_vocab_consensus
    from gfedntm_tpu.federated.trainer import FederatedTrainer
    from gfedntm_tpu.models.avitm import AVITM

    # ---- corpus ---------------------------------------------------------
    t0 = time.perf_counter()
    clients_raw, info = build_docstring_corpus(
        DocstringCorpusConfig(
            docs_per_client=max(200, int(3000 * scale)), seed=seed,
        )
    )
    extract_s = time.perf_counter() - t0

    # Shared preprocessing over the POOLED corpus (one df table — the same
    # filtered vocabulary for every client), then split back per client.
    stop = load_wordlist(
        os.path.join(REPO_ROOT, "wordlists", "english_generic.json")
    )
    pooled = [d for c in clients_raw for d in c.documents]
    bounds = np.cumsum([0] + [len(c.documents) for c in clients_raw])
    prep = preprocess_corpus(
        pooled,
        PreprocConfig(
            min_lemas=15, no_below=20, no_above=0.3, keep_n=10_000,
            stopwords=stop,
        ),
    )
    docs_by_client: list[list[str]] = [[] for _ in clients_raw]
    for pos, idx in enumerate(prep.kept_indices):
        client = int(np.searchsorted(bounds, idx, side="right") - 1)
        docs_by_client[client].append(" ".join(prep.docs[pos]))
    clients = [RawCorpus(documents=d) for d in docs_by_client]
    corpus_tokens = [list(d) for d in prep.docs]
    prep_s = time.perf_counter() - t0 - extract_s

    names = list(info["per_client"].keys())
    report: dict = {
        "backend": backend,
        "seed": seed,
        "corpus": {
            "source": "site-packages docstrings (offline; "
                      "data/local_corpus.py)",
            "clients": {
                n: len(c.documents) for n, c in zip(names, clients)
            },
            "n_docs_after_prep": len(prep.docs),
            "vocab_after_prep": len(prep.vocabulary),
            "extract_s": round(extract_s, 1),
            "preproc_s": round(prep_s, 1),
            "extraction_info": info["per_client"],
        },
        "arms": {},
    }
    epochs = int(
        os.environ.get("REALTEXT_EPOCHS", str(max(3, int(100 * scale))))
    )
    K = 50

    def score(topics):
        return {
            "npmi": round(npmi_coherence(topics, corpus_tokens, topn=TOPN), 4),
            "topic_diversity": round(topic_diversity(topics, topn=TOPN), 4),
            "inverted_rbo": round(inverted_rbo(topics, topn=TOPN), 4),
        }

    # ---- consensus + federated arms ------------------------------------
    consensus = run_vocab_consensus(clients, max_features=10_000)
    V = len(consensus.global_vocab)
    report["corpus"]["consensus_vocab"] = V
    steps_per_epoch = max(
        1, -(-max(len(d) for d in consensus.datasets) // 64)
    )

    # REALTEXT_ARMS: comma-list of exchange periods E (in minibatches) to
    # sweep; default = parity, one local epoch, five local epochs.
    arms_env = os.environ.get("REALTEXT_ARMS")
    if arms_env:
        arm_list = []
        for e_str in arms_env.split(","):
            e_val = int(e_str)
            name = (
                "federated_parity" if e_val == 1
                else f"federated_local_steps_E{e_val}"
            )
            arm_list.append((name, e_val))
    else:
        arm_list = [
            ("federated_parity", 1),
            ("federated_local_steps", steps_per_epoch),
            ("federated_local_steps_5ep", 5 * steps_per_epoch),
        ]
    for arm_name, local_steps in arm_list:
        template = AVITM(
            input_size=V, n_components=K, hidden_sizes=(50, 50),
            batch_size=64, num_epochs=epochs, lr=2e-3, momentum=0.99,
            seed=seed,
        )
        trainer = FederatedTrainer(
            template, n_clients=len(clients), local_steps=local_steps,
            seed=seed,
        )
        t0 = time.perf_counter()
        result = trainer.fit(consensus.datasets)
        wall = time.perf_counter() - t0
        gm = trainer.make_global_model(result, dataset=consensus.datasets[0])
        topics = gm.get_topics(TOPN)
        report["arms"][arm_name] = {
            "local_steps": local_steps,
            "wall_s": round(wall, 1),
            "global_steps": int(result.losses.shape[0]),
            "final_mean_loss": float(result.losses[-1].mean()),
            **score(topics),
            "topics_top10": topics,
        }
        print(arm_name, json.dumps(report["arms"][arm_name])[:300],
              flush=True)

    # ---- centralized context arm ----------------------------------------
    from gfedntm_tpu.data.preparation import prepare_dataset

    union_docs = [d for c in clients for d in c.documents]
    train_data, val_data, input_size, id2token, _, _ = prepare_dataset(
        union_docs
    )
    model = AVITM(
        input_size=input_size, n_components=K, hidden_sizes=(50, 50),
        batch_size=64, num_epochs=epochs, lr=2e-3, momentum=0.99, seed=seed,
    )
    t0 = time.perf_counter()
    model.fit(train_data, val_data)
    wall = time.perf_counter() - t0
    topics_c = model.get_topics(TOPN)
    report["arms"]["centralized"] = {
        "wall_s": round(wall, 1),
        **score(topics_c),
        "topics_top10": topics_c,
    }

    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(
        {k: (v if k != "arms" else {
            a: {kk: vv for kk, vv in arm.items() if kk != "topics_top10"}
            for a, arm in v.items()
        }) for k, v in report.items()}, indent=2))


if __name__ == "__main__":
    main()
