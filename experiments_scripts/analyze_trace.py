"""Summarize a jax.profiler trace: where does the step time go?

VERDICT r3 task 1c: the committed TPU trace must come with an accounting
of the ~per-step milliseconds. This reads the TensorBoard-format trace
(`plugins/profile/<run>/*.trace.json.gz`, Chrome trace events) written by
``jax.profiler.trace`` (bench.py wires it via BENCH_TRACE_DIR /
results/profile_trace) and aggregates wall time by event name, separating
device compute streams from host threads, so the top entries answer
"dispatch overhead or math?" directly.

Usage: python experiments_scripts/analyze_trace.py <trace_dir> [top_n]
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os
import sys


def load_events(trace_dir: str) -> tuple[list[dict], dict]:
    paths = glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True
    ) + glob.glob(os.path.join(trace_dir, "**", "*.trace.json"),
                  recursive=True)
    if not paths:
        raise SystemExit(f"no trace files under {trace_dir}")
    path = max(paths, key=os.path.getsize)
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", data if isinstance(data, list) else [])
    # pid -> process name (device streams vs host threads)
    pids = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pids[e["pid"]] = e.get("args", {}).get("name", str(e["pid"]))
    return events, pids


def summarize(trace_dir: str, top_n: int = 20) -> dict:
    events, pids = load_events(trace_dir)
    by_bucket: dict[str, collections.Counter] = collections.defaultdict(
        collections.Counter
    )
    span = [float("inf"), 0.0]
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        pname = pids.get(e.get("pid"), "?").lower()
        bucket = (
            "device"
            if any(s in pname for s in ("tpu", "gpu", "stream", "xla", "/device"))
            else "host"
        )
        by_bucket[bucket][e.get("name", "?")] += e["dur"]
        ts = e.get("ts", 0.0)
        span[0] = min(span[0], ts)
        span[1] = max(span[1], ts + e["dur"])
    out = {
        "trace_dir": trace_dir,
        "wall_span_ms": round((span[1] - span[0]) / 1e3, 3),
        "processes": sorted(set(pids.values())),
    }
    for bucket, counter in by_bucket.items():
        total = sum(counter.values())
        out[bucket] = {
            "total_ms": round(total / 1e3, 3),
            "top": [
                {"name": n[:120], "ms": round(d / 1e3, 3),
                 "pct": round(100.0 * d / max(total, 1), 1)}
                for n, d in counter.most_common(top_n)
            ],
        }
    return out


if __name__ == "__main__":
    trace_dir = sys.argv[1] if len(sys.argv) > 1 else "results/profile_trace"
    top_n = int(sys.argv[2]) if len(sys.argv) > 2 else 20
    print(json.dumps(summarize(trace_dir, top_n), indent=2))
