"""Measured compute baseline: the reference's own PyTorch AVITM.

VERDICT r2 task 2: the round-1/2 bench compared only against the
reference's >=3 s-sleep orchestration floor (21.3 docs/s for 5 clients) —
"beating a sleep is not matching-or-beating on perf". This script runs the
reference implementation itself (`/root/reference/src/models/base/
pytorchavitm/avitm_network/avitm.py:323-443`, imported, not copied) on the
*same* synthetic regime as `bench.py` and records measured docs/s, so
`vs_torch_cpu` in the bench is a ratio of two measurements on this host.

Regime match (bench.py `run()`): V=5000, K=50, hidden (50,50), batch 64,
Adam(lr 2e-3, beta1=0.99), 5x2000 docs trained centrally (the reference's
federated path adds the gRPC/sleep orchestration on top of exactly this
compute, so centralized torch is its compute-only best case).

Timing is `_train_epoch` only — the same boundary the bench's steady-state
fit measures (no MC doc-topic inference pass on either side).

Usage: python experiments_scripts/torch_baseline.py [out_json] [epochs]
Writes ``results/torch_baseline.json`` (default).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time

REFERENCE_ROOT = "/root/reference"


def make_reference_avitm(
    input_size: int,
    n_components: int,
    num_epochs: int,
    hidden_sizes: tuple[int, ...] = (100, 100),
    logger_name: str = "torch-avitm",
    **overrides,
):
    """Construct the UNMODIFIED reference AVITM with its experiment-regime
    defaults (`run_simulation.py:271-318` / dft_params.cf): prodLDA,
    softplus, dropout 0.2, batch 64, Adam(lr 2e-3, beta1 0.99), 20 theta
    samples. Every script that drives the reference as a baseline
    (torch_baseline, noncollab_probe, parity_vs_torch, time_to_quality)
    builds it HERE so the arms can never silently drift to different
    regimes. Also installs the sys.path + numpy-2 shims the reference
    needs."""
    sys.path.insert(0, REFERENCE_ROOT)
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import numpy as np

    # The reference targets numpy<2 (`np.Inf` in pytorchtools.py:26); shim
    # the removed alias so the unmodified reference runs under numpy 2.
    if not hasattr(np, "Inf"):
        np.Inf = np.inf

    from src.models.base.pytorchavitm.avitm_network.avitm import AVITM

    kwargs = dict(
        logger=logging.getLogger(logger_name), input_size=input_size,
        n_components=n_components, model_type="prodLDA",
        hidden_sizes=tuple(hidden_sizes), activation="softplus",
        dropout=0.2, learn_priors=True, batch_size=64, lr=2e-3,
        momentum=0.99, solver="adam", num_epochs=num_epochs,
        reduce_on_plateau=False, topic_prior_mean=0.0,
        topic_prior_variance=None, num_samples=20,
        num_data_loader_workers=0, verbose=False,
    )
    kwargs.update(overrides)
    return AVITM(**kwargs)


class _LocalTorchAVITM:
    """Reference-equivalent torch AVITM for hosts without /root/reference.

    Same architecture and per-doc compute profile as the reference
    (prodLDA: V -> softplus MLP encoder -> K-dim mu/logvar heads with
    BatchNorm, reparameterized softmax theta -> BN'd beta decode -> V
    log-softmax; KL + reconstruction loss; Adam(lr 2e-3, beta1 0.99)),
    written independently so the live torch-CPU baseline can still be
    MEASURED when the reference checkout is absent (this container).
    Only ``_train_epoch(loader)`` is implemented — the exact boundary
    ``run_torch_baseline`` times."""

    def __init__(self, input_size, n_components, hidden_sizes=(50, 50),
                 dropout=0.2, lr=2e-3, beta1=0.99):
        import torch
        from torch import nn

        layers, prev = [], input_size
        for h in hidden_sizes:
            layers += [nn.Linear(prev, h), nn.Softplus()]
            prev = h
        self.encoder = nn.Sequential(*layers, nn.Dropout(dropout))
        self.f_mu = nn.Linear(prev, n_components)
        self.f_mu_bn = nn.BatchNorm1d(n_components, affine=False)
        self.f_sigma = nn.Linear(prev, n_components)
        self.f_sigma_bn = nn.BatchNorm1d(n_components, affine=False)
        self.beta = nn.Parameter(
            torch.empty(n_components, input_size)
        )
        nn.init.xavier_uniform_(self.beta)
        self.beta_bn = nn.BatchNorm1d(input_size, affine=False)
        self.drop_theta = nn.Dropout(dropout)
        self.prior_mean = nn.Parameter(torch.zeros(n_components))
        self.prior_var = nn.Parameter(
            torch.full((n_components,), 1.0 - 1.0 / n_components)
        )
        params = (
            list(self.encoder.parameters()) + list(self.f_mu.parameters())
            + list(self.f_sigma.parameters())
            + [self.beta, self.prior_mean, self.prior_var]
        )
        self._modules_with_state = [
            self.encoder, self.f_mu_bn, self.f_sigma_bn, self.beta_bn,
            self.drop_theta,
        ]
        self.optimizer = torch.optim.Adam(
            params, lr=lr, betas=(beta1, 0.999)
        )

    def _loss(self, x):
        import torch

        h = self.encoder(x)
        mu = self.f_mu_bn(self.f_mu(h))
        log_var = self.f_sigma_bn(self.f_sigma(h))
        eps = torch.randn_like(mu)
        theta = torch.softmax(mu + eps * torch.exp(0.5 * log_var), dim=1)
        theta = self.drop_theta(theta)
        word_dist = torch.softmax(
            self.beta_bn(torch.matmul(theta, self.beta)), dim=1
        )
        recon = -(x * torch.log(word_dist + 1e-10)).sum(dim=1)
        var = torch.exp(log_var)
        kl = 0.5 * (
            (var / self.prior_var).sum(dim=1)
            + ((self.prior_mean - mu) ** 2 / self.prior_var).sum(dim=1)
            - mu.shape[1]
            + torch.log(self.prior_var).sum() - log_var.sum(dim=1)
        )
        return (recon + kl).sum()

    def _train_epoch(self, loader):
        import torch

        for m in self._modules_with_state:
            m.train()
        total, n = 0.0, 0
        for batch in loader:
            x = batch["X"] if isinstance(batch, dict) else batch
            x = x.float()
            self.optimizer.zero_grad()
            loss = self._loss(x)
            loss.backward()
            self.optimizer.step()
            total += float(loss.detach())
            n += x.shape[0]
        return None, total / max(n, 1)


def run_torch_baseline(epochs: int = 3, out_path: str | None = None) -> dict:
    sys.path.insert(0, REFERENCE_ROOT)
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import numpy as np
    import torch
    from torch.utils.data import DataLoader

    # The reference targets numpy<2 (`np.Inf` in pytorchtools.py:26); shim
    # the removed alias so the unmodified reference runs under numpy 2.
    if not hasattr(np, "Inf"):
        np.Inf = np.inf

    from gfedntm_tpu.data.synthetic import generate_synthetic_corpus

    n_clients, vocab, k, batch = 5, 5000, 50, 64
    docs_per_node = 2000
    corpus = generate_synthetic_corpus(
        vocab_size=vocab, n_topics=k, n_docs=docs_per_node,
        nwords=(150, 250), n_nodes=n_clients, frozen_topics=5, seed=0,
        materialize_docs=False,
    )
    X = np.concatenate([node.bow for node in corpus.nodes]).astype(np.float32)
    idx2token = {i: f"wd{i}" for i in range(vocab)}

    # Prefer the UNMODIFIED reference implementation; fall back to the
    # reference-equivalent local architecture when /root/reference is
    # absent so the baseline stays live-MEASURED (labeled impl below)
    # instead of silently reusing a committed artifact from another host.
    have_reference = os.path.isdir(REFERENCE_ROOT)
    if have_reference:
        from src.models.base.pytorchavitm.datasets.bow_dataset import (
            BOWDataset,
        )

        dataset = BOWDataset(X, idx2token)
        model = make_reference_avitm(
            input_size=vocab, n_components=k, num_epochs=epochs,
            hidden_sizes=(50, 50), logger_name="torch_baseline",
            batch_size=batch,
        )
        impl = "reference torch AVITM (imported from /root/reference)"
    else:
        class _Wrap(torch.utils.data.Dataset):
            def __len__(self):
                return X.shape[0]

            def __getitem__(self, i):
                return {"X": torch.from_numpy(X[i])}

        dataset = _Wrap()
        model = _LocalTorchAVITM(
            input_size=vocab, n_components=k, hidden_sizes=(50, 50),
        )
        impl = (
            "local torch AVITM (reference-equivalent architecture; "
            "/root/reference absent on this host)"
        )
    # fit()'s own loader config (avitm.py:371-375) minus the worker pool —
    # on this 1-core host mp.cpu_count() workers only add IPC overhead.
    loader = DataLoader(dataset, batch_size=batch, shuffle=True,
                        num_workers=0)

    # Warm epoch (allocator, thread pools), then timed epochs.
    model._train_epoch(loader)
    losses = []
    t0 = time.perf_counter()
    for _ in range(epochs):
        sp, loss = model._train_epoch(loader)
        losses.append(float(loss))
    elapsed = time.perf_counter() - t0

    docs = epochs * X.shape[0]
    report = {
        "impl": impl,
        "source": "src/models/base/pytorchavitm/avitm_network/avitm.py:323-443",
        "docs_per_s": round(docs / elapsed, 1),
        "epoch_s": round(elapsed / epochs, 2),
        "step_ms": round(elapsed / (epochs * np.ceil(X.shape[0] / batch)) * 1e3, 2),
        "epochs_timed": epochs,
        "final_train_loss": losses[-1],
        "device": "cpu",
        "torch_version": torch.__version__,
        "torch_threads": torch.get_num_threads(),
        "host_cores": len(os.sched_getaffinity(0)),
        "regime": {
            "n_docs": int(X.shape[0]), "vocab": vocab, "k": k,
            "batch": batch, "hidden": [50, 50], "lr": 2e-3,
            "beta1": 0.99,
        },
        "note": (
            "centralized fit = the reference's compute-only best case; its "
            "federated loop adds >=3 s/client/step orchestration on top "
            "(server.py:417-420,472)"
        ),
    }
    if out_path:
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(report, f, indent=2)
    return report


def main() -> None:
    out_path = (
        sys.argv[1] if len(sys.argv) > 1 else "results/torch_baseline.json"
    )
    epochs = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    report = run_torch_baseline(epochs=epochs, out_path=out_path)
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
