"""Full federated fit at production vocabulary (V=50k/100k) on TPU with the
fused Pallas kernel engaged (VERDICT r4 #2).

The reference's preprocessing targets vocabularies up to 100k
(``/root/reference/aux_scripts/preprocessing/text_preproc.py:49`` keep_n);
that regime is the fused decode+loss kernel's raison d'être, but until this
round it had only been soaked standalone. This script runs the REAL thing: a
5-client federated ProdLDA fit end-to-end (consensus-free synthetic corpus,
the whole-run SPMD program) at V in {50k, 100k}, with ``fused_decoder="auto"``
resolving to the Pallas path on TPU, and commits throughput, quality
(ground-truth TSS), the resolved tile, and in-fit HBM utilization.

Corpus sizing is HBM-bound: the staged dense BoW is [C, N, V] f32, so
docs-per-node is chosen to keep the corpus ~1.3 GB (640 @ V=100k, 1280 @
V=50k). The per-STEP math is exactly the production regime — [64, V]
batches against a [50, V] beta — which is what the kernel accelerates;
corpus depth only bounds how many distinct steps exist.

Arms per V: f32 storage and bf16 storage (compute_dtype="bfloat16" — the
VERDICT r4 #3 HBM-traffic halver) — both full fits, same corpus.

Usage: python experiments_scripts/run_full_v100k.py [out_json]
Writes results/full_largev/metrics.json (default).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

N_NODES, K, BATCH = 5, 50, 64
EPOCHS = 20
SEED = 0
# v5e nominal peaks (same constants as bench.py).
_PEAK_HBM_GBS = 819.0


def run_case(V: int, docs_per_node: int, compute_dtype: str) -> dict:
    import numpy as np

    import jax

    from gfedntm_tpu.data.datasets import BowDataset
    from gfedntm_tpu.data.synthetic import generate_synthetic_corpus
    from gfedntm_tpu.eval.metrics import (
        convert_topic_word_to_init_size,
        topic_similarity_score,
    )
    from gfedntm_tpu.federated.trainer import FederatedTrainer
    from gfedntm_tpu.models.avitm import AVITM
    from gfedntm_tpu.ops.fused_decoder import resolve_tile_v

    t0 = time.perf_counter()
    corpus = generate_synthetic_corpus(
        vocab_size=V, n_topics=K, n_docs=docs_per_node, nwords=(150, 250),
        n_nodes=N_NODES, frozen_topics=5, seed=SEED, materialize_docs=False,
    )
    idx2token = {i: f"wd{i}" for i in range(V)}
    datasets = [
        BowDataset(X=node.bow, idx2token=idx2token) for node in corpus.nodes
    ]
    gen_s = time.perf_counter() - t0

    template = AVITM(
        input_size=V, n_components=K, hidden_sizes=(50, 50),
        batch_size=BATCH, num_epochs=EPOCHS, lr=2e-3, momentum=0.99,
        seed=SEED, fused_decoder="auto", compute_dtype=compute_dtype,
    )
    fused_on = bool(template.module.fused_decoder)
    trainer = FederatedTrainer(template, n_clients=N_NODES)

    # Warmup fit: stages the corpus (one big host->device upload) and
    # compiles the whole-run program; the timed fit below reuses both.
    t0 = time.perf_counter()
    warm = trainer.fit(datasets)
    jax.block_until_ready(warm.client_params)
    compile_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    result = trainer.fit(datasets)
    jax.block_until_ready(result.client_params)
    steady_s = time.perf_counter() - t0

    steps = int(result.losses.shape[0])
    docs_per_s = steps * N_NODES * BATCH / steady_s
    step_ms = steady_s / steps * 1e3

    # In-fit HBM utilization (analytic, loss-path only — the dominant
    # traffic at large V): per client-step the fused loss streams beta 3x
    # and x 2x at storage width plus one f32 g_beta write; the encoder
    # adds ~3 reads of its [V, 50] weights + grads (f32). Padded clients
    # compute too, so count c_pad blocks.
    sb = 2.0 if compute_dtype == "bfloat16" else 4.0
    loss_bytes = sb * (3 * K * V + 2 * BATCH * V) + 4.0 * K * V
    enc_bytes = 3 * 4.0 * (V * 50) + 2 * sb * BATCH * V  # w reads + x in/out
    bytes_per_step = (loss_bytes + enc_bytes) * trainer.c_pad
    hbm_gbs = bytes_per_step / (step_ms / 1e3) / 1e9

    # Quality: ground-truth recovery (single softmax, correct mapping).
    def softmax_rows(a):
        e = np.exp(a - a.max(axis=1, keepdims=True))
        return e / e.sum(axis=1, keepdims=True)

    gm = trainer.make_global_model(result, dataset=datasets[0])
    beta_dist = softmax_rows(np.asarray(gm.params["beta"]))
    full = convert_topic_word_to_init_size(V, beta_dist, idx2token)
    tss = float(topic_similarity_score(full, corpus.topic_vectors))
    rand_floor = float(
        topic_similarity_score(
            np.random.default_rng(SEED + 9).dirichlet(
                np.full(V, 0.01), K
            ),
            corpus.topic_vectors,
        )
    )

    return {
        "vocab": V,
        "docs_per_node": docs_per_node,
        "compute_dtype": compute_dtype,
        "fused_decoder_engaged": fused_on,
        "resolved_tile_v": resolve_tile_v(
            V, BATCH, K,
            "bfloat16" if compute_dtype == "bfloat16" else "float32",
        ),
        "global_steps": steps,
        "steady_fit_s": round(steady_s, 2),
        "step_ms": round(step_ms, 3),
        "docs_per_s": round(docs_per_s, 1),
        "compile_and_first_fit_s": round(compile_s, 1),
        "corpus_gen_s": round(gen_s, 1),
        "staged_corpus_gb": round(
            trainer.c_pad * docs_per_node * V * 4 / 1e9, 2
        ),
        "in_fit_hbm_gb_per_s_analytic": round(hbm_gbs, 1),
        "in_fit_hbm_util_analytic": round(hbm_gbs / _PEAK_HBM_GBS, 3),
        "final_mean_loss": float(np.asarray(result.losses)[-1].mean()),
        "tss_vs_ground_truth": round(tss, 3),
        "tss_max": K,
        "tss_random_floor": round(rand_floor, 3),
    }


def main() -> None:
    out_path = (
        sys.argv[1] if len(sys.argv) > 1
        else os.path.join(REPO_ROOT, "results/full_largev/metrics.json")
    )
    logging.basicConfig(level=logging.WARNING)
    import jax

    if os.environ.get("FORCE_CPU"):
        # Must precede any backend query (dead-tunnel hang; see bench.py).
        jax.config.update("jax_platforms", "cpu")
    backend = jax.default_backend()

    cases = [(50_000, 1280), (100_000, 640)]
    if os.environ.get("LARGEV_SMOKE"):
        # CPU shakeout: tiny V (unfused — auto is off-TPU) to validate the
        # harness end-to-end without an hour of interpret-mode math.
        cases = [(2048, 128)]

    report: dict = {"backend": backend, "epochs": EPOCHS, "cases": {}}
    for V, docs in cases:
        for dtype in ("float32", "bfloat16"):
            key = f"V{V}_{dtype}"
            try:
                report["cases"][key] = run_case(V, docs, dtype)
            except Exception as err:  # noqa: BLE001 — keep other cases
                report["cases"][key] = {
                    "error": f"{type(err).__name__}: {err}"[:600]
                }
            print(f"{key}: {json.dumps(report['cases'][key])[:300]}",
                  flush=True)

    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
