// Native BoW tokenizer/vectorizer — the host-side data-layer hot path.
//
// The reference vectorizes every client corpus against the global vocabulary
// with sklearn's CountVectorizer (client.py:460-468); at production corpus
// sizes that is millions of Python-dict token lookups per client. This
// implements the same semantics for ASCII text (the Python layer verifies
// ASCII-ness and falls back otherwise, so parity is exact):
//
//   token pattern \b\w\w+\b over ASCII \w = [A-Za-z0-9_]  ==  maximal runs
//   of word characters of length >= 2; optional ASCII lowercasing.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in the build image).
// Documents and vocabularies cross the boundary as one contiguous blob plus
// an offsets array — one copy, no per-string marshalling.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

namespace {

inline bool is_word(unsigned char c) {
    return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
           (c >= '0' && c <= '9') || c == '_';
}

inline char lower(char c) {
    return (c >= 'A' && c <= 'Z') ? static_cast<char>(c + 32) : c;
}

// Calls fn(token) for every >=2-char word-character run in [begin, end).
// When lowercasing, the token is materialized into `scratch`.
template <typename Fn>
void for_each_token(const char* begin, const char* end, bool lowercase,
                    std::string& scratch, Fn&& fn) {
    const char* p = begin;
    while (p < end) {
        while (p < end && !is_word(static_cast<unsigned char>(*p))) ++p;
        const char* start = p;
        while (p < end && is_word(static_cast<unsigned char>(*p))) ++p;
        if (p - start >= 2) {
            if (lowercase) {
                scratch.assign(start, p - start);
                for (char& c : scratch) c = lower(c);
                fn(std::string_view(scratch));
            } else {
                fn(std::string_view(start, p - start));
            }
        }
    }
}

using VocabMap = std::unordered_map<std::string_view, int64_t>;

VocabMap build_map(const char* blob, const int64_t* offsets, int64_t n) {
    VocabMap map;
    map.reserve(static_cast<size_t>(n) * 2);
    for (int64_t i = 0; i < n; ++i) {
        map.emplace(
            std::string_view(blob + offsets[i], offsets[i + 1] - offsets[i]),
            i);
    }
    return map;
}

}  // namespace

extern "C" {

// Dense count matrix [n_docs, n_vocab] (float32, row-major) of each doc's
// tokens against a FIXED vocabulary; unknown tokens are dropped
// (CountVectorizer transform semantics). Returns 0.
int gfed_vectorize(const char* docs_blob, const int64_t* doc_offsets,
                   int64_t n_docs, const char* vocab_blob,
                   const int64_t* vocab_offsets, int64_t n_vocab,
                   int lowercase, float* out) {
    VocabMap vocab = build_map(vocab_blob, vocab_offsets, n_vocab);
    std::string scratch;
    for (int64_t d = 0; d < n_docs; ++d) {
        float* row = out + d * n_vocab;
        for_each_token(docs_blob + doc_offsets[d], docs_blob + doc_offsets[d + 1],
                       lowercase != 0, scratch,
                       [&](std::string_view tok) {
                           auto it = vocab.find(tok);
                           if (it != vocab.end()) row[it->second] += 1.0f;
                       });
    }
    return 0;
}

// Corpus-wide term -> document-count-independent frequency map (total token
// occurrences, what CountVectorizer's max_features ranks by). Results are
// returned as one \n-joined token blob + parallel counts array, both
// allocated here; free with gfed_free. Returns the number of distinct terms,
// or -1 on allocation failure.
int64_t gfed_count_terms(const char* docs_blob, const int64_t* doc_offsets,
                         int64_t n_docs, int lowercase, char** out_tokens,
                         int64_t* out_tokens_len, int64_t** out_counts) {
    std::unordered_map<std::string, int64_t> counts;
    std::string scratch;
    for (int64_t d = 0; d < n_docs; ++d) {
        for_each_token(docs_blob + doc_offsets[d], docs_blob + doc_offsets[d + 1],
                       lowercase != 0, scratch,
                       [&](std::string_view tok) { counts[std::string(tok)] += 1; });
    }

    size_t blob_len = 0;
    for (const auto& kv : counts) blob_len += kv.first.size() + 1;

    char* blob = static_cast<char*>(std::malloc(blob_len ? blob_len : 1));
    int64_t* cnts = static_cast<int64_t*>(
        std::malloc(sizeof(int64_t) * (counts.empty() ? 1 : counts.size())));
    if (blob == nullptr || cnts == nullptr) {
        std::free(blob);
        std::free(cnts);
        return -1;
    }

    char* w = blob;
    int64_t i = 0;
    for (const auto& kv : counts) {
        std::memcpy(w, kv.first.data(), kv.first.size());
        w += kv.first.size();
        *w++ = '\n';
        cnts[i++] = kv.second;
    }
    *out_tokens = blob;
    *out_tokens_len = static_cast<int64_t>(blob_len);
    *out_counts = cnts;
    return static_cast<int64_t>(counts.size());
}

void gfed_free(void* p) { std::free(p); }

}  // extern "C"
