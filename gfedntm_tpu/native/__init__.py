"""ctypes loader for the native BoW tokenizer/vectorizer (``bow.cpp``).

The shared library is compiled on first use with the system ``g++`` (the
build image ships no pybind11; the C ABI + ctypes needs nothing beyond the
toolchain) and cached next to the source, keyed by a source hash, so repeat
imports pay nothing. Every public function raises :class:`NativeUnavailable`
when the fast path cannot guarantee *exact* parity with the Python
tokenizer — no compiler, or non-ASCII text (the C++ matcher implements the
ASCII projection of the ``(?u)\\b\\w\\w+\\b`` pattern) — and callers fall
back to the pure-Python implementation in :mod:`gfedntm_tpu.data.vocab`.

Set ``GFEDNTM_NO_NATIVE=1`` to disable the native path entirely.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from pathlib import Path

import numpy as np

__all__ = [
    "NativeUnavailable",
    "available",
    "count_terms",
    "vectorize",
]

_SRC = Path(__file__).with_name("bow.cpp")
_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None
_LOAD_ERROR: str | None = None


class NativeUnavailable(RuntimeError):
    """The native fast path cannot serve this request; use the Python path."""


def _cache_path(digest: str) -> Path:
    # Per-user cache (XDG default ~/.cache): the library is dlopen'd, so a
    # world-writable location like /tmp would let another local user plant a
    # predictable-path .so and execute code in this process.
    cache_root = Path(
        os.environ.get("XDG_CACHE_HOME", Path.home() / ".cache")
    )
    d = cache_root / "gfedntm_tpu"
    d.mkdir(parents=True, exist_ok=True)
    return d / f"bow_{digest}.so"


def _compile() -> Path:
    src = _SRC.read_bytes()
    digest = hashlib.sha256(src).hexdigest()[:16]
    out = _cache_path(digest)
    if out.exists():
        return out
    tmp = out.with_suffix(f".{os.getpid()}.tmp.so")
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
        str(_SRC), "-o", str(tmp),
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, out)  # atomic: concurrent builders race benignly
    return out


def _get_lib() -> ctypes.CDLL:
    global _LIB, _LOAD_ERROR
    if _LIB is not None:
        return _LIB
    if _LOAD_ERROR is not None:
        raise NativeUnavailable(_LOAD_ERROR)
    if os.environ.get("GFEDNTM_NO_NATIVE"):
        _LOAD_ERROR = "disabled by GFEDNTM_NO_NATIVE"
        raise NativeUnavailable(_LOAD_ERROR)
    with _LOCK:
        if _LIB is not None:
            return _LIB
        try:
            lib = ctypes.CDLL(str(_compile()))
        except (OSError, subprocess.CalledProcessError, FileNotFoundError) as e:
            _LOAD_ERROR = f"native bow build failed: {e}"
            raise NativeUnavailable(_LOAD_ERROR) from e
        lib.gfed_vectorize.restype = ctypes.c_int
        lib.gfed_vectorize.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_int, ctypes.POINTER(ctypes.c_float),
        ]
        lib.gfed_count_terms.restype = ctypes.c_int64
        lib.gfed_count_terms.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
        ]
        lib.gfed_free.restype = None
        lib.gfed_free.argtypes = [ctypes.c_void_p]
        _LIB = lib
    return _LIB


def available() -> bool:
    try:
        _get_lib()
        return True
    except NativeUnavailable:
        return False


def _pack(strings, what: str) -> tuple[bytes, np.ndarray]:
    """One UTF-8 blob + int64 offsets[n+1]; rejects non-ASCII (the C++
    tokenizer implements the ASCII projection of the unicode pattern)."""
    encoded = []
    for s in strings:
        if not s.isascii():
            raise NativeUnavailable(f"non-ASCII {what}; use the Python path")
        encoded.append(s.encode())
    offsets = np.zeros(len(encoded) + 1, dtype=np.int64)
    np.cumsum([len(e) for e in encoded], out=offsets[1:])
    return b"".join(encoded), offsets


def vectorize(docs, vocab_tokens, lowercase: bool = True) -> np.ndarray:
    """Dense [n_docs, n_vocab] float32 count matrix against a fixed
    vocabulary — the native twin of :func:`gfedntm_tpu.data.vocab.vectorize`."""
    lib = _get_lib()
    docs_blob, doc_off = _pack(docs, "document")
    vocab_blob, vocab_off = _pack(vocab_tokens, "vocabulary token")
    out = np.zeros((len(docs), len(vocab_tokens)), dtype=np.float32)
    rc = lib.gfed_vectorize(
        docs_blob, doc_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(docs),
        vocab_blob, vocab_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(vocab_tokens),
        int(lowercase),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
    )
    if rc != 0:  # pragma: no cover - no failing path today
        raise NativeUnavailable(f"gfed_vectorize returned {rc}")
    return out


def count_terms(docs, lowercase: bool = True) -> dict[str, int]:
    """Corpus-wide term frequencies (token occurrences) — the counting core
    of :func:`gfedntm_tpu.data.vocab.build_vocabulary`."""
    lib = _get_lib()
    docs_blob, doc_off = _pack(docs, "document")
    tokens_ptr = ctypes.c_char_p()
    tokens_len = ctypes.c_int64()
    counts_ptr = ctypes.POINTER(ctypes.c_int64)()
    n = lib.gfed_count_terms(
        docs_blob, doc_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(docs), int(lowercase),
        ctypes.byref(tokens_ptr), ctypes.byref(tokens_len),
        ctypes.byref(counts_ptr),
    )
    if n < 0:  # pragma: no cover - allocation failure
        raise NativeUnavailable("gfed_count_terms allocation failed")
    try:
        blob = ctypes.string_at(tokens_ptr, tokens_len.value)
        counts = np.ctypeslib.as_array(counts_ptr, shape=(n,)).copy() if n else []
        terms = blob.decode().split("\n")[:n]
        return {t: int(c) for t, c in zip(terms, counts)}
    finally:
        lib.gfed_free(tokens_ptr)
        lib.gfed_free(counts_ptr)
