"""Vocabulary building and BoW vectorization (CountVectorizer semantics).

The reference builds client vocabularies and vectorizes corpora with sklearn's
``CountVectorizer`` (``client.py:358-376``, ``server.py:282-288``,
``pytorchavitm/utils/data_preparation.py:30-40``). This module reimplements
the exact semantics needed — lowercase, ``\\b\\w\\w+\\b`` token pattern,
optional english stop words, ``max_features`` by corpus frequency with
alphabetical tie-ordering — so the framework has no hard sklearn dependency
in its core path, plus a C++ fast path (``gfedntm_tpu.native``)
for tokenizing/counting/vectorizing large corpora on host.

Vocabulary-consensus helpers mirror ``server.py:270-288``: the global
vocabulary is the sorted set-union of client vocabularies.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Iterable, Sequence

import numpy as np

try:
    from gfedntm_tpu import native as _native
except ImportError:  # pragma: no cover - native loader always importable
    _native = None

_TOKEN_RE = re.compile(r"(?u)\b\w\w+\b")

try:  # the canonical english stop-word list; vendored fallback not needed
    from sklearn.feature_extraction.text import ENGLISH_STOP_WORDS as _SK_STOP
except Exception:  # pragma: no cover
    _SK_STOP = frozenset()


def get_stop_words(name: str | None) -> frozenset[str]:
    if name is None:
        return frozenset()
    if name == "english":
        return frozenset(_SK_STOP)
    raise ValueError(f"unknown stop_words {name!r}")


def tokenize(
    doc: str, lowercase: bool = True, token_pattern: str | None = None
) -> list[str]:
    """sklearn default analyzer: lowercase + ``(?u)\\b\\w\\w+\\b`` (or a
    custom ``token_pattern``, e.g. the ``[a-zA-Z]{2,}`` of
    ``preprocessing.py:47``)."""
    if lowercase:
        doc = doc.lower()
    pattern = _TOKEN_RE if token_pattern is None else re.compile(token_pattern)
    return pattern.findall(doc)


@dataclass
class Vocabulary:
    """An ordered token->id map plus its inverse. ``token_pattern`` records
    the analyzer the vocabulary was built with so ``vectorize`` tokenizes
    consistently (None = sklearn default ``\\b\\w\\w+\\b``)."""

    tokens: tuple[str, ...]
    token_pattern: str | None = None

    def __post_init__(self):
        self.token2id = {t: i for i, t in enumerate(self.tokens)}

    def __len__(self) -> int:
        return len(self.tokens)

    @property
    def id2token(self) -> dict[int, str]:
        return dict(enumerate(self.tokens))

    def __contains__(self, token: str) -> bool:
        return token in self.token2id


def _count_terms(
    corpus: Iterable[str], lowercase: bool, token_pattern: str | None
) -> dict[str, int]:
    """Corpus-wide token occurrence counts, via the C++ fast path
    (``gfedntm_tpu.native``) when it can guarantee exact parity (default
    token pattern, ASCII text), else pure Python."""
    docs = corpus if isinstance(corpus, (list, tuple)) else list(corpus)
    if token_pattern is None and _native is not None:
        try:
            return _native.count_terms(docs, lowercase)
        except _native.NativeUnavailable:
            pass
    counts: dict[str, int] = {}
    for doc in docs:
        for tok in tokenize(doc, lowercase, token_pattern):
            counts[tok] = counts.get(tok, 0) + 1
    return counts


def build_vocabulary(
    corpus: Iterable[str],
    max_features: int | None = None,
    stop_words: str | None = None,
    lowercase: bool = True,
    token_pattern: str | None = None,
) -> Vocabulary:
    """Fit a vocabulary with CountVectorizer semantics.

    With ``max_features``, keep the most frequent terms (ties broken
    alphabetically, as sklearn's stable sort over the alphabetical vocab
    does), then order the kept terms alphabetically.
    """
    stops = get_stop_words(stop_words)
    counts = _count_terms(corpus, lowercase, token_pattern)
    if stops:
        counts = {t: c for t, c in counts.items() if t not in stops}
    terms = sorted(counts)
    if max_features is not None and len(terms) > max_features:
        # sklearn's _limit_features: keep argsort(-term_freqs)[:k] over the
        # alphabetical vocabulary (numpy's default introsort — ties resolve
        # exactly as sklearn's do), then features stay in alphabetical order.
        tfs = np.array([counts[t] for t in terms])
        keep = np.sort(np.argsort(-tfs, kind="quicksort")[:max_features])
        terms = [terms[i] for i in keep]
    return Vocabulary(tuple(terms), token_pattern=token_pattern)


def vectorize(
    corpus: Sequence[str],
    vocab: Vocabulary,
    lowercase: bool = True,
    dtype=np.float32,
) -> np.ndarray:
    """Dense document-term count matrix [n_docs, len(vocab)] against a FIXED
    vocabulary (``client.py:460-468``: local docs x global vocab)."""
    if vocab.token_pattern is None and dtype == np.float32 and _native is not None:
        try:
            return _native.vectorize(
                corpus if isinstance(corpus, (list, tuple)) else list(corpus),
                vocab.tokens, lowercase,
            )
        except _native.NativeUnavailable:
            pass
    token2id = vocab.token2id
    n_docs, n_terms = len(corpus), len(vocab)
    X = np.zeros((n_docs, n_terms), dtype=dtype)
    for i, doc in enumerate(corpus):
        for tok in tokenize(doc, lowercase, vocab.token_pattern):
            j = token2id.get(tok)
            if j is not None:
                X[i, j] += 1
    return X


def union_vocabularies(vocabs: Sequence[Vocabulary]) -> Vocabulary:
    """Vocabulary consensus: sorted set-union (``server.py:270-279``)."""
    merged: set[str] = set()
    for v in vocabs:
        merged.update(v.tokens)
    return Vocabulary(tuple(sorted(merged)))
