"""Dataset containers and batch scheduling for TPU training.

Replaces the reference's torch datasets + DataLoader iterators:
- ``BowDataset``  <- ``pytorchavitm/datasets/bow_dataset.py:6-34``
- ``CTMDataset``  <- ``contextualized_topic_models/datasets/dataset.py:6-48``
- ``EpochSchedule`` <- the DataLoader(shuffle=True) iterator semantics of
  ``federated_model.py:82-88`` / ``avitm.py:371-375``, re-expressed as
  precomputed index arrays so a whole epoch (or a whole federated run) can be
  driven by one ``lax.scan`` over static-shape batches.

TPU constraint: XLA needs static shapes, but dataset sizes are arbitrary.
Every epoch is padded to ``ceil(n/B)`` full batches; a parallel boolean mask
marks real rows. Mask-aware loss/BatchNorm make the padded program compute
exactly what the reference computes on its ragged final batch.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass
class BowDataset:
    """Dense doc-term matrix plus vocabulary mapping."""

    X: np.ndarray  # [n_docs, V] float32 counts
    idx2token: dict[int, str] = field(default_factory=dict)

    def __post_init__(self):
        self.X = np.asarray(self.X, dtype=np.float32)

    def __len__(self) -> int:
        return self.X.shape[0]

    @property
    def vocab_size(self) -> int:
        return self.X.shape[1]


@dataclass
class CTMDataset(BowDataset):
    """BoW + contextual (SBERT) embeddings + optional one-hot labels.

    Validates length agreement like the reference (``dataset.py:17-27``).
    """

    X_ctx: np.ndarray | None = None  # [n_docs, contextual_size]
    labels: np.ndarray | None = None  # [n_docs, label_size] one-hot

    def __post_init__(self):
        super().__post_init__()
        if self.X_ctx is None:
            raise ValueError("CTMDataset requires contextual embeddings")
        self.X_ctx = np.asarray(self.X_ctx, dtype=np.float32)
        if len(self.X_ctx) != len(self.X):
            raise ValueError(
                f"length mismatch: {len(self.X)} bow vs {len(self.X_ctx)} contextual"
            )
        if self.labels is not None:
            self.labels = np.asarray(self.labels, dtype=np.float32)
            if len(self.labels) != len(self.X):
                raise ValueError("length mismatch between labels and bow")

    @property
    def contextual_size(self) -> int:
        return self.X_ctx.shape[1]


@dataclass(frozen=True)
class EpochSchedule:
    """Static-shape batch schedule for one dataset.

    ``indices`` [steps_per_epoch, batch_size] int32 (pad rows repeat index 0),
    ``mask``    [steps_per_epoch, batch_size] bool (False on pad rows).
    """

    indices: np.ndarray
    mask: np.ndarray

    @property
    def steps_per_epoch(self) -> int:
        return self.indices.shape[0]


def make_epoch_schedule(
    n_docs: int, batch_size: int, rng: np.random.Generator, shuffle: bool = True
) -> EpochSchedule:
    """One epoch of DataLoader(shuffle)-equivalent batches, padded to full
    static shape. drop_last=False semantics: the ragged final batch becomes a
    full batch with masked padding rows."""
    order = rng.permutation(n_docs) if shuffle else np.arange(n_docs)
    steps = max(1, -(-n_docs // batch_size))
    padded = np.zeros(steps * batch_size, dtype=np.int32)
    padded[:n_docs] = order
    mask = np.zeros(steps * batch_size, dtype=bool)
    mask[:n_docs] = True
    return EpochSchedule(
        indices=padded.reshape(steps, batch_size),
        mask=mask.reshape(steps, batch_size),
    )


def make_run_schedule(
    n_docs: int,
    batch_size: int,
    num_steps: int,
    seed: int,
    shuffle: bool = True,
) -> EpochSchedule:
    """Concatenate per-epoch schedules until ``num_steps`` global steps are
    covered (a client whose epochs are shorter keeps cycling with fresh
    shuffles, mirroring the iterator reset at ``federated_avitm.py:114-138``).
    Returns arrays shaped [num_steps, batch_size]."""
    rng = np.random.default_rng(seed)
    idx_chunks, mask_chunks, have = [], [], 0
    while have < num_steps:
        ep = make_epoch_schedule(n_docs, batch_size, rng, shuffle)
        idx_chunks.append(ep.indices)
        mask_chunks.append(ep.mask)
        have += ep.steps_per_epoch
    indices = np.concatenate(idx_chunks, axis=0)[:num_steps]
    mask = np.concatenate(mask_chunks, axis=0)[:num_steps]
    return EpochSchedule(indices=indices, mask=mask)


def train_val_split(
    n_docs: int, val_fraction: float = 0.25, seed: int = 42
) -> tuple[np.ndarray, np.ndarray]:
    """Index split mirroring ``prepare_dataset``'s 75/25 split with seed 42
    (``pytorchavitm/utils/data_preparation.py:26-33``)."""
    rng = np.random.default_rng(seed)
    order = rng.permutation(n_docs)
    n_val = int(round(n_docs * val_fraction))
    return np.sort(order[n_val:]), np.sort(order[:n_val])
