from gfedntm_tpu.data import datasets as datasets
from gfedntm_tpu.data import loaders as loaders
from gfedntm_tpu.data import synthetic as synthetic
from gfedntm_tpu.data import vocab as vocab
from gfedntm_tpu.data.datasets import (
    BowDataset,
    CTMDataset,
    EpochSchedule,
    make_epoch_schedule,
    make_run_schedule,
    train_val_split,
)
from gfedntm_tpu.data.loaders import (
    RawCorpus,
    load_20newsgroups,
    load_parquet_corpus,
    partition_corpus,
)
from gfedntm_tpu.data.synthetic import (
    SyntheticCorpus,
    SyntheticNode,
    generate_synthetic_corpus,
    load_reference_npz,
    save_reference_npz,
)
from gfedntm_tpu.data.vocab import (
    Vocabulary,
    build_vocabulary,
    tokenize,
    union_vocabularies,
    vectorize,
)
