from gfedntm_tpu.data import datasets as datasets
from gfedntm_tpu.data import loaders as loaders
from gfedntm_tpu.data import preparation as preparation
from gfedntm_tpu.data import preproc as preproc
from gfedntm_tpu.data import synthetic as synthetic
from gfedntm_tpu.data import vocab as vocab
from gfedntm_tpu.data.datasets import (
    BowDataset,
    CTMDataset,
    EpochSchedule,
    make_epoch_schedule,
    make_run_schedule,
    train_val_split,
)
from gfedntm_tpu.data.loaders import (
    RawCorpus,
    load_20newsgroups,
    load_parquet_corpus,
    partition_corpus,
)
from gfedntm_tpu.data.preparation import (
    TopicModelDataPreparation,
    WhiteSpacePreprocessing,
    prepare_ctm_dataset,
    prepare_dataset,
    prepare_hold_out_dataset,
)
from gfedntm_tpu.data.preproc import (
    PreprocConfig,
    PreprocResult,
    load_wordlist,
    preprocess_corpus,
)
from gfedntm_tpu.data.synthetic import (
    SyntheticCorpus,
    SyntheticNode,
    generate_synthetic_corpus,
    load_reference_npz,
    save_reference_npz,
)
from gfedntm_tpu.data.vocab import (
    Vocabulary,
    build_vocabulary,
    tokenize,
    union_vocabularies,
    vectorize,
)
