"""Dataset preparation with the reference's public API.

Rebuilds (behavioral parity, TPU-native containers):
- ``prepare_dataset`` — ``src/models/base/pytorchavitm/utils/data_preparation.py:11-64``:
  75/25 train/val split (seed 42), CountVectorizer(lowercase, english
  stop-words) fit on the TRAIN portion only, val vectorized against the
  train vocabulary.
- ``prepare_ctm_dataset`` / ``prepare_hold_out_dataset`` /
  ``TopicModelDataPreparation`` —
  ``src/models/base/contextualized_topic_models/utils/data_preparation.py:65-328``.
  SBERT embedding generation is pluggable (``embedder`` callable); this
  environment precomputes embeddings (the reference likewise expects them
  precomputed in the parquet — its sentence-transformers import is commented
  out, ``data_preparation.py:5``).
- ``WhiteSpacePreprocessing`` —
  ``src/models/base/contextualized_topic_models/utils/preprocessing.py:6-60``:
  lowercase → punctuation→space → stop-word removal → top-N ``[a-zA-Z]{2,}``
  vocabulary → restrict docs to vocabulary → drop emptied docs.
"""

from __future__ import annotations

import string
from typing import Callable, Sequence

import numpy as np

from gfedntm_tpu.data.datasets import BowDataset, CTMDataset
from gfedntm_tpu.data.vocab import (
    Vocabulary,
    build_vocabulary,
    get_stop_words,
    vectorize,
)


def _join_if_tokens(corpus: Sequence) -> list[str]:
    """The reference's corpora are lists of token-lists which it joins with
    spaces before vectorizing (``data_preparation.py:43``); accept both."""
    return [
        " ".join(doc) if not isinstance(doc, str) else doc for doc in corpus
    ]


def _train_test_split(items, *arrays, test_size: float = 0.25, seed: int = 42):
    """sklearn ``train_test_split(random_state=42)``-compatible split (the
    reference's exact regime, ``data_preparation.py:35``)."""
    from sklearn.model_selection import train_test_split

    return train_test_split(items, *arrays, test_size=test_size, random_state=seed)


def prepare_dataset(corpus, val_size: float = 0.25, seed: int = 42):
    """Returns ``(train_data, val_data, input_size, id2token, docs_train,
    vocab)`` — the reference's tuple with the fitted CountVectorizer replaced
    by the fitted :class:`Vocabulary` (same role: vectorize new text)."""
    docs_train, docs_val = _train_test_split(
        list(corpus), test_size=val_size, seed=seed
    )
    train_texts = _join_if_tokens(docs_train)
    vocab = build_vocabulary(train_texts, stop_words="english")
    id2token = vocab.id2token
    train_data = BowDataset(X=vectorize(train_texts, vocab), idx2token=id2token)
    val_data = BowDataset(
        X=vectorize(_join_if_tokens(docs_val), vocab), idx2token=id2token
    )
    return train_data, val_data, len(vocab), id2token, docs_train, vocab


class TopicModelDataPreparation:
    """Fit/transform/load around a vocabulary + a pluggable document embedder
    (``data_preparation.py:195-328``).

    ``embedder(list[str]) -> np.ndarray`` replaces the reference's SBERT
    model name; pass precomputed embeddings to skip it entirely.
    """

    def __init__(
        self,
        contextualized_model: str | None = None,
        embedder: Callable[[list[str]], np.ndarray] | None = None,
    ):
        self.contextualized_model = contextualized_model
        self.embedder = embedder
        self.vocab: tuple[str, ...] = ()
        self.id2token: dict[int, str] = {}
        self.vectorizer: Vocabulary | None = None
        self.label_index: dict | None = None

    def _embed(self, texts: list[str], custom: np.ndarray | None) -> np.ndarray:
        if custom is not None:
            return np.asarray(custom, dtype=np.float32)
        if self.embedder is None:
            raise ValueError(
                "no embedder configured and no custom_embeddings provided "
                "(this environment has no network egress for SBERT downloads; "
                "precompute embeddings as the reference's parquet does)"
            )
        return np.asarray(self.embedder(texts), dtype=np.float32)

    def _one_hot_labels(self, labels) -> np.ndarray | None:
        if labels is None:
            return None
        if self.label_index is None:
            classes = sorted(set(labels))
            self.label_index = {c: i for i, c in enumerate(classes)}
        n = len(self.label_index)
        out = np.zeros((len(labels), n), dtype=np.float32)
        for i, lab in enumerate(labels):
            out[i, self.label_index[lab]] = 1.0
        return out

    def fit(
        self,
        text_for_contextual: list[str],
        text_for_bow: list[str],
        labels=None,
        custom_embeddings: np.ndarray | None = None,
    ) -> CTMDataset:
        """Learn the BoW vocabulary and build the training CTMDataset
        (``data_preparation.py:232-274``)."""
        self.vectorizer = build_vocabulary(text_for_bow)
        self.vocab = self.vectorizer.tokens
        self.id2token = self.vectorizer.id2token
        X = vectorize(text_for_bow, self.vectorizer)
        X_ctx = self._embed(text_for_contextual, custom_embeddings)
        return CTMDataset(
            X=X, idx2token=self.id2token, X_ctx=X_ctx,
            labels=self._one_hot_labels(labels),
        )

    def transform(
        self,
        text_for_contextual: list[str],
        text_for_bow: list[str] | None = None,
        labels=None,
        custom_embeddings: np.ndarray | None = None,
    ) -> CTMDataset:
        """Vectorize new text against the FITTED vocabulary
        (``data_preparation.py:276-311``); without ``text_for_bow`` the BoW
        block is zeros (zero-shot inference regime)."""
        if self.vectorizer is None:
            raise RuntimeError("fit (or load) must be called before transform")
        if text_for_bow is not None:
            X = vectorize(text_for_bow, self.vectorizer)
        else:
            X = np.zeros(
                (len(text_for_contextual), len(self.vocab)), dtype=np.float32
            )
        X_ctx = self._embed(text_for_contextual, custom_embeddings)
        return CTMDataset(
            X=X, idx2token=self.id2token, X_ctx=X_ctx,
            labels=self._one_hot_labels(labels),
        )

    def load(
        self, contextualized_embeddings: np.ndarray, bow_embeddings: np.ndarray,
        id2token: dict[int, str], labels=None,
    ) -> CTMDataset:
        """Assemble a CTMDataset from precomputed pieces
        (``data_preparation.py:313-328``)."""
        X = np.asarray(
            bow_embeddings.toarray()
            if hasattr(bow_embeddings, "toarray")
            else bow_embeddings,
            dtype=np.float32,
        )
        return CTMDataset(
            X=X, idx2token=dict(id2token),
            X_ctx=np.asarray(contextualized_embeddings, dtype=np.float32),
            labels=self._one_hot_labels(labels),
        )


def prepare_ctm_dataset(
    corpus,
    unpreprocessed_corpus=None,
    custom_embeddings: np.ndarray | None = None,
    embedder: Callable[[list[str]], np.ndarray] | None = None,
    val_size: float = 0.25,
    seed: int = 42,
):
    """Returns ``(training_dataset, validation_dataset, input_size, id2token,
    qt, embeddings_train, custom_embeddings, docs_train)`` —
    ``data_preparation.py:65-161`` with a pluggable embedder."""
    if custom_embeddings is None and unpreprocessed_corpus is None:
        raise TypeError(
            "Custom embeddings or an unpreprocessed corpus to generate the "
            "embeddings from must be provided"
        )
    qt = TopicModelDataPreparation(embedder=embedder)
    if custom_embeddings is None:
        custom_embeddings = qt._embed(
            _join_if_tokens(unpreprocessed_corpus), None
        )
    custom_embeddings = np.asarray(custom_embeddings, dtype=np.float32)

    docs_train, docs_val, emb_train, emb_val = _train_test_split(
        list(corpus), custom_embeddings, test_size=val_size, seed=seed
    )
    train_texts = _join_if_tokens(docs_train)
    val_texts = _join_if_tokens(docs_val)

    qt.vectorizer = build_vocabulary(train_texts, stop_words="english")
    qt.vocab = qt.vectorizer.tokens
    qt.id2token = qt.vectorizer.id2token

    training_dataset = qt.load(
        emb_train, vectorize(train_texts, qt.vectorizer), qt.id2token
    )
    validation_dataset = qt.transform(
        text_for_contextual=val_texts, text_for_bow=val_texts,
        custom_embeddings=emb_val,
    )
    return (
        training_dataset, validation_dataset, len(qt.vocab), qt.id2token, qt,
        np.asarray(emb_train), custom_embeddings, docs_train,
    )


def prepare_hold_out_dataset(
    hold_out_corpus,
    qt: TopicModelDataPreparation,
    unpreprocessed_ho_corpus=None,
    embeddings_ho: np.ndarray | None = None,
):
    """Vectorize a hold-out corpus with a fitted preparation object
    (``data_preparation.py:163-192``)."""
    if embeddings_ho is None and unpreprocessed_ho_corpus is None:
        raise TypeError(
            "Custom embeddings or an unpreprocessed corpus to generate the "
            "embeddings from must be provided"
        )
    texts = _join_if_tokens(hold_out_corpus)
    if embeddings_ho is None:
        embeddings_ho = qt._embed(_join_if_tokens(unpreprocessed_ho_corpus), None)
    return qt.transform(
        text_for_contextual=texts, text_for_bow=texts,
        custom_embeddings=embeddings_ho,
    )


def _nltk_stopwords(language: str) -> set[str]:
    """The reference uses NLTK stop-word lists (``preprocessing.py:24``);
    prefer them when the NLTK corpus is installed locally, else fall back to
    the sklearn English list (documented divergence: 318 vs 179 words)."""
    try:  # pragma: no cover - depends on local nltk data
        from nltk.corpus import stopwords as nltk_stop

        return set(nltk_stop.words(language))
    except Exception:
        if language == "english":
            return set(get_stop_words("english"))
        raise ValueError(
            f"stop words for {language!r} need the NLTK stopwords corpus, "
            "which is not installed in this environment"
        ) from None


class WhiteSpacePreprocessing:
    """Minimal corpus preprocessing (``preprocessing.py:6-60``): lowercase,
    punctuation→spaces, stop-word removal, restrict to the
    ``vocabulary_size`` most frequent ``[a-zA-Z]{2,}`` tokens, drop emptied
    docs (returning the surviving raw docs alongside)."""

    def __init__(
        self,
        documents: list[str],
        stopwords_language: str = "english",
        vocabulary_size: int = 2000,
    ):
        self.documents = documents
        self.stopwords = _nltk_stopwords(stopwords_language)
        self.vocabulary_size = vocabulary_size

    def preprocess(self) -> tuple[list[str], list[str], list[str]]:
        table = str.maketrans(string.punctuation, " " * len(string.punctuation))
        cleaned = []
        for doc in self.documents:
            words = doc.lower().translate(table).split()
            cleaned.append(" ".join(w for w in words if w not in self.stopwords))

        vocab = build_vocabulary(
            cleaned, max_features=self.vocabulary_size,
            token_pattern=r"\b[a-zA-Z]{2,}\b",
        )
        keep = set(vocab.tokens)
        preprocessed_docs, unpreprocessed_docs = [], []
        for raw, doc in zip(self.documents, cleaned):
            filtered = " ".join(w for w in doc.split() if w in keep)
            if filtered:
                preprocessed_docs.append(filtered)
                unpreprocessed_docs.append(raw)
        return preprocessed_docs, unpreprocessed_docs, list(vocab.tokens)
