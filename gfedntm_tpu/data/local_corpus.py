"""Offline real-text corpus: Python-library docstrings from site-packages.

The BASELINE/VERDICT quality targets need a *healthy* real-text federated
corpus (>= 10k documents), but this host has zero egress and no offline
snapshot of 20Newsgroups or the S2 corpus — the only real-text fixture the
reference ships is the 334-doc ``s2cs_tiny.parquet``, which starves every
arm (round-4 artifact: NPMI -0.42, junk topics). This module assembles a
corpus from what IS on the machine: the installed Python libraries carry
~90k English docstrings (numpy/scipy math, torch/tensorflow deep learning,
google-cloud RPC, sklearn/pandas data analysis, ...), averaging ~130 words
— real, coherent technical prose with naturally distinct topical domains.

Federation shape: one client per PACKAGE FAMILY (math, deep learning,
cloud/RPC, NLP, data analysis) — a genuinely non-IID split in the same
sense as the reference's fieldsOfStudy partitioning of Semantic Scholar
(``docker-compose.yaml:21-149``: one client per research field).

Nothing here reads the reference repo or the network; the extractor only
walks an installed ``site-packages`` tree with ``ast``.
"""

from __future__ import annotations

import ast
import hashlib
import os
import re
import sysconfig
from dataclasses import dataclass, field

from gfedntm_tpu.data.loaders import RawCorpus

# One client per package family — the non-IID axis. Vendored subpackages
# (e.g. pip._vendor) are excluded by the top-level-name match.
DEFAULT_CLIENT_GROUPS: dict[str, tuple[str, ...]] = {
    "math": ("numpy", "scipy", "sympy", "networkx", "mpmath"),
    "deep_learning": ("torch", "tensorflow", "keras", "tf_keras", "flax",
                      "optax", "jax"),
    "cloud_rpc": ("google", "grpc", "proto", "googleapiclient", "vertexai"),
    "nlp": ("transformers", "nltk", "tokenizers", "datasets", "sentencepiece"),
    "data_analysis": ("sklearn", "pandas", "matplotlib", "statsmodels",
                      "PIL"),
}

_DOCTEST_RE = re.compile(r"^\s*(>>>|\.\.\.)")
_RST_ROLE_RE = re.compile(r":[a-zA-Z]+:`~?([^`]*)`")
_WORD_RE = re.compile(r"[a-z]{3,}")


def clean_docstring(text: str) -> list[str]:
    """Docstring -> lowercase alpha tokens: doctest lines and rst
    field-list markers dropped, rst roles unwrapped, identifiers split on
    underscores (``load_state_dict`` -> load state dict)."""
    lines = []
    for line in text.splitlines():
        if _DOCTEST_RE.match(line):
            continue
        stripped = line.strip()
        # rst field lists (:param x:, :returns:, Args:/Returns: headers)
        if stripped.startswith(":") or stripped.endswith("::"):
            continue
        lines.append(_RST_ROLE_RE.sub(r"\1", line))
    text = " ".join(lines).lower().replace("_", " ")
    return _WORD_RE.findall(text)


@dataclass
class DocstringCorpusConfig:
    site_packages: str | None = None  # default: the running interpreter's
    client_groups: dict[str, tuple[str, ...]] = field(
        default_factory=lambda: dict(DEFAULT_CLIENT_GROUPS)
    )
    min_words: int = 40       # raw docstring length gate (pre-clean)
    min_tokens: int = 25      # cleaned token gate
    docs_per_client: int = 3000
    seed: int = 0


def build_docstring_corpus(
    config: DocstringCorpusConfig | None = None,
) -> tuple[list[RawCorpus], dict]:
    """Extract, clean, dedup, and partition the docstring corpus.

    Returns ``(clients, info)``: one :class:`RawCorpus` per client group
    (documents are space-joined cleaned tokens, ready for the consensus /
    preprocessing pipeline) and an info dict with per-client counts.
    Deterministic for a fixed installation: files are walked in sorted
    order; the per-client cap keeps a seed-deterministic random subset
    (shuffled before capping so the kept docs aren't biased toward
    whichever subpackage sorts first).
    """
    import numpy as np

    config = config or DocstringCorpusConfig()
    root = config.site_packages or sysconfig.get_paths()["purelib"]
    top_to_client: dict[str, str] = {
        pkg: client
        for client, pkgs in config.client_groups.items()
        for pkg in pkgs
    }

    docs: dict[str, list[str]] = {c: [] for c in config.client_groups}
    seen: set[bytes] = set()
    scanned_files = 0
    for dirpath, dirnames, filenames in os.walk(root):
        # In-place pruning only works on the LIVE walk generator: sort for
        # determinism, drop __pycache__, and skip entire non-target
        # top-level packages (site-packages holds tens of thousands of
        # directories outside the client groups).
        rel = os.path.relpath(dirpath, root)
        if rel == ".":
            dirnames[:] = sorted(d for d in dirnames if d in top_to_client)
        else:
            dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        top = rel.split(os.sep)[0] if rel != "." else ""
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            if rel == ".":
                top = fn[:-3]
            client = top_to_client.get(top)
            if client is None:
                continue
            scanned_files += 1
            try:
                with open(
                    os.path.join(dirpath, fn), encoding="utf8",
                    errors="ignore",
                ) as f:
                    tree = ast.parse(f.read())
            except (SyntaxError, ValueError, OSError):
                continue
            for node in ast.walk(tree):
                if not isinstance(
                    node,
                    (ast.Module, ast.FunctionDef, ast.AsyncFunctionDef,
                     ast.ClassDef),
                ):
                    continue
                ds = ast.get_docstring(node)
                if not ds or len(ds.split()) < config.min_words:
                    continue
                tokens = clean_docstring(ds)
                if len(tokens) < config.min_tokens:
                    continue
                digest = hashlib.blake2b(
                    " ".join(tokens).encode(), digest_size=16
                ).digest()
                if digest in seen:  # vendored/duplicated docstrings
                    continue
                seen.add(digest)
                docs[client].append(" ".join(tokens))

    # Balanced cap: a deterministic shuffle before capping so the kept
    # subset isn't biased toward whichever subpackage sorts first.
    rng = np.random.default_rng(config.seed)
    clients: list[RawCorpus] = []
    info: dict = {"site_packages": root, "per_client": {}, "scanned_files":
                  scanned_files}
    for client in config.client_groups:
        d = docs[client]
        order = rng.permutation(len(d))
        kept = [d[i] for i in order[: config.docs_per_client]]
        info["per_client"][client] = {
            "extracted": len(d), "kept": len(kept),
        }
        clients.append(RawCorpus(documents=kept))
    info["total_docs"] = sum(
        v["kept"] for v in info["per_client"].values()
    )
    return clients, info
