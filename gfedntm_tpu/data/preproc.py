"""Topic-model corpus preprocessing pipeline (native, no Spark/Dask/Java).

Rebuilds the reference's preprocessing stage, which `aux_scripts/preprocessing/
text_preproc.py:44-136` configures and delegates to the external
``topicmodeler`` submodule: stop-word and equivalence wordlists, then
dictionary filtering with ``no_below`` / ``no_above`` / ``keep_n`` (gensim
``Dictionary.filter_extremes`` semantics) and a ``min_lemas`` document floor.
Wordlist JSON files use the reference schema (``{"wordlist": [...]}``,
``aux_scripts/preprocessing/wordlists/*.json``); equivalence entries are
``"original:replacement"`` strings.
"""

from __future__ import annotations

import json
from collections import Counter
from dataclasses import dataclass, field


def load_wordlist(path: str) -> list[str]:
    """Read a reference-format wordlist JSON (key ``wordlist``)."""
    with open(path) as f:
        payload = json.load(f)
    return list(payload.get("wordlist", []))


def parse_equivalences(entries: list[str]) -> dict[str, str]:
    """``"original:replacement"`` pairs → mapping (malformed entries skipped)."""
    out: dict[str, str] = {}
    for entry in entries:
        if ":" in entry:
            src, dst = entry.split(":", 1)
            src, dst = src.strip(), dst.strip()
            if src:
                out[src] = dst
    return out


@dataclass
class PreprocConfig:
    """Defaults mirror ``text_preproc.py:44-52``."""

    min_lemas: int = 15
    no_below: int = 15
    no_above: float = 0.4
    keep_n: int = 100_000
    stopwords: list[str] = field(default_factory=list)
    equivalences: list[str] = field(default_factory=list)


@dataclass
class PreprocResult:
    docs: list[list[str]]  # filtered token lists (surviving docs)
    kept_indices: list[int]  # positions of surviving docs in the input
    vocabulary: list[str]  # final filtered vocabulary (alphabetical)


def preprocess_corpus(
    docs: list[list[str]] | list[str], config: PreprocConfig | None = None
) -> PreprocResult:
    """Apply stopwords → equivalences → filter_extremes(no_below, no_above,
    keep_n) → min_lemas doc filter.

    ``filter_extremes`` semantics (gensim): drop tokens in fewer than
    ``no_below`` docs or more than ``no_above`` fraction of docs, then keep
    the ``keep_n`` most frequent survivors (by document frequency).
    """
    config = config or PreprocConfig()
    stop = set(config.stopwords)
    equiv = parse_equivalences(config.equivalences)

    token_docs: list[list[str]] = []
    for doc in docs:
        tokens = doc.split() if isinstance(doc, str) else list(doc)
        cleaned = []
        for tok in tokens:
            if tok in stop:
                continue
            tok = equiv.get(tok, tok)
            if tok and tok not in stop:
                cleaned.append(tok)
        token_docs.append(cleaned)

    n_docs = len(token_docs)
    df = Counter()
    for tokens in token_docs:
        df.update(set(tokens))

    max_df = config.no_above * n_docs
    survivors = [
        t for t, c in df.items() if c >= config.no_below and c <= max_df
    ]
    if len(survivors) > config.keep_n:
        # keep_n most document-frequent, ties broken alphabetically
        survivors.sort(key=lambda t: (-df[t], t))
        survivors = survivors[: config.keep_n]
    keep = set(survivors)

    out_docs, kept = [], []
    for i, tokens in enumerate(token_docs):
        filtered = [t for t in tokens if t in keep]
        if len(filtered) >= config.min_lemas:
            out_docs.append(filtered)
            kept.append(i)
    return PreprocResult(
        docs=out_docs, kept_indices=kept, vocabulary=sorted(keep)
    )
