"""Synthetic LDA corpus generator (vectorized, seedable).

Rebuild of ``src/utils/generate_synthetic.py:1-96`` and the generator inside
``experiments/dss_tss/run_simulation.py:77-181``: documents are drawn from a
known LDA generative model so ground-truth topic-word (``topic_vectors``) and
doc-topic (``doc_topics``) distributions are available for recovery tests
(TSS/DSS — the reference's de-facto correctness metric, SURVEY.md §4.1).

Node priors: ``frozen_topics`` shared topics get alpha each; each node
additionally owns ``(K - frozen)/n_nodes`` topics at alpha with the rest
suppressed at alpha/10000, rotating per node
(``generate_synthetic.py:42-60``).

The reference samples word-by-word in Python (~minutes); here each document's
BoW is drawn as topic-count multinomial then per-topic word multinomials —
identical distribution, vectorized over documents."""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass
class SyntheticNode:
    """One client's corpus with its ground truth."""

    bow: np.ndarray  # [n_docs, V] counts
    documents: list[str]  # whitespace-joined token strings ('wd17 wd5 ...')
    doc_topics: np.ndarray  # [n_docs, K] ground-truth theta


@dataclass
class SyntheticCorpus:
    topic_vectors: np.ndarray  # [K, V] ground-truth beta
    nodes: list[SyntheticNode]
    vocab_tokens: tuple[str, ...] = field(default_factory=tuple)

    @property
    def n_nodes(self) -> int:
        return len(self.nodes)


def _rotate(arr: list[float], d: int) -> list[float]:
    """Left-rotate by d (generate_synthetic.py:3-31)."""
    d = d % max(len(arr), 1)
    return arr[d:] + arr[:d]


def generate_synthetic_corpus(
    vocab_size: int = 5000,
    n_topics: int = 50,
    beta: float = 1e-2,
    alpha: float | None = None,
    n_docs: int = 1000,
    nwords: tuple[int, int] = (150, 250),
    n_nodes: int = 5,
    frozen_topics: int = 5,
    seed: int = 0,
    materialize_docs: bool = True,
) -> SyntheticCorpus:
    """Generate per-node corpora from the LDA generative model.

    Defaults mirror ``generate_synthetic.py:33-46``. ``alpha`` defaults to
    1/n_topics. ``materialize_docs=False`` skips building the token-string
    documents (BoW only — much faster for large benchmark corpora).
    """
    rng = np.random.default_rng(seed)
    alpha = 1.0 / n_topics if alpha is None else alpha

    # Step 1: topic-word distributions ~ Dirichlet(beta) (line 50).
    topic_vectors = rng.dirichlet(np.full(vocab_size, beta), n_topics)

    prior_frozen = [alpha] * frozen_topics
    own = (n_topics - frozen_topics) // max(n_nodes, 1)
    prior_nofrozen = [alpha] * own + [alpha / 10000.0] * (
        n_topics - frozen_topics - own
    )

    nodes = []
    for _node in range(n_nodes):
        # Step 2: per-node doc-topic proportions (lines 56-60).
        doc_topics = rng.dirichlet(np.array(prior_frozen + prior_nofrozen), n_docs)
        prior_nofrozen = _rotate(prior_nofrozen, own)

        # Step 3: documents — fully vectorized equivalent of lines 62-79.
        # Per-doc topic counts in one batched multinomial, then per topic the
        # words of ALL docs at once by inverse-CDF sampling (a multinomial is
        # the histogram of iid categorical draws — same distribution as the
        # reference's per-doc word loop, at O(total_words·log V) instead of
        # O(doc·topic·V) multinomial calls).
        doc_lens = rng.integers(nwords[0], nwords[1], size=n_docs)
        topic_counts = rng.multinomial(doc_lens, doc_topics)  # [n_docs, K]
        bow = np.zeros((n_docs, vocab_size), dtype=np.float32)
        doc_ids_all = np.arange(n_docs)
        for k in range(n_topics):
            c_k = topic_counts[:, k]
            total = int(c_k.sum())
            if total == 0:
                continue
            cdf = np.cumsum(topic_vectors[k])
            words = np.searchsorted(cdf, rng.random(total), side="right")
            words = np.minimum(words, vocab_size - 1)  # float-rounding guard
            np.add.at(bow, (np.repeat(doc_ids_all, c_k), words), 1.0)
        docs = []
        if materialize_docs:
            word_range = np.arange(vocab_size)
            for d in range(n_docs):
                word_ids = np.repeat(word_range, bow[d].astype(np.int64))
                docs.append(" ".join(f"wd{w}" for w in word_ids))
        nodes.append(SyntheticNode(bow=bow, documents=docs, doc_topics=doc_topics))

    vocab_tokens = tuple(f"wd{i}" for i in range(vocab_size))
    return SyntheticCorpus(
        topic_vectors=topic_vectors, nodes=nodes, vocab_tokens=vocab_tokens
    )


def dominant_topics(node: SyntheticNode) -> np.ndarray:
    """Per-doc dominant-topic labels from the ground-truth doc-topic
    proportions — the label axis the Dirichlet-α partitioner
    (:func:`gfedntm_tpu.data.loaders.heterogeneous_partition`) skews."""
    return np.argmax(np.asarray(node.doc_topics), axis=1)


def apply_vocabulary_skew(
    documents: list[str],
    client_id: int,
    private_frac: float,
    seed: int = 0,
) -> list[str]:
    """Pathological vocabulary skew persona: remap a seeded fraction of
    this client's vocabulary TYPES into a client-private token namespace
    (``c<id>x<token>``), so the federation's consensus vocabulary becomes
    a mostly-disjoint union — the regime that stresses vocab consensus
    and cross-client topic alignment (README "Scenario matrix").

    The privatize decision is per token type (first occurrence order),
    deterministic for a fixed ``(seed, client_id)`` and document order;
    every occurrence of a privatized type is rewritten consistently.
    """
    if not 0.0 <= private_frac <= 1.0:
        raise ValueError(
            f"private_frac must be in [0, 1], got {private_frac}"
        )
    rng = np.random.default_rng([int(seed), int(client_id)])
    mapping: dict[str, str] = {}
    out = []
    for doc in documents:
        toks = []
        for tok in doc.split():
            if tok not in mapping:
                mapping[tok] = (
                    f"c{client_id}x{tok}"
                    if rng.random() < private_frac
                    else tok
                )
            toks.append(mapping[tok])
        out.append(" ".join(toks))
    return out


def save_reference_npz(corpus: SyntheticCorpus, path: str, **meta) -> None:
    """Write the combined-archive format of ``synthetic_all_nodes.npz``
    (generate_synthetic.py:95-96) so reference tooling can read it."""
    np.savez(
        path,
        n_nodes=corpus.n_nodes,
        vocab_size=corpus.topic_vectors.shape[1],
        n_topics=corpus.topic_vectors.shape[0],
        topic_vectors=corpus.topic_vectors,
        doc_topics=np.array([n.doc_topics for n in corpus.nodes]),
        documents=np.array(
            [n.documents for n in corpus.nodes], dtype=object
        ),
        **meta,
    )


def load_reference_npz(path: str) -> SyntheticCorpus:
    """Load a reference-format synthetic archive (single- or multi-node):
    keys ``topic_vectors``, ``doc_topics``, ``documents``
    (``main.py:138-146`` reads the same keys)."""
    with np.load(path, allow_pickle=True) as z:
        topic_vectors = z["topic_vectors"]
        docs = z["documents"]
        doc_topics = z["doc_topics"]
        vocab_size = int(z["vocab_size"]) if "vocab_size" in z else topic_vectors.shape[1]
    if docs.ndim == 1 and isinstance(docs[0], str):  # single node
        docs = docs[None, :]
        doc_topics = doc_topics[None, ...]
    nodes = []
    for i in range(len(docs)):
        node_docs = [
            d if isinstance(d, str) else " ".join(d) for d in list(docs[i])
        ]
        nodes.append(
            SyntheticNode(
                bow=_bow_from_wd_docs(node_docs, vocab_size),
                documents=node_docs,
                doc_topics=np.asarray(doc_topics[i]),
            )
        )
    return SyntheticCorpus(
        topic_vectors=topic_vectors,
        nodes=nodes,
        vocab_tokens=tuple(f"wd{i}" for i in range(vocab_size)),
    )


def _bow_from_wd_docs(docs: list[str], vocab_size: int) -> np.ndarray:
    bow = np.zeros((len(docs), vocab_size), dtype=np.float32)
    for i, doc in enumerate(docs):
        for tok in doc.split():
            bow[i, int(tok[2:])] += 1
    return bow
