"""Corpus loaders: reference npz/parquet formats + 20Newsgroups.

Mirrors the reference entry point's data paths (``main.py:138-152``):
- synthetic ``.npz`` archives (see ``gfedntm_tpu.data.synthetic``),
- real ``.parquet`` corpora with a text column, optional ``fos``
  category filter, and optional precomputed SBERT ``embeddings`` column
  (``client.py:321-356`` pulls the embeddings column for CTM).
- 20Newsgroups (the BASELINE.json config-3 corpus) from a local scikit-learn
  cache or an explicit path; this environment has no network egress, so no
  download is attempted.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class RawCorpus:
    """Host-side corpus: raw text plus optional per-doc extras."""

    documents: list[str]
    embeddings: np.ndarray | None = None  # [n_docs, contextual_size]
    labels: np.ndarray | None = None  # [n_docs] int or [n_docs, L] one-hot

    def __len__(self) -> int:
        return len(self.documents)


def load_parquet_corpus(
    path: str,
    text_column: str = "all_rawtext",
    fos: str | None = None,
    fos_column: str = "fos",
    embeddings_column: str = "embeddings",
    max_docs: int | None = None,
) -> RawCorpus:
    """Read a reference-format parquet corpus, optionally filtered to one
    ``fos`` category (``main.py:147-152``)."""
    import pandas as pd

    df = pd.read_parquet(path)
    if fos is not None:
        df = df[df[fos_column] == fos]
    if max_docs is not None:
        df = df.head(max_docs)
    if text_column not in df.columns:
        # fall back to the first string-typed column
        candidates = [c for c in df.columns if df[c].dtype == object]
        if not candidates:
            raise ValueError(f"no text column found in {path}")
        text_column = candidates[0]
    docs = df[text_column].astype(str).tolist()
    embeddings = None
    if embeddings_column in df.columns:
        embeddings = np.stack(
            [np.asarray(e, dtype=np.float32) for e in df[embeddings_column]]
        )
    return RawCorpus(documents=docs, embeddings=embeddings)


def load_parquet_partitions(
    path: str,
    categories: list[str],
    text_column: str = "all_rawtext",
    fos_column: str = "fos",
    embeddings_column: str = "embeddings",
) -> list[RawCorpus]:
    """One read of the parquet, partitioned into one :class:`RawCorpus` per
    FOS category — avoids re-reading a multi-GB file once per client the
    way per-category :func:`load_parquet_corpus` calls would."""
    import pandas as pd

    df = pd.read_parquet(path)
    if text_column not in df.columns:
        candidates = [c for c in df.columns if df[c].dtype == object]
        if not candidates:
            raise ValueError(f"no text column found in {path}")
        text_column = candidates[0]
    out = []
    for category in categories:
        part = df[df[fos_column] == category]
        embeddings = None
        if embeddings_column in part.columns:
            embeddings = np.stack(
                [
                    np.asarray(e, dtype=np.float32)
                    for e in part[embeddings_column]
                ]
            ) if len(part) else None
        out.append(
            RawCorpus(
                documents=part[text_column].astype(str).tolist(),
                embeddings=embeddings,
            )
        )
    return out


def load_20newsgroups(
    data_home: str | None = None, subset: str = "train"
) -> RawCorpus:
    """Load 20Newsgroups from a local sklearn cache (no download)."""
    from sklearn.datasets import fetch_20newsgroups

    bunch = fetch_20newsgroups(
        subset=subset,
        data_home=data_home,
        remove=("headers", "footers", "quotes"),
        download_if_missing=False,
    )
    return RawCorpus(
        documents=list(bunch.data), labels=np.asarray(bunch.target)
    )


def partition_corpus(
    corpus: RawCorpus, n_clients: int, seed: int = 0, iid: bool = True
) -> list[RawCorpus]:
    """Split one corpus into per-client shards. ``iid=True`` shuffles then
    chunks evenly; ``iid=False`` sorts by label first (label-skewed non-IID,
    the collab_vs_non_collab regime of fos-partitioned corpora)."""
    n = len(corpus)
    rng = np.random.default_rng(seed)
    if iid or corpus.labels is None:
        order = rng.permutation(n)
    else:
        order = np.argsort(np.asarray(corpus.labels), kind="stable")
    shards = np.array_split(order, n_clients)
    out = []
    for shard in shards:
        out.append(
            RawCorpus(
                documents=[corpus.documents[i] for i in shard],
                embeddings=None
                if corpus.embeddings is None
                else corpus.embeddings[shard],
                labels=None if corpus.labels is None else np.asarray(corpus.labels)[shard],
            )
        )
    return out
