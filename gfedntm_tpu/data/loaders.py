"""Corpus loaders: reference npz/parquet formats + 20Newsgroups.

Mirrors the reference entry point's data paths (``main.py:138-152``):
- synthetic ``.npz`` archives (see ``gfedntm_tpu.data.synthetic``),
- real ``.parquet`` corpora with a text column, optional ``fos``
  category filter, and optional precomputed SBERT ``embeddings`` column
  (``client.py:321-356`` pulls the embeddings column for CTM).
- 20Newsgroups (the BASELINE.json config-3 corpus) from a local scikit-learn
  cache or an explicit path; this environment has no network egress, so no
  download is attempted.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class RawCorpus:
    """Host-side corpus: raw text plus optional per-doc extras."""

    documents: list[str]
    embeddings: np.ndarray | None = None  # [n_docs, contextual_size]
    labels: np.ndarray | None = None  # [n_docs] int or [n_docs, L] one-hot

    def __len__(self) -> int:
        return len(self.documents)


def load_parquet_corpus(
    path: str,
    text_column: str = "all_rawtext",
    fos: str | None = None,
    fos_column: str = "fos",
    embeddings_column: str = "embeddings",
    max_docs: int | None = None,
) -> RawCorpus:
    """Read a reference-format parquet corpus, optionally filtered to one
    ``fos`` category (``main.py:147-152``)."""
    import pandas as pd

    df = pd.read_parquet(path)
    if fos is not None:
        df = df[df[fos_column] == fos]
    if max_docs is not None:
        df = df.head(max_docs)
    if text_column not in df.columns:
        # fall back to the first string-typed column
        candidates = [c for c in df.columns if df[c].dtype == object]
        if not candidates:
            raise ValueError(f"no text column found in {path}")
        text_column = candidates[0]
    docs = df[text_column].astype(str).tolist()
    embeddings = None
    if embeddings_column in df.columns:
        embeddings = np.stack(
            [np.asarray(e, dtype=np.float32) for e in df[embeddings_column]]
        )
    return RawCorpus(documents=docs, embeddings=embeddings)


def load_parquet_partitions(
    path: str,
    categories: list[str],
    text_column: str = "all_rawtext",
    fos_column: str = "fos",
    embeddings_column: str = "embeddings",
) -> list[RawCorpus]:
    """One read of the parquet, partitioned into one :class:`RawCorpus` per
    FOS category — avoids re-reading a multi-GB file once per client the
    way per-category :func:`load_parquet_corpus` calls would."""
    import pandas as pd

    df = pd.read_parquet(path)
    if text_column not in df.columns:
        candidates = [c for c in df.columns if df[c].dtype == object]
        if not candidates:
            raise ValueError(f"no text column found in {path}")
        text_column = candidates[0]
    out = []
    for category in categories:
        part = df[df[fos_column] == category]
        embeddings = None
        if embeddings_column in part.columns:
            embeddings = np.stack(
                [
                    np.asarray(e, dtype=np.float32)
                    for e in part[embeddings_column]
                ]
            ) if len(part) else None
        out.append(
            RawCorpus(
                documents=part[text_column].astype(str).tolist(),
                embeddings=embeddings,
            )
        )
    return out


def load_20newsgroups(
    data_home: str | None = None, subset: str = "train"
) -> RawCorpus:
    """Load 20Newsgroups from a local sklearn cache (no download)."""
    from sklearn.datasets import fetch_20newsgroups

    bunch = fetch_20newsgroups(
        subset=subset,
        data_home=data_home,
        remove=("headers", "footers", "quotes"),
        download_if_missing=False,
    )
    return RawCorpus(
        documents=list(bunch.data), labels=np.asarray(bunch.target)
    )


def _subset(corpus: RawCorpus, idx: np.ndarray) -> RawCorpus:
    """One client shard of ``corpus`` at the given doc indices."""
    return RawCorpus(
        documents=[corpus.documents[i] for i in idx],
        embeddings=None
        if corpus.embeddings is None
        else corpus.embeddings[idx],
        labels=None
        if corpus.labels is None
        else np.asarray(corpus.labels)[idx],
    )


def imbalance_weights(n_clients: int, size_ratio: float) -> np.ndarray:
    """Geometric client-size weights whose largest/smallest ratio is
    ``size_ratio`` (1 = balanced) — the 10-100x client-size imbalance
    persona that stresses Horvitz-Thompson reweighting and sample
    weighting together (README "Scenario matrix")."""
    if size_ratio < 1.0:
        raise ValueError(f"size_ratio must be >= 1, got {size_ratio}")
    if n_clients == 1 or size_ratio == 1.0:
        return np.full(n_clients, 1.0 / n_clients)
    w = size_ratio ** (np.arange(n_clients) / (n_clients - 1))
    return w / w.sum()


def heterogeneous_partition(
    labels: "np.ndarray | None",
    n_docs: int,
    n_clients: int,
    alpha: float | None = None,
    size_ratio: float | None = None,
    seed: int = 0,
    min_docs: int = 1,
) -> list[np.ndarray]:
    """EXACT non-IID partition of ``n_docs`` docs into ``n_clients``
    index shards: every doc lands on exactly one client and the shard
    sizes sum to the corpus (multinomial splits, never rounding).

    Two orthogonal, composable axes:

    - ``alpha`` — Dirichlet-α label skew: per label class, client
      proportions are drawn from Dirichlet(α·1) and the class's docs
      split by an exact multinomial. α→∞ recovers ~IID mixtures; small α
      concentrates each class on few clients (the FL heterogeneity
      benchmark regime, arXiv:2309.13102). Requires ``labels``.
    - ``size_ratio`` — geometric client-size imbalance with
      largest/smallest = ratio (:func:`imbalance_weights`).

    When both are set, each class's Dirichlet proportions are tilted by
    the size weights (renormalized per class), so label skew and size
    skew compose. ``min_docs`` rebalances deterministically afterwards:
    starved shards take docs from the largest shard, preserving
    exactness. Fully seeded — the same inputs give the same partition.
    """
    if n_clients < 1:
        raise ValueError(f"n_clients must be >= 1, got {n_clients}")
    if alpha is not None and alpha <= 0:
        raise ValueError(f"alpha must be > 0, got {alpha}")
    if alpha is not None and labels is None:
        raise ValueError("Dirichlet-alpha partitioning needs labels")
    if min_docs * n_clients > n_docs:
        raise ValueError(
            f"min_docs={min_docs} x {n_clients} clients exceeds "
            f"{n_docs} docs"
        )
    rng = np.random.default_rng(seed)
    size_w = (
        imbalance_weights(n_clients, size_ratio)
        if size_ratio is not None
        else np.full(n_clients, 1.0 / n_clients)
    )
    if labels is None:
        labels = np.zeros(n_docs, dtype=np.int64)
    labels = np.asarray(labels)
    if len(labels) != n_docs:
        raise ValueError(
            f"labels length {len(labels)} != n_docs {n_docs}"
        )
    assign = np.full(n_docs, -1, dtype=np.int64)
    for cls in np.unique(labels):
        idx = np.flatnonzero(labels == cls)
        rng.shuffle(idx)
        p = (
            rng.dirichlet(np.full(n_clients, float(alpha)))
            if alpha is not None
            else np.ones(n_clients)
        )
        p = p * size_w
        p = p / p.sum()
        counts = rng.multinomial(len(idx), p)
        for c, part in enumerate(np.split(idx, np.cumsum(counts)[:-1])):
            assign[part] = c
    shards = [list(np.flatnonzero(assign == c)) for c in range(n_clients)]
    # Deterministic min_docs rebalance: starved shards draw from the
    # current largest shard (its tail docs), so totals stay exact.
    for c in range(n_clients):
        while len(shards[c]) < min_docs:
            donor = max(
                (k for k in range(n_clients) if k != c),
                key=lambda k: (len(shards[k]), -k),
            )
            if len(shards[donor]) <= min_docs:
                break  # nothing left to give without starving the donor
            shards[c].append(shards[donor].pop())
    return [np.asarray(sorted(s), dtype=np.int64) for s in shards]


def partition_corpus(
    corpus: RawCorpus,
    n_clients: int,
    seed: int = 0,
    iid: bool = True,
    alpha: float | None = None,
    size_ratio: float | None = None,
    min_docs: int = 1,
) -> list[RawCorpus]:
    """Split one corpus into per-client shards.

    Default modes (unchanged): ``iid=True`` shuffles then chunks evenly;
    ``iid=False`` sorts by label first (label-skewed non-IID, the
    collab_vs_non_collab regime of fos-partitioned corpora).

    Heterogeneity personas (README "Scenario matrix"): ``alpha`` and/or
    ``size_ratio`` route through :func:`heterogeneous_partition` —
    exact Dirichlet-α label skew and geometric client-size imbalance,
    composable and seeded.
    """
    n = len(corpus)
    if alpha is not None or size_ratio is not None:
        shards = heterogeneous_partition(
            None if corpus.labels is None else np.asarray(corpus.labels),
            n, n_clients, alpha=alpha, size_ratio=size_ratio, seed=seed,
            min_docs=min_docs,
        )
        return [_subset(corpus, shard) for shard in shards]
    rng = np.random.default_rng(seed)
    if iid or corpus.labels is None:
        order = rng.permutation(n)
    else:
        order = np.argsort(np.asarray(corpus.labels), kind="stable")
    return [
        _subset(corpus, shard) for shard in np.array_split(order, n_clients)
    ]
