"""GL006 rng-discipline: noise paths must never draw ambient randomness.

Ground truth (PR 18, the privacy plane): every DP noise draw must be a
pure function of an explicit ``(seed, application index)`` — the
accountant's ledger, the crash-autorecovery contract (a restored server
resumes the exact noise stream), and the host-oracle/device parity
tests all depend on it. Two failure shapes sneak past review:

- ``np.random.<fn>(...)`` **module-level** draws (``np.random.normal``,
  ``np.random.rand``, even ``np.random.seed`` — mutating the ambient
  global stream is as bad as reading it): any other library touching
  the global ``RandomState`` silently reorders the draws. The seeded
  factories (``np.random.default_rng``, ``Generator``, ``PCG64``, ...)
  are the sanctioned spelling and stay quiet.
- ``jax.random.PRNGKey(<literal>)`` with a hard-coded constant key
  outside tests: every process folds the SAME stream, so per-client /
  per-round noise is perfectly correlated — exactly the independence
  assumption the RDP composition theorem needs. Keys must derive from
  a seed that was passed in (``PRNGKey(int(seed))``, ``fold_in``).

Scope: the privacy package plus the two aggregation modules whose
noise/estimator paths the mechanisms ride through. Test files configure
the rule onto fixtures; the live-repo self-run must stay clean.
"""

from __future__ import annotations

import ast

from gfedntm_tpu.analysis.core import (
    Finding,
    LintContext,
    Rule,
    SourceFile,
    attr_root,
)

NP_ROOTS = frozenset({"np", "numpy"})

#: ``np.random.<name>`` attributes that are seeded constructors / types,
#: not draws from (or mutations of) the ambient global stream.
SEEDED_FACTORIES = frozenset({
    "default_rng",
    "Generator",
    "SeedSequence",
    "BitGenerator",
    "Philox",
    "PCG64",
    "PCG64DXSM",
    "MT19937",
    "SFC64",
})


def _np_random_fn(func: ast.AST) -> str | None:
    """``np.random.<fn>`` / ``numpy.random.<fn>`` -> ``fn`` (else None)."""
    if not isinstance(func, ast.Attribute):
        return None
    mid = func.value
    if not (isinstance(mid, ast.Attribute) and mid.attr == "random"):
        return None
    if not (isinstance(mid.value, ast.Name) and mid.value.id in NP_ROOTS):
        return None
    return func.attr


def _is_prngkey(func: ast.AST) -> bool:
    """``jax.random.PRNGKey`` / ``jrandom.PRNGKey`` / ``random.PRNGKey``
    (any chain ending in the attribute, rooted at a plausible jax
    handle)."""
    if not (isinstance(func, ast.Attribute) and func.attr == "PRNGKey"):
        return False
    root = attr_root(func)
    return root in {"jax", "jrandom", "jr", "random"}


class RngDisciplineRule(Rule):
    id = "GL006"
    name = "rng-discipline"
    description = (
        "noise paths must not draw from np.random's ambient global "
        "stream or hard-code jax PRNGKey literals — DP noise is a pure "
        "function of (seed, index)"
    )
    default_paths = (
        "gfedntm_tpu/privacy/",
        "gfedntm_tpu/federation/device_agg.py",
        "gfedntm_tpu/federation/aggregation.py",
    )

    NP_HINT = (
        "draw from an explicitly-seeded generator — "
        "np.random.default_rng((seed, index)) — so the stream is a pure "
        "function of the mechanism seed, not ambient process state"
    )
    KEY_HINT = (
        "derive the key from a seed that was passed in "
        "(jax.random.PRNGKey(int(seed)) + fold_in), never a hard-coded "
        "literal — a constant key correlates every process's noise"
    )

    def check_file(self, src: SourceFile, ctx: LintContext) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = _np_random_fn(node.func)
            if fn is not None and fn not in SEEDED_FACTORIES:
                out.append(self.finding(
                    src, node.lineno,
                    f"np.random.{fn}() draws from (or mutates) the "
                    "ambient global stream in a noise path",
                    hint=self.NP_HINT,
                ))
                continue
            if _is_prngkey(node.func) and node.args and isinstance(
                node.args[0], ast.Constant
            ):
                out.append(self.finding(
                    src, node.lineno,
                    f"{ast.unparse(node.func)}({node.args[0].value!r}) "
                    "hard-codes the PRNG key in a noise path",
                    hint=self.KEY_HINT,
                ))
        return out
