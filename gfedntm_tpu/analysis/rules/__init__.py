"""graftlint rule registry.

Adding an analyzer: implement a :class:`~gfedntm_tpu.analysis.core.Rule`
subclass in a module here, then register an instance in
:func:`make_default_rules` — the single list every execution path (the
CLI, ``scripts/check.sh``, the shims, ``run_lint``) draws from. See
README "Static analysis" for the checklist, and
``tests/test_analysis.py`` for the fixture pattern every rule ships
with: at least one seeded violation it catches and one negative fixture
it stays quiet on.
"""

from __future__ import annotations

from gfedntm_tpu.analysis.rules.donation import DonationSafetyRule
from gfedntm_tpu.analysis.rules.exceptions import ExceptionHygieneRule
from gfedntm_tpu.analysis.rules.locks import LockDisciplineRule
from gfedntm_tpu.analysis.rules.precision import PrecisionPinRule
from gfedntm_tpu.analysis.rules.rng import RngDisciplineRule
from gfedntm_tpu.analysis.rules.telemetry import TelemetryContractRule

__all__ = [
    "make_default_rules",
    "DonationSafetyRule",
    "ExceptionHygieneRule",
    "LockDisciplineRule",
    "PrecisionPinRule",
    "RngDisciplineRule",
    "TelemetryContractRule",
]


def make_default_rules() -> list:
    """Fresh instances of every registered rule (rules are stateless,
    but fresh instances keep test re-scoping from leaking)."""
    return [
        TelemetryContractRule(),
        PrecisionPinRule(),
        DonationSafetyRule(),
        LockDisciplineRule(),
        ExceptionHygieneRule(),
        RngDisciplineRule(),
    ]
