"""GL001 telemetry-contract: the telemetry schema is machine-checked.

Folds ``scripts/lint_telemetry.py`` into the framework (the script is
now a thin shim over this rule). Four sub-checks, all grounded in bugs
PRs 1/5/7 caught by hand:

- every ``<logger>.log("<event>", ...)`` call site names an event
  registered in ``observability.EVENT_SCHEMAS`` — an unregistered event
  passes silently in un-validated production loggers and explodes the
  first time a test constructs ``MetricsLogger(validate=True)``;
- reverse-lint: every DATA_PLANE_EVENTS + MODEL_QUALITY_EVENTS +
  SCALEOUT_EVENTS + SERVING_EVENTS + SCENARIO_EVENTS + FLEET_EVENTS +
  SURVIVAL_EVENTS + PRIVACY_EVENTS + INCIDENT_EVENTS entry keeps BOTH
  a schema registration and at least
  one emission site — a refactor that disconnects the admission-gate/
  guardian/quality/scale-plane/serving/scenario/fleet-alerting/
  crash-recovery/privacy/incident-forensics telemetry must not pass
  silently;
- every ``observability.TRACE_PLANE_SPANS`` name keeps a ``span(...)``
  call site — the ``trace`` CLI merges and parents by these names;
- scanner self-checks: zero ``.log(``/``span(`` sites at all means the
  regexes rotted, which is itself a finding.
"""

from __future__ import annotations

import re

from gfedntm_tpu.analysis.core import (
    Finding,
    LintContext,
    Rule,
    SourceFile,
)

#: An emission is `<expr>.log(` followed by a string-literal event name;
#: the codebase's MetricsLogger handles are `metrics`, `m`,
#: `logger.metrics`, `self.metrics`. Python `logging` handles use level
#: methods (.info/.warning) and never pass a string literal to .log, so
#: a quoted first argument marks a telemetry emission. (Spelled without
#: a literal example here — this module is inside its own scan scope.)
LOG_CALL = re.compile(r"""\.log\(\s*\n?\s*["']([a-z][a-z0-9_]*)["']""")

#: `span(` call sites with a logger expression and a string-literal
#: span name — the vocabulary the trace-merge CLI keys on.
SPAN_CALL = re.compile(
    r"""\bspan\(\s*\n?\s*[\w.()\[\]]+\s*,\s*\n?\s*["']([a-z][a-z0-9_]*)["']"""
)

#: Where the schema constants live (findings about the *registry* side
#: anchor on the constant's definition line in this module).
SCHEMA_MODULE = "gfedntm_tpu/utils/observability.py"


def _call_sites(
    files: list[SourceFile], pattern: "re.Pattern"
) -> dict[str, list[tuple[str, int]]]:
    """Map of matched name -> [(rel_path, line)] across the file set."""
    sites: dict[str, list[tuple[str, int]]] = {}
    for src in files:
        for m in pattern.finditer(src.text):
            line = src.text.count("\n", 0, m.start()) + 1
            sites.setdefault(m.group(1), []).append((src.rel, line))
    return sites


class TelemetryContractRule(Rule):
    id = "GL001"
    name = "telemetry-contract"
    description = (
        "events registered in EVENT_SCHEMAS <=> emitted; trace-plane "
        "span call sites exist; data-plane/model-quality reverse-lint"
    )
    # The historical lint scanned the package + bench.py; main.py rides
    # along in the default scan set but has no telemetry of its own.
    default_paths = ("gfedntm_tpu/", "bench.py", "main.py")

    def _contract(self, ctx: LintContext) -> dict:
        """The schema contract: event names, required reverse-lint
        groups, span vocabulary. Tests override via
        ``ctx.options["telemetry"]``; the default imports the live
        registry."""
        override = ctx.options.get("telemetry")
        if override is not None:
            return override
        from gfedntm_tpu.utils.observability import (
            DATA_PLANE_EVENTS,
            EVENT_SCHEMAS,
            FLEET_EVENTS,
            INCIDENT_EVENTS,
            MODEL_QUALITY_EVENTS,
            PRIVACY_EVENTS,
            SCALEOUT_EVENTS,
            SCENARIO_EVENTS,
            SERVING_EVENTS,
            SURVIVAL_EVENTS,
            TRACE_PLANE_SPANS,
        )

        return {
            "events": EVENT_SCHEMAS,
            "required": {
                "DATA_PLANE_EVENTS": tuple(DATA_PLANE_EVENTS),
                "MODEL_QUALITY_EVENTS": tuple(MODEL_QUALITY_EVENTS),
                "SCALEOUT_EVENTS": tuple(SCALEOUT_EVENTS),
                "SERVING_EVENTS": tuple(SERVING_EVENTS),
                "SCENARIO_EVENTS": tuple(SCENARIO_EVENTS),
                "FLEET_EVENTS": tuple(FLEET_EVENTS),
                "SURVIVAL_EVENTS": tuple(SURVIVAL_EVENTS),
                "PRIVACY_EVENTS": tuple(PRIVACY_EVENTS),
                "INCIDENT_EVENTS": tuple(INCIDENT_EVENTS),
            },
            "spans": tuple(TRACE_PLANE_SPANS),
            "schema_module": SCHEMA_MODULE,
        }

    def _covers_default_scan(
        self, files: list[SourceFile], ctx: LintContext
    ) -> bool:
        import os

        from gfedntm_tpu.analysis.core import collect_default_files

        rels = {f.rel for f in files}
        root = os.path.abspath(ctx.root)
        for path in collect_default_files(root):
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            if self.applies_to(rel) and rel not in rels:
                return False
        return True

    def _anchor(self, files: list[SourceFile], module: str,
                symbol: str) -> tuple[str, int]:
        """Anchor registry-side findings at the constant's definition."""
        for src in files:
            if src.rel == module:
                for i, text in enumerate(src.lines, start=1):
                    if text.startswith(symbol):
                        return (src.rel, i)
                return (src.rel, 1)
        return (module, 1)

    def check_repo(
        self, files: list[SourceFile], ctx: LintContext
    ) -> list[Finding]:
        if not files:  # nothing in this rule's scope was scanned
            return []
        contract = self._contract(ctx)
        schemas = contract["events"]
        module = contract.get("schema_module", SCHEMA_MODULE)
        out: list[Finding] = []

        # The reverse-lints ("this event is emitted NOWHERE", "zero call
        # sites at all") are whole-repo statements — meaningless on an
        # explicit file subset, INCLUDING a subset that happens to
        # contain the schema module (the emission sites live elsewhere).
        # They run only when the scanned set covers the rule's whole
        # default scope, or under a test-fixture contract.
        full_scan = (
            ctx.options.get("telemetry") is not None
            or self._covers_default_scan(files, ctx)
        )

        log_sites = _call_sites(files, LOG_CALL)
        if full_scan and not log_sites:
            rel, line = self._anchor(files, module, "EVENT_SCHEMAS")
            out.append(self.finding(
                rel, line,
                "found no .log() call sites anywhere — the telemetry "
                "scanner regex is probably broken",
                hint="fix LOG_CALL in analysis/rules/telemetry.py",
            ))
            return out

        for event, sites in sorted(log_sites.items()):
            if event in schemas:
                continue
            for rel, line in sites:
                out.append(self.finding(
                    rel, line,
                    f"event {event!r} is emitted here but not registered "
                    "in observability.EVENT_SCHEMAS",
                    hint=(
                        "add the event (with its field set) to "
                        "EVENT_SCHEMAS, or rename the emission to a "
                        "registered event"
                    ),
                ))

        if not full_scan:
            return out

        for group, events in contract.get("required", {}).items():
            rel, line = self._anchor(files, module, group)
            for event in events:
                if event not in schemas:
                    out.append(self.finding(
                        rel, line,
                        f"required {group} event {event!r} is missing "
                        "from EVENT_SCHEMAS",
                        hint="re-register the event — this group is the "
                             "data-plane/quality defense contract",
                    ))
                if event not in log_sites:
                    out.append(self.finding(
                        rel, line,
                        f"required {group} event {event!r} has no "
                        ".log() emission site left",
                        hint="the defense telemetry was disconnected by a "
                             "refactor; restore the emission",
                    ))

        spans = contract.get("spans", ())
        if spans:
            span_sites = _call_sites(files, SPAN_CALL)
            if not span_sites:
                rel, line = self._anchor(files, module, "TRACE_PLANE_SPANS")
                out.append(self.finding(
                    rel, line,
                    "found no span() call sites anywhere — the span "
                    "scanner regex is probably broken",
                    hint="fix SPAN_CALL in analysis/rules/telemetry.py",
                ))
            else:
                for name in spans:
                    if name not in span_sites:
                        rel, line = self._anchor(
                            files, module, "TRACE_PLANE_SPANS"
                        )
                        out.append(self.finding(
                            rel, line,
                            f"trace-plane span {name!r} has no span() "
                            "call site — the trace CLI merges and "
                            "parents by this name",
                            hint="restore the span or update "
                                 "TRACE_PLANE_SPANS",
                        ))
        return out

    # Expose the scan maps so the lint_telemetry shim (and summarize
    # tooling) can keep reporting totals.
    @staticmethod
    def emitted_events(files: list[SourceFile]) -> dict[str, list[tuple[str, int]]]:
        return _call_sites(files, LOG_CALL)

    @staticmethod
    def declared_spans(files: list[SourceFile]) -> dict[str, list[tuple[str, int]]]:
        return _call_sites(files, SPAN_CALL)
