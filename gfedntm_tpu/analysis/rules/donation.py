"""GL003 donation-safety: donated buffers must not be touched again.

Ground truth (PR 6 review pass): ``avitm`` built its epoch program with
``donate=True`` while the fused-decoder fallback path *retried the same
call with the same state arrays* — an execution-time failure of a
donating program leaves its donated inputs deleted, so the retry would
read dead buffers. The same composition hazard applies to any
``jax.jit(..., donate_argnums=...)`` program whose inputs are referenced
after the call.

Mechanics, per function scope:

- a name assigned from a call carrying ``donate=True`` (literal),
  a literal ``donate_argnums=(...)``, or the repo's
  ``donation_argnums((...))`` helper with a literal position tuple is a
  *donating program*; the literal positions are its donated argument
  slots (``donate=True`` alone donates every positional argument —
  conservative, because the builder's convention is unknown statically);
- at each later call of that program, the names passed in donated slots
  are *consumed*;
- any ``Load`` of a consumed name after the call — before the name is
  rebound — is a finding. Rebinding through the calling statement's own
  assignment targets (``state = prog(state, ...)``) is the sanctioned
  linear-state pattern and passes; a retry of the program with the same
  name (e.g. in an ``except`` handler) is exactly the fused-fallback
  hazard and fails.
"""

from __future__ import annotations

import ast

from gfedntm_tpu.analysis.core import (
    Finding,
    LintContext,
    Rule,
    SourceFile,
    iter_scopes,
    walk_scope,
)

#: The repo's backend-gated donation helper (train/steps.py).
DONATION_HELPER = "donation_argnums"


def _literal_positions(node: ast.AST) -> tuple[int, ...] | None:
    """Donated positions from a literal int / tuple-of-ints AST node."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            if not (
                isinstance(elt, ast.Constant) and isinstance(elt.value, int)
            ):
                return None
        return tuple(elt.value for elt in node.elts)
    return None


def _donated_positions(call: ast.Call) -> tuple[int, ...] | None | bool:
    """Classify a call expression: ``False`` when it is not a donating
    build, a position tuple when the donated slots are known, ``None``
    when it donates but the slots are unknown (all positionals)."""
    for kw in call.keywords:
        if kw.arg == "donate":
            if isinstance(kw.value, ast.Constant) and kw.value.value is True:
                return None
            continue
        if kw.arg == "donate_argnums":
            pos = _literal_positions(kw.value)
            if pos is not None:
                return pos
            # donation_argnums((0, 1, 2)[, donate=...]): the repo helper
            # returns its literal argnums on accelerators — donating
            # unless its own donate flag is literally False.
            v = kw.value
            if (
                isinstance(v, ast.Call)
                and isinstance(v.func, ast.Name)
                and v.func.id == DONATION_HELPER
                and v.args
            ):
                gate_off = any(
                    k.arg == "donate"
                    and isinstance(k.value, ast.Constant)
                    and k.value.value is False
                    for k in v.keywords
                )
                if not gate_off:
                    return _literal_positions(v.args[0])
            continue
    return False


def _pos(node: ast.AST) -> tuple[int, int]:
    return (
        getattr(node, "end_lineno", node.lineno),
        getattr(node, "end_col_offset", node.col_offset),
    )


class DonationSafetyRule(Rule):
    id = "GL003"
    name = "donation-safety"
    description = (
        "arrays passed to a buffer-donating jitted program must not be "
        "referenced after the call (fallback retries included)"
    )
    default_paths = None  # donation can appear anywhere in the package

    HINT = (
        "a donating program deletes its donated inputs even when it "
        "FAILS at execution time — rebind the result "
        "(state = prog(state)), copy before the call "
        "(jax.tree.map(jnp.copy, state)), or build the program with "
        "donate=False on paths that may retry"
    )

    def check_file(self, src: SourceFile, ctx: LintContext) -> list[Finding]:
        out: list[Finding] = []
        for _scope, body in iter_scopes(src.tree):
            out.extend(self._check_scope(body, src))
        return out

    def _check_scope(
        self, body: list[ast.stmt], src: SourceFile
    ) -> list[Finding]:
        # Pass 1: donating-program names and their donated slots.
        programs: dict[str, tuple[int, ...] | None] = {}
        for node in walk_scope(body):
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            donated = _donated_positions(node.value)
            if donated is False:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    programs[tgt.id] = donated
        if not programs:
            return []

        # Pass 2: donation events (call site, consumed names) and name
        # accesses, in source order. An assignment's targets bind AFTER
        # its value evaluates, so target stores are emitted at the
        # statement's END position — `state = prog(state)` rebinds
        # `state` after the donation, which is the sanctioned pattern.
        consumed: list[tuple[tuple[int, int], ast.Call, list[str]]] = []
        accesses: list[tuple[tuple[int, int], str, str, int]] = []
        assign_spans: list[tuple[tuple[int, int], tuple[int, int]]] = []
        for node in walk_scope(body):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in programs
            ):
                slots = programs[node.func.id]
                names = [
                    a.id for i, a in enumerate(node.args)
                    if isinstance(a, ast.Name)
                    and (slots is None or i in slots)
                ]
                if names:
                    consumed.append((_pos(node), node, names))
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                end = _pos(node)
                assign_spans.append(
                    ((node.lineno, node.col_offset), end)
                )
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for tgt in targets:
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name):
                            accesses.append(
                                (end, n.id, "store", n.lineno)
                            )
                            if isinstance(node, ast.AugAssign):
                                # `x += ...` also READS x, at its own
                                # position — a donated-buffer use.
                                accesses.append((
                                    (n.lineno, n.col_offset), n.id,
                                    "load", n.lineno,
                                ))
        for node in walk_scope(body):
            if not isinstance(node, ast.Name):
                continue
            own = (node.lineno, node.col_offset)
            if isinstance(node.ctx, ast.Load):
                accesses.append((own, node.id, "load", node.lineno))
            elif not any(
                start <= own <= end for start, end in assign_spans
            ):
                # Store/Del outside any assignment (for-targets,
                # with-as, except-as): binds at its own position.
                accesses.append((own, node.id, "store", node.lineno))
        if not consumed:
            return []
        accesses.sort(key=lambda a: a[0])

        out: list[Finding] = []
        flagged: set[tuple[str, int]] = set()
        for call_end, call, names in consumed:
            pending = set(names)
            for pos, name, kind, line in accesses:
                if not pending:
                    break
                if name not in pending:
                    continue
                if kind == "store":
                    # Rebound at-or-after the donating call (the
                    # `state = prog(state)` assign's target store is
                    # emitted at the statement end, which EQUALS the
                    # call end): the old buffer is no longer reachable
                    # through this name.
                    if pos >= call_end:
                        pending.discard(name)
                    continue
                if pos <= call_end:
                    continue
                key = (name, line)
                if key not in flagged:
                    flagged.add(key)
                    out.append(self.finding(
                        src, line,
                        f"{name!r} was donated to "
                        f"{ast.unparse(call.func)}() on line "
                        f"{call.lineno} and is referenced again here",
                        hint=self.HINT,
                    ))
                pending.discard(name)
        return out
