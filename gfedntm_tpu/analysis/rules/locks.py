"""GL004 lock-discipline: guarded attributes mutate only under their lock.

The federation control plane is multi-threaded: gRPC servicer threads
(OfferVocab / ReadyForTraining / disconnects) race the training loop and
its poll/push pool workers. Shared mutable state is declared with a
``# guarded-by: <lock>[, <lock2>...]`` comment on the attribute's
declaration line (a dataclass field, or its ``__init__`` assignment):

    self._push_acked: set[int] = set()  # guarded-by: _push_lock
    _clients: dict = field(default_factory=dict)  # guarded-by: _lock, _cond

Naming several locks means holding ANY of them suffices — the idiom for
a ``threading.Condition`` wrapping the same ``RLock`` (``with
self._cond:`` acquires ``_lock``).

The rule then checks every method of the class: assignments to
``self.<attr>``, item/attribute stores through it, ``del``, and calls to
known mutator methods (``add``/``discard``/``pop``/``update``/...) must
sit lexically inside ``with self.<lock>:`` for one of the declared
locks. ``__init__``/``__post_init__`` are exempt (construction
happens-before publication), and a nested function body does NOT
inherit the enclosing ``with`` — closures handed to thread pools run
after the lock is released, which is exactly the bug class this catches.
Reads are not checked (snapshot-read-then-act patterns are reviewed by
humans); the write side is what corrupts registries.
"""

from __future__ import annotations

import ast
import re

from gfedntm_tpu.analysis.core import (
    Finding,
    LintContext,
    Rule,
    SourceFile,
)

GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([\w, |]+)")

#: Mutating container/set/dict/list method names.
MUTATORS = frozenset({
    "add", "append", "appendleft", "clear", "discard", "extend",
    "insert", "pop", "popitem", "popleft", "remove", "reverse",
    "setdefault", "sort", "update",
})

EXEMPT_METHODS = frozenset({"__init__", "__post_init__"})


def _guarded_decls(cls: ast.ClassDef, src: SourceFile) -> dict[str, tuple[str, ...]]:
    """Attribute -> allowed locks, from guarded-by comments on class-level
    field declarations and ``__init__``/``__post_init__`` self-assignments."""
    decls: dict[str, tuple[str, ...]] = {}

    def note(attr: str, line: int) -> None:
        m = GUARDED_BY_RE.search(src.lines[line - 1]) if line <= len(src.lines) else None
        if m:
            locks = tuple(
                p.strip() for p in re.split(r"[|,]", m.group(1)) if p.strip()
            )
            if locks:
                decls[attr] = locks

    for stmt in cls.body:
        if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
            note(stmt.target.id, stmt.lineno)
        elif isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    note(tgt.id, stmt.lineno)
        elif (
            isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
            and stmt.name in EXEMPT_METHODS
        ):
            for node in ast.walk(stmt):
                attr = _self_attr_target(node)
                if attr is not None:
                    note(attr, node.lineno)
    return decls


def _self_attr_target(node: ast.AST) -> str | None:
    """``self.<attr>`` assignment target name for Assign/AnnAssign."""
    if isinstance(node, (ast.Assign, ast.AnnAssign)):
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        for tgt in targets:
            if (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
            ):
                return tgt.attr
    return None


def _with_locks(node: ast.With) -> set[str]:
    """Lock attribute names acquired by ``with self.<lock>[, ...]:``."""
    out: set[str] = set()
    for item in node.items:
        expr = item.context_expr
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
        ):
            out.add(expr.attr)
    return out


class LockDisciplineRule(Rule):
    id = "GL004"
    name = "lock-discipline"
    description = (
        "attributes declared '# guarded-by: <lock>' are only mutated "
        "inside 'with self.<lock>:' (closures do not inherit the lock)"
    )
    default_paths = None  # annotation-driven: fires only where declared

    def check_file(self, src: SourceFile, ctx: LintContext) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                out.extend(self._check_class(node, src))
        return out

    def _check_class(
        self, cls: ast.ClassDef, src: SourceFile
    ) -> list[Finding]:
        guarded = _guarded_decls(cls, src)
        if not guarded:
            return []
        out: list[Finding] = []
        for stmt in cls.body:
            if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if stmt.name in EXEMPT_METHODS:
                continue
            self._walk(stmt.body, frozenset(), guarded, src, out)
        return out

    def _walk(
        self,
        body: list[ast.stmt],
        held: frozenset[str],
        guarded: dict[str, tuple[str, ...]],
        src: SourceFile,
        out: list[Finding],
    ) -> None:
        """Visit one statement block with the set of lexically held
        locks; recurse into sub-blocks (with-bodies gain their locks,
        nested function bodies LOSE everything — a closure runs when
        called, usually on another thread)."""
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk(stmt.body, frozenset(), guarded, src, out)
                continue
            if isinstance(stmt, ast.With):
                self._check_stmt(stmt, held, guarded, src, out)
                self._walk(
                    stmt.body, held | _with_locks(stmt), guarded, src, out
                )
                continue
            self._check_stmt(stmt, held, guarded, src, out)
            for block in self._sub_blocks(stmt):
                self._walk(block, held, guarded, src, out)

    @staticmethod
    def _sub_blocks(stmt: ast.stmt) -> list[list[ast.stmt]]:
        blocks: list[list[ast.stmt]] = []
        for name in ("body", "orelse", "finalbody"):
            child = getattr(stmt, name, None)
            if isinstance(child, list) and child and isinstance(
                child[0], ast.stmt
            ):
                blocks.append(child)
        for handler in getattr(stmt, "handlers", ()):
            blocks.append(handler.body)
        return blocks

    @staticmethod
    def _expr_parts(stmt: ast.stmt):
        """Every expression node belonging to this statement itself —
        pruned at nested statements and nested functions/lambdas."""
        stack: list[ast.AST] = []
        for child in ast.iter_child_nodes(stmt):
            if not isinstance(
                child,
                (ast.stmt, ast.ExceptHandler, ast.FunctionDef,
                 ast.AsyncFunctionDef, ast.Lambda),
            ):
                stack.append(child)
        while stack:
            node = stack.pop()
            yield node
            for child in ast.iter_child_nodes(node):
                if not isinstance(
                    child,
                    (ast.stmt, ast.ExceptHandler, ast.FunctionDef,
                     ast.AsyncFunctionDef, ast.Lambda),
                ):
                    stack.append(child)

    def _check_stmt(
        self, stmt, held, guarded, src, out
    ) -> None:
        candidates: list[ast.AST] = [stmt]
        candidates.extend(
            n for n in self._expr_parts(stmt) if isinstance(n, ast.Call)
        )
        for node in candidates:
            attr, how = self._mutation(node)
            if attr is None or attr not in guarded:
                continue
            allowed = guarded[attr]
            if not (held & set(allowed)):
                locks = " or ".join(f"self.{lk}" for lk in allowed)
                out.append(self.finding(
                    src, node.lineno,
                    f"self.{attr} is '# guarded-by: "
                    f"{', '.join(allowed)}' but is {how} without "
                    f"holding {locks}",
                    hint=(
                        f"wrap the mutation in 'with self.{allowed[0]}:' "
                        "(note: a closure does not inherit an enclosing "
                        "with-block's lock)"
                    ),
                ))

    def _mutation(self, node: ast.AST) -> tuple[str | None, str]:
        """``(attr, description)`` when this node mutates ``self.<attr>``."""
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for tgt in targets:
                attr = self._target_attr(tgt)
                if attr is not None:
                    return attr, "assigned"
        if isinstance(node, ast.Delete):
            for tgt in node.targets:
                attr = self._target_attr(tgt)
                if attr is not None:
                    return attr, "deleted"
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in MUTATORS
            and isinstance(node.func.value, ast.Attribute)
            and isinstance(node.func.value.value, ast.Name)
            and node.func.value.value.id == "self"
        ):
            return (
                node.func.value.attr,
                f"mutated via .{node.func.attr}()",
            )
        return None, ""

    def _target_attr(self, tgt: ast.AST) -> str | None:
        """self.<attr> (direct) or self.<attr>[...] (item store)."""
        if isinstance(tgt, ast.Subscript):
            tgt = tgt.value
        if (
            isinstance(tgt, ast.Attribute)
            and isinstance(tgt.value, ast.Name)
            and tgt.value.id == "self"
        ):
            return tgt.attr
        return None
    # NOTE: reads are deliberately unchecked — see module docstring.
