"""GL002 precision-pin: gram-style device matmuls must pin HIGHEST.

Ground truth (PR 6 review pass): TPUs default f32 matmuls to bf16
passes, and the gram identity ``||a-b||^2 = ||a||^2 + ||b||^2 - 2ab``
cancels catastrophically for nearby rows — exactly the distances Krum
ranks and the cosines contribution analytics report — so an unpinned
matmul is bitwise-fine on the CPU test mesh and silently wrong on the
hardware the code exists for. In the gram-path modules
(``federation/device_agg.py``, ``federation/aggregation.py``,
``eval/monitor.py``) every jax matmul must pin
``precision=jax.lax.Precision.HIGHEST``.

``utils/flops.py`` (PR 12) is gram-adjacent and in scope too: its
matmul probe is the MFU *denominator* for the multi-chip throughput
accounting, and an unpinned probe on TPU would measure the bf16-pass
peak — silently inflating the reported peak ~4x and deflating every
MFU built on it. The training-step matmuls themselves
(``train/steps.py``, the model modules) stay out of scope: they are
ordinary forward/backward compute whose precision is the model's
``compute_dtype`` policy, not a gram identity.

Mechanics: only *jax-traced* scopes are checked — a function (or the
module body) counts as jax-traced when its own statements reference the
``jnp``/``jax``/``lax`` roots. Inside such a scope:

- calls to ``{jnp,jax,lax}...{matmul,dot,dot_general,tensordot,vdot,
  einsum}`` must carry a ``precision=`` keyword naming ``HIGHEST``;
- a bare ``@`` (``ast.MatMult``) is flagged unless both operands are
  provably numpy-derived (host oracles like ``aggregation.Krum`` and
  ``monitor._cosine_matrix`` run pure numpy and are exempt both ways:
  their scopes reference no jax root, and their operands carry np
  taint).
"""

from __future__ import annotations

import ast

from gfedntm_tpu.analysis.core import (
    Finding,
    LintContext,
    Rule,
    SourceFile,
    attr_root,
    expr_roots,
    iter_scopes,
    walk_scope,
)

JAX_ROOTS = frozenset({"jnp", "jax", "lax"})
NP_ROOTS = frozenset({"np", "numpy"})
MATMUL_ATTRS = frozenset(
    {"matmul", "dot", "dot_general", "tensordot", "vdot", "einsum"}
)


def _mentions_jax(body: list[ast.stmt]) -> bool:
    for n in walk_scope(body):
        if isinstance(n, ast.Name) and n.id in JAX_ROOTS:
            return True
    return False


def _precision_is_highest(kw_value: ast.AST) -> bool:
    for n in ast.walk(kw_value):
        if isinstance(n, ast.Attribute) and n.attr == "HIGHEST":
            return True
        if isinstance(n, ast.Constant) and str(n.value).upper() == "HIGHEST":
            return True
    return False


class PrecisionPinRule(Rule):
    id = "GL002"
    name = "precision-pin"
    description = (
        "jax matmuls in gram-path modules must pin "
        "precision=Precision.HIGHEST (TPU bf16 passes cancel in gram "
        "identities)"
    )
    default_paths = (
        "gfedntm_tpu/federation/device_agg.py",
        "gfedntm_tpu/federation/aggregation.py",
        "gfedntm_tpu/eval/monitor.py",
        "gfedntm_tpu/utils/flops.py",
    )

    HINT = (
        "use jnp.matmul(..., precision=jax.lax.Precision.HIGHEST) — "
        "TPU f32 matmuls default to bf16 passes and the gram identity "
        "cancels catastrophically for nearby rows"
    )

    def check_file(self, src: SourceFile, ctx: LintContext) -> list[Finding]:
        out: list[Finding] = []
        for _scope, body in iter_scopes(src.tree):
            if not _mentions_jax(body):
                continue
            np_tainted: set[str] = set()
            # Taint propagates in statement order within the scope:
            # collect (node, kind) events sorted by position.
            nodes = sorted(
                (n for n in walk_scope(body)
                 if isinstance(n, (ast.Assign, ast.BinOp, ast.Call))),
                key=lambda n: (n.lineno, n.col_offset),
            )
            for node in nodes:
                if isinstance(node, ast.Assign):
                    self._propagate_taint(node, np_tainted)
                elif isinstance(node, ast.Call):
                    f = self._check_call(node, src)
                    if f is not None:
                        out.append(f)
                elif isinstance(node.op, ast.MatMult):
                    if not (
                        self._np_derived(node.left, np_tainted)
                        and self._np_derived(node.right, np_tainted)
                    ):
                        out.append(self.finding(
                            src, node.lineno,
                            "bare '@' matmul in a jax-traced scope has no "
                            "precision pin",
                            hint=self.HINT,
                        ))
        return out

    def _propagate_taint(self, node: ast.Assign, tainted: set[str]) -> None:
        roots = expr_roots(node.value)
        is_np = bool(roots & NP_ROOTS) or (
            bool(roots) and roots <= (tainted | NP_ROOTS)
        )
        is_jax = bool(roots & JAX_ROOTS)
        for tgt in node.targets:
            for n in ast.walk(tgt):
                if isinstance(n, ast.Name):
                    if is_np and not is_jax:
                        tainted.add(n.id)
                    else:
                        tainted.discard(n.id)

    def _np_derived(self, node: ast.AST, tainted: set[str]) -> bool:
        roots = expr_roots(node)
        if not roots:
            return False
        return all(r in NP_ROOTS or r in tainted for r in roots)

    def _check_call(self, node: ast.Call, src: SourceFile) -> Finding | None:
        func = node.func
        if not (
            isinstance(func, ast.Attribute) and func.attr in MATMUL_ATTRS
        ):
            return None
        if attr_root(func) not in JAX_ROOTS:
            return None
        precision = next(
            (kw for kw in node.keywords if kw.arg == "precision"), None
        )
        if precision is None:
            return self.finding(
                src, node.lineno,
                f"{ast.unparse(func)}() in a gram-path module has no "
                "precision= pin",
                hint=self.HINT,
            )
        if not _precision_is_highest(precision.value):
            return self.finding(
                src, node.lineno,
                f"{ast.unparse(func)}() pins precision="
                f"{ast.unparse(precision.value)}, not Precision.HIGHEST",
                hint=self.HINT,
            )
        return None
