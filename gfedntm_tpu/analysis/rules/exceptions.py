"""GL005 exception-hygiene: no silent ``except Exception`` in the planes.

The federation round loop and the telemetry planes contain ``except
Exception`` blocks by design — telemetry must never kill the round loop,
checkpoints are the recovery path, not the workload. The discipline PRs
1-7 converged on: every such handler must make the failure *observable*:
log it (``logger.exception``/``error``/``warning``), bump a counter /
emit a telemetry event (``.inc()``/``.log()``), re-raise, or hand the
exception object on to a helper that does (``self._note_client_failure(
..., exc, ...)``). A handler that does none of those converts a failure
into silence — the bug class where the bench shipped CPU numbers for
three rounds because the accelerator path swallowed its timeout.

A finding anchors at the ``except`` line. Intentionally-silent probes
(e.g. device memory-stats feature detection, where the absence of stats
IS the answer) carry an inline ``# graftlint: disable=exception-hygiene``
with a justification, or a baseline entry.
"""

from __future__ import annotations

import ast

from gfedntm_tpu.analysis.core import (
    Finding,
    LintContext,
    Rule,
    SourceFile,
)

#: Calls that make a failure observable when they appear in the handler.
OBSERVING_ATTRS = frozenset({
    "log", "inc", "observe",                      # telemetry emission
    "exception", "error", "warning", "critical",  # logging
})

BROAD = frozenset({"Exception", "BaseException"})


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:  # bare except:
        return True
    names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
    for n in names:
        if isinstance(n, ast.Name) and n.id in BROAD:
            return True
        if isinstance(n, ast.Attribute) and n.attr in BROAD:
            return True
    return False


def _observes(handler: ast.ExceptHandler) -> bool:
    exc_name = handler.name
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if not isinstance(node, ast.Call):
            continue
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in OBSERVING_ATTRS
        ):
            return True
    if exc_name is not None:
        # Delegation/surfacing: the bound exception object is USED —
        # handed to a callee that owns the accounting
        # (self._note_client_failure(..., exc, ...)), written to stderr,
        # formatted into an HTTP 500 body, banked into a summary field.
        # Silence means catching and never looking at the failure.
        for node in ast.walk(handler):
            if (
                isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node.id == exc_name
            ):
                return True
    return False


class ExceptionHygieneRule(Rule):
    id = "GL005"
    name = "exception-hygiene"
    description = (
        "except Exception in federation/telemetry code must log an "
        "event, bump a counter, delegate the exception, or re-raise"
    )
    default_paths = (
        "gfedntm_tpu/federation/",
        # The serving plane answers live user traffic: a swallowed model-
        # load or request-path failure is an outage nobody can see.
        "gfedntm_tpu/serving/",
        "gfedntm_tpu/utils/observability.py",
        "gfedntm_tpu/train/guardian.py",
        "gfedntm_tpu/train/checkpoint.py",
        "gfedntm_tpu/eval/monitor.py",
        "bench.py",
        # The process-level chaos harness manages subprocess lifecycles
        # with the same stakes: a reconnect/supervision loop that
        # swallows its failure reports a green kill-test that proved
        # nothing.
        "tests/chaos/",
    )

    HINT = (
        "log it (logger.exception/.warning), bump a counter "
        "(registry.counter(...).inc()), emit a telemetry event "
        "(metrics.log(...)), pass the exception to a handler helper, or "
        "re-raise; genuinely-intentional silence takes an inline "
        "'# graftlint: disable=exception-hygiene' with a justification"
    )

    def check_file(self, src: SourceFile, ctx: LintContext) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                if not _is_broad(handler):
                    continue
                if _observes(handler):
                    continue
                out.append(self.finding(
                    src, handler.lineno,
                    "broad except swallows the failure silently (no "
                    "log, no counter, no re-raise)",
                    hint=self.HINT,
                ))
        return out
