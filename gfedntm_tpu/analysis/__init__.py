"""graftlint — repo-native static analysis for gfedntm-tpu.

Machine-checks the invariants PRs 1-7 established by hand review:

====== ==================== ===============================================
id     rule                 invariant
====== ==================== ===============================================
GL001  telemetry-contract   events registered <=> emitted; span call
                            sites; data-plane/model-quality reverse-lint
GL002  precision-pin        gram-path jax matmuls pin Precision.HIGHEST
GL003  donation-safety      donated buffers never referenced after the
                            donating call (fallback retries included)
GL004  lock-discipline      '# guarded-by: <lock>' attributes mutate only
                            under 'with self.<lock>:'
GL005  exception-hygiene    broad excepts in the planes log/count/
                            delegate/re-raise — never silent
====== ==================== ===============================================

Run it::

    python -m gfedntm_tpu.analysis            # whole repo, with baseline
    python scripts/graftlint.py               # same (shim)
    python -m gfedntm_tpu.analysis --list-rules

Suppress one finding inline (justification is free text for review)::

    except Exception:  # graftlint: disable=exception-hygiene -- probe
        ...

Accept a finding into the baseline (``scripts/lint_baseline.json``)::

    python -m gfedntm_tpu.analysis --update-baseline
    # then FILL IN the empty "justification" fields — the gate fails
    # on baselined findings without one.
"""

from __future__ import annotations

from gfedntm_tpu.analysis.core import (
    Finding,
    LintContext,
    Rule,
    SourceFile,
    collect_default_files,
    load_source,
    run_rules,
)
from gfedntm_tpu.analysis.runner import LintResult, run_lint

__all__ = [
    "Finding",
    "LintContext",
    "LintResult",
    "Rule",
    "SourceFile",
    "collect_default_files",
    "load_source",
    "run_lint",
    "run_rules",
]
