"""graftlint core: parse/walk infrastructure shared by every analyzer.

The framework is deliberately small: a :class:`SourceFile` wraps one
parsed module (text, AST, per-line ``# graftlint: disable=...``
suppressions), a :class:`Rule` contributes :class:`Finding`\\ s over a
list of source files, and :func:`run_rules` drives the set and filters
suppressed findings. Baseline handling (so the gate fails only on *new*
findings) lives in :mod:`gfedntm_tpu.analysis.baseline`; the CLI in
``__main__``.

Rules are registered in
:func:`gfedntm_tpu.analysis.rules.make_default_rules` — adding an
analyzer is: subclass :class:`Rule`, give it a unique ``id``/``name``,
implement :meth:`Rule.check_file` (or :meth:`Rule.check_repo` for
cross-file contracts), and add an instance to that list (see README
"Static analysis").
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Any, Iterable, Iterator

__all__ = [
    "Finding",
    "SourceFile",
    "Rule",
    "LintContext",
    "collect_default_files",
    "load_source",
    "run_rules",
]

#: Inline suppression: ``# graftlint: disable=<rule-name>[,<rule-name>...]``
#: (or ``disable=all``). Applies to findings anchored on the same physical
#: line, or — when the comment is the whole line — to the next
#: non-comment, non-blank line. Anything after the rule list (e.g. an
#: ``-- why`` justification) is free text for the reviewer.
_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([\w\-]+(?:\s*,\s*[\w\-]+)*)"
)

#: Default scan set relative to the repo root (mirrors the telemetry
#: lint's historical coverage plus the entry points).
#: tests/chaos rides along for GL005 only (its path scope): the
#: process-level chaos harness's supervision loops must not swallow
#: failures silently — a green kill-test that hid its errors proved
#: nothing. The rest of tests/ stays out of scope.
DEFAULT_SCAN_ROOTS = ("gfedntm_tpu", "bench.py", "main.py", "tests/chaos")


@dataclass(frozen=True)
class Finding:
    """One ``file:line``-anchored diagnostic."""

    rule_id: str     # stable short id, e.g. "GL002"
    rule_name: str   # human name, e.g. "precision-pin"
    path: str        # repo-relative, forward slashes
    line: int        # 1-based anchor line
    message: str
    hint: str = ""   # how to fix (or legitimately suppress) it

    def render(self) -> str:
        out = (
            f"{self.path}:{self.line}: "
            f"[{self.rule_name} {self.rule_id}] {self.message}"
        )
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


class SourceFile:
    """One parsed module: text, line table, AST, and suppressions.

    ``path`` is the absolute filesystem path; ``rel`` the repo-relative
    path every finding and baseline entry uses. A file that fails to
    parse keeps ``tree=None`` and carries the syntax error in
    ``parse_error`` — the runner turns that into a finding rather than
    crashing the whole lint.
    """

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree: ast.AST | None = None
        self.parse_error: SyntaxError | None = None
        try:
            self.tree = ast.parse(text, filename=rel)
        except SyntaxError as err:
            self.parse_error = err
        self._suppressed = self._parse_suppressions()

    def _parse_suppressions(self) -> dict[int, frozenset[str]]:
        """Map of 1-based line -> set of suppressed rule names ('all'
        suppresses everything on that line)."""
        out: dict[int, set[str]] = {}
        pending: set[str] | None = None
        for i, raw in enumerate(self.lines, start=1):
            stripped = raw.strip()
            m = _SUPPRESS_RE.search(raw)
            names: set[str] | None = None
            if m:
                names = {
                    n.strip() for n in m.group(1).split(",") if n.strip()
                }
            if names and stripped.startswith("#"):
                # Comment-only line: the suppression targets the next
                # code line (accumulate across stacked comments).
                pending = (pending or set()) | names
                continue
            here: set[str] = set(names or ())
            if pending and stripped and not stripped.startswith("#"):
                here |= pending
                pending = None
            if here:
                out[i] = here
        return {k: frozenset(v) for k, v in out.items()}

    def is_suppressed(self, rule_name: str, line: int) -> bool:
        names = self._suppressed.get(line)
        if not names:
            return False
        return "all" in names or rule_name in names

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""


@dataclass
class LintContext:
    """Shared run state handed to every rule: the repo root (for
    anchoring cross-file findings) and per-rule option overrides —
    tests use ``options`` to point the telemetry rule at a fixture
    schema instead of importing the live one."""

    root: str
    options: dict[str, Any] = field(default_factory=dict)


class Rule:
    """Base analyzer. Subclasses set ``id``/``name``/``description`` and
    implement :meth:`check_file` (per-module rules) or :meth:`check_repo`
    (cross-file contracts like the telemetry schema). ``paths`` scopes the
    rule to repo-relative path prefixes; ``None`` means every scanned
    file. Constructor kwargs override the class defaults so tests can
    re-scope a rule onto fixture files."""

    id: str = "GL000"
    name: str = "base"
    description: str = ""
    #: repo-relative path prefixes this rule applies to (None = all).
    default_paths: tuple[str, ...] | None = None

    def __init__(self, paths: tuple[str, ...] | None = None):
        self.paths = paths if paths is not None else self.default_paths

    def applies_to(self, rel: str) -> bool:
        if self.paths is None:
            return True
        return any(rel == p or rel.startswith(p) for p in self.paths)

    def check_file(self, src: SourceFile, ctx: LintContext) -> Iterable[Finding]:
        return ()

    def check_repo(
        self, files: list[SourceFile], ctx: LintContext
    ) -> Iterable[Finding]:
        return ()

    # -- shared helpers ------------------------------------------------
    def finding(
        self, src_or_rel, line: int, message: str, hint: str = ""
    ) -> Finding:
        rel = src_or_rel.rel if isinstance(src_or_rel, SourceFile) else src_or_rel
        return Finding(self.id, self.name, rel, int(line), message, hint)


def load_source(path: str, root: str) -> SourceFile:
    rel = os.path.relpath(os.path.abspath(path), os.path.abspath(root))
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    return SourceFile(os.path.abspath(path), rel, text)


def collect_default_files(root: str) -> list[str]:
    """The default scan set: every ``.py`` under ``gfedntm_tpu/`` (the
    analysis package lints itself too) plus the repo entry points."""
    paths: list[str] = []
    for entry in DEFAULT_SCAN_ROOTS:
        full = os.path.join(root, entry)
        if os.path.isfile(full):
            paths.append(full)
            continue
        for dirpath, dirs, files in os.walk(full):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            paths.extend(
                os.path.join(dirpath, f) for f in files if f.endswith(".py")
            )
    return sorted(paths)


def run_rules(
    rules: Iterable[Rule],
    files: list[SourceFile],
    ctx: LintContext,
) -> list[Finding]:
    """Run every rule over its in-scope files and return the surviving
    (non-suppressed) findings sorted by location. Unparseable files
    surface as one finding each (the compileall gate catches them too,
    but the lint must not crash on them)."""
    findings: list[Finding] = []
    by_rel = {f.rel: f for f in files}
    for src in files:
        if src.parse_error is not None:
            findings.append(Finding(
                "GL000", "parse", src.rel,
                src.parse_error.lineno or 1,
                f"file does not parse: {src.parse_error.msg}",
            ))
    for rule in rules:
        scoped = [
            f for f in files
            if rule.applies_to(f.rel) and f.parse_error is None
        ]
        findings.extend(rule.check_repo(scoped, ctx))
        for src in scoped:
            findings.extend(rule.check_file(src, ctx))
    kept = []
    for f in findings:
        src = by_rel.get(f.path)
        if src is not None and src.is_suppressed(f.rule_name, f.line):
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule_id, f.message))
    return kept


def iter_scopes(tree: ast.AST) -> Iterator[tuple[ast.AST, list]]:
    """Yield ``(scope_node, body)`` for the module and every (possibly
    nested) function — each function body EXCLUDES statements that belong
    to functions nested inside it, so per-scope analyses (taint tracking,
    donation liveness) don't leak across closure boundaries. Lambdas are
    scopes too (their body is a single expression): an unpinned gram
    matmul hiding in a lambda must not be invisible."""
    yield tree, _own_body(tree)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node, _own_body(node)
        elif isinstance(node, ast.Lambda):
            yield node, [node.body]


def _own_body(scope: ast.AST) -> list[ast.stmt]:
    return list(getattr(scope, "body", []))


def walk_scope(scope_body: list) -> Iterator[ast.AST]:
    """``ast.walk`` over a scope's statements (or a lambda's body
    expression), pruning nested function bodies (their *signatures* —
    decorators/defaults — still belong to the enclosing scope and are
    yielded)."""
    stack: list[ast.AST] = list(scope_body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # A nested def's BODY belongs to its own scope; only its
            # signature parts evaluate in the enclosing one. (The prune
            # applies whether the def arrived as a body statement or as
            # a child — both land on this stack.)
            stack.extend(node.decorator_list)
            stack.extend(node.args.defaults)
            stack.extend(
                d for d in (node.args.kw_defaults or []) if d is not None
            )
            continue
        if isinstance(node, ast.Lambda):
            continue
        stack.extend(ast.iter_child_nodes(node))


def attr_root(node: ast.AST) -> str | None:
    """The root ``Name`` of an attribute/call/subscript chain
    (``jnp.matmul`` -> ``jnp``; ``jax.lax.Precision.HIGHEST`` -> ``jax``;
    ``x.T`` -> ``x``)."""
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Call)):
        node = node.func if isinstance(node, ast.Call) else node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def expr_roots(node: ast.AST) -> set[str]:
    """Every root Name loaded anywhere inside an expression."""
    roots: set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
            roots.add(n.id)
    return roots
