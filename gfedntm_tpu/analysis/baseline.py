"""graftlint baseline: fail only on *new* findings.

A baseline entry pins one accepted finding by ``(rule, path, the
stripped source text of its anchor line, ordinal)`` — content-keyed, so
unrelated edits that shift line numbers don't invalidate it, while
editing the offending line itself (or fixing it) does. Every entry MUST
carry a non-empty ``justification``: a baseline is a reviewed decision,
not a mute button. Entries whose finding disappeared are *stale* — the
run reports them (exit stays 0) and ``--update-baseline`` prunes them.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass

from gfedntm_tpu.analysis.core import Finding, SourceFile

__all__ = [
    "BaselineEntry",
    "BaselineError",
    "load_baseline",
    "save_baseline",
    "split_by_baseline",
    "build_baseline",
]

VERSION = 1


class BaselineError(ValueError):
    """Malformed baseline file (bad JSON, wrong version, missing keys)."""


@dataclass(frozen=True)
class BaselineEntry:
    rule: str        # rule *name* (stable across id renumbering)
    path: str        # repo-relative
    line_text: str   # stripped anchor-line source at baseline time
    index: int       # ordinal among findings sharing (rule, path, line_text)
    justification: str

    @property
    def key(self) -> tuple[str, str, str, int]:
        return (self.rule, self.path, self.line_text, self.index)


def load_baseline(path: str) -> list[BaselineEntry]:
    if not os.path.exists(path):
        return []
    try:
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, json.JSONDecodeError) as err:
        raise BaselineError(f"unreadable baseline {path}: {err}") from err
    if not isinstance(doc, dict) or doc.get("version") != VERSION:
        raise BaselineError(
            f"baseline {path} has version {doc.get('version')!r}, "
            f"expected {VERSION}"
        )
    entries = []
    for i, raw in enumerate(doc.get("entries", ())):
        try:
            entries.append(BaselineEntry(
                rule=raw["rule"], path=raw["path"],
                line_text=raw["line_text"], index=int(raw.get("index", 0)),
                justification=str(raw.get("justification", "")),
            ))
        except (KeyError, TypeError, ValueError) as err:
            raise BaselineError(
                f"baseline {path} entry {i} is malformed: {err}"
            ) from err
    return entries


def save_baseline(path: str, entries: list[BaselineEntry]) -> None:
    doc = {
        "version": VERSION,
        "entries": [
            {
                "rule": e.rule, "path": e.path, "line_text": e.line_text,
                "index": e.index, "justification": e.justification,
            }
            for e in sorted(
                entries, key=lambda e: (e.path, e.rule, e.line_text, e.index)
            )
        ],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2)
        fh.write("\n")


def _finding_keys(
    findings: list[Finding], files_by_rel: dict[str, SourceFile]
) -> list[tuple[Finding, tuple[str, str, str, int]]]:
    """Content keys for current findings, with per-(rule, path, text)
    ordinals assigned in line order."""
    counters: dict[tuple[str, str, str], int] = {}
    keyed = []
    for f in sorted(findings, key=lambda f: (f.path, f.line)):
        src = files_by_rel.get(f.path)
        text = src.line_text(f.line) if src is not None else ""
        base = (f.rule_name, f.path, text)
        idx = counters.get(base, 0)
        counters[base] = idx + 1
        keyed.append((f, base + (idx,)))
    return keyed


def split_by_baseline(
    findings: list[Finding],
    entries: list[BaselineEntry],
    files_by_rel: dict[str, SourceFile],
) -> tuple[list[Finding], list[tuple[Finding, BaselineEntry]], list[BaselineEntry]]:
    """Partition current findings against the baseline: returns
    ``(new, baselined, stale_entries)``."""
    remaining: dict[tuple, BaselineEntry] = {e.key: e for e in entries}
    new: list[Finding] = []
    baselined: list[tuple[Finding, BaselineEntry]] = []
    for f, key in _finding_keys(findings, files_by_rel):
        entry = remaining.pop(key, None)
        if entry is None:
            new.append(f)
        else:
            baselined.append((f, entry))
    stale = sorted(
        remaining.values(), key=lambda e: (e.path, e.rule, e.index)
    )
    return new, baselined, stale


def build_baseline(
    findings: list[Finding],
    previous: list[BaselineEntry],
    files_by_rel: dict[str, SourceFile],
) -> list[BaselineEntry]:
    """Baseline entries for the current findings, carrying forward the
    justification of any previous entry with the same key (new entries
    get an empty justification the operator must fill in before the
    gate passes)."""
    prev = {e.key: e for e in previous}
    out = []
    for _f, key in _finding_keys(findings, files_by_rel):
        old = prev.get(key)
        out.append(BaselineEntry(
            rule=key[0], path=key[1], line_text=key[2], index=key[3],
            justification=old.justification if old is not None else "",
        ))
    return out
