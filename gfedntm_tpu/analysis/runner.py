"""graftlint runner: files + rules + baseline -> verdict.

Programmatic entry point (:func:`run_lint`) shared by the CLI
(``python -m gfedntm_tpu.analysis``), the ``scripts/graftlint.py`` /
``scripts/lint_telemetry.py`` shims, and the self-run test.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from gfedntm_tpu.analysis import baseline as bl
from gfedntm_tpu.analysis.core import (
    Finding,
    LintContext,
    Rule,
    SourceFile,
    collect_default_files,
    load_source,
    run_rules,
)

__all__ = ["LintResult", "run_lint", "default_baseline_path", "repo_root"]


def repo_root() -> str:
    """The repo checkout this package lives in (two levels up from
    ``gfedntm_tpu/analysis/``)."""
    return os.path.abspath(
        os.path.join(os.path.dirname(__file__), os.pardir, os.pardir)
    )


def default_baseline_path(root: str) -> str:
    return os.path.join(root, "scripts", "lint_baseline.json")


@dataclass
class LintResult:
    findings: list[Finding] = field(default_factory=list)   # all surviving
    new: list[Finding] = field(default_factory=list)        # not baselined
    baselined: list = field(default_factory=list)           # (finding, entry)
    stale: list = field(default_factory=list)               # BaselineEntry
    unjustified: list = field(default_factory=list)         # BaselineEntry
    files: int = 0

    @property
    def ok(self) -> bool:
        """Gate verdict: new findings and unjustified baseline entries
        fail; stale entries only warn (they mean something got FIXED —
        prune with --update-baseline)."""
        return not self.new and not self.unjustified


def run_lint(
    root: str | None = None,
    paths: list[str] | None = None,
    rules: list[Rule] | None = None,
    baseline_path: str | None = None,
    use_baseline: bool = True,
    update_baseline: bool = False,
    options: dict | None = None,
) -> LintResult:
    """Run the rule set and reconcile against the baseline.

    ``paths`` restricts the scan to explicit files (fixture tests);
    default is the full repo scan set. ``update_baseline=True`` rewrites
    the baseline from the current findings (preserving justifications of
    entries that survive) instead of judging against it.
    """
    root = os.path.abspath(root or repo_root())
    if rules is None:
        from gfedntm_tpu.analysis.rules import make_default_rules

        rules = make_default_rules()
    ctx = LintContext(root=root, options=dict(options or {}))
    file_paths = (
        [os.path.abspath(p) for p in paths]
        if paths is not None else collect_default_files(root)
    )
    files: list[SourceFile] = [load_source(p, root) for p in file_paths]
    by_rel = {f.rel: f for f in files}

    result = LintResult(files=len(files))
    result.findings = run_rules(rules, files, ctx)

    if not use_baseline:
        result.new = list(result.findings)
        return result

    bpath = baseline_path or default_baseline_path(root)
    entries = bl.load_baseline(bpath)
    # A subset run (explicit paths and/or a rule filter) makes no
    # statement about entries outside its scope: they are neither
    # matched nor stale, and --update-baseline must carry them (and
    # their human-authored justifications) through untouched.
    rule_names = {r.name for r in rules}
    scanned = {f.rel for f in files}
    in_scope, out_of_scope = [], []
    for e in entries:
        (in_scope if e.rule in rule_names and e.path in scanned
         else out_of_scope).append(e)
    if update_baseline:
        rebuilt = bl.build_baseline(result.findings, in_scope, by_rel)
        bl.save_baseline(bpath, rebuilt + out_of_scope)
        result.baselined = [(f, e) for f, e in zip(result.findings, rebuilt)]
        result.unjustified = [e for e in rebuilt if not e.justification.strip()]
        return result

    result.new, result.baselined, result.stale = bl.split_by_baseline(
        result.findings, in_scope, by_rel
    )
    result.unjustified = [
        e for _f, e in result.baselined if not e.justification.strip()
    ]
    return result
