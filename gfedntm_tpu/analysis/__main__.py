"""graftlint CLI: ``python -m gfedntm_tpu.analysis``.

Exit codes: 0 = clean (baselined-with-justification and stale-baseline
warnings allowed), 1 = new findings or unjustified baseline entries,
2 = usage/internal error.
"""

from __future__ import annotations

import argparse
import sys

from gfedntm_tpu.analysis.baseline import BaselineError
from gfedntm_tpu.analysis.runner import (
    default_baseline_path,
    repo_root,
    run_lint,
)
from gfedntm_tpu.analysis.rules import make_default_rules


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graftlint",
        description=(
            "repo-native static analysis: telemetry contract, precision "
            "pins, donation safety, lock discipline, exception hygiene"
        ),
    )
    p.add_argument(
        "paths", nargs="*",
        help="explicit files to lint (default: the whole repo scan set)",
    )
    p.add_argument("--root", default=None, help="repo root (default: auto)")
    p.add_argument(
        "--baseline", default=None,
        help="baseline file (default: <root>/scripts/lint_baseline.json)",
    )
    p.add_argument(
        "--no-baseline", action="store_true",
        help="judge every finding as new (ignore the baseline)",
    )
    p.add_argument(
        "--update-baseline", action="store_true",
        help=(
            "rewrite the baseline from the current findings, preserving "
            "justifications of surviving entries; new entries get an "
            "empty justification you MUST fill in"
        ),
    )
    p.add_argument(
        "--rules", default=None,
        help="comma-separated rule names to run (default: all)",
    )
    p.add_argument(
        "--list-rules", action="store_true",
        help="print the rule table and exit",
    )
    return p


def main(argv: list[str] | None = None) -> int:
    args = _parser().parse_args(argv)
    if args.no_baseline and args.update_baseline:
        print(
            "graftlint: --no-baseline and --update-baseline conflict "
            "(there is no baseline to rewrite without baseline mode)",
            file=sys.stderr,
        )
        return 2
    rules = make_default_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.id}  {r.name:20s} {r.description}")
        return 0
    if args.rules:
        wanted = {n.strip() for n in args.rules.split(",") if n.strip()}
        unknown = wanted - {r.name for r in rules}
        if unknown:
            print(
                f"graftlint: unknown rule(s) {sorted(unknown)} "
                f"(want {sorted(r.name for r in rules)})",
                file=sys.stderr,
            )
            return 2
        rules = [r for r in rules if r.name in wanted]

    root = args.root
    try:
        result = run_lint(
            root=root,
            paths=args.paths or None,
            rules=rules,
            baseline_path=args.baseline,
            use_baseline=not args.no_baseline,
            update_baseline=args.update_baseline,
        )
    except BaselineError as err:
        print(f"graftlint: {err}", file=sys.stderr)
        return 2

    bpath = args.baseline or default_baseline_path(root or repo_root())
    if args.update_baseline:
        print(
            f"graftlint: baseline rewritten with "
            f"{len(result.findings)} finding(s) -> {bpath}"
        )
        if result.unjustified:
            print(
                f"graftlint: {len(result.unjustified)} entr"
                f"{'y' if len(result.unjustified) == 1 else 'ies'} carry "
                "an empty justification — fill them in before the gate "
                "passes:", file=sys.stderr,
            )
            for e in result.unjustified:
                print(f"  {e.path}: [{e.rule}] {e.line_text}",
                      file=sys.stderr)
        return 0

    for f in result.new:
        print(f.render(), file=sys.stderr)
    for e in result.stale:
        print(
            f"graftlint: stale baseline entry (finding fixed?) "
            f"{e.path}: [{e.rule}] {e.line_text!r} — prune with "
            "--update-baseline",
            file=sys.stderr,
        )
    for e in result.unjustified:
        print(
            f"graftlint: baselined finding WITHOUT justification "
            f"{e.path}: [{e.rule}] {e.line_text!r} — edit {bpath}",
            file=sys.stderr,
        )
    n_rules = len(rules)
    print(
        f"graftlint: {result.files} files, {n_rules} rules -> "
        f"{len(result.new)} new finding(s), "
        f"{len(result.baselined)} baselined, {len(result.stale)} stale "
        "baseline entr" + ("y" if len(result.stale) == 1 else "ies")
    )
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
