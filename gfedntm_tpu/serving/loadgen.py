"""Closed-loop saturating load generator for the serving plane
(README "Serving": BENCH_SERVE methodology).

Closed loop means each of ``concurrency`` workers keeps exactly one
request in flight: send, wait, record, send again. Offered load then
self-adjusts to what the plane sustains — the measured docs/s IS the
saturation throughput at that concurrency, and latency percentiles are
honest (an open-loop generator would queue unboundedly past saturation
and measure its own backlog).

The generator is transport-agnostic: ``infer_fn`` is any callable
``(x_bow) -> (theta, model_round)`` — the in-process batcher
(``lambda x: batcher.submit(x).result()``), a gRPC stub
(:func:`gfedntm_tpu.serving.service.make_infer_stub`), or an HTTP
wrapper. Every observation lands in per-second windows that are ALSO
emitted as ``serve_load_window`` telemetry events, so the BENCH_SERVE
series is reproducible from the JSONL stream alone.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

import numpy as np

__all__ = ["ClosedLoopLoadGen", "percentile_ms"]


def percentile_ms(latencies_s: "list[float]", q: float) -> float | None:
    """The q-quantile (0..1) of a latency sample, in milliseconds."""
    if not latencies_s:
        return None
    return float(np.quantile(np.asarray(latencies_s, np.float64), q) * 1e3)


class ClosedLoopLoadGen:
    """Drive ``infer_fn`` with ``concurrency`` closed-loop workers for
    ``duration_s`` and summarize sustained docs/s + latency percentiles.

    ``make_batch(worker_idx, seq) -> np.ndarray [B, V]`` supplies request
    payloads (defaults to nothing — callers must provide one); results
    are verified row-stochastic-ish (finite, right row count) so a
    serving-plane bug cannot masquerade as throughput. Failures are
    counted, never retried (closed loop: a failed request is a lost
    slot), and the run FAILS its zero-failure acceptance if any request
    errors — the hot-swap contract under test is "no dropped in-flight
    requests".
    """

    def __init__(
        self,
        infer_fn: Callable[[np.ndarray], tuple],
        make_batch: Callable[[int, int], np.ndarray],
        concurrency: int = 4,
        duration_s: float = 10.0,
        metrics=None,
        window_s: float = 1.0,
        min_rounds: int | None = None,
        max_duration_s: float | None = None,
    ):
        """``min_rounds`` makes the run condition-driven: after the
        ``duration_s`` floor, the load stays up until it has observed
        that many DISTINCT model rounds in responses (or
        ``max_duration_s`` elapses, default ``6 * duration_s``). Use it
        for hot-swap acceptance — a fixed wall-clock window races the
        trainer's round rate and the plane's swap cost, both of which
        scale with machine load."""
        if concurrency < 1:
            raise ValueError(f"concurrency must be >= 1, got {concurrency}")
        if min_rounds is not None and min_rounds < 1:
            raise ValueError(f"min_rounds must be >= 1, got {min_rounds}")
        self.infer_fn = infer_fn
        self.make_batch = make_batch
        self.concurrency = int(concurrency)
        self.duration_s = float(duration_s)
        self.metrics = metrics
        self.window_s = float(window_s)
        self.min_rounds = None if min_rounds is None else int(min_rounds)
        self.max_duration_s = float(
            max_duration_s if max_duration_s is not None
            else 6.0 * self.duration_s
        )
        self._lock = threading.Lock()
        self._latencies: list[float] = []
        self._failures: list[str] = []
        self._docs = 0
        self._requests = 0
        self._rounds_seen: set[int] = set()
        # (t_rel_window_end, docs, requests, failures, [latencies])
        self._windows: dict[int, dict[str, Any]] = {}

    # ---- worker ------------------------------------------------------------
    def _worker(self, idx: int, t_start: float, stop: threading.Event):
        seq = 0
        while not stop.is_set():
            x = self.make_batch(idx, seq)
            seq += 1
            t0 = time.perf_counter()
            try:
                theta, model_round = self.infer_fn(x)
            except Exception as err:
                with self._lock:
                    self._failures.append(f"{type(err).__name__}: {err}")
                    self._bump_window(t_start, failed=True)
                continue
            dt = time.perf_counter() - t0
            theta = np.asarray(theta)
            ok = (
                theta.shape[0] == x.shape[0]
                and np.isfinite(theta).all()
            )
            with self._lock:
                if not ok:
                    self._failures.append(
                        f"bad theta shape/values {theta.shape}"
                    )
                    self._bump_window(t_start, failed=True)
                    continue
                self._latencies.append(dt)
                self._docs += x.shape[0]
                self._requests += 1
                self._rounds_seen.add(int(model_round))
                self._bump_window(
                    t_start, docs=x.shape[0], latency=dt,
                )

    def _bump_window(
        self, t_start: float, docs: int = 0,
        latency: float | None = None, failed: bool = False,
    ) -> None:
        """Fold one completed call into its per-second window (caller
        holds the lock)."""
        w = int((time.perf_counter() - t_start) / self.window_s)
        win = self._windows.setdefault(
            w, {"docs": 0, "requests": 0, "failures": 0, "latencies": []},
        )
        win["docs"] += docs
        win["requests"] += 0 if failed else 1
        win["failures"] += 1 if failed else 0
        if latency is not None:
            win["latencies"].append(latency)

    # ---- run ---------------------------------------------------------------
    def run(self) -> dict[str, Any]:
        """Run the closed loop and return the summary dict (the
        BENCH_SERVE building block)."""
        stop = threading.Event()
        t_start = time.perf_counter()
        workers = [
            threading.Thread(
                target=self._worker, args=(i, t_start, stop),
                name=f"loadgen-{i}", daemon=True,
            )
            for i in range(self.concurrency)
        ]
        for w in workers:
            w.start()
        time.sleep(self.duration_s)
        if self.min_rounds is not None:
            hard = t_start + self.max_duration_s
            while time.perf_counter() < hard:
                with self._lock:
                    if len(self._rounds_seen) >= self.min_rounds:
                        break
                time.sleep(min(0.25, self.window_s))
        stop.set()
        for w in workers:
            w.join(timeout=60.0)
        wall = time.perf_counter() - t_start
        return self._summarize(wall)

    def _summarize(self, wall_s: float) -> dict[str, Any]:
        with self._lock:
            latencies = list(self._latencies)
            failures = list(self._failures)
            docs, requests = self._docs, self._requests
            rounds = sorted(self._rounds_seen)
            windows = {k: dict(v) for k, v in sorted(self._windows.items())}
        series = []
        for w, win in windows.items():
            lats = win.pop("latencies")
            row = {
                "t_s": round((w + 1) * self.window_s, 3),
                **win,
                "docs_per_s": win["docs"] / self.window_s,
                "p50_ms": percentile_ms(lats, 0.50),
                "p99_ms": percentile_ms(lats, 0.99),
            }
            series.append(row)
            if self.metrics is not None:
                self.metrics.log(
                    "serve_load_window", seconds=self.window_s,
                    docs=row["docs"], requests=row["requests"],
                    failures=row["failures"],
                    docs_per_s=row["docs_per_s"],
                    p50_ms=row["p50_ms"], p99_ms=row["p99_ms"],
                    t_s=row["t_s"],
                )
        return {
            "concurrency": self.concurrency,
            "duration_s": round(wall_s, 3),
            "requests": requests,
            "docs": docs,
            "failures": len(failures),
            "failure_samples": failures[:5],
            "docs_per_s": docs / wall_s if wall_s > 0 else 0.0,
            "qps": requests / wall_s if wall_s > 0 else 0.0,
            "p50_ms": percentile_ms(latencies, 0.50),
            "p95_ms": percentile_ms(latencies, 0.95),
            "p99_ms": percentile_ms(latencies, 0.99),
            "model_rounds_seen": rounds,
            "swaps_observed": max(0, len(rounds) - 1),
            "series": series,
        }
