"""Serving plane: hot-swappable doc→topic inference as a first-class
production workload (README "Serving").

- :mod:`~gfedntm_tpu.serving.engine` — published-round model source
  (journal/checkpoint prefer-newer), the JIT'd bucket-padded encoder-only
  doc→θ engine, and the quality-gated atomic hot-swap.
- :mod:`~gfedntm_tpu.serving.service` — micro-batch coalescing, the
  gRPC ``Infer`` servicer, the ops-HTTP ``/infer`` + ``/ready`` surface,
  and the :class:`ServingPlane` process wrapper the ``serve`` CLI role
  runs.
- :mod:`~gfedntm_tpu.serving.loadgen` — the closed-loop saturating load
  generator behind the BENCH_SERVE artifacts.
"""

from gfedntm_tpu.serving.engine import (
    ModelSource,
    PublishedModel,
    ServingEngine,
    default_buckets,
)
from gfedntm_tpu.serving.loadgen import ClosedLoopLoadGen
from gfedntm_tpu.serving.service import (
    Batcher,
    InferenceServicer,
    QueueFullError,
    ServingPlane,
    make_infer_stub,
)

__all__ = [
    "Batcher",
    "ClosedLoopLoadGen",
    "InferenceServicer",
    "ModelSource",
    "PublishedModel",
    "QueueFullError",
    "ServingEngine",
    "ServingPlane",
    "default_buckets",
    "make_infer_stub",
]
