"""Hot-swappable doc→topic inference engine (README "Serving").

The training planes end at the last averaged round; this module is the
first *serving* workload: it loads published global models from the same
journal/checkpoint store the federation server writes (PR 10
:class:`~gfedntm_tpu.train.checkpoint.RoundJournal` +
:class:`~gfedntm_tpu.train.checkpoint.FederationCheckpointer`, with
``restore_from_checkpoint``'s prefer-newer rule), JITs the encoder-only
doc→θ path (:meth:`DecoderNetwork.get_theta` with ``noise=0`` — the
deterministic posterior-mean theta, eval-mode BatchNorm, no dropout, no
decoder matmul), and swaps models atomically as the federation publishes
new rounds — without dropping in-flight requests.

Design points:

- **Bucketed padding** (:func:`gfedntm_tpu.parallel.mesh.pad_to_multiple`
  semantics on the batch axis): request batches are padded up to a small
  set of power-of-two bucket sizes, so the steady state runs a handful of
  compiled programs instead of recompiling per ragged batch — the same
  recompile-kill recipe as ``train.steps.pad_batch_axis``. Padded rows
  are all-zero BoW vectors; eval-mode BatchNorm uses running statistics,
  so they cannot perturb the real rows and are sliced off before return.
- **Donated steady state** (:func:`gfedntm_tpu.train.steps.donation_argnums`
  gating, accelerator-only): the padded input buffer is freshly built per
  batch and never read after the call, so donating it lets XLA reuse its
  HBM for the θ output instead of double-buffering every request.
- **Atomic hot-swap**: a published round is loaded, applied, and **warmed
  through every bucket** off to the side, then installed by a single
  attribute rebind. In-flight requests snapshot the slot once at batch
  time — a swap under them is invisible; nothing is ever torn down while
  referenced.
- **Quality gate**: a candidate whose journaled ``quality`` record says
  the PR 7 coherence guard had a live unhealthy streak
  (``quality.flagged``) is refused — the plane keeps serving the last
  good model and emits a ``serve_swap_refused`` event + counter.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Any, Mapping

import numpy as np

__all__ = [
    "PublishedModel",
    "ModelSource",
    "ServingEngine",
    "default_buckets",
]


@dataclasses.dataclass
class PublishedModel:
    """One published global model, as read from the recovery store."""

    round: int
    source: str  # "journal" | "checkpoint"
    vocab: tuple[str, ...]
    family: str
    model_kwargs: dict[str, Any]
    average: dict[str, np.ndarray]
    quality: dict[str, Any] | None = None

    @property
    def flagged(self) -> bool:
        """True when the coherence guard had a live unhealthy streak at
        the time this round was journaled (README "Model-quality
        observability") — the serving plane must not swap it in."""
        return bool((self.quality or {}).get("flagged"))


class ModelSource:
    """Read-side twin of ``FederatedServer.restore_from_checkpoint``:
    watches a federation ``save_dir`` for newly published rounds and
    loads the newest of the round journal and the orbax checkpoint.

    ``family``/``model_kwargs`` are fallbacks for recovery state written
    before the journal became self-describing; newer state carries both
    in its ``extra`` record and wins. :meth:`peek` reads only the two
    JSON halves (cheap enough for a poll loop); :meth:`load` pays the
    array read.
    """

    def __init__(
        self,
        save_dir: str,
        family: str = "avitm",
        model_kwargs: dict[str, Any] | None = None,
        logger: logging.Logger | None = None,
        metrics=None,
    ):
        import os

        self.directory = os.path.join(os.path.abspath(save_dir), "checkpoints")
        self.family = family
        self.model_kwargs = dict(model_kwargs or {})
        self.logger = logger or logging.getLogger("ModelSource")
        self.metrics = metrics
        # Both stores are constructed lazily AND only once the directory
        # exists: this is a pure READER — RoundJournal/
        # FederationCheckpointer.__init__ would mkdir the store, and a
        # serve role pointed at a typo'd save_dir must keep polling an
        # absent store (ready stays 503), not plant an empty one there.
        self._journal = None
        self._ckpt = None

    def _store_exists(self) -> bool:
        import os

        return os.path.isdir(self.directory)

    def _journal_obj(self):
        if self._journal is None and self._store_exists():
            from gfedntm_tpu.train.checkpoint import RoundJournal

            self._journal = RoundJournal(self.directory)
        return self._journal

    def _checkpointer(self):
        if self._ckpt is None and self._store_exists():
            from gfedntm_tpu.train.checkpoint import FederationCheckpointer

            self._ckpt = FederationCheckpointer(self.directory)
        return self._ckpt

    def _journal_meta(self) -> dict[str, Any] | None:
        """Journal JSON half, or None; corruption is loud but demotes to
        the checkpoint (the server's own degradation rule)."""
        from gfedntm_tpu.train.checkpoint import CheckpointIntegrityError

        journal = self._journal_obj()
        if journal is None:
            return None
        try:
            meta = journal.load_meta()
        except CheckpointIntegrityError as err:
            self.logger.error("round journal unusable for serving: %s", err)
            if self.metrics is not None:
                self.metrics.registry.counter("serving_source_errors").inc()
            return None
        # A finished journal still describes a perfectly servable model —
        # recovery must not resurrect it, but serving it is the point.
        return meta

    def peek(self) -> tuple[int, str] | None:
        """Newest published ``(model_round, source)`` without touching
        arrays, or ``None`` when nothing is published yet. Both sources
        are reported on the JOURNAL's scale — the round the model was
        averaged at: the journal records the last fully-pushed round R
        directly, while the checkpoint sidecar's ``round`` is the RESUME
        round (the round training continues FROM), i.e. model round + 1,
        so it is normalized down by one. Mixing the two scales would
        both mislabel ``model_round`` in replies and make ``publish``
        refuse a journal round strictly newer than a checkpoint-sourced
        slot. Same prefer-newer rule as ``restore_from_checkpoint``."""
        from gfedntm_tpu.train.checkpoint import CheckpointIntegrityError

        jmeta = self._journal_meta()
        j_round = int(jmeta["round"]) if jmeta is not None else None
        if j_round is not None and j_round < 0:
            j_round = None  # finished-stamp placeholder, no arrays
        ckpt = self._checkpointer()
        try:
            cmeta = ckpt.load_meta() if ckpt is not None else None
        except CheckpointIntegrityError as err:
            self.logger.error("checkpoint unusable for serving: %s", err)
            cmeta = None
        c_model = (
            max(int(cmeta["round"]) - 1, 0) if cmeta is not None else None
        )
        if j_round is None and c_model is None:
            return None
        if c_model is None or (j_round is not None and j_round >= c_model):
            return (j_round, "journal")
        return (c_model, "checkpoint")

    def load(self) -> PublishedModel | None:
        """Load the newest published model (arrays included), or ``None``
        when nothing is published. Integrity failures degrade journal →
        checkpoint and raise only when neither half is usable."""
        from gfedntm_tpu.train.checkpoint import CheckpointIntegrityError

        newest = self.peek()
        if newest is None:
            return None
        _round, source = newest
        if source == "journal":
            try:
                jstate = self._journal_obj().load(include_finished=True)
            except CheckpointIntegrityError as err:
                # For a LIVE reader a halves-disagreement is usually the
                # server mid-write (npz lands before the JSON) — the next
                # poll self-heals. Degrade to the checkpoint quietly but
                # visibly (counter); the server-side recovery path is the
                # one that treats this state as corruption.
                self.logger.info(
                    "journal not readable this poll (%s); degrading to "
                    "the checkpoint and retrying next poll", err,
                )
                if self.metrics is not None:
                    self.metrics.registry.counter(
                        "serving_source_retries"
                    ).inc()
                jstate = None
            if jstate is not None:
                return self._published_from_meta(
                    int(jstate["round"]), "journal", jstate,
                    jstate["average"],
                )
        return self._load_checkpoint()

    def _load_checkpoint(self) -> PublishedModel | None:
        from gfedntm_tpu.train.checkpoint import CheckpointIntegrityError

        ckpt = self._checkpointer()
        if ckpt is None:
            return None
        try:
            meta = ckpt.load_meta()
        except CheckpointIntegrityError:
            meta = None
        if meta is None or ckpt.latest_round() is None:
            return None
        vocab, family, kwargs = self._model_identity(meta)
        template = _flat_template(family, vocab, kwargs)
        try:
            round_idx, average = ckpt.restore_round(template)
        except (CheckpointIntegrityError, FileNotFoundError) as err:
            self.logger.error("checkpoint restore failed for serving: %s", err)
            if self.metrics is not None:
                self.metrics.registry.counter("serving_source_errors").inc()
            return None
        # Normalize the sidecar's RESUME-round label to the model-round
        # scale the journal (and every reply/gauge) uses — see peek().
        return self._published_from_meta(
            max(int(round_idx) - 1, 0), "checkpoint", meta, average
        )

    def _model_identity(
        self, meta: Mapping[str, Any]
    ) -> tuple[tuple[str, ...], str, dict[str, Any]]:
        vocab = tuple(meta.get("vocab") or ())
        if not vocab:
            raise ValueError(
                f"recovery state under {self.directory} has no consensus "
                "vocabulary; the serving plane cannot rebuild the model"
            )
        family = meta.get("family") or self.family
        kwargs = dict(meta.get("model_kwargs") or self.model_kwargs)
        if not kwargs:
            raise ValueError(
                "recovery state predates self-describing journals and no "
                "model_kwargs were configured; pass the training model "
                "config to the serve role"
            )
        return vocab, family, kwargs

    def _published_from_meta(
        self, round_idx: int, source: str, meta: Mapping[str, Any],
        average: dict[str, np.ndarray],
    ) -> PublishedModel:
        vocab, family, kwargs = self._model_identity(meta)
        quality = meta.get("quality")
        return PublishedModel(
            round=int(round_idx), source=source, vocab=vocab,
            family=family, model_kwargs=kwargs,
            average={k: np.asarray(v) for k, v in average.items()},
            quality=dict(quality) if isinstance(quality, dict) else None,
        )


def default_buckets(max_batch: int) -> tuple[int, ...]:
    """Power-of-two bucket sizes up to (and including) ``max_batch`` —
    the padded batch shapes the serving programs compile for."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    out = []
    b = 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(int(max_batch))
    return tuple(out)


def _flat_template(
    family: str, vocab: tuple[str, ...], model_kwargs: dict[str, Any]
):
    """Flat ``key -> np.ndarray`` view of a freshly built template model's
    variables — the restore target for checkpoint rounds (covers every
    possible ``average_keys`` subset)."""
    from flax.traverse_util import flatten_dict

    from gfedntm_tpu.federation.server import build_template_model

    model = build_template_model(family, len(vocab), model_kwargs)
    flat = flatten_dict(
        {"params": model.params, "batch_stats": model.batch_stats}, sep="/"
    )
    return {k: np.asarray(v) for k, v in flat.items()}


class _ModelSlot:
    """One immutable serving model: module + applied variables. Requests
    snapshot the slot reference once per batch, so an engine-level swap
    can never change state under a running program."""

    __slots__ = (
        "round", "source", "module", "params", "batch_stats", "vocab",
        "family", "model_kwargs", "n_components",
    )

    def __init__(self, pub: PublishedModel, module, params, batch_stats):
        self.round = pub.round
        self.source = pub.source
        self.module = module
        self.params = params
        self.batch_stats = batch_stats
        self.vocab = pub.vocab
        self.family = pub.family
        self.model_kwargs = dict(pub.model_kwargs)
        self.n_components = int(module.n_components)


class ServingEngine:
    """JIT-compiled, bucket-padded, hot-swappable doc→θ inference.

    :meth:`publish` installs a :class:`PublishedModel` (building the
    template, applying the averaged variables, and pre-warming every
    bucket program) behind the quality gate; :meth:`infer` answers one
    BoW batch against whatever slot is installed at that moment. Both are
    safe to call concurrently: ``publish`` serializes on a lock and
    installs by atomic rebind, ``infer`` reads the slot exactly once.
    """

    def __init__(
        self,
        max_batch: int = 64,
        buckets: tuple[int, ...] | None = None,
        metrics=None,
        logger: logging.Logger | None = None,
        quality_gate: bool = True,
        donate: bool = True,
        warm_on_publish: bool = True,
    ):
        self.max_batch = int(max_batch)
        self.buckets = tuple(sorted(buckets or default_buckets(max_batch)))
        if self.buckets[-1] != self.max_batch:
            raise ValueError(
                f"largest bucket {self.buckets[-1]} must equal max_batch "
                f"{self.max_batch}"
            )
        self.metrics = metrics
        self.logger = logger or logging.getLogger("ServingEngine")
        self.quality_gate = bool(quality_gate)
        self.donate = bool(donate)
        self.warm_on_publish = bool(warm_on_publish)
        self._slot: _ModelSlot | None = None
        self._fns: dict[tuple[Any, int], Any] = {}
        self._publish_lock = threading.Lock()
        if metrics is not None:
            from gfedntm_tpu.utils.observability import DeviceMemoryMonitor

            # Swap/warm is where serving's device footprint moves (two
            # model slots live during the rebind, fresh warm compiles):
            # sample accelerator memory there so the per-device gauges
            # track the swap's high-water mark, not just steady state.
            self._device_mem = DeviceMemoryMonitor(metrics.registry)
        else:
            self._device_mem = None

    # ---- state ------------------------------------------------------------
    @property
    def ready(self) -> bool:
        """Loaded and warm — the ``/ready`` contract (README "Serving")."""
        return self._slot is not None

    @property
    def model_round(self) -> int | None:
        slot = self._slot
        return slot.round if slot is not None else None

    @property
    def vocab(self) -> tuple[str, ...] | None:
        """The serving model's consensus vocabulary (token order = BoW
        column order), or None before the first publish."""
        slot = self._slot
        return slot.vocab if slot is not None else None

    def status(self) -> dict[str, Any]:
        """JSON-safe view for ``/status``'s ``serving`` key."""
        slot = self._slot
        reg = self.metrics.registry if self.metrics is not None else None

        def count(name):
            m = reg.get(name) if reg is not None else None
            return int(m.value) if m is not None else 0

        out: dict[str, Any] = {
            "ready": slot is not None,
            "quality_gate": self.quality_gate,
            "max_batch": self.max_batch,
            "buckets": list(self.buckets),
            "swaps": count("serving_swaps"),
            "swaps_refused": count("serving_swaps_refused"),
        }
        if slot is not None:
            out.update(
                model_round=slot.round,
                model_source=slot.source,
                family=slot.family,
                vocab_size=len(slot.vocab),
                n_components=slot.n_components,
            )
        return out

    # ---- hot-swap ---------------------------------------------------------
    def publish(self, pub: PublishedModel) -> bool:
        """Install ``pub`` as the serving model. Returns True when the
        swap happened; False when the candidate was refused (quality
        flag) or is not newer than the installed round. Never tears down
        the installed slot on failure — the last good model keeps
        serving."""
        with self._publish_lock:
            slot = self._slot
            if slot is not None and pub.round <= slot.round:
                return False
            if self.quality_gate and pub.flagged:
                self.logger.warning(
                    "refusing to swap in round %d: the coherence guard "
                    "flagged it (unhealthy streak %s); keeping round %s",
                    pub.round,
                    (pub.quality or {}).get("unhealthy_streak"),
                    slot.round if slot is not None else None,
                )
                if self.metrics is not None:
                    self.metrics.registry.counter(
                        "serving_swaps_refused"
                    ).inc()
                    self.metrics.log(
                        "serve_swap_refused", round=pub.round,
                        reason="coherence_flagged",
                        kept_round=slot.round if slot is not None else None,
                    )
                return False
            new_slot = self._build_slot(pub)
            if self.warm_on_publish:
                # Warm every bucket BEFORE the rebind: the first real
                # request after a swap must hit a compiled program, not a
                # compile stall — in-flight and post-swap traffic both
                # see steady-state latency.
                self._warm(new_slot)
            prev_round = slot.round if slot is not None else None
            self._slot = new_slot
        if self._device_mem is not None:
            self._device_mem.sample()
        if self.metrics is not None:
            reg = self.metrics.registry
            reg.gauge("serving_model_round").set(pub.round)
            if prev_round is None:
                self.metrics.log(
                    "serve_model_loaded", round=pub.round, source=pub.source,
                )
            else:
                reg.counter("serving_swaps").inc()
                self.metrics.log(
                    "serve_model_swapped", round=pub.round,
                    prev_round=prev_round, source=pub.source,
                )
        self.logger.info(
            "serving round %d (%s)%s", pub.round, pub.source,
            "" if prev_round is None else f" (swapped from {prev_round})",
        )
        return True

    def _build_slot(self, pub: PublishedModel) -> _ModelSlot:
        """Template + averaged variables for one published round. When
        the model identity (family, vocab, kwargs) matches the installed
        slot, start from ITS variables instead of re-initializing — the
        non-averaged leaves are identical by construction (deterministic
        seeded init) and the rebuild is one flat-dict merge."""
        import jax.numpy as jnp
        from flax.traverse_util import flatten_dict, unflatten_dict

        slot = self._slot
        if (
            slot is not None
            and slot.family == pub.family
            and slot.vocab == pub.vocab
            and slot.model_kwargs == dict(pub.model_kwargs)
        ):
            module = slot.module
            variables = {
                "params": slot.params, "batch_stats": slot.batch_stats,
            }
        else:
            from gfedntm_tpu.federation.server import build_template_model

            model = build_template_model(
                pub.family, len(pub.vocab), pub.model_kwargs
            )
            module = model.module
            variables = {
                "params": model.params, "batch_stats": model.batch_stats,
            }
        flat = dict(flatten_dict(variables, sep="/"))
        unknown = [k for k in pub.average if k not in flat]
        if unknown:
            raise ValueError(
                f"published round {pub.round} carries keys the template "
                f"does not have (model config drift?): {unknown[:3]}"
            )
        for key, value in pub.average.items():
            flat[key] = jnp.asarray(value, flat[key].dtype)
        restored = unflatten_dict(flat, sep="/")
        return _ModelSlot(
            pub, module, restored["params"], restored.get("batch_stats", {}),
        )

    def _warm(self, slot: _ModelSlot) -> None:
        import jax

        vocab_size = len(slot.vocab)
        ctx_size = self._ctx_size(slot.module)
        for bucket in self.buckets:
            x = np.zeros((bucket, vocab_size), np.float32)
            ctx = (
                np.zeros((bucket, ctx_size), np.float32) if ctx_size else None
            )
            theta = self._theta_fn(slot.module, bucket)(
                slot.params, slot.batch_stats, x, ctx
            )
            jax.block_until_ready(theta)

    @staticmethod
    def _ctx_size(module) -> int:
        """Contextual-embedding width a CTM encoder requires per doc
        (0 for the BoW-only AVITM encoder)."""
        if getattr(module, "inference_type", "bow") == "bow":
            return 0
        return int(getattr(module, "contextual_size", 0))

    # ---- inference --------------------------------------------------------
    def _theta_fn(self, module, bucket: int):
        """The jitted encoder-only program for one (module, bucket) pair.
        Modules are frozen config dataclasses, so an unchanged model
        identity across swaps reuses both the callable and its compiled
        executables; the input buffers are donated (accelerator-only) —
        they are freshly padded per batch and never read back."""
        import jax

        from gfedntm_tpu.models.networks import DecoderNetwork
        from gfedntm_tpu.train.steps import donation_argnums
        from gfedntm_tpu.utils.observability import timed_jit

        key = (module, bucket)
        fn = self._fns.get(key)
        if fn is None:

            def serve(params, batch_stats, x_bow, x_ctx):
                return module.apply(
                    {"params": params, "batch_stats": batch_stats},
                    x_bow, x_ctx,
                    method=DecoderNetwork.get_theta,
                    noise=0.0,
                )

            fn = timed_jit(
                jax.jit(
                    serve,
                    donate_argnums=donation_argnums((2, 3), self.donate),
                ),
                self.metrics, f"serve_theta_b{bucket}",
            )
            self._fns[key] = fn
        return fn

    def bucket_for(self, rows: int) -> int:
        """Smallest bucket that holds ``rows`` (callers chunk above
        ``max_batch`` first)."""
        for b in self.buckets:
            if rows <= b:
                return b
        raise ValueError(
            f"batch of {rows} exceeds max_batch {self.max_batch}"
        )

    def infer(
        self, x_bow: np.ndarray, x_ctx: np.ndarray | None = None
    ) -> tuple[np.ndarray, int]:
        """Answer one ``[B, V]`` BoW batch: returns ``(theta [B, K],
        model_round)``. Deterministic (posterior-mean θ, eval-mode BN),
        batch-size invariant under the bucket padding, and pinned to ONE
        slot for its whole duration — a concurrent hot-swap affects only
        later batches."""
        slot = self._slot
        if slot is None:
            raise RuntimeError(
                "serving engine has no model yet (nothing published under "
                "the watched save_dir)"
            )
        x_bow = np.asarray(x_bow, np.float32)
        if x_bow.ndim != 2:
            raise ValueError(f"x_bow must be [B, V], got {x_bow.shape}")
        if x_bow.shape[1] != len(slot.vocab):
            raise ValueError(
                f"x_bow has vocab width {x_bow.shape[1]}, the serving "
                f"model expects {len(slot.vocab)}"
            )
        ctx_size = self._ctx_size(slot.module)
        if ctx_size and x_ctx is None:
            raise ValueError(
                f"the serving model is a CTM ({slot.module.inference_type} "
                f"encoder): each doc needs a [{ctx_size}]-wide contextual "
                "embedding (x_ctx)"
            )
        if x_ctx is not None:
            x_ctx = np.asarray(x_ctx, np.float32)
        rows = x_bow.shape[0]
        outs = []
        for lo in range(0, rows, self.max_batch):
            chunk = x_bow[lo:lo + self.max_batch]
            ctx_chunk = (
                x_ctx[lo:lo + self.max_batch] if x_ctx is not None else None
            )
            outs.append(self._infer_bucket(slot, chunk, ctx_chunk))
        theta = outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)
        return theta, slot.round

    def _infer_bucket(self, slot, x_bow, x_ctx):
        b = x_bow.shape[0]
        bucket = self.bucket_for(b)
        if bucket != b:
            pad = np.zeros((bucket, x_bow.shape[1]), np.float32)
            pad[:b] = x_bow
            x_bow = pad
            if x_ctx is not None:
                cpad = np.zeros((bucket, x_ctx.shape[1]), np.float32)
                cpad[:b] = x_ctx
                x_ctx = cpad
        if self.metrics is not None:
            reg = self.metrics.registry
            reg.histogram(
                "serve_batch_fill",
                buckets=(0.125, 0.25, 0.5, 0.75, 0.9, 1.0),
            ).observe(b / bucket)
            reg.gauge("serving_batch_fill").set(b / bucket)
            reg.counter("serving_docs").inc(b)
        theta = self._theta_fn(slot.module, bucket)(
            slot.params, slot.batch_stats, x_bow, x_ctx
        )
        return np.asarray(theta)[:b]
