"""The serving plane's request path: coalescing batcher, gRPC/HTTP
servicers, model watcher, and the :class:`ServingPlane` process wrapper
(README "Serving").

Request flow::

    gRPC Infer / HTTP POST /infer
        └─> Batcher.submit(rows)          # returns a Future
              └─> worker thread coalesces pending requests into one
                  bucket-padded micro-batch
                    └─> ServingEngine.infer (JIT, slot-pinned)
              <─ per-request θ slices fulfil the Futures

Coalescing is what turns many small user requests into the few padded
shapes the engine compiled for: the worker drains whatever is queued the
moment it goes idle (up to ``max_batch`` docs, with a tiny linger so
concurrent callers can pile on), so under closed-loop load the batch
size tracks the offered concurrency — the ``serving_batch_fill`` gauge
tells you how full the buckets run.

Hot-swap safety: the batcher holds NO model state — every micro-batch
pins the engine slot for its own duration, so the watcher thread can
swap models at any moment without a dropped or torn request. In-flight
futures complete against the slot their batch started with.
"""

from __future__ import annotations

import collections
import json
import logging
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable

import numpy as np

from gfedntm_tpu.serving.engine import ModelSource, ServingEngine
from gfedntm_tpu.utils.observability import span

__all__ = ["Batcher", "InferenceServicer", "QueueFullError", "ServingPlane"]


class QueueFullError(RuntimeError):
    """The batcher's pending queue is at its ``max_queue`` doc bound:
    the ARRIVING request is shed (gRPC ``RESOURCE_EXHAUSTED``, HTTP
    429) so queue depth and tail latency stay bounded under sustained
    overload — queued and in-flight requests are never dropped."""


class _Pending:
    __slots__ = ("x_bow", "future", "t_submit")

    def __init__(self, x_bow: np.ndarray):
        self.x_bow = x_bow
        self.future: "Future[tuple[np.ndarray, int]]" = Future()
        self.t_submit = time.perf_counter()


class Batcher:
    """Micro-batch coalescing in front of a :class:`ServingEngine`.

    One worker thread drains the pending queue into engine batches of up
    to ``max_batch`` docs. ``linger_s`` bounds how long the FIRST queued
    request may wait for company once the worker is idle (0 = dispatch
    immediately; a couple ms trades that latency for fuller buckets).
    Requests are never split below request granularity — a request's rows
    always travel in one micro-batch, so its future resolves exactly
    once.
    """

    def __init__(
        self,
        engine: ServingEngine,
        linger_s: float = 0.002,
        metrics=None,
        logger: logging.Logger | None = None,
        max_queue: int = 0,
    ):
        if max_queue < 0:
            raise ValueError(f"max_queue must be >= 0, got {max_queue}")
        self.engine = engine
        self.linger_s = float(linger_s)
        self.metrics = metrics
        self.logger = logger or logging.getLogger("Batcher")
        # Load shedding (README "Serving"): bound on PENDING DOCS (not
        # requests — requests vary in width). 0 = unbounded, the
        # historical behavior. When an arrival would push the pending
        # total past the bound it is shed alone via QueueFullError.
        self.max_queue = int(max_queue)
        # The bound applies against a NON-EMPTY backlog: a lone request
        # on an idle queue is always admitted, so a request wider than
        # max_queue (but within max_batch) is servable rather than shed
        # with a "retry later" that could never succeed.
        self._queued_docs = 0  # guarded-by: _cond
        self._queue: "collections.deque[_Pending]" = collections.deque()
        self._cond = threading.Condition()
        self._stopping = False
        self._thread: threading.Thread | None = None
        # Rolling (timestamp, docs, requests) window for the live QPS /
        # docs-per-s gauges — counters alone need two scrapes to rate.
        self._window: "collections.deque[tuple[float, int, int]]" = (
            collections.deque()
        )

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stopping = False
        self._thread = threading.Thread(
            target=self._run, name="serve-batcher", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        # Drain anything still queued: a stopping plane must FAIL pending
        # requests loudly, not leave callers blocked on forever-futures.
        with self._cond:
            pending = list(self._queue)
            self._queue.clear()
            self._queued_docs = 0
        for p in pending:
            p.future.set_exception(RuntimeError("serving plane stopped"))

    def submit(self, x_bow: np.ndarray) -> "Future[tuple[np.ndarray, int]]":
        """Enqueue one request batch; the future resolves to
        ``(theta, model_round)``."""
        x_bow = np.asarray(x_bow, np.float32)
        if x_bow.ndim != 2 or x_bow.shape[0] < 1:
            raise ValueError(
                f"request must be a non-empty [B, V] batch, got "
                f"{x_bow.shape}"
            )
        if x_bow.shape[0] > self.engine.max_batch:
            raise ValueError(
                f"request of {x_bow.shape[0]} docs exceeds max_batch "
                f"{self.engine.max_batch}; split client-side"
            )
        vocab = self.engine.vocab
        if vocab is not None and x_bow.shape[1] != len(vocab):
            # Reject a wrong-width request HERE, alone — coalesced into a
            # micro-batch it would fail the np.concatenate and poison
            # every co-batched request's future.
            raise ValueError(
                f"request has vocab width {x_bow.shape[1]}, the serving "
                f"model expects {len(vocab)}"
            )
        p = _Pending(x_bow)
        docs = int(x_bow.shape[0])
        with self._cond:
            if self._stopping:
                raise RuntimeError("serving plane is stopping")
            if (
                self.max_queue
                and self._queued_docs > 0
                and self._queued_docs + docs > self.max_queue
            ):
                queued = self._queued_docs
                if self.metrics is not None:
                    self.metrics.registry.counter(
                        "serving_requests_shed"
                    ).inc()
                    self.metrics.log(
                        "serve_shed", docs=docs, queued=queued,
                        max_queue=self.max_queue,
                    )
                raise QueueFullError(
                    f"serving queue full ({queued}/{self.max_queue} "
                    f"docs pending); retry later"
                )
            self._queue.append(p)
            self._queued_docs += docs
            if self.metrics is not None:
                self.metrics.registry.gauge("serving_queue_depth").set(
                    self._queued_docs
                )
            self._cond.notify()
        return p.future

    # ---- worker ------------------------------------------------------------
    def _take_batch(self) -> list[_Pending]:
        """Block for the first pending request, linger briefly for more,
        then take the largest prefix that fits one engine batch."""
        with self._cond:
            while not self._queue and not self._stopping:
                self._cond.wait(timeout=0.5)
            if self._stopping:
                return []
            if self.linger_s > 0 and len(self._queue) == 1:
                self._cond.wait(timeout=self.linger_s)
            batch: list[_Pending] = []
            docs = 0
            while self._queue:
                nxt = self._queue[0]
                if batch and (
                    docs + nxt.x_bow.shape[0] > self.engine.max_batch
                    # Only same-width requests coalesce: a width change
                    # between submit-time validation and dispatch (hot
                    # swap to a different vocabulary, or pre-load mixed
                    # widths) must fail ITS batch, never poison
                    # co-batched requests via the concatenate.
                    or nxt.x_bow.shape[1] != batch[0].x_bow.shape[1]
                ):
                    break
                batch.append(self._queue.popleft())
                docs += nxt.x_bow.shape[0]
            self._queued_docs -= docs
            if self.metrics is not None:
                self.metrics.registry.gauge("serving_queue_depth").set(
                    self._queued_docs
                )
            return batch

    def _run(self) -> None:
        while True:
            batch = self._take_batch()
            if not batch:
                if self._stopping:
                    return
                continue
            try:
                x = (
                    batch[0].x_bow if len(batch) == 1
                    else np.concatenate([p.x_bow for p in batch], axis=0)
                )
                with span(self.metrics, "serve_batch",
                          requests=len(batch), docs=int(x.shape[0])):
                    theta, model_round = self.engine.infer(x)
            except Exception as err:
                self.logger.exception("micro-batch inference failed")
                if self.metrics is not None:
                    self.metrics.registry.counter("serving_errors").inc(
                        len(batch)
                    )
                    self.metrics.log(
                        "serve_error", reason=f"{type(err).__name__}: {err}",
                        requests=len(batch),
                    )
                for p in batch:
                    if not p.future.set_running_or_notify_cancel():
                        continue
                    p.future.set_exception(err)
                continue
            now = time.perf_counter()
            lo = 0
            for p in batch:
                hi = lo + p.x_bow.shape[0]
                if p.future.set_running_or_notify_cancel():
                    p.future.set_result((theta[lo:hi], model_round))
                lo = hi
            if self.metrics is not None:
                reg = self.metrics.registry
                hist = reg.histogram("serve_latency_s")
                for p in batch:
                    hist.observe(now - p.t_submit)
                reg.counter("serving_requests").inc(len(batch))
                self._rate_gauges(now, lo, len(batch))

    def _rate_gauges(self, now: float, docs: int, requests: int) -> None:
        """Fold one completed micro-batch into the rolling 10 s QPS /
        docs-per-s gauges."""
        window = self._window
        window.append((now, docs, requests))
        horizon = now - 10.0
        while window and window[0][0] < horizon:
            window.popleft()
        span = max(now - window[0][0], 1e-3) if len(window) > 1 else None
        if span is not None:
            reg = self.metrics.registry
            reg.gauge("serving_docs_per_s").set(
                sum(d for _t, d, _r in window) / span
            )
            reg.gauge("serving_qps").set(
                sum(r for _t, _d, r in window) / span
            )


class InferenceServicer:
    """The ``gfedntm.Inference`` gRPC service: decodes the request's BoW
    bundle, rides the batcher, encodes θ back. Registered via
    :func:`gfedntm_tpu.federation.rpc.add_service` like every other
    service — fault injection and serve-span tracing compose unchanged."""

    def __init__(self, batcher: Batcher, timeout_s: float = 30.0,
                 metrics=None):
        self.batcher = batcher
        self.timeout_s = float(timeout_s)
        self.metrics = metrics

    def Infer(self, request, context):
        import grpc

        from gfedntm_tpu.federation import codec
        from gfedntm_tpu.federation.protos import federated_pb2 as pb

        try:
            records = {r.name: r for r in request.bow.tensors}
            if "bow" not in records:
                raise ValueError(
                    "InferRequest.bow must carry a 'bow' tensor record"
                )
            x = codec.record_to_array(records["bow"])
            with span(self.metrics, "infer",
                      request_id=int(request.request_id)):
                theta, model_round = self.batcher.submit(x).result(
                    timeout=self.timeout_s
                )
        except QueueFullError as err:
            # Load shed: the queue is at its --serve_max_queue bound.
            # RESOURCE_EXHAUSTED is the standard gRPC pushback code —
            # transient by the resilience classification, so polite
            # clients retry with backoff.
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(err))
        except (ValueError, TypeError) as err:
            # TypeError covers codec.record_to_array's disallowed-dtype
            # rejection — a malformed request, not a retryable outage.
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(err))
        except Exception as err:
            context.abort(grpc.StatusCode.UNAVAILABLE, str(err))
        reply = pb.InferReply(
            model_round=int(model_round),
            request_id=request.request_id,
        )
        reply.theta.tensors.append(
            codec.array_to_record("theta", np.asarray(theta, np.float32))
        )
        return reply


class ServingPlane:
    """One serving process: model watcher + engine + batcher + the two
    front doors (gRPC ``Infer``, ops-HTTP ``/infer``), run by the
    ``serve`` CLI role.

    The watcher polls the federation ``save_dir`` every ``poll_s`` for a
    newer published round and hands it to the engine, which hot-swaps it
    behind the quality gate. ``/ready`` turns 200 the moment the first
    model is loaded AND warmed; ``/status`` carries the ``serving`` view
    (model round, swap counters, latency percentiles, batch fill).
    """

    def __init__(
        self,
        save_dir: str,
        family: str = "avitm",
        model_kwargs: dict[str, Any] | None = None,
        max_batch: int = 64,
        linger_s: float = 0.002,
        max_queue: int = 0,
        poll_s: float = 1.0,
        quality_gate: bool = True,
        metrics=None,
        logger: logging.Logger | None = None,
        ops_port: int | None = None,
        ops_host: str = "127.0.0.1",
        grpc_workers: int = 16,
        slo_specs=None,
        dump_dir: str | None = None,
        flightrec_entries: int = 2048,
        flightrec_seconds: float = 300.0,
    ):
        self.logger = logger or logging.getLogger("ServingPlane")
        self.metrics = metrics
        self.poll_s = float(poll_s)
        # Incident forensics (README "Incident forensics"): --dump_dir
        # arms a flight recorder on the serving stream plus a trigger —
        # a swap refusal or a shed storm dumps the ring (recent infer /
        # serve_batch spans, queue depth history) with /status attached.
        # Unset constructs nothing.
        self.dump_dir = dump_dir
        self._incident_trigger = None
        if dump_dir is not None and metrics is not None:
            from gfedntm_tpu.utils import flightrec

            recorder = flightrec.FlightRecorder(
                max_entries=flightrec_entries,
                max_seconds=flightrec_seconds,
                registry=metrics.registry,
            )
            metrics.recorder = recorder
            self._incident_trigger = flightrec.IncidentTrigger(
                recorder, dump_dir, metrics=metrics,
                node=metrics.node or "serve",
                status_cb=lambda: self._status(full=False),
            )
        if slo_specs:
            from gfedntm_tpu.utils.slo import SLOEngine

            # The serving plane evaluates its OWN registry (serve latency
            # / shed / error objectives) on the watcher's poll cadence —
            # same engine, same alert lifecycle as the federation root.
            self.slo = SLOEngine(
                slo_specs,
                snapshot_fn=(
                    metrics.registry.snapshot if metrics is not None
                    else dict
                ),
                metrics=metrics,
            )
        else:
            self.slo = None
        self.source = ModelSource(
            save_dir, family=family, model_kwargs=model_kwargs,
            logger=self.logger, metrics=metrics,
        )
        self.engine = ServingEngine(
            max_batch=max_batch, metrics=metrics, logger=self.logger,
            quality_gate=quality_gate,
        )
        self.batcher = Batcher(
            self.engine, linger_s=linger_s, metrics=metrics,
            logger=self.logger, max_queue=max_queue,
        )
        self.ops_port = ops_port
        self.ops_host = ops_host
        self.grpc_workers = int(grpc_workers)
        self._grpc_server = None
        self._ops_server = None
        self._watcher: threading.Thread | None = None
        self._stopping = threading.Event()
        self._last_considered: int | None = None
        self._vocab_cache = None
        self.bound_port: int | None = None
        self.ops_actual_port: int | None = None

    # ---- lifecycle ---------------------------------------------------------
    def start(self, listen_address: str = "[::]:0") -> int:
        """Bind the gRPC Infer endpoint (returns the bound port), start
        the batcher, the model watcher, and — when ``ops_port`` is set —
        the ops HTTP endpoint with ``/ready`` + ``/infer`` mounted."""
        from gfedntm_tpu.federation import rpc

        self.batcher.start()
        self._grpc_server = rpc.make_server(max_workers=self.grpc_workers)
        rpc.add_service(
            self._grpc_server, "gfedntm.Inference",
            InferenceServicer(self.batcher, metrics=self.metrics),
            metrics=self.metrics,
        )
        self.bound_port = self._grpc_server.add_insecure_port(listen_address)
        self._grpc_server.start()
        if self.ops_port is not None:
            from gfedntm_tpu.utils.observability import OpsServer

            registry = (
                self.metrics.registry if self.metrics is not None else None
            )
            self._ops_server = OpsServer(
                registry=registry, status_fn=self._status,
                host=self.ops_host, port=self.ops_port,
                ready_fn=lambda: self.engine.ready,
                routes={"/infer": self._http_infer},
                alerts_fn=self.slo.status if self.slo is not None else None,
            )
            self.ops_actual_port = self._ops_server.start()
            if self.metrics is not None:
                self.metrics.log(
                    "ops_server_started", port=self.ops_actual_port,
                    role="serve",
                )
        self._stopping.clear()
        self._watcher = threading.Thread(
            target=self._watch, name="serve-watcher", daemon=True
        )
        self._watcher.start()
        self.logger.info(
            "serving plane up: gRPC Infer on %s, ops on %s",
            self.bound_port, self.ops_actual_port,
        )
        return self.bound_port

    def stop(self) -> None:
        self._stopping.set()
        if self._watcher is not None:
            self._watcher.join(timeout=30.0)
            self._watcher = None
        if self._grpc_server is not None:
            # Grace lets in-flight Infer calls finish — the zero-dropped-
            # requests contract holds through shutdown too.
            self._grpc_server.stop(grace=5.0).wait(timeout=10.0)
            self._grpc_server = None
        self.batcher.stop()
        if self._ops_server is not None:
            self._ops_server.stop()
            self._ops_server = None

    def wait(self, timeout: float | None = None) -> bool:
        """Block until :meth:`stop` (the CLI role's foreground wait)."""
        return self._stopping.wait(timeout)

    # ---- model watcher ------------------------------------------------------
    def _watch(self) -> None:
        """Poll-and-swap loop. The FIRST poll (the initial model load +
        bucket warm-up) runs here too, not in :meth:`start` — the front
        doors bind immediately and ``/ready`` honestly answers 503 while
        the plane warms, instead of the process being unreachable."""
        while True:
            try:
                self._try_swap()
            except Exception:
                # The watcher must survive transient store states (a
                # checkpoint mid-write, a journal briefly ahead of its
                # sidecar) — next poll retries.
                self.logger.exception("model watch poll failed")
                if self.metrics is not None:
                    self.metrics.registry.counter(
                        "serving_source_errors"
                    ).inc()
            if self.slo is not None:
                # SLO tick on the watcher's clock: alert latency is
                # bounded by poll_s, and no extra thread exists.
                self.slo.evaluate()
            if self._stopping.wait(self.poll_s):
                return

    def _try_swap(self) -> bool:
        """One watcher step: peek the store, load + publish when a round
        newer than anything considered so far appears. Refused rounds
        count as considered — a flagged candidate is not re-refused every
        poll; the NEXT published round gets its own verdict."""
        newest = self.source.peek()
        if newest is None:
            return False
        round_idx, _source = newest
        if (
            self._last_considered is not None
            and round_idx <= self._last_considered
        ):
            return False
        pub = self.source.load()
        if pub is None:
            return False
        self._last_considered = max(
            pub.round, self._last_considered or pub.round
        )
        with span(self.metrics, "serve_swap", round=int(pub.round)):
            return self.engine.publish(pub)

    # ---- HTTP front door ----------------------------------------------------
    def _vocabulary(self):
        """Cached :class:`~gfedntm_tpu.data.vocab.Vocabulary` for the
        serving model — rebuilt only when a swap changes the token set
        (the token2id map is O(V); it must not be rebuilt per request)."""
        tokens = self.engine.vocab
        if tokens is None:
            return None
        cached = self._vocab_cache
        if cached is None or cached.tokens != tokens:
            from gfedntm_tpu.data.vocab import Vocabulary

            cached = Vocabulary(tokens)
            self._vocab_cache = cached
        return cached

    def _bow_from_json(self, payload: dict) -> np.ndarray:
        """A request body's documents as a dense [B, V] BoW batch:
        ``bow`` rows pass through; ``docs`` (raw text) are vectorized
        with the training analyzer (:func:`gfedntm_tpu.data.vocab
        .vectorize` — the same path clients build their corpora with,
        C++ fast path included) against the SERVING model's vocabulary —
        the serving plane owns the vocab, users send text."""
        if "bow" in payload:
            x = np.asarray(payload["bow"], np.float32)
            if x.ndim == 1:
                x = x[None, :]
            return x
        docs = payload.get("docs")
        if not docs or not isinstance(docs, list):
            raise ValueError(
                "request JSON needs 'docs' (list of text documents) or "
                "'bow' (dense [B, V] count rows)"
            )
        vocab = self._vocabulary()
        if vocab is None:
            raise RuntimeError("no model loaded yet")
        from gfedntm_tpu.data.vocab import vectorize

        return vectorize([str(d) for d in docs], vocab)

    def _http_infer(self, body: bytes, query: str):
        """POST /infer handler mounted on the OpsServer: JSON in, JSON
        out. Errors map to 400 (bad request) / 503 (no model yet)."""
        try:
            payload = json.loads(body or b"{}")
            if not isinstance(payload, dict):
                raise ValueError("request body must be a JSON object")
            x = self._bow_from_json(payload)
            theta, model_round = self.batcher.submit(x).result(timeout=30.0)
        except QueueFullError as err:
            # Load shed (the serve_shed event + shed counter were
            # already recorded at the rejection site): HTTP 429.
            return 429, "application/json", json.dumps(
                {"error": str(err)}
            ).encode()
        except ValueError as err:
            if self.metrics is not None:
                self.metrics.registry.counter("serving_errors").inc()
                self.metrics.log("serve_error", reason=str(err))
            return 400, "application/json", json.dumps(
                {"error": str(err)}
            ).encode()
        except RuntimeError as err:
            if self.metrics is not None:
                self.metrics.registry.counter("serving_errors").inc()
                self.metrics.log("serve_error", reason=str(err))
            return 503, "application/json", json.dumps(
                {"error": str(err)}
            ).encode()
        body = json.dumps({
            "theta": np.asarray(theta, np.float64).round(6).tolist(),
            "model_round": int(model_round),
        }).encode()
        return 200, "application/json", body

    # ---- status -------------------------------------------------------------
    def _status(self, full: bool = False) -> dict[str, Any]:
        from gfedntm_tpu.utils.observability import quantile_from_snapshot

        serving = self.engine.status()
        reg = self.metrics.registry if self.metrics is not None else None
        if reg is not None:
            hist = reg.get("serve_latency_s")
            snap = hist.snapshot() if hist is not None else None
            if snap and snap.get("count"):
                serving["latency_s"] = {
                    "count": snap["count"],
                    "p50": quantile_from_snapshot(snap, 0.50),
                    "p99": quantile_from_snapshot(snap, 0.99),
                }

            def _val(name):
                m = reg.get(name)
                return m.value if m is not None else None

            serving["qps"] = _val("serving_qps")
            serving["docs_per_s"] = _val("serving_docs_per_s")
            serving["batch_fill"] = _val("serving_batch_fill")
            serving["requests"] = int(_val("serving_requests") or 0)
            serving["errors"] = int(_val("serving_errors") or 0)
            serving["requests_shed"] = int(
                _val("serving_requests_shed") or 0
            )
            serving["queue_depth"] = _val("serving_queue_depth")
        serving["max_queue"] = self.batcher.max_queue
        if self.slo is not None:
            serving["alerts_firing"] = self.slo.status()["firing"]
        serving["watch"] = {
            "directory": self.source.directory,
            "poll_s": self.poll_s,
            "last_considered": self._last_considered,
        }
        return {"role": "serve", "serving": serving}


def make_infer_stub(address: str, timeout_s: float = 30.0, metrics=None):
    """Client-side convenience: a callable ``infer(x_bow) -> (theta,
    model_round)`` over a fresh channel to a serving plane — what the
    load generator and remote users drive."""
    from gfedntm_tpu.federation import codec, rpc
    from gfedntm_tpu.federation.protos import federated_pb2 as pb

    channel = rpc.make_channel(address)
    stub = rpc.ServiceStub(
        channel, "gfedntm.Inference", default_timeout=timeout_s,
        metrics=metrics, peer=address,
    )

    def infer(x_bow: np.ndarray, request_id: int = 0):
        req = pb.InferRequest(request_id=int(request_id))
        req.bow.tensors.append(
            codec.array_to_record("bow", np.asarray(x_bow, np.float32))
        )
        reply = stub.Infer(req)
        theta = codec.record_to_array(reply.theta.tensors[0])
        return theta, int(reply.model_round)

    infer.channel = channel  # callers own the channel lifetime
    return infer
