"""RDP/moments (ε, δ) accountant for the federated DP mechanisms.

The ledger tracks Rényi differential privacy at a fixed grid of integer
orders α ∈ [2, 64] (the classic moments-accountant grid; integer orders
admit the exact binomial-expansion bound for subsampled Gaussians) and
converts to (ε, δ) on demand:

- One application of the Gaussian mechanism with noise multiplier σ
  (noise std = σ × sensitivity) costs ``α / (2σ²)`` RDP at order α
  (Mironov 2017, Prop. 7).
- Under Poisson/uniform subsampling with inclusion probability q < 1
  the per-round cost drops to the subsampled-Gaussian bound
  ``(1/(α−1)) · log Σ_{j=0}^{α} C(α,j) (1−q)^{α−j} q^j e^{j(j−1)/(2σ²)}``
  (Mironov–Talwar–Zhang 2019, the integer-α closed form) — privacy
  amplification by subsampling, which is exactly what the PR 9 cohort
  sampler provides. The bound reduces to ``α/(2σ²)`` at q = 1 and is
  monotone increasing in q (unit-tested), so crediting the *live*
  per-round q from :meth:`pacing.CohortEngine.inclusion_q` is always
  sound: a round where probation shrank the eligible pool (larger q)
  is charged more, never less.
- Rounds compose by *adding* the per-order RDP; the (ε, δ) conversion
  is ``ε(δ) = min_α [ rdp(α) + log(1/δ)/(α−1) ]`` (Mironov 2017,
  Prop. 3). RDP composition beats naive ε-summing for every T ≥ 2
  (unit-tested inequality).

Async/push pacing has no per-round sampling distribution the bound
applies to (participation is availability-driven, not sampled), so the
server charges those policies conservatively at q = 1.

The state is a flat JSON-able dict (:meth:`state_dict`) persisted inside
the server's checkpoint/journal extra state, so a crash-autorecovered
run resumes its spent budget — ε continues, never resets.
"""

from __future__ import annotations

import math
from typing import Any

__all__ = [
    "ALPHAS",
    "gaussian_rdp",
    "subsampled_gaussian_rdp",
    "eps_from_rdp",
    "PrivacyAccountant",
]

#: Integer Rényi orders tracked by the ledger. 2..64 brackets the
#: optimal order for every (σ, δ) regime the knobs can express: small σ
#: optimizes at low α, large σ at α ≈ 1 + σ·sqrt(2 log(1/δ)).
ALPHAS: tuple[int, ...] = tuple(range(2, 65))


def gaussian_rdp(alpha: float, sigma: float) -> float:
    """RDP of one Gaussian mechanism application at order ``alpha`` with
    noise multiplier ``sigma`` (std = sigma × L2 sensitivity)."""
    if sigma <= 0.0:
        return math.inf
    return float(alpha) / (2.0 * sigma * sigma)


def _log_comb(n: int, k: int) -> float:
    return (
        math.lgamma(n + 1) - math.lgamma(k + 1) - math.lgamma(n - k + 1)
    )


def _logsumexp(terms: "list[float]") -> float:
    hi = max(terms)
    if hi == -math.inf:
        return -math.inf
    return hi + math.log(sum(math.exp(t - hi) for t in terms))


def subsampled_gaussian_rdp(alpha: int, q: float, sigma: float) -> float:
    """RDP at integer order ``alpha`` of one subsampled-Gaussian round
    with inclusion probability ``q``: the exact binomial-expansion bound
    (valid for integer α ≥ 2), clamped at the non-subsampled cost so a
    numerically-degenerate q can never *under*-charge."""
    if sigma <= 0.0:
        return math.inf
    full = gaussian_rdp(alpha, sigma)
    if q >= 1.0:
        return full
    if q <= 0.0:
        return 0.0
    a = int(alpha)
    c = 1.0 / (2.0 * sigma * sigma)
    terms = [
        _log_comb(a, j)
        + (a - j) * math.log1p(-q)
        + j * math.log(q)
        + j * (j - 1) * c
        for j in range(a + 1)
    ]
    bound = max(0.0, _logsumexp(terms) / (a - 1))
    return min(bound, full)


def eps_from_rdp(
    rdp: "dict[int, float]", delta: float
) -> "tuple[float, int]":
    """Convert an RDP curve to (ε, best order) at the given δ."""
    if not 0.0 < delta < 1.0:
        raise ValueError(f"delta must be in (0, 1), got {delta}")
    log_inv = math.log(1.0 / delta)
    best_eps, best_alpha = math.inf, 0
    for alpha, r in rdp.items():
        eps = r + log_inv / (alpha - 1)
        if eps < best_eps:
            best_eps, best_alpha = eps, int(alpha)
    return float(best_eps), best_alpha


class PrivacyAccountant:
    """The per-run (ε, δ) ledger: one :meth:`step` per aggregation round
    that actually applied a mechanism, composed in RDP, converted to
    (ε, δ) on demand. Budget exhaustion flips :attr:`exceeded` but never
    stops training — the offline ``privacy`` CLI gate is the enforcement
    point (the PR 16 slo-gate pattern)."""

    def __init__(
        self,
        *,
        sigma: float,
        delta: float = 1e-5,
        budget: float = 0.0,
        mode: str = "server",
    ):
        if sigma <= 0.0:
            raise ValueError(
                f"accountant needs a positive noise multiplier, got {sigma}"
            )
        if not 0.0 < delta < 1.0:
            raise ValueError(f"delta must be in (0, 1), got {delta}")
        self.sigma = float(sigma)
        self.delta = float(delta)
        #: Declared ε budget; 0 means "track only, no declared budget".
        self.budget = float(budget)
        self.mode = str(mode)
        self.steps = 0
        self.last_q = 1.0
        self._rdp: dict[int, float] = {a: 0.0 for a in ALPHAS}

    # ---- composition ---------------------------------------------------
    def step(self, q: float = 1.0, sigma: "float | None" = None) -> float:
        """Charge one mechanism application with inclusion probability
        ``q`` (1.0 = every eligible client participated — the
        conservative default for sync/async/push pacing); returns the
        spent ε at the ledger's δ."""
        s = self.sigma if sigma is None else float(sigma)
        q = min(1.0, max(0.0, float(q)))
        for alpha in ALPHAS:
            self._rdp[alpha] += subsampled_gaussian_rdp(alpha, q, s)
        self.steps += 1
        self.last_q = q
        return self.epsilon()

    def epsilon(self, delta: "float | None" = None) -> float:
        """Spent ε at ``delta`` (default: the ledger's δ)."""
        if self.steps == 0:
            return 0.0
        eps, _ = eps_from_rdp(
            self._rdp, self.delta if delta is None else float(delta)
        )
        return eps

    @property
    def exceeded(self) -> bool:
        return self.budget > 0.0 and self.epsilon() > self.budget

    # ---- persistence (rides the checkpoint/journal extra state) --------
    def state_dict(self) -> "dict[str, Any]":
        return {
            "version": 1,
            "mode": self.mode,
            "sigma": self.sigma,
            "delta": self.delta,
            "budget": self.budget,
            "steps": int(self.steps),
            "last_q": float(self.last_q),
            # JSON keys are strings; keep the grid explicit so a future
            # ALPHAS change cannot silently misalign a restored ledger.
            "rdp": {str(a): float(v) for a, v in self._rdp.items()},
        }

    def load_state_dict(self, state: "dict[str, Any]") -> None:
        if int(state.get("version", 1)) != 1:
            raise ValueError(
                f"unknown privacy ledger version {state.get('version')!r}"
            )
        self.steps = int(state["steps"])
        self.last_q = float(state.get("last_q", 1.0))
        rdp = {int(a): float(v) for a, v in dict(state["rdp"]).items()}
        # A restored ledger keeps ITS grid values for orders we track;
        # orders the snapshot lacks restart at the conservative maximum
        # already spent (never below — the budget must not reset).
        fallback = max(rdp.values(), default=0.0)
        self._rdp = {a: rdp.get(a, fallback) for a in ALPHAS}

    # ---- surfacing -----------------------------------------------------
    def status(self) -> "dict[str, Any]":
        eps = self.epsilon()
        return {
            "mode": self.mode,
            "eps": eps,
            "delta": self.delta,
            "sigma": self.sigma,
            "steps": int(self.steps),
            "last_q": float(self.last_q),
            "budget": self.budget,
            "exceeded": bool(self.exceeded),
        }
