"""DP noise mechanisms: server-side FedLD noise and client-side DP-SGD.

Both mechanisms share one layout contract: noise is drawn over the
round's float32 tensors in **sorted key order** (the same canonical
order ``aggregation._stacked`` and the device ``FlatPlane`` use), from
an explicitly-seeded generator — never ambient global RNG state (the
GL006 ``rng-discipline`` lint enforces this in the noise paths).

Host oracle vs device path: the numpy oracle
(:func:`host_noise_vector`, ``np.random.default_rng((seed, index))``)
is the reference; the device generator
(:meth:`device_agg.DeviceAggEngine.noise_vector`, jax threefry keys
folded per shard) is **deliberately bitwise-off** from it — the two
PRNGs are different algorithms and no seed mapping makes their streams
coincide. The parity contract, mirroring the estimators' documented
tolerance tiers, is therefore: each path is exactly reproducible given
(seed, application index), both paths are zero-mean Gaussian at the
same std (distribution-tested), and the *privacy* accounting depends
only on the std — which is identical by construction. Tests pin both
halves (``tests/test_privacy.py``).

Sensitivity bookkeeping: in server mode the per-client L2 sensitivity
is enforced by the PR 5 update gate — the server tightens
``--max_update_norm`` to ``--dp_clip`` so every admitted update sits in
the clip ball — and the weighted-mean aggregate of n contributors has
sensitivity ``clip / n``; the injected noise std is
``sigma * clip / max(1, n)``. In client mode each client clips its own
outgoing delta and adds ``sigma * clip`` noise locally, so the update
is private before any server or relay tier sees it (local DP).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Mapping

import numpy as np

__all__ = [
    "DPSpec",
    "parse_dp",
    "host_noise_vector",
    "ServerNoiser",
    "ClientSanitizer",
]

DP_MODES = ("off", "server", "client")


@dataclass(frozen=True)
class DPSpec:
    """Parsed ``--dp`` configuration (see :func:`parse_dp`)."""

    mode: str  # "off" | "server" | "client"
    clip: float = 1.0  # L2 sensitivity bound (the DP clip)
    sigma: float = 0.0  # noise multiplier (std = sigma x sensitivity)
    delta: float = 1e-5  # the delta the (eps, delta) ledger reports at
    budget: float = 0.0  # declared eps budget (0 = track only)
    seed: int = 0  # mechanism seed (never ambient RNG state)

    @property
    def enabled(self) -> bool:
        return self.mode != "off"


def parse_dp(
    mode: "str | DPSpec | None",
    *,
    clip: float = 1.0,
    sigma: float = 0.0,
    delta: float = 1e-5,
    budget: float = 0.0,
    seed: int = 0,
) -> DPSpec:
    """Parse the ``--dp`` knobs into a validated spec. ``off`` ignores
    every other knob (and the caller constructs no mechanism objects at
    all — the bitwise default-off contract)."""
    if isinstance(mode, DPSpec):
        return mode
    raw = (mode or "off").strip().lower()
    if raw not in DP_MODES:
        raise ValueError(
            f"unknown dp mode {raw!r} (want one of {DP_MODES})"
        )
    if raw == "off":
        return DPSpec("off")
    if clip <= 0.0:
        raise ValueError(f"--dp_clip must be > 0, got {clip}")
    if sigma <= 0.0:
        raise ValueError(
            f"--dp {raw} needs a positive noise multiplier --dp_sigma, "
            f"got {sigma}"
        )
    if not 0.0 < delta < 1.0:
        raise ValueError(f"--dp_delta must be in (0, 1), got {delta}")
    if budget < 0.0:
        raise ValueError(f"--dp_budget must be >= 0, got {budget}")
    return DPSpec(
        raw, clip=float(clip), sigma=float(sigma), delta=float(delta),
        budget=float(budget), seed=int(seed),
    )


def host_noise_vector(
    dim: int, std: float, seed: int, index: int,
    extra: "tuple[int, ...]" = (),
) -> np.ndarray:
    """The numpy noise oracle: ``dim`` float32 standard-normal draws
    scaled by ``std``, from a generator seeded by the tuple
    ``(seed, *extra, index)`` — deterministic per application, shared by
    the server host path and the client sanitizer (with the client id in
    ``extra`` so clients never draw correlated noise)."""
    rng = np.random.default_rng((int(seed),) + tuple(
        int(x) for x in extra
    ) + (int(index),))
    return (
        rng.standard_normal(int(dim)).astype(np.float32)
        * np.float32(std)
    )


def _f32_layout(
    average: Mapping[str, Any],
) -> "list[tuple[str, int, int]]":
    """(key, offset, size) slices of the sorted-f32-key noise vector."""
    out: list[tuple[str, int, int]] = []
    off = 0
    for k in sorted(average):
        arr = np.asarray(average[k])
        if arr.dtype == np.float32:
            out.append((k, off, int(arr.size)))
            off += int(arr.size)
    return out


class ServerNoiser:
    """FedLD posterior-sampling noise on the server aggregate.

    Applied by :meth:`aggregation.ServerAggregator._mean` **after** the
    (possibly robust) mean stage — robust estimators first discard the
    byzantine tail, then calibrated Gaussian noise is added to the clean
    estimate, so noise can never mask a poisoned update from the robust
    screen (README "Differential privacy & posterior sampling").

    The noiser keeps its own application counter: draw ``i`` is a pure
    function of ``(spec.seed, i)``, so a crash-autorecovered server that
    restores the counter from the accountant's step count resumes the
    exact noise stream. ``device_engine`` switches generation to the
    sharded jax path (:meth:`DeviceAggEngine.noise_vector`); the numpy
    oracle is the default and the reference.
    """

    name = "fedld"

    def __init__(
        self,
        spec: DPSpec,
        *,
        device_engine: Any = None,
        metrics: Any = None,
    ):
        if spec.mode != "server":
            raise ValueError(
                f"ServerNoiser needs a server-mode spec, got {spec.mode!r}"
            )
        self.spec = spec
        self.device_engine = device_engine
        self.metrics = metrics
        #: Applications so far — restored to the accountant's step count
        #: on crash recovery so the noise stream continues, not restarts.
        self.applications = 0
        self._plane_cache: "tuple[tuple, Any] | None" = None

    def noise_std(self, n_contributors: int) -> float:
        """Noise std for an n-contributor aggregate: the mean of n
        clip-bounded updates has L2 sensitivity ``clip / n``."""
        return self.spec.sigma * self.spec.clip / max(1, int(n_contributors))

    def _noise_vec(self, average: Mapping[str, Any], dim: int,
                   std: float, index: int) -> np.ndarray:
        if self.device_engine is None:
            return host_noise_vector(dim, std, self.spec.seed, index)
        from gfedntm_tpu.federation.device_agg import FlatPlane

        keys = tuple(sorted(
            k for k in average
            if np.asarray(average[k]).dtype == np.float32
        ))
        cached = self._plane_cache
        if cached is None or cached[0] != keys:
            plane = FlatPlane({k: average[k] for k in keys})
            self._plane_cache = (keys, plane)
        else:
            plane = cached[1]
        return self.device_engine.noise_vector(
            plane, std=std, seed=self.spec.seed, index=index,
        )

    def apply(
        self, average: "dict[str, np.ndarray]", n_contributors: int,
    ) -> "dict[str, np.ndarray]":
        """Add calibrated Gaussian noise to the aggregate's float32
        tensors (non-f32 tensors — int batch counters — carry no client
        signal the mechanism models and pass through untouched)."""
        layout = _f32_layout(average)
        index = self.applications
        self.applications += 1
        std = self.noise_std(n_contributors)
        dim = sum(size for _k, _off, size in layout)
        vec = self._noise_vec(average, dim, std, index)
        out = dict(average)
        for key, off, size in layout:
            arr = np.asarray(average[key])
            out[key] = arr + vec[off:off + size].reshape(arr.shape)
        if self.metrics is not None:
            self.metrics.log(
                "dp_noise_applied", mode="server", index=index,
                std=float(std), n=int(n_contributors), dim=int(dim),
                backend=(
                    "device" if self.device_engine is not None else "host"
                ),
            )
        return out


class ClientSanitizer:
    """Client-side DP-SGD on the outgoing update: clip the round delta
    to the L2 ball ``clip`` (the gate-clip semantics, applied at the
    source), then add ``sigma * clip`` Gaussian noise — the update is
    differentially private before it leaves the client, so the server,
    every relay tier, and any wire observer see only the sanitized
    version (local DP)."""

    def __init__(self, spec: DPSpec, *, client_id: int = 0,
                 metrics: Any = None):
        if spec.mode != "client":
            raise ValueError(
                f"ClientSanitizer needs a client-mode spec, "
                f"got {spec.mode!r}"
            )
        self.spec = spec
        self.client_id = int(client_id)
        self.metrics = metrics
        self.applications = 0

    def apply(
        self,
        params: "dict[str, np.ndarray]",
        reference: "Mapping[str, np.ndarray]",
        round_index: int,
    ) -> "dict[str, np.ndarray]":
        """Sanitize one outgoing parameter bundle against ``reference``
        (the last applied aggregate, or the initial template before any
        broadcast): clip the float delta, noise the float32 tensors,
        return ``reference + sanitized delta`` in the bundle's dtypes."""
        spec = self.spec
        # Global L2 of the float delta in f64 — the same accumulation
        # sanitize.update_norm uses, so the clip ball is the ball the
        # server's admission gate measures.
        sq = 0.0
        fkeys = []
        for k in sorted(params):
            arr = np.asarray(params[k])
            if arr.dtype.kind != "f":
                continue
            fkeys.append(k)
            d = (np.asarray(arr, np.float64)
                 - np.asarray(reference[k], np.float64))
            sq += float(np.sum(d * d))
        norm = float(np.sqrt(sq))
        factor = min(1.0, spec.clip / norm) if norm > 0.0 else 1.0
        index = self.applications
        self.applications += 1
        std = spec.sigma * spec.clip
        layout = _f32_layout({k: params[k] for k in fkeys})
        dim = sum(size for _k, _off, size in layout)
        # The draw is keyed by the APPLICATION counter, not the round: an
        # async/push client can uplink several snapshots at the same base
        # round, and reusing a noise vector across distinct uplinks would
        # correlate them (breaking the independent-Gaussian assumption the
        # accountant composes over).
        vec = host_noise_vector(
            dim, std, spec.seed, index, extra=(self.client_id,),
        )
        out = dict(params)
        noise_by_key = {k: (off, size) for k, off, size in layout}
        for k in fkeys:
            arr = np.asarray(params[k])
            ref = np.asarray(reference[k], np.float64)
            delta = np.asarray(arr, np.float64) - ref
            if factor < 1.0:
                delta = factor * delta
            sanitized = ref + delta
            if k in noise_by_key:
                off, size = noise_by_key[k]
                sanitized = sanitized + np.asarray(
                    vec[off:off + size].reshape(arr.shape), np.float64
                )
            out[k] = np.asarray(sanitized, dtype=arr.dtype)
        if self.metrics is not None:
            self.metrics.log(
                "dp_noise_applied", mode="client", index=index,
                std=float(std), n=1, dim=int(dim),
                round=int(round_index), norm=norm,
                clipped=bool(factor < 1.0),
            )
        return out
