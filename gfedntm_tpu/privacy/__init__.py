"""Privacy plane: DP mechanisms + the (ε, δ) accountant (ROADMAP item 3).

The federation's premise is private client corpora, yet the shared
parameter stream is exactly what membership-inference attacks read.
This package bounds that leakage with two composable mechanisms and one
ledger:

- **Server-side FedLD noise** (:class:`~.mechanisms.ServerNoiser`):
  calibrated Gaussian noise injected into the aggregate *after* the
  (possibly robust) mean stage — the Federated Averaging Langevin
  Dynamics construction (arXiv:2112.05120, arXiv:2211.00100), which
  turns the round loop into posterior sampling and yields central DP
  against recipients of the broadcast stream.
- **Client-side DP-SGD** (:class:`~.mechanisms.ClientSanitizer`): each
  client clips its outgoing update to an L2 ball (the PR 5
  ``--max_update_norm`` gate-clip semantics reused as the DP clip) and
  adds seeded Gaussian noise *before* the update leaves the client —
  local DP against the server itself (and every relay tier).
- **The accountant** (:class:`~.accountant.PrivacyAccountant`): an
  RDP/moments ledger composed per aggregation round with the *actual*
  mechanism used, crediting cohort-subsampling amplification with the
  live q = K/N from :meth:`pacing.CohortEngine.inclusion_q` and staying
  conservative (q = 1) for sync/async/push pacing. The ledger rides the
  PR 10 journal/checkpoint state so crash-autorecovery resumes the
  budget instead of resetting it.

Everything is default-off: ``--dp off`` constructs none of these
objects and every existing trajectory is bitwise unchanged.
"""

from gfedntm_tpu.privacy.accountant import (
    ALPHAS,
    PrivacyAccountant,
    eps_from_rdp,
    gaussian_rdp,
    subsampled_gaussian_rdp,
)
from gfedntm_tpu.privacy.mechanisms import (
    ClientSanitizer,
    DPSpec,
    ServerNoiser,
    host_noise_vector,
    parse_dp,
)

__all__ = [
    "ALPHAS",
    "PrivacyAccountant",
    "eps_from_rdp",
    "gaussian_rdp",
    "subsampled_gaussian_rdp",
    "DPSpec",
    "parse_dp",
    "ServerNoiser",
    "ClientSanitizer",
    "host_noise_vector",
]
