"""Flight recorder & incident forensics (README "Incident forensics").

The JSONL telemetry stream is deliberately lossy: per-request successes
aggregate into histograms, gate verdicts surface only as rejections,
retry/backoff decisions never leave the process. That is the right
trade at 10^3-10^4 clients — and exactly wrong in the minutes before an
incident, when an operator needs the full-fidelity sequence of events
that led in. This module closes the detect->explain loop:

- :class:`FlightRecorder` — a bounded, lock-guarded ring (entry- AND
  time-capped, O(1) append) that taps every record the node's
  :class:`~gfedntm_tpu.utils.observability.MetricsLogger` emits plus
  fine-grained :func:`note` context the stream drops (per-RPC retry
  decisions, per-client gate verdicts, pacing deadline math), with
  periodic registry snapshots so EWMA/counter state rides along.
- :class:`IncidentTrigger` — the one seam every existing detector fires
  through: when a trigger event (see :data:`TRIGGER_EVENTS`) passes the
  logger, the ring + ``/status`` + process self-metrics + faulthandler
  thread stacks are snapshotted into an ATOMIC on-disk bundle
  (:func:`~gfedntm_tpu.train.checkpoint.atomic_write_bytes`), debounced
  per reason so an alert storm yields one bundle, with oldest-first
  eviction bounding the incident directory.
- remote-capture helpers (:func:`build_remote_snapshot`,
  :func:`encode_bundles` / :func:`decode_bundles`) — the wire format for
  server-solicited flight-record pulls that ride the next RPC exchange
  (best-effort, loss-tolerant, relay pre-bundled to O(relays) upstream
  cost; README "Incident forensics", remote-capture notes).

Recorder absent (``--dump_dir`` unset) is the contract default: nothing
is constructed, the logger tap is a single attribute check, and the
JSONL stream is bitwise identical to a recorder-less run.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
import zlib
from typing import Any, Callable

#: Bundle file schema version (bumped on incompatible layout changes;
#: the `incident` CLI refuses versions it does not know).
BUNDLE_SCHEMA = 1

#: Prefix every bundle file name carries — the eviction scan, the
#: `incident` CLI, and the --assert-no-incidents gate all key on it.
BUNDLE_PREFIX = "inc-"

#: Trigger event -> incident reason: the detectors already built (SLO
#: alerting, divergence guardian, probation/quarantine, crash
#: autorecovery, privacy accountant, serving admission, chaos injection)
#: all announce through schema'd events on the node's logger, so ONE
#: tap on the logger is the whole wiring. ``incident_captured`` itself
#: is deliberately absent (no self-triggering), and ``client_suspect``
#: is included because a dead relay surfaces at the root only as its
#: member record entering probation.
TRIGGER_EVENTS: dict[str, str] = {
    "alert_firing": "slo_alert",
    "divergence_rollback": "divergence_rollback",
    "client_quarantined": "quarantine",
    "client_suspect": "client_suspect",
    "server_recovered": "autorecovery",
    "relay_recovered": "autorecovery",
    "privacy_budget_exceeded": "privacy_budget",
    "serve_swap_refused": "swap_refused",
    "serve_shed": "shed_storm",
    "partition_injected": "chaos",
}


def note(metrics: Any, kind: str, **fields: Any) -> None:
    """Record fine-grained context into ``metrics``' flight ring, if one
    is attached — a single ``getattr`` when there is none, so hot paths
    (retry loops, gate verdicts, pacing math) can call this
    unconditionally without measurable cost when forensics is off."""
    recorder = getattr(metrics, "recorder", None)
    if recorder is not None:
        recorder.note(kind, **fields)


class FlightRecorder:
    """Bounded ring of recent full-fidelity records for one node.

    Entry-capped by ``max_entries`` (deque maxlen — O(1) append) and
    time-capped by ``max_seconds`` (stale head records are pruned on
    append/snapshot). Two record shapes share the ring: logger event
    records (tapped verbatim from :meth:`MetricsLogger.log`, keyed by
    ``event``) and :meth:`note` records (keyed by ``kind``); both carry
    ``time``. When a ``registry`` is given, a snapshot of it is folded
    into the ring every ``snapshot_every_s`` so the EWMA/counter state
    leading into an incident is preserved too.
    """

    def __init__(self, max_entries: int = 2048, max_seconds: float = 300.0,
                 registry: Any = None, snapshot_every_s: float = 10.0):
        if max_entries <= 0:
            raise ValueError(f"max_entries must be positive: {max_entries}")
        self.max_entries = int(max_entries)
        self.max_seconds = float(max_seconds)
        self.registry = registry
        self.snapshot_every_s = float(snapshot_every_s)
        self.trigger: "IncidentTrigger | None" = None
        self._ring: collections.deque = collections.deque(
            maxlen=self.max_entries
        )
        self._lock = threading.Lock()
        self._last_registry_snap = 0.0
        self.dropped = 0  # entries evicted by the caps (observability)

    # -- append paths ------------------------------------------------------

    def observe(self, record: dict[str, Any]) -> None:
        """Logger tap: ring every emitted event record (pre-sampling,
        full fidelity), then give the trigger seam a look at it."""
        self._append(record)
        trigger = self.trigger
        if trigger is not None:
            trigger.maybe_trigger(record)

    def note(self, kind: str, **fields: Any) -> None:
        """Ring a fine-grained record the JSONL stream drops (retry
        decisions, gate verdicts, pacing math, RPC outcomes)."""
        self._append({"kind": kind, "time": time.time(), **fields})

    def _append(self, record: dict[str, Any]) -> None:
        now = time.time()
        with self._lock:
            if len(self._ring) == self.max_entries:
                self.dropped += 1
            self._ring.append(record)
            self._prune(now)
            reg = self.registry
            if (
                reg is not None
                and now - self._last_registry_snap >= self.snapshot_every_s
            ):
                # Stamp BEFORE snapshotting: a slow snapshot must not
                # re-arm itself for every queued append behind the lock.
                self._last_registry_snap = now
                try:
                    snap = reg.snapshot()
                except Exception:
                    snap = None
                if snap:
                    self._ring.append({
                        "kind": "registry_snapshot", "time": now,
                        "metrics": snap,
                    })

    def _prune(self, now: float) -> None:
        horizon = now - self.max_seconds
        ring = self._ring
        while ring and float(ring[0].get("time", now)) < horizon:
            ring.popleft()
            self.dropped += 1

    # -- read side ---------------------------------------------------------

    def snapshot(self) -> list[dict[str, Any]]:
        """Copy of the ring, oldest first, stale head pruned."""
        with self._lock:
            self._prune(time.time())
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


def _process_info() -> dict[str, Any]:
    """Cheap process self-metrics for a bundle (no psutil in the image:
    /proc + os.times cover what a postmortem needs)."""
    info: dict[str, Any] = {
        "pid": os.getpid(),
        "threads": threading.active_count(),
    }
    try:
        t = os.times()
        info["cpu_user_s"] = t.user
        info["cpu_system_s"] = t.system
    except Exception:
        pass
    try:
        with open("/proc/self/status") as fh:
            for line in fh:
                if line.startswith(("VmRSS:", "VmHWM:", "Threads:")):
                    key, _, value = line.partition(":")
                    info[key.strip().lower()] = value.strip()
    except OSError:
        pass
    return info


def _thread_stacks() -> str:
    """Every thread's current stack, via faulthandler (needs a real fd —
    a TemporaryFile, not StringIO), degrading to the pure-Python
    traceback walk if faulthandler is unavailable."""
    try:
        import faulthandler
        import tempfile

        with tempfile.TemporaryFile(mode="w+") as fh:
            faulthandler.dump_traceback(file=fh, all_threads=True)
            fh.seek(0)
            return fh.read()
    except Exception:
        import sys
        import traceback

        chunks = []
        for tid, frame in sys._current_frames().items():
            chunks.append(f"Thread {tid}:\n")
            chunks.extend(traceback.format_stack(frame))
        return "".join(chunks)


def bundle_filename(incident_id: str, node: str) -> str:
    """Canonical on-disk name: ``inc-<id>__<node>.json`` — the double
    underscore separates the CLI's (incident, node) grouping keys."""
    safe_node = "".join(
        c if c.isalnum() or c in "-." else "_" for c in (node or "unknown")
    )
    safe_id = "".join(
        c if c.isalnum() or c in "-." else "_" for c in incident_id
    )
    return f"{BUNDLE_PREFIX}{safe_id}__{safe_node}.json"


class IncidentTrigger:
    """The one trigger-driven dump seam for a node.

    Watches the logger tap for :data:`TRIGGER_EVENTS`, debounces per
    reason (an alert storm yields one bundle, with a suppressed count),
    and atomically writes an incident bundle: the flight ring, the
    node's ``/status`` payload (via ``status_cb``), process
    self-metrics, and faulthandler thread stacks. The on-disk incident
    directory is bounded (``max_bundles``, oldest evicted first). The
    JSONL stream is flushed AND fsynced around the dump
    (:meth:`MetricsLogger.sync`) so the stream on disk is consistent
    with every captured bundle — the crash-durability contract the
    postmortem merge depends on.

    ``on_capture(incident_id, reason, trigger_record)`` is the server's
    remote-solicitation hook; leaf nodes leave it unset.
    """

    def __init__(self, recorder: FlightRecorder, dump_dir: str,
                 metrics: Any = None, node: str | None = None,
                 status_cb: Callable[[], dict] | None = None,
                 debounce_s: float = 30.0, max_bundles: int = 32,
                 on_capture: Callable[[str, str, dict], None] | None = None):
        self.recorder = recorder
        self.dump_dir = dump_dir
        self.metrics = metrics
        self.node = node or (getattr(metrics, "node", None) or "unknown")
        self.status_cb = status_cb
        self.debounce_s = float(debounce_s)
        self.max_bundles = int(max_bundles)
        self.on_capture = on_capture
        self._lock = threading.Lock()
        self._last_by_reason: dict[str, float] = {}
        self._suppressed: dict[str, int] = {}
        self._capturing = threading.local()
        os.makedirs(dump_dir, exist_ok=True)
        recorder.trigger = self

    def maybe_trigger(self, record: dict[str, Any]) -> "str | None":
        """Logger-tap entry: capture iff ``record`` is a trigger event
        outside its reason's debounce window. Returns the bundle path
        when one was written."""
        reason = TRIGGER_EVENTS.get(record.get("event"))
        if reason is None:
            return None
        if getattr(self._capturing, "active", False):
            # A capture's own emissions (incident_captured, sync
            # side-effects) must never recurse into another capture.
            return None
        now = time.time()
        with self._lock:
            last = self._last_by_reason.get(reason)
            if last is not None and now - last < self.debounce_s:
                self._suppressed[reason] = (
                    self._suppressed.get(reason, 0) + 1
                )
                return None
            self._last_by_reason[reason] = now
        return self.capture(reason, trigger_record=record)

    def capture(self, reason: str, trigger_record: dict | None = None,
                incident_id: str | None = None) -> str:
        """Snapshot everything into one atomic bundle file; returns its
        path. Never raises into the emitting hot path — a forensics
        failure must not take down the plane it is explaining."""
        from gfedntm_tpu.train.checkpoint import atomic_write_bytes

        self._capturing.active = True
        try:
            now = time.time()
            if incident_id is None:
                incident_id = f"{int(now * 1000):x}-{reason}"
            status = None
            if self.status_cb is not None:
                try:
                    status = self.status_cb()
                except Exception:
                    status = None
            with self._lock:
                suppressed = dict(self._suppressed)
            bundle = {
                "schema": BUNDLE_SCHEMA,
                "incident_id": incident_id,
                "node": self.node,
                "reason": reason,
                "time": now,
                "trigger": trigger_record,
                "ring": self.recorder.snapshot(),
                "ring_dropped": self.recorder.dropped,
                "suppressed": suppressed,
                "status": status,
                "process": _process_info(),
                "stacks": _thread_stacks(),
            }
            path = os.path.join(
                self.dump_dir, bundle_filename(incident_id, self.node)
            )
            # Stream-before-bundle ordering: everything the ring holds is
            # durably on disk before (and after) the bundle referencing it.
            self._sync_stream()
            self._evict()
            atomic_write_bytes(
                path, json.dumps(bundle, default=str).encode("utf-8")
            )
            if self.metrics is not None:
                self.metrics.log(
                    "incident_captured", reason=reason,
                    incident_id=incident_id, records=len(bundle["ring"]),
                    path=path,
                )
            self._sync_stream()
            if self.on_capture is not None:
                try:
                    self.on_capture(incident_id, reason, trigger_record or {})
                except Exception:
                    pass
            return path
        finally:
            self._capturing.active = False

    def ingest_remote(self, blob: bytes) -> list[str]:
        """Server-side landing zone for solicited remote snapshots: each
        decoded node bundle becomes its own file in the same incident
        dir (grouped with the local bundle by incident id), deduplicated
        by filename — re-shipped blobs from retried RPCs are free."""
        try:
            bundles = decode_bundles(blob)
        except Exception:
            return []
        written: list[str] = []
        from gfedntm_tpu.train.checkpoint import atomic_write_bytes

        for bundle in bundles:
            if not isinstance(bundle, dict):
                continue
            incident_id = str(bundle.get("incident_id") or "unknown")
            node = str(bundle.get("node") or "unknown")
            path = os.path.join(
                self.dump_dir, bundle_filename(incident_id, node)
            )
            if os.path.exists(path):
                continue
            self._evict()
            try:
                atomic_write_bytes(
                    path, json.dumps(bundle, default=str).encode("utf-8")
                )
            except OSError:
                continue
            written.append(path)
            if self.metrics is not None:
                self.metrics.log(
                    "flightrec_received", incident_id=incident_id,
                    node=node,
                )
        return written

    def _sync_stream(self) -> None:
        sync = getattr(self.metrics, "sync", None)
        if sync is not None:
            try:
                sync()
            except Exception:
                pass

    def _evict(self) -> None:
        """Keep the incident dir bounded: oldest bundles leave first
        (mtime order), leaving room for one incoming bundle."""
        try:
            names = [
                n for n in os.listdir(self.dump_dir)
                if n.startswith(BUNDLE_PREFIX) and n.endswith(".json")
            ]
        except OSError:
            return
        if len(names) < self.max_bundles:
            return
        def mtime(name: str) -> float:
            try:
                return os.path.getmtime(os.path.join(self.dump_dir, name))
            except OSError:
                return 0.0
        for name in sorted(names, key=mtime)[: len(names) - self.max_bundles + 1]:
            try:
                os.remove(os.path.join(self.dump_dir, name))
            except OSError:
                pass


# ---- remote-capture wire format ---------------------------------------------

def encode_bundles(bundles: list[dict[str, Any]]) -> bytes:
    """zlib-compressed JSON list of node bundles — the
    ``StepReply.flightrec`` payload. Always a LIST so a relay can
    pre-bundle its members' snapshots with its own into one upstream
    blob (O(relays) root-side cost)."""
    return zlib.compress(json.dumps(bundles, default=str).encode("utf-8"))


def decode_bundles(blob: bytes) -> list[dict[str, Any]]:
    out = json.loads(zlib.decompress(blob).decode("utf-8"))
    if not isinstance(out, list):
        raise ValueError("flightrec blob must decode to a list of bundles")
    return out


def build_remote_snapshot(metrics: Any, incident_id: str) -> "bytes | None":
    """A leaf node's answer to a solicited capture: its ring (plus
    process self-metrics) as a one-element encoded bundle list, or
    ``None`` when no recorder is attached (best-effort — the server
    merges whatever arrives)."""
    recorder = getattr(metrics, "recorder", None)
    if recorder is None:
        return None
    bundle = {
        "schema": BUNDLE_SCHEMA,
        "incident_id": incident_id,
        "node": getattr(metrics, "node", None) or "unknown",
        "reason": "remote_capture",
        "time": time.time(),
        "trigger": None,
        "ring": recorder.snapshot(),
        "ring_dropped": recorder.dropped,
        "process": _process_info(),
    }
    return encode_bundles([bundle])
