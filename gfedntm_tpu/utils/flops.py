"""Live FLOPs + MFU accounting for the multi-chip training paths.

Every MFU number this repo reports divides *measured* work by a peak:

- the work side comes from the XLA cost model of the ACTUAL lowered
  program (:func:`measure_program_flops` — ``Lowered.cost_analysis()``,
  or the compiled executable's analysis when an AOT handle is available),
  never from a hand-maintained analytic formula that drifts when the
  model changes;
- the peak side is the nominal accelerator spec when one is published
  (TPU v5e bf16 MXU), and a LIVE matmul probe on backends without one
  (:func:`measure_peak_flops_per_device` — the CPU tier), so a CPU MFU
  is "fraction of what this host's BLAS can do", not a number divided by
  a TPU spec it never had (the meaningless ~1e-4 of BENCH_r03-r05).

GL002 note: this module sits in the MFU/throughput accounting path and
is in the precision-pin rule's scope — its probe matmul pins
``precision=jax.lax.Precision.HIGHEST``. On CPU the pin is a no-op (f32
is f32); on TPUs it makes the probe measure the HIGHEST-precision f32
peak, which is the right comparator for this repo's f32 training math
(the nominal bf16 peak stays the accelerator denominator, reported
separately as ``peak_source``).
"""

from __future__ import annotations

import time
from typing import Any

#: Nominal per-device peaks for backends with a published spec (FLOP/s).
#: The chip behind the tunnel reports "TPU v5 lite": 197 TFLOP/s bf16 MXU.
NOMINAL_PEAK_FLOPS: dict[str, float] = {
    "tpu": 197.0e12,
    "axon": 197.0e12,
}

_peak_cache: dict[str, float] = {}


def measure_program_flops(fn: Any, *args, compiled: Any = None) -> float | None:
    """FLOPs of ONE invocation of ``fn(*args)`` from the XLA cost model.

    ``fn`` must be a ``jax.jit`` product (anything with ``.lower``).
    Lowering + cost analysis runs the compiler's own accounting over the
    real program — a live measurement of the code as built, not an
    analytic estimate. Pass ``compiled=`` (an AOT ``Compiled`` handle)
    to reuse an existing compilation instead of re-lowering.

    Scan caveat (pinned by test_multichip): XLA's analysis counts a
    ``scan``/``while`` body ONCE regardless of trip count, so for a
    length-S scan program the returned number approximates ONE step,
    not S steps. Callers whose program is a step scan must multiply by
    their own step count (fit_data_sharded, the federated trainer).

    Returns None when the backend/jax version exposes no cost analysis —
    callers must treat MFU as unavailable rather than report 0.
    """
    try:
        if compiled is not None:
            analysis = compiled.cost_analysis()
        else:
            lower = getattr(fn, "lower", None)
            if lower is None:
                return None
            analysis = lower(*args).cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else {}
        flops = float(analysis.get("flops", 0.0))
        return flops if flops > 0.0 else None
    except Exception:  # graftlint: disable=exception-hygiene -- cost
        # analysis is best-effort telemetry; a backend without it yields
        # "MFU unavailable", which the caller reports as such
        return None


def measure_peak_flops_per_device(
    backend: str | None = None, n: int = 1024, repeats: int = 3
) -> float | None:
    """Live-measured matmul peak of ONE device (FLOP/s), best-of-N timed
    ``[n, n] @ [n, n]`` f32 matmuls pinned HIGHEST. Cached per backend —
    the probe costs ~100 ms once. Used as the MFU denominator on backends
    without a published spec (the CPU tier)."""
    import jax
    import jax.numpy as jnp

    key = backend or jax.default_backend()
    if key in _peak_cache:
        return _peak_cache[key]
    try:
        a = jnp.ones((n, n), jnp.float32)
        prog = jax.jit(
            lambda x: jnp.matmul(
                x, x, precision=jax.lax.Precision.HIGHEST
            )
        )
        jax.block_until_ready(prog(a))  # compile + warm
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            jax.block_until_ready(prog(a))
            best = min(best, time.perf_counter() - t0)
        peak = 2.0 * n * n * n / best
    except Exception:  # graftlint: disable=exception-hygiene -- a probe
        # failure means "no peak reference"; callers report MFU as
        # unavailable instead of dividing by a made-up number
        return None
    _peak_cache[key] = peak
    return peak


def resolve_peak_flops_per_device(
    backend: str,
) -> tuple[float | None, str]:
    """(peak FLOP/s per device, source) for an MFU denominator: the
    published nominal peak for known accelerators, else a live matmul
    probe (``"measured-matmul-probe"``), else ``(None, "unavailable")``."""
    if backend in NOMINAL_PEAK_FLOPS:
        return NOMINAL_PEAK_FLOPS[backend], "nominal-spec"
    peak = measure_peak_flops_per_device(backend)
    if peak is not None:
        return peak, "measured-matmul-probe"
    return None, "unavailable"


def mfu(
    flops_per_call: float | None,
    seconds_per_call: float,
    n_devices: int,
    peak_per_device: float | None,
) -> float | None:
    """Model FLOPs utilization: achieved FLOP/s per device over the peak.

    ``flops_per_call`` is the WHOLE program's cost (all devices — the XLA
    analysis counts the full computation), so per-device achieved FLOP/s
    is ``flops / seconds / n_devices``."""
    if (
        flops_per_call is None
        or peak_per_device is None
        or seconds_per_call <= 0.0
        or n_devices < 1
    ):
        return None
    return flops_per_call / seconds_per_call / n_devices / peak_per_device
