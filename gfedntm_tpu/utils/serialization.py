"""Model artifact serialization.

Two formats:
- ``save_variables`` / ``load_variables``: a single ``.npz`` of the Flax
  variable tree with '/'-joined path keys — the ``.pth`` equivalent of the
  reference's ``AVITM.save`` (``avitm.py:598-617``) without pickling.
- ``save_model_as_npz``: the reference's final-artifact bundle of
  betas/thetas/topics (``auxiliary_functions.py:66-99``) so downstream
  tooling (notebooks, WMD eval) reads the same schema.
"""

from __future__ import annotations

import json
import os

import numpy as np
from flax.traverse_util import flatten_dict, unflatten_dict


def save_variables(path: str, variables: dict) -> None:
    flat = flatten_dict(variables, sep="/")
    np.savez(path, **{k: np.asarray(v) for k, v in flat.items()})


def load_variables(path: str) -> dict:
    with np.load(path) as z:
        flat = {k: z[k] for k in z.files}
    return unflatten_dict(flat, sep="/")


def save_model_as_npz(
    save_dir: str,
    betas: np.ndarray,
    thetas: np.ndarray | None,
    topics: list[list[str]] | None,
    n_components: int,
    name: str = "model",
) -> str:
    """Reference final-artifact schema: keys ``betas``, ``thetas``,
    ``ntopics``, ``topics`` (``auxiliary_functions.py:66-99``; the server-side
    variant stores betas only, ``federated_model.py:183-197``)."""
    os.makedirs(save_dir, exist_ok=True)
    path = os.path.join(save_dir, f"{name}.npz")
    payload = {"betas": betas, "ntopics": n_components}
    if thetas is not None:
        payload["thetas"] = thetas
    if topics is not None:
        payload["topics"] = np.array(
            json.dumps([list(t) for t in topics])
        )
    np.savez(path, **payload)
    return path
