"""Federation-wide telemetry: structured events, spans, metrics, reports.

The reference's telemetry is log-line based (per-minibatch loss strings,
``federated_avitm.py:109``) with a vestigial ``GRPC_TRACE`` constant and no
profiler hooks (SURVEY.md §5). Here telemetry is a first-class subsystem —
the substrate every perf/robustness PR reports against:

- :class:`MetricsLogger` — thread-safe structured JSONL event stream (one
  object per line), flushed eagerly so a crashed run keeps its telemetry.
  Every logger carries a :class:`MetricRegistry` whose cumulative state
  snapshots into the same stream (``metrics_snapshot`` events).
- :func:`span` — hierarchical timing contexts (parent/child ids, monotonic
  durations) so a run decomposes into round → client → {poll, average,
  push, local_step}. Nesting is implicit within a thread (contextvars) and
  explicit (``parent=``) across threads.
- :class:`MetricRegistry` — counters, gauges, and fixed-bucket histograms
  (step time, RPC latency, payload bytes) with percentile estimation.
- :func:`validate_record` — schema lint for the event stream, so new events
  can't silently drift from the documented schema (README "Telemetry").
- :func:`summarize_metrics` / :func:`format_report` — the ``summarize`` CLI
  subcommand's engine: phase breakdown, p50/p95/p99 step time, bytes moved
  per round, slowest client.
- :func:`phase_timer` — wall-phase timing (consensus, compile, train).
- :func:`trace` — ``jax.profiler`` trace context for TPU timeline capture
  (view in TensorBoard / xprof).

Cross-process observability plane (README "Distributed tracing & ops
endpoint"): a federation is N processes, so a round's story needs shared
trace identity, live introspection, and device-level visibility —

- trace-context propagation — :func:`new_trace_id`, :func:`trace_pairs` /
  :func:`ambient_trace_pairs` (outbound gRPC metadata) and
  :func:`extract_trace_context` (servicer side), Dapper-style: the server
  stamps every poll/push with ``trace_id``/``parent_span_id``/``round``,
  the remote servicer parents its local ``serve`` span under it, and one
  federation round becomes one tree spanning server and all clients;
- :func:`merge_chrome_trace` — the ``trace`` CLI subcommand's engine:
  merges per-node JSONL streams into one Chrome trace-event (Perfetto-
  loadable) JSON, aligning clocks via the paired RPC send/recv timestamps
  the trace plane records;
- :func:`render_prometheus` + :class:`OpsServer` — a stdlib ``http.server``
  thread serving ``/metrics`` (Prometheus text exposition of the registry),
  ``/healthz``, and ``/status`` (live round, membership, codec state);
- :class:`RoundProfiler` — ``jax.profiler`` start/stop around a
  configurable round window (``--profile_dir``);
- :class:`DeviceMemoryMonitor` — per-device memory gauges from
  ``jax.local_devices()`` ``memory_stats()`` (no-op on CPU);
- :class:`StragglerDetector` — rolling per-client step-time EWMAs with
  z-score ``straggler_detected`` events.

Every hook is a no-op when no logger is passed (``logger=None``), so
un-instrumented hot paths pay nothing. Durations come from
``time.perf_counter`` (monotonic — NTP steps cannot produce negative phase
times); wall-clock ``time.time()`` appears as the ``time`` event timestamp
field and in the paired RPC send/recv stamps the clock aligner consumes.
"""

from __future__ import annotations

import bisect
import contextlib
import heapq
import contextvars
import inspect
import itertools
import json
import os
import re
import threading
import time
import zlib
from typing import Any, Iterator

# ---- event schema -----------------------------------------------------------

#: Required fields per event name, beyond the implicit ``event`` + ``time``.
#: Extra fields are always allowed; MISSING required fields (or an event name
#: absent from this table, under strict validation) are schema drift.
EVENT_SCHEMAS: dict[str, frozenset[str]] = {
    # timing
    "phase": frozenset({"phase", "seconds"}),
    "span": frozenset({"name", "span_id", "parent_id", "seconds"}),
    "jit_compile": frozenset({"what", "seconds"}),
    # registry state
    "metrics_snapshot": frozenset({"metrics"}),
    # RPC failures (successes aggregate into registry histograms only)
    "rpc": frozenset({"service", "method", "seconds", "ok"}),
    # resilience lifecycle (federation probation / quorum / checkpoint /
    # client watchdog; see README "Fault tolerance")
    "client_suspect": frozenset({"client", "failures", "status"}),
    "client_recovered": frozenset({"client"}),
    "quorum_skip": frozenset({"round", "got", "needed"}),
    "checkpoint": frozenset({"round"}),
    "watchdog_fired": frozenset({"client", "idle_s"}),
    # crash-survival plane (durable sessions / idempotent RPCs / server
    # auto-recovery / partition chaos; README "Crash recovery & sessions")
    "client_reconnected": frozenset({"client", "attempts"}),
    "session_restored": frozenset({"client"}),
    "rpc_deduplicated": frozenset({"client", "method"}),
    "server_recovered": frozenset({"round", "source"}),
    "partition_injected": frozenset({"peer", "window_s"}),
    # survivable hierarchy (relay crash recovery / member re-homing /
    # journal degradation; README "Crash recovery & sessions"): a
    # respawned relay that restored its shard from its own journal, a
    # member adopted by a new tier after its relay never came back (the
    # adoptive tier logs this LOUDLY — an unknown-but-valid-format token
    # is evidence of a cross-tier failover, not a fresh fleet member),
    # and a journal write that failed (ENOSPC/EIO) — training continues
    # but autorecovery is disabled for the rest of the run.
    "relay_recovered": frozenset({"relay", "round", "members"}),
    "member_rehomed": frozenset({"client"}),
    "journal_write_failed": frozenset({"round", "error"}),
    # data-plane defense (update admission gate / divergence guardian;
    # see README "Robust aggregation & divergence recovery")
    "update_rejected": frozenset({"client", "round", "reason"}),
    "update_clipped": frozenset({"client", "round", "norm", "max_norm"}),
    "divergence_rollback": frozenset({"round", "reason"}),
    "client_quarantined": frozenset({"client", "round"}),
    "checkpoint_invalid": frozenset({"reason"}),
    # wire codec negotiation + delta-reference discipline (federation
    # compression subsystem; see README "Aggregation strategies & wire
    # compression")
    "codec_negotiated": frozenset({"client", "codec"}),
    "codec_mismatch": frozenset({"client", "server_codec", "client_codec"}),
    "codec_ref_miss": frozenset({"client", "ref_round"}),
    # bounded reference caches + wire-efficient scale-out (per-recipient
    # delta encoding, push pacing, relay tier; README "Hierarchical
    # federation & wire efficiency")
    "codec_ref_evicted": frozenset({"direction", "round", "age"}),
    "push_aggregated": frozenset({"round", "buffered", "admitted"}),
    "relay_joined": frozenset({"relay", "members", "weight"}),
    "relay_preaggregated": frozenset({"relay", "round", "members",
                                      "admitted"}),
    # cross-process observability plane (README "Distributed tracing & ops
    # endpoint"): trace identity, live ops endpoint, device profiler window,
    # straggler analytics
    # federation pacing (cohort sampling / buffered async; README
    # "Federation pacing")
    "cohort_sampled": frozenset({"round", "k", "eligible", "q"}),
    "async_aggregated": frozenset({"round", "buffered", "admitted"}),
    "update_stale_discounted": frozenset(
        {"client", "round", "staleness", "factor"}
    ),
    "trace_started": frozenset({"trace_id"}),
    "ops_server_started": frozenset({"port"}),
    "profiler_started": frozenset({"dir", "round"}),
    "profiler_stopped": frozenset({"round"}),
    "straggler_detected": frozenset({"client", "round", "z"}),
    # model-quality plane (topic coherence / diversity / drift telemetry;
    # README "Model-quality observability")
    "quality_computed": frozenset({"round", "npmi", "diversity"}),
    "topic_drift": frozenset({"round", "mean_drift", "churn"}),
    # training progress
    "resume": frozenset({"step"}),
    "epoch": frozenset({"epoch"}),
    "federated_segment": frozenset({"step", "mean_loss"}),
    "federated_iteration": frozenset({"iteration", "mean_loss"}),
    "summary": frozenset(),
    # bench stream (bench.py emits through the same logger/schema)
    "bench_summary": frozenset({"backend"}),
    "bench_result": frozenset({"metric", "value", "unit", "backend"}),
    # staged bench sub-phases (bench.py run-phase staging: a stage record
    # lands in the stream the moment the stage completes, so a later hang
    # cannot erase it; README "Multi-chip training & bench interpretation")
    "bench_stage": frozenset({"stage", "seconds"}),
    # multi-chip data-sharded local training (parallel.sharded
    # .fit_data_sharded / the mesh-enabled federation client)
    "sharded_fit": frozenset({"devices", "docs_per_s"}),
    # serving plane (hot-swappable doc->topic inference; README "Serving"):
    # model lifecycle + request-path failures. Per-request successes stay
    # out of the JSONL stream (they aggregate into the serve_latency_s
    # histogram and the serving_* counters, surfaced via
    # metrics_snapshot) — at production QPS one event per request would
    # dwarf every other stream combined.
    "serve_model_loaded": frozenset({"round", "source"}),
    "serve_model_swapped": frozenset({"round", "prev_round"}),
    "serve_swap_refused": frozenset({"round", "reason"}),
    "serve_error": frozenset({"reason"}),
    # closed-loop load generator summary (scripts/serve_bench.py + the
    # serving e2e tests): one record per measured window, the JSONL
    # ground truth BENCH_SERVE artifacts are reproduced from.
    "serve_load_window": frozenset(
        {"seconds", "docs", "requests", "failures", "docs_per_s"}
    ),
    # serving-plane load shedding (README "Serving"): a full pending
    # queue sheds the ARRIVING request alone (RESOURCE_EXHAUSTED / 429);
    # queued and accepted requests are never dropped.
    "serve_shed": frozenset({"docs", "queued"}),
    # scenario matrix engine (README "Scenario matrix"): cell lifecycle
    # + per-cell degradation-contract verdicts — the ground truth the
    # BENCH_SCENARIO artifact and the SCENARIO=1 smoke stage key on.
    "scenario_cell_started": frozenset({"cell", "workload", "pacing"}),
    "scenario_contract": frozenset({"cell", "contract", "ok"}),
    "scenario_cell_finished": frozenset({"cell", "ok", "seconds"}),
    # fleet telemetry plane + SLO/alerting engine (README "Fleet telemetry
    # & SLOs"): alert lifecycle transitions from the pending→firing→
    # resolved state machine, plus the FleetRegistry cardinality guard's
    # report-withholding record (a report over the node/series cap is
    # dropped observably, never silently).
    "alert_pending": frozenset({"alert", "metric", "threshold"}),
    "alert_firing": frozenset({"alert", "metric", "threshold"}),
    "alert_resolved": frozenset({"alert"}),
    "fleet_overflow": frozenset({"node", "reason"}),
    # privacy plane (README "Differential privacy & posterior sampling"):
    # one dp_noise_applied per mechanism application (server FedLD /
    # client DP-SGD), one privacy_budget ledger row per aggregated round
    # (the accountant's running (eps, delta) — what the `privacy` CLI
    # gate replays), and a once-per-transition budget-exceeded marker.
    "dp_noise_applied": frozenset({"mode", "index", "std", "n", "dim"}),
    "privacy_budget": frozenset(
        {"round", "eps", "delta", "steps", "q", "sigma", "mode", "budget"}
    ),
    "privacy_budget_exceeded": frozenset(
        {"round", "eps", "budget", "delta"}
    ),
    # incident-forensics plane (README "Incident forensics"): one
    # incident_captured per atomic bundle a node's IncidentTrigger
    # writes, one flightrec_requested when the root solicits remote
    # flight-record snapshots from implicated nodes, and one
    # flightrec_received per remote node bundle that lands in the
    # root's incident dir off a piggybacked RPC reply.
    "incident_captured": frozenset(
        {"reason", "incident_id", "records", "path"}
    ),
    "flightrec_requested": frozenset({"incident_id", "reason"}),
    "flightrec_received": frozenset({"incident_id"}),
}


def validate_record(record: Any, strict: bool = True) -> dict[str, Any]:
    """Schema-lint one event record; returns it unchanged or raises
    ``ValueError``. ``strict=False`` lets unknown event names pass (their
    ``event``/``time`` envelope is still checked)."""
    if not isinstance(record, dict):
        raise ValueError(f"record must be a dict, got {type(record).__name__}")
    event = record.get("event")
    if not isinstance(event, str) or not event:
        raise ValueError(f"record needs a non-empty 'event' str: {record!r}")
    if not isinstance(record.get("time"), (int, float)):
        raise ValueError(f"record {event!r} needs a numeric 'time' field")
    required = EVENT_SCHEMAS.get(event)
    if required is None:
        if strict:
            raise ValueError(
                f"unknown event {event!r}: register it in "
                "observability.EVENT_SCHEMAS (and README 'Telemetry')"
            )
        return record
    missing = required - record.keys()
    if missing:
        raise ValueError(
            f"event {event!r} missing required fields {sorted(missing)}"
        )
    return record


# ---- metric registry --------------------------------------------------------

#: Exponential-ish latency edges, 100 µs .. 5 min (upper-inclusive buckets).
DEFAULT_TIME_BUCKETS_S: tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)

#: Payload-size edges, 256 B .. 256 MB (the gRPC message cap).
DEFAULT_BYTE_BUCKETS: tuple[float, ...] = tuple(
    256.0 * 4.0 ** i for i in range(11)
)


class Counter:
    """Monotonic counter."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n

    def snapshot(self) -> dict[str, Any]:
        return {"type": "counter", "value": self.value}


class Gauge:
    """Last-value-wins gauge."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value: float | None = None
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def snapshot(self) -> dict[str, Any]:
        return {"type": "gauge", "value": self.value}


class Histogram:
    """Fixed-bucket histogram with upper-inclusive edges.

    ``counts[i]`` counts observations ``v <= edges[i]`` (first matching
    bucket); ``counts[-1]`` is the overflow bucket. Percentiles are
    estimated by linear interpolation inside the selected bucket, clamped
    to the observed [min, max] — exact at the tracked extremes, bucket-
    resolution elsewhere.
    """

    __slots__ = ("name", "edges", "counts", "count", "sum", "min", "max",
                 "_lock")

    def __init__(self, name: str, buckets: tuple[float, ...] | None = None):
        self.name = name
        self.edges = tuple(sorted(buckets or DEFAULT_TIME_BUCKETS_S))
        self.counts = [0] * (len(self.edges) + 1)
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        i = bisect.bisect_left(self.edges, v)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            if not self.count:
                return {
                    "type": "histogram", "count": 0, "sum": 0.0,
                    "edges": list(self.edges), "counts": list(self.counts),
                }
            return {
                "type": "histogram",
                "count": self.count,
                "sum": self.sum,
                "min": self.min,
                "max": self.max,
                "edges": list(self.edges),
                "counts": list(self.counts),
            }

    def quantile(self, q: float) -> float | None:
        return quantile_from_snapshot(self.snapshot(), q)


def quantile_from_snapshot(snap: dict[str, Any], q: float) -> float | None:
    """Estimate the ``q``-quantile (0..1) from a histogram snapshot dict
    (the serialized form inside ``metrics_snapshot`` events)."""
    n = snap.get("count", 0)
    if not n:
        return None
    edges, counts = snap["edges"], snap["counts"]
    lo_all, hi_all = snap["min"], snap["max"]
    target = max(q, 0.0) * n
    cum = 0.0
    for i, c in enumerate(counts):
        if c and cum + c >= target:
            lo = lo_all if i == 0 else edges[i - 1]
            hi = edges[i] if i < len(edges) else hi_all
            lo = min(max(lo, lo_all), hi_all)
            hi = max(min(hi, hi_all), lo)
            frac = (target - cum) / c
            return lo + frac * (hi - lo)
        cum += c
    return hi_all


class MetricRegistry:
    """Get-or-create store of named counters/gauges/histograms; thread-safe.

    The first creation fixes a histogram's buckets; later ``histogram``
    calls for the same name return the existing instance (their ``buckets``
    argument is ignored).
    """

    def __init__(self):
        self._metrics: dict[str, Any] = {}
        self._lock = threading.Lock()

    def _get(self, name: str, cls, *args):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, *args)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}"
                )
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(
        self, name: str, buckets: tuple[float, ...] | None = None
    ) -> Histogram:
        return self._get(name, Histogram, buckets)

    def get(self, name: str):
        """Read-only lookup: the metric, or None — unlike the typed
        accessors this never creates (the ops endpoint's /status must not
        mint empty gauges just by being curled)."""
        with self._lock:
            return self._metrics.get(name)

    def drop(self, name: str) -> bool:
        """Remove a metric from the registry (idempotent; returns whether
        it existed). The eviction path of per-client series: detectors
        tracking a churning client population must drop a departed
        client's gauges, or the registry (and every later snapshot /
        Prometheus scrape) grows one series per client that ever lived."""
        with self._lock:
            return self._metrics.pop(name, None) is not None

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            metrics = list(self._metrics.items())
        return {name: m.snapshot() for name, m in sorted(metrics)}


# ---- structured event log ---------------------------------------------------

class MetricsLogger:
    """Append-only structured metrics. ``path=None`` keeps records in memory
    only (tests); otherwise each event is one JSON line, flushed eagerly so
    a crashed run keeps its telemetry.

    Thread-safe: the federation server's training loop drives one logger
    from many poll/push worker threads, and interleaved JSONL lines would
    corrupt the stream. ``validate=True`` schema-lints every record at log
    time (tests; see :func:`validate_record`).

    ``node`` names this process in the federation ("server", "client3");
    it is stamped on every record so the ``trace`` CLI can merge per-node
    streams without guessing from filenames. ``trace_id`` is the process's
    ambient trace identity — spans inherit it (see :class:`Span`) and
    outbound RPCs advertise it (:func:`ambient_trace_pairs`); the
    federation server mints one per training run and clients adopt it
    per-call from gRPC metadata.
    """

    def __init__(self, path: str | None = None, validate: bool = False,
                 mode: str = "a", keep_records: bool | None = None,
                 node: str | None = None, trace_id: str | None = None):
        self.path = path
        self.validate = validate
        self.node = node
        self.trace_id = trace_id
        # Flight-recorder tap (README "Incident forensics"): when a
        # FlightRecorder is attached, every record is ALSO ringed at
        # full fidelity and checked against the incident-trigger seam.
        # None (the default, and the only state when --dump_dir is
        # unset) costs one attribute load per log() call.
        self.recorder = None
        # In-memory retention is for in-process consumers (.events(), tests,
        # bench phase accounting). Default: retain only when there is no
        # file — a long path-backed server run would otherwise accumulate
        # every round's span events for the process lifetime.
        self.keep_records = (
            path is None if keep_records is None else bool(keep_records)
        )
        self.records: list[dict[str, Any]] = []
        self.registry = MetricRegistry()
        self._lock = threading.Lock()
        self._fh = None
        if path is not None:
            if mode not in ("a", "w"):
                raise ValueError(f"mode must be 'a' or 'w', got {mode!r}")
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._fh = open(path, mode)

    def log(self, event: str, **fields: Any) -> dict[str, Any]:
        record = {"event": event, "time": time.time(), **fields}
        if self.node is not None:
            record.setdefault("node", self.node)
        if self.validate:
            validate_record(record)
        # Serialize outside the lock; append + write inside it so lines
        # never interleave and records keeps file order.
        line = (
            json.dumps(record, default=float) if self.path is not None
            else None
        )
        with self._lock:
            if self.keep_records:
                self.records.append(record)
            if self._fh is not None and line is not None:
                self._fh.write(line + "\n")
                self._fh.flush()
        # Outside the lock: the recorder has its own lock, and a capture
        # it triggers logs incident_captured back through this method —
        # re-entry must find the stream lock free.
        recorder = self.recorder
        if recorder is not None:
            recorder.observe(record)
        return record

    def sync(self) -> None:
        """Flush AND fsync the JSONL stream (README "Incident
        forensics"): the per-line flush() already survives a SIGKILL of
        this process, but only fsync pushes the tail past the OS cache —
        the incident dump path calls this so the stream on disk is
        consistent with every captured bundle."""
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                os.fsync(self._fh.fileno())

    def events(self, event: str) -> list[dict[str, Any]]:
        if not self.keep_records:
            raise RuntimeError(
                "events() needs in-memory retention: construct with "
                "keep_records=True (or path=None), or read the JSONL file "
                "via read_metrics()"
            )
        return [r for r in self.records if r["event"] == event]

    def snapshot_registry(self, **fields: Any) -> dict[str, Any] | None:
        """Dump the registry's cumulative state into the event stream."""
        snap = self.registry.snapshot()
        if not snap:
            return None
        return self.log("metrics_snapshot", metrics=snap, **fields)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ---- hierarchical spans -----------------------------------------------------

_SPAN_IDS = itertools.count(1)
_CURRENT_SPAN: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "gfedntm_current_span", default=None
)


class Span:
    """One timed region of a run. Logs a ``span`` event on exit with its
    monotonic duration, id, parent id, and any annotated attributes.

    Within a thread, nesting is implicit (contextvars). Work handed to a
    pool thread does NOT inherit the submitting thread's context — pass the
    enclosing span explicitly: ``span(logger, "poll", parent=round_span)``.

    Trace identity: a ``trace_id`` field is inherited from the parent span
    (explicit or ambient), falling back to the logger's ``trace_id`` — so
    once the federation server mints a trace, every span in the process
    carries it without call-site changes, and remote children stamped via
    gRPC metadata land in the same tree. The emitting thread id is recorded
    too (``thread``) so the trace merger can lay concurrent servicer spans
    on separate tracks.
    """

    __slots__ = ("logger", "name", "fields", "span_id", "parent_id",
                 "_parent", "_token", "_t0")

    def __init__(self, logger: MetricsLogger, name: str, parent: Any,
                 fields: dict[str, Any]):
        self.logger = logger
        self.name = name
        self.fields = dict(fields)
        self.span_id = next(_SPAN_IDS)
        self.parent_id: int | None = None
        self._parent = parent
        self._token = None
        self._t0 = 0.0

    def annotate(self, **fields: Any) -> "Span":
        """Attach attributes that become fields of the logged span event."""
        self.fields.update(fields)
        return self

    def __enter__(self) -> "Span":
        cur = self._parent if self._parent is not None else _CURRENT_SPAN.get()
        if cur is not None:
            self.parent_id = getattr(cur, "span_id", cur)
        if self.fields.get("trace_id") is None:
            inherited = getattr(cur, "fields", {}).get("trace_id") if (
                cur is not None
            ) else None
            if inherited is None:
                inherited = getattr(self.logger, "trace_id", None)
            if inherited is not None:
                self.fields["trace_id"] = inherited
        self._token = _CURRENT_SPAN.set(self)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        seconds = time.perf_counter() - self._t0
        _CURRENT_SPAN.reset(self._token)
        self.logger.log(
            "span", name=self.name, span_id=self.span_id,
            parent_id=self.parent_id, seconds=seconds,
            ok=exc_type is None, thread=threading.get_ident(),
            **self.fields,
        )


class _NullSpan:
    """No-op span returned for ``logger=None`` call sites (zero overhead)."""

    __slots__ = ()
    span_id = None
    parent_id = None

    def annotate(self, **fields: Any) -> "_NullSpan":
        return self

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        return None


_NULL_SPAN = _NullSpan()


def span(logger: MetricsLogger | None, name: str, parent: Any = None,
         **fields: Any):
    """Hierarchical timing context; a no-op when ``logger`` is None."""
    if logger is None:
        return _NULL_SPAN
    return Span(logger, name, parent, fields)


def current_span() -> Span | None:
    """The thread's innermost open span, if any (contextvar-scoped)."""
    return _CURRENT_SPAN.get()


# ---- trace-context propagation (gRPC metadata) ------------------------------

#: gRPC metadata keys of the trace plane (lowercase per the HTTP/2 rules).
TRACE_ID_KEY = "x-gfedntm-trace-id"
PARENT_SPAN_KEY = "x-gfedntm-parent-span"
ROUND_KEY = "x-gfedntm-round"
SEND_TIME_KEY = "x-gfedntm-send-time"
NODE_KEY = "x-gfedntm-node"

#: Span names the trace plane is built on: ``round`` (the server's per-round
#: root, used to pick the merge reference node) and ``serve`` (the servicer-
#: side child every instrumented RPC dispatch logs, carrying the extracted
#: trace context + the paired send/recv clock stamps). graftlint's
#: telemetry-contract rule (GL001; scripts/lint_telemetry.py is a shim
#: over it) verifies every name still exists as a span() call site.
#: ``relay_fanout``/``relay_push`` time the relay tier's downstream
#: fan-out + pre-reduce and its aggregate re-broadcast; ``infer``,
#: ``serve_batch``, and ``serve_swap`` time the serving path (Infer RPC
#: dispatch, batcher micro-batch drain, hot-swap install) — without
#: them hierarchical and serving incidents merged into timelines with
#: no tier-local spans (README "Incident forensics").
TRACE_PLANE_SPANS: tuple[str, ...] = (
    "round", "serve", "relay_fanout", "relay_push", "infer",
    "serve_batch", "serve_swap",
)

#: Data-plane defense events (update admission gate, divergence guardian,
#: checkpoint integrity — README "Robust aggregation & divergence
#: recovery"). graftlint's telemetry-contract rule verifies each still
#: has an emission call site: the defense must never be silently
#: disconnected from telemetry.
DATA_PLANE_EVENTS: tuple[str, ...] = (
    "update_rejected",
    "update_clipped",
    "divergence_rollback",
    "client_quarantined",
    "checkpoint_invalid",
)

#: Model-quality plane events (topic coherence / drift telemetry — README
#: "Model-quality observability"). Same reverse-lint contract as the
#: data-plane events: graftlint's telemetry-contract rule verifies each
#: keeps an emission call site, so the quality monitor can never be silently disconnected
#: from the stream the `report` CLI reconstructs trajectories from.
MODEL_QUALITY_EVENTS: tuple[str, ...] = (
    "quality_computed",
    "topic_drift",
)

#: Wire-efficient scale-out events (bounded reference-cache evictions,
#: push-paced aggregations, the relay tier — README "Hierarchical
#: federation & wire efficiency"). Same reverse-lint contract: graftlint
#: verifies each keeps an emission call site, so the scale plane's
#: telemetry (which BENCH_SCALE reproducibility depends on) can never be
#: silently disconnected.
SCALEOUT_EVENTS: tuple[str, ...] = (
    "codec_ref_evicted",
    "push_aggregated",
    "relay_joined",
    "relay_preaggregated",
)

#: Serving-plane events (model load / hot-swap / quality-gated refusal /
#: request-path errors — README "Serving"). Same reverse-lint contract:
#: graftlint verifies each keeps an emission call site, so a refactor can
#: never silently disconnect the swap audit trail BENCH_SERVE
#: reproducibility (and the zero-dropped-requests claim) depends on.
SERVING_EVENTS: tuple[str, ...] = (
    "serve_model_loaded",
    "serve_model_swapped",
    "serve_swap_refused",
    "serve_error",
    "serve_load_window",
    "serve_shed",
)

#: Scenario-matrix events (cell lifecycle + per-cell degradation-
#: contract verdicts — README "Scenario matrix"). Same reverse-lint
#: contract: graftlint verifies each keeps an emission call site, so the
#: scenario engine can never silently stop recording the contract
#: verdicts BENCH_SCENARIO reproducibility depends on.
SCENARIO_EVENTS: tuple[str, ...] = (
    "scenario_cell_started",
    "scenario_contract",
    "scenario_cell_finished",
)

#: Fleet-telemetry / SLO plane events (alert state-machine transitions +
#: the FleetRegistry cardinality guard — README "Fleet telemetry & SLOs").
#: Same reverse-lint contract: graftlint verifies each keeps an emission
#: call site, so the alerting plane (which the `slo` CI gate and the
#: /alerts endpoint both key on) can never be silently disconnected.
FLEET_EVENTS: tuple[str, ...] = (
    "alert_pending",
    "alert_firing",
    "alert_resolved",
    "fleet_overflow",
)

#: Survivable-hierarchy events (relay crash autorecovery, cross-tier
#: member re-homing, journal-write degradation — README "Crash recovery
#: & sessions"). Same reverse-lint contract: graftlint verifies each
#: keeps an emission call site, so the hierarchy's crash-recovery audit
#: trail (which the chaos suite and the relay-crash scenario cells
#: assert against) can never be silently disconnected.
SURVIVAL_EVENTS: tuple[str, ...] = (
    "server_recovered",
    "relay_recovered",
    "member_rehomed",
    "journal_write_failed",
)

#: Privacy-plane events (DP mechanism applications + the accountant's
#: per-round (eps, delta) ledger — README "Differential privacy &
#: posterior sampling"). Same reverse-lint contract: graftlint verifies
#: each keeps an emission call site, so the privacy ledger (which the
#: `privacy` CI gate replays and the budget_monotone scenario contract
#: asserts against) can never be silently disconnected.
PRIVACY_EVENTS: tuple[str, ...] = (
    "dp_noise_applied",
    "privacy_budget",
    "privacy_budget_exceeded",
)

#: Incident-forensics events (flight-recorder bundles + server-
#: solicited remote capture — README "Incident forensics"). Same
#: reverse-lint contract: graftlint verifies each keeps an emission
#: call site, so the postmortem plane (which the `incident` CLI gate
#: replays bundles against) can never be silently disconnected.
INCIDENT_EVENTS: tuple[str, ...] = (
    "incident_captured",
    "flightrec_requested",
    "flightrec_received",
)


def new_trace_id() -> str:
    """A fresh 16-hex-char trace id (one federation training run)."""
    import uuid

    return uuid.uuid4().hex[:16]


def trace_pairs(trace_id: str | None = None, parent_span: int | None = None,
                round_idx: int | None = None) -> list[tuple[str, str]]:
    """Explicit outbound trace metadata — the server's poll/push workers
    use this (pool threads do not inherit the round span's contextvars)."""
    pairs: list[tuple[str, str]] = []
    if trace_id:
        pairs.append((TRACE_ID_KEY, str(trace_id)))
    if parent_span is not None:
        pairs.append((PARENT_SPAN_KEY, str(parent_span)))
    if round_idx is not None:
        pairs.append((ROUND_KEY, str(round_idx)))
    return pairs


def ambient_trace_pairs(logger: MetricsLogger | None) -> list[tuple[str, str]]:
    """Outbound trace metadata from the calling thread's ambient context:
    the innermost open span (id + inherited trace id), falling back to the
    logger's process-level ``trace_id``."""
    cur = _CURRENT_SPAN.get()
    trace_id = cur.fields.get("trace_id") if cur is not None else None
    if trace_id is None:
        trace_id = getattr(logger, "trace_id", None)
    return trace_pairs(
        trace_id, cur.span_id if cur is not None else None
    )


def extract_trace_context(invocation_metadata) -> dict[str, Any]:
    """Parse inbound gRPC metadata into span fields: ``trace_id``,
    ``remote_parent_id`` (the SENDER's span id — a different id space than
    local ``parent_id``), ``round``, ``rpc_send_time`` (sender wall clock),
    ``remote_node``. Missing or malformed entries are simply absent —
    un-instrumented peers must interoperate."""
    md: dict[str, str] = {}
    for k, v in (invocation_metadata or ()):
        md[str(k).lower()] = v
    out: dict[str, Any] = {}
    if md.get(TRACE_ID_KEY):
        out["trace_id"] = str(md[TRACE_ID_KEY])
    if md.get(NODE_KEY):
        out["remote_node"] = str(md[NODE_KEY])
    for key, field, conv in (
        (PARENT_SPAN_KEY, "remote_parent_id", int),
        (ROUND_KEY, "round", int),
        (SEND_TIME_KEY, "rpc_send_time", float),
    ):
        v = md.get(key)
        if v is not None:
            try:
                out[field] = conv(v)
            except (TypeError, ValueError):
                pass
    return out


# ---- jit wrappers -----------------------------------------------------------

def timed_jit(fn, logger: MetricsLogger | None, what: str):
    """Wrap a jitted callable for compile-time capture: the FIRST call
    (trace + compile dominated) is logged as a ``jit_compile`` event; later
    calls feed the ``jit_dispatch_s/<what>`` histogram. Note that jax's
    async dispatch means post-compile durations measure dispatch, not device
    execution, and a later re-specialization (new shapes) is not separated
    out. Passthrough when ``logger`` is None."""
    if logger is None:
        return fn
    hist = logger.registry.histogram(f"jit_dispatch_s/{what}")
    state = {"first": True}
    lock = threading.Lock()

    def wrapper(*args, **kwargs):
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        with lock:
            first, state["first"] = state["first"], False
        if first:
            logger.log("jit_compile", what=what, seconds=dt)
        else:
            hist.observe(dt)
        return out

    return wrapper


# ---- phase timing + profiler ------------------------------------------------

@contextlib.contextmanager
def phase_timer(
    logger: MetricsLogger | None, phase: str, **fields: Any
) -> Iterator[None]:
    """Time a named phase; logs ``{"event": "phase", "phase": ..., "seconds": ...}``."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        elapsed = time.perf_counter() - t0
        if logger is not None:
            logger.log("phase", phase=phase, seconds=elapsed, **fields)


@contextlib.contextmanager
def trace(log_dir: str | None) -> Iterator[None]:
    """``jax.profiler.trace`` context when ``log_dir`` is set; no-op
    otherwise (so call sites need no branching)."""
    if log_dir is None:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield


def parse_round_window(spec: str) -> tuple[int, int]:
    """Parse a ``--profile_rounds`` window: ``"start:stop"`` (half-open) or
    a single round ``"N"`` (= ``N:N+1``)."""
    try:
        if ":" in spec:
            lo_s, hi_s = spec.split(":", 1)
            lo, hi = int(lo_s), int(hi_s)
        else:
            lo = int(spec)
            hi = lo + 1
    except ValueError:
        raise ValueError(
            f"bad round window {spec!r}: expected 'start:stop' or 'round'"
        )
    if lo < 0 or hi <= lo:
        raise ValueError(
            f"bad round window {spec!r}: need 0 <= start < stop"
        )
    return lo, hi


class RoundProfiler:
    """``jax.profiler`` capture around a round window [start, stop).

    Driven by :meth:`observe` with the current round index — the server's
    round loop and the client servicer (which learns the round from each
    ``StepRequest``) both just report rounds as they see them; the profiler
    starts the trace on the first round inside the window and stops it on
    the first round at/after ``stop`` (or at :meth:`close`). A ``None``
    ``profile_dir`` makes every method a no-op; a profiler backend failure
    disables the instance loudly rather than killing the round loop.
    """

    def __init__(self, profile_dir: str | None, rounds: str = "1:2",
                 metrics: MetricsLogger | None = None):
        self.profile_dir = profile_dir
        self.metrics = metrics
        self.start_round, self.stop_round = parse_round_window(rounds)
        self._active = False
        self._disabled = profile_dir is None
        self._lock = threading.Lock()

    def observe(self, round_idx: int) -> None:
        if self._disabled:
            return
        with self._lock:
            if (not self._active and
                    self.start_round <= round_idx < self.stop_round):
                self._start(round_idx)
            elif self._active and round_idx >= self.stop_round:
                self._stop(round_idx)

    def close(self) -> None:
        if self._disabled:
            return
        with self._lock:
            if self._active:
                self._stop(self.stop_round)

    # callers hold self._lock
    def _start(self, round_idx: int) -> None:
        try:
            import jax

            jax.profiler.start_trace(self.profile_dir)
        except Exception as err:  # backend without profiler support
            self._disabled = True
            if self.metrics is not None:
                self.metrics.registry.counter("profiler_failures").inc()
            import logging

            logging.getLogger("RoundProfiler").warning(
                "jax.profiler unavailable (%s); device profiling disabled",
                err,
            )
            return
        self._active = True
        if self.metrics is not None:
            self.metrics.log(
                "profiler_started", dir=self.profile_dir, round=round_idx,
            )

    def _stop(self, round_idx: int) -> None:
        self._active = False
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as err:
            self._disabled = True
            import logging

            logging.getLogger("RoundProfiler").warning(
                "jax.profiler.stop_trace failed: %s", err,
            )
            return
        if self.metrics is not None:
            self.metrics.log("profiler_stopped", round=round_idx)


class DeviceMemoryMonitor:
    """Per-device memory gauges (``device_bytes_in_use/<dev>``,
    ``device_peak_bytes_in_use/<dev>``) from ``jax.local_devices()``'s
    ``memory_stats()``. Devices are probed once on the first :meth:`sample`;
    platforms without memory introspection (CPU) leave the device list
    empty and every later call returns immediately."""

    def __init__(self, registry: MetricRegistry):
        self.registry = registry
        self._devices: list[tuple[str, Any]] | None = None

    def _probe(self) -> list[tuple[str, Any]]:
        devices: list[tuple[str, Any]] = []
        try:
            import jax

            for d in jax.local_devices():
                try:
                    stats = d.memory_stats()
                # graftlint: disable=exception-hygiene -- feature probe:
                # a device without memory_stats() IS the no-op answer
                except Exception:
                    continue
                if isinstance(stats, dict) and stats:
                    devices.append((f"{d.platform}{d.id}", d))
        # graftlint: disable=exception-hygiene -- feature probe: no jax /
        # no backend means no gauges, by design
        except Exception:
            pass
        return devices

    def sample(self) -> None:
        if self._devices is None:
            self._devices = self._probe()
        for label, dev in self._devices:
            try:
                stats = dev.memory_stats() or {}
            # graftlint: disable=exception-hygiene -- sampling a probed
            # device that stopped answering: skip the gauge, keep sampling
            except Exception:
                continue
            in_use = stats.get("bytes_in_use")
            if in_use is not None:
                self.registry.gauge(f"device_bytes_in_use/{label}").set(
                    in_use
                )
            peak = stats.get("peak_bytes_in_use")
            if peak is not None:
                self.registry.gauge(
                    f"device_peak_bytes_in_use/{label}"
                ).set(peak)


# ---- fleet telemetry plane (README "Fleet telemetry & SLOs") ----------------

def merge_metric_snapshots(
    a: dict[str, Any], b: dict[str, Any]
) -> dict[str, Any]:
    """Merge two snapshot dicts of the SAME metric from different nodes.

    The merge is exact by construction: counters are monotone (values
    add), gauges are last-write-wins (``b`` wins when it carries a value),
    and histograms are fixed-bucket (identical edges ⇒ bucket-wise count
    addition loses nothing). This one primitive backs the relay tier's
    upstream pre-reduction, the server's :class:`FleetRegistry`, and the
    offline ``summarize`` cross-node merge, so live and post-hoc fleet
    views can never drift apart. Raises ``ValueError`` on a type or
    bucket-layout mismatch."""
    ta, tb = a.get("type"), b.get("type")
    if ta != tb:
        raise ValueError(f"cannot merge snapshot types {ta!r} and {tb!r}")
    if ta == "counter":
        return {"type": "counter",
                "value": float(a.get("value") or 0.0)
                + float(b.get("value") or 0.0)}
    if ta == "gauge":
        return {"type": "gauge",
                "value": b["value"] if b.get("value") is not None
                else a.get("value")}
    if ta == "histogram":
        if list(a["edges"]) != list(b["edges"]):
            raise ValueError(
                "cannot merge histograms with different bucket edges"
            )
        out: dict[str, Any] = {
            "type": "histogram",
            "count": a.get("count", 0) + b.get("count", 0),
            "sum": a.get("sum", 0.0) + b.get("sum", 0.0),
            "edges": list(a["edges"]),
            "counts": [x + y for x, y in zip(a["counts"], b["counts"])],
        }
        # Empty histograms omit min/max (Histogram.snapshot contract).
        mins = [s["min"] for s in (a, b) if "min" in s]
        maxs = [s["max"] for s in (a, b) if "max" in s]
        if mins:
            out["min"], out["max"] = min(mins), max(maxs)
        return out
    raise ValueError(f"cannot merge unknown snapshot type {ta!r}")


def merge_node_snapshots(
    nodes: "dict[str, dict[str, Any]]"
) -> dict[str, Any]:
    """Merge per-node registry snapshots (``{node: {metric: snapshot}}``)
    into one fleet-wide snapshot dict via :func:`merge_metric_snapshots`.
    A metric whose snapshots are unmergeable across nodes (type or bucket
    mismatch — a fleet running mixed code) is dropped from the merged view
    rather than poisoning the scrape; iteration order is node-sorted so
    gauge last-write-wins resolution is deterministic."""
    merged: dict[str, Any] = {}
    dropped: set[str] = set()
    for node in sorted(nodes):
        for name, snap in nodes[node].items():
            if name in dropped:
                continue
            cur = merged.get(name)
            if cur is None:
                merged[name] = dict(snap)
                continue
            try:
                merged[name] = merge_metric_snapshots(cur, snap)
            except (ValueError, KeyError, TypeError):
                del merged[name]
                dropped.add(name)
    return merged


def encode_telemetry_report(
    nodes: "dict[str, dict[str, Any]]", full: bool
) -> bytes:
    """Serialize one telemetry report (``{node: {metric: snapshot}}``) to
    the compact zlib+JSON wire form carried in the ``telemetry`` proto
    fields. ``full`` tells the receiver to REPLACE each included node's
    series (healing any deltas lost to partitions) instead of patching."""
    return zlib.compress(json.dumps(
        {"nodes": nodes, "full": bool(full)}, default=float,
    ).encode())


def decode_telemetry_report(data: bytes) -> dict[str, Any]:
    """Parse a wire telemetry report; raises ``ValueError`` on garbage
    (truncated zlib stream, non-JSON, wrong shape)."""
    try:
        report = json.loads(zlib.decompress(data).decode())
    except Exception as err:
        raise ValueError(f"bad telemetry report: {err}")
    if not isinstance(report, dict) or not isinstance(
        report.get("nodes"), dict
    ):
        raise ValueError("bad telemetry report: missing 'nodes' mapping")
    return report


class TelemetryShipper:
    """Builds the delta-encoded telemetry reports a node piggybacks on
    RPCs it already makes (StepReply / PushUpdate / rejoin — zero extra
    round-trips).

    Registry snapshots are cumulative, so each :meth:`build` ships only
    the metrics whose snapshot CHANGED since the last ship (usually a
    handful of counters/histograms per round); every ``full_every``-th
    ship is a full snapshot, which re-synchronizes a receiver that missed
    deltas to a partition or crash — shipping is best-effort by design
    and the periodic full report is the loss-healing mechanism. Returns
    ``b""`` when nothing changed (the proto field stays empty and costs
    nothing on the wire).

    ``nodes_fn`` generalizes the source to multi-node reports: a relay
    ships its own registry PLUS its shard's pre-reduced merge in one
    report (see :class:`FleetRegistry`). Not thread-safe — call from the
    single thread that builds the carrying RPC reply.
    """

    def __init__(self, registry: MetricRegistry | None = None,
                 node: str = "", nodes_fn=None, full_every: int = 10):
        if nodes_fn is None:
            if registry is None:
                raise ValueError("need a registry or a nodes_fn")
            reg, name = registry, node

            def nodes_fn():
                return {name: reg.snapshot()}

        self._nodes_fn = nodes_fn
        self.full_every = max(1, int(full_every))
        self._ships = 0
        self._last: dict[str, dict[str, Any]] = {}

    def build(self) -> bytes:
        """The next report's wire bytes (``b""`` = nothing changed)."""
        nodes = self._nodes_fn()
        full = self._ships % self.full_every == 0
        self._ships += 1
        if full:
            payload = nodes
        else:
            payload = {}
            for node, metrics in nodes.items():
                prev = self._last.get(node, {})
                changed = {
                    name: snap for name, snap in metrics.items()
                    if prev.get(name) != snap
                }
                if changed:
                    payload[node] = changed
        self._last = {n: dict(m) for n, m in nodes.items()}
        if not payload:
            return b""
        return encode_telemetry_report(payload, full)


class FleetRegistry:
    """Server-side store of per-node registry snapshots: the live,
    federation-wide metrics view.

    Reports arrive via :meth:`ingest_bytes` (the wire form), are patched
    per-node with replace-semantics (cumulative snapshots ⇒ ingesting the
    same report twice is a no-op, so RPC replays deduplicate naturally),
    and merge on demand into one fleet snapshot (:meth:`merged`) via the
    exact merge primitive. A cardinality guard bounds both the node count
    and the per-node series count — an adversarial or runaway client can
    at worst have its OWN report withheld (counted in the
    ``fleet_reports_dropped`` counter + one ``fleet_overflow`` event per
    offending node, never silently)."""

    def __init__(self, metrics: "MetricsLogger | None" = None,
                 max_nodes: int = 512, max_series_per_node: int = 512):
        self.metrics = metrics
        self.max_nodes = int(max_nodes)
        self.max_series_per_node = int(max_series_per_node)
        self._nodes: dict[str, dict[str, Any]] = {}
        self._last_report: dict[str, float] = {}
        self._overflow_seen: set[tuple[str, str]] = set()
        self._lock = threading.Lock()

    def _overflow(self, node: str, reason: str) -> None:
        if self.metrics is not None:
            self.metrics.registry.counter("fleet_reports_dropped").inc()
            key = (node, reason)
            if key not in self._overflow_seen:
                self._overflow_seen.add(key)
                self.metrics.log("fleet_overflow", node=node, reason=reason)

    def ingest_bytes(self, data: bytes) -> bool:
        """Ingest one wire report; corrupt bytes are counted
        (``fleet_reports_invalid``), never raised — a garbled telemetry
        payload must not perturb the round loop carrying it."""
        if not data:
            return False
        try:
            report = decode_telemetry_report(bytes(data))
        except ValueError:
            if self.metrics is not None:
                self.metrics.registry.counter("fleet_reports_invalid").inc()
            return False
        ok = False
        full = bool(report.get("full"))
        for node in sorted(report["nodes"]):
            metrics = report["nodes"][node]
            if isinstance(metrics, dict):
                ok = self.ingest(str(node), metrics, full=full) or ok
        return ok

    def ingest(self, node: str, metrics: dict[str, Any],
               full: bool = False) -> bool:
        """Patch (or, with ``full``, replace) one node's series."""
        overflow_reason = None
        with self._lock:
            cur = self._nodes.get(node)
            if cur is None:
                if len(self._nodes) >= self.max_nodes:
                    overflow_reason = "max_nodes"
                else:
                    cur = self._nodes[node] = {}
            if cur is not None:
                if full:
                    cur.clear()
                for name in sorted(metrics):
                    if (name not in cur
                            and len(cur) >= self.max_series_per_node):
                        overflow_reason = "max_series_per_node"
                        break
                    cur[name] = metrics[name]
                self._last_report[node] = time.time()
        if overflow_reason is not None:
            self._overflow(node, overflow_reason)
        return overflow_reason is None

    def node_snapshots(self) -> dict[str, dict[str, Any]]:
        with self._lock:
            return {node: dict(m) for node, m in self._nodes.items()}

    def merged(self) -> dict[str, Any]:
        """The fleet-wide merged snapshot (one dict, same shape as a
        :meth:`MetricRegistry.snapshot` — every downstream consumer of
        single-registry snapshots works on it unchanged)."""
        return merge_node_snapshots(self.node_snapshots())

    def summary(self, top_k: int = 8) -> dict[str, Any]:
        """Bounded fleet summary for ``/status.fleet``: totals plus the
        top-k nodes by series count and the top-k busiest merged
        histograms — the response size is O(top_k) regardless of fleet
        size (the StragglerDetector top-k pattern)."""
        now = time.time()
        with self._lock:
            sizes = {node: len(m) for node, m in self._nodes.items()}
            ages = {node: now - t for node, t in self._last_report.items()}
        top_nodes = heapq.nlargest(
            top_k, sizes.items(), key=lambda kv: (kv[1], str(kv[0]))
        )
        merged = self.merged()
        hists = [
            (name, snap) for name, snap in merged.items()
            if snap.get("type") == "histogram" and snap.get("count")
        ]
        top_hists = heapq.nlargest(
            top_k, hists, key=lambda kv: (kv[1]["count"], kv[0])
        )
        return {
            "nodes": len(sizes),
            "series": sum(sizes.values()),
            "merged_series": len(merged),
            "top_nodes": [
                {"node": node, "series": n,
                 "report_age_s": round(ages.get(node, 0.0), 3)}
                for node, n in top_nodes
            ],
            "histograms": {
                name: _hist_stats(snap) for name, snap in top_hists
            },
        }


def render_fleet_prometheus(
    nodes: "dict[str, dict[str, Any]]", prefix: str = "gfedntm",
    max_series: int = 256,
) -> str:
    """Prometheus exposition of a fleet view: ``<prefix>_fleet_*``
    families carry the exact cross-node merge, ``<prefix>_node_*``
    families carry the per-node series with a ``node`` label (plus the
    usual ``key`` label). Distinct family prefixes keep both valid in one
    scrape alongside the process's own ``<prefix>_*`` registry. The
    per-node section shares the cardinality-cap discipline of
    :func:`render_prometheus`: each family exports its first
    ``max_series`` (node, key) pairs sorted (stable across scrapes) plus
    an overflow counter for the withheld remainder."""
    out = [render_prometheus(
        merge_node_snapshots(nodes), prefix=f"{prefix}_fleet",
        max_series=max_series,
    )]

    families: dict[str, list[tuple[str, str, dict[str, Any]]]] = {}
    for node, metrics in nodes.items():
        for name, snap in metrics.items():
            base, _, key = name.partition("/")
            families.setdefault(_prom_name(base), []).append(
                (node, key, snap)
            )
    overflow: dict[str, int] = {}
    lines: list[str] = []
    for base in sorted(families):
        series = sorted(families[base], key=lambda t: (t[0], t[1]))
        if max_series and len(series) > max_series:
            overflow[base] = len(series) - max_series
            series = series[:max_series]
        kind = series[0][2].get("type")
        full = f"{prefix}_node_{base}"
        if kind == "counter":
            full += "_total"
        if kind not in ("counter", "gauge", "histogram"):
            continue
        lines.append(f"# TYPE {full} {kind}")
        for node, key, snap in series:
            if snap.get("type") != kind:
                continue  # cross-node type mismatch: skip, never 500
            label_parts = [f'node="{_prom_label(node)}"']
            if key:
                label_parts.append(f'key="{_prom_label(key)}"')
            label = "{" + ",".join(label_parts) + "}"
            if kind == "counter":
                lines.append(f"{full}{label} {snap['value']}")
            elif kind == "gauge":
                if snap["value"] is not None:
                    lines.append(f"{full}{label} {snap['value']}")
            else:
                base_label = ",".join(label_parts)
                cum = 0
                for edge, count in zip(snap["edges"], snap["counts"]):
                    cum += count
                    lines.append(
                        f'{full}_bucket{{{base_label},le="{edge}"}} {cum}'
                    )
                cum += snap["counts"][-1]
                lines.append(
                    f'{full}_bucket{{{base_label},le="+Inf"}} {cum}'
                )
                lines.append(f"{full}_sum{label} {snap['sum']}")
                lines.append(f"{full}_count{label} {snap['count']}")
    if overflow:
        full = f"{prefix}_node_series_overflow_total"
        lines.append(f"# TYPE {full} counter")
        for base in sorted(overflow):
            lines.append(
                f'{full}{{family="{_prom_label(base)}"}} {overflow[base]}'
            )
    if lines:
        out.append("\n".join(lines) + "\n")
    return "".join(out)


#: Process start reference for the ``process_uptime_s`` gauge.
_PROCESS_START_TIME = time.time()


def sample_process_metrics(registry: MetricRegistry) -> None:
    """Refresh the process self-gauges (``process_rss_bytes``,
    ``process_uptime_s``, ``process_threads``) — stdlib only, sampled per
    ops scrape so every plane that serves ``/metrics`` exposes them
    without per-plane wiring. Makes the BENCH_SCALE flat-RSS claim
    scrapeable live instead of only measurable via subprocess
    ``ru_maxrss``."""
    rss = None
    try:
        # Current RSS (not the rusage high-water mark) when /proc exists.
        with open("/proc/self/statm") as fh:
            rss = int(fh.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    # graftlint: disable=exception-hygiene -- platform probe: no /proc
    # (macOS) falls back to the rusage peak below
    except Exception:
        try:
            import resource
            import sys

            ru = resource.getrusage(resource.RUSAGE_SELF)
            # ru_maxrss (the peak, the best available without /proc) is
            # bytes on macOS, KiB elsewhere.
            scale = 1 if sys.platform == "darwin" else 1024
            rss = int(ru.ru_maxrss) * scale
        # graftlint: disable=exception-hygiene -- no resource module
        # (non-POSIX): the gauge is simply absent
        except Exception:
            rss = None
    if rss is not None:
        registry.gauge("process_rss_bytes").set(rss)
    registry.gauge("process_uptime_s").set(
        time.time() - _PROCESS_START_TIME
    )
    registry.gauge("process_threads").set(threading.active_count())


# ---- run summaries (the `summarize` CLI subcommand's engine) ----------------

def read_metrics(path: str) -> list[dict[str, Any]]:
    """Parse a ``metrics.jsonl`` file; blank lines are skipped, malformed
    lines raise (a corrupt stream should be loud, not silently partial)."""
    records = []
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError as err:
                raise ValueError(f"{path}:{lineno}: bad JSONL line: {err}")
    return records


def _agg(groups: dict, key: str, seconds: float) -> None:
    g = groups.setdefault(key, {"count": 0, "total_s": 0.0, "max_s": 0.0})
    g["count"] += 1
    g["total_s"] += seconds
    g["max_s"] = max(g["max_s"], seconds)


def _hist_stats(snap: dict[str, Any]) -> dict[str, Any]:
    count = snap.get("count", 0)
    out: dict[str, Any] = {"count": count}
    if count:
        out["mean_s"] = snap["sum"] / count
        for q, label in ((0.5, "p50_s"), (0.95, "p95_s"), (0.99, "p99_s")):
            out[label] = quantile_from_snapshot(snap, q)
        out["min_s"], out["max_s"] = snap["min"], snap["max"]
    return out


def collect_data_plane(records: list[dict[str, Any]]) -> dict[str, Any]:
    """Aggregate the data-plane defense events of a stream (admission-gate
    rejections per client by reason, norm clips, divergence rollbacks,
    quarantines — README "Robust aggregation & divergence recovery") into
    one dict. Shared by the ``summarize`` and ``report`` engines so both
    CLIs show identical accounting."""
    rejections: dict[str, dict[str, int]] = {}
    clips: dict[str, int] = {}
    rollbacks: list[dict[str, Any]] = []
    quarantines: dict[str, int] = {}
    for r in records:
        event = r.get("event")
        if event == "update_rejected":
            by = rejections.setdefault(str(r.get("client")), {})
            reason = str(r.get("reason", "?"))
            by[reason] = by.get(reason, 0) + 1
        elif event == "update_clipped":
            cid = str(r.get("client"))
            clips[cid] = clips.get(cid, 0) + 1
        elif event == "divergence_rollback":
            rollbacks.append({
                "round": r.get("round"), "reason": r.get("reason"),
                "restored_round": r.get("restored_round"),
            })
        elif event == "client_quarantined":
            cid = str(r.get("client"))
            quarantines[cid] = quarantines.get(cid, 0) + 1
    return {
        "rejections": rejections,
        "clips": clips,
        "rollbacks": rollbacks,
        "quarantines": quarantines,
    }


def collect_wire_tiers(
    node_records: "dict[str, list[dict[str, Any]]]"
) -> dict[str, dict[str, Any]]:
    """Per-node (per-tier) wire accounting from each stream's LAST
    ``metrics_snapshot`` (registries are cumulative): bytes moved raw vs
    compressed per direction, the resulting compression ratios, and the
    per-recipient-encoding counters (catch-up / self-contained pushes,
    reference evictions). In a hierarchical topology each relay and the
    root write their own ``metrics.jsonl``, so feeding them all to
    ``summarize``/``report`` reproduces the BENCH_SCALE per-tier numbers
    from JSONL alone (README "Hierarchical federation & wire
    efficiency")."""
    out: dict[str, dict[str, Any]] = {}
    for node, records in sorted(node_records.items()):
        last: dict[str, dict] = {}
        for r in records:
            if r.get("event") == "metrics_snapshot":
                for name, snap in (r.get("metrics") or {}).items():
                    last[name] = snap

        def cval(name: str) -> float:
            snap = last.get(name)
            if snap is None or snap.get("type") != "counter":
                return 0.0
            return float(snap.get("value") or 0.0)

        sent_raw, sent = (
            cval("uncompressed_bytes_sent"), cval("compressed_bytes_sent")
        )
        recv_raw, recv = (
            cval("uncompressed_bytes_recv"), cval("compressed_bytes_recv")
        )
        out[node] = {
            "sent_bytes": sent,
            "sent_raw_bytes": sent_raw,
            "ratio_sent": (sent_raw / sent) if sent else None,
            "recv_bytes": recv,
            "recv_raw_bytes": recv_raw,
            "ratio_recv": (recv_raw / recv) if recv else None,
            "rpc_bytes_sent": cval("rpc_bytes_sent"),
            "rpc_bytes_recv": cval("rpc_bytes_recv"),
            "catchup_pushes": cval("codec_catchup_pushes"),
            "selfcontained_pushes": cval("codec_selfcontained_pushes"),
            "refs_evicted": cval("codec_refs_evicted"),
        }
    return out


def format_wire_tiers(tiers: dict[str, dict[str, Any]]) -> str:
    """Render :func:`collect_wire_tiers` as the per-tier table the
    ``summarize``/``report`` CLIs append when fed multiple streams."""
    lines = ["wire accounting per tier:"]
    lines.append(
        f"  {'node':<16}{'sent':>12}{'ratio':>8}{'recv':>12}{'ratio':>8}"
        f"{'catchup':>9}{'selfcont':>10}{'evicted':>9}"
    )
    for node, t in tiers.items():
        sent = t["sent_bytes"] or t["rpc_bytes_sent"]
        recv = t["recv_bytes"] or t["rpc_bytes_recv"]
        rs = f"{t['ratio_sent']:.2f}x" if t["ratio_sent"] else "-"
        rr = f"{t['ratio_recv']:.2f}x" if t["ratio_recv"] else "-"
        lines.append(
            f"  {node:<16}{_fmt_bytes(sent):>12}{rs:>8}"
            f"{_fmt_bytes(recv):>12}{rr:>8}"
            f"{t['catchup_pushes']:>9.0f}{t['selfcontained_pushes']:>10.0f}"
            f"{t['refs_evicted']:>9.0f}"
        )
    return "\n".join(lines)


def summarize_metrics(records: list[dict[str, Any]]) -> dict[str, Any]:
    """Aggregate a run's event stream into a report dict (see
    :func:`format_report` for the rendered form)."""
    times = [r["time"] for r in records
             if isinstance(r.get("time"), (int, float))]
    event_counts: dict[str, int] = {}
    phases: dict[str, dict] = {}
    spans: dict[str, dict] = {}
    rounds = {"count": 0, "total_s": 0.0, "bytes_pulled": 0.0,
              "bytes_pushed": 0.0}
    slowest: dict[Any, dict] = {}
    stragglers: dict[Any, dict] = {}
    compile_events: list[dict[str, Any]] = []
    rpc_errors: list[dict[str, Any]] = []
    per_node_snapshots: dict[str, dict[str, dict]] = {}
    alerts: dict[str, dict[str, Any]] = {}
    summary_event: dict[str, Any] | None = None

    for r in records:
        event = r.get("event", "?")
        event_counts[event] = event_counts.get(event, 0) + 1
        if event == "phase":
            _agg(phases, str(r.get("phase", "?")), float(r.get("seconds", 0)))
        elif event == "span":
            name = str(r.get("name", "?"))
            secs = float(r.get("seconds", 0))
            _agg(spans, name, secs)
            if name == "round":
                rounds["count"] += 1
                rounds["total_s"] += secs
                rounds["bytes_pulled"] += float(r.get("bytes_pulled", 0))
                rounds["bytes_pushed"] += float(r.get("bytes_pushed", 0))
                cid = r.get("slowest_client")
                if cid is not None:
                    s = slowest.setdefault(
                        cid, {"rounds_slowest": 0, "max_poll_s": 0.0}
                    )
                    s["rounds_slowest"] += 1
                    s["max_poll_s"] = max(
                        s["max_poll_s"], float(r.get("slowest_s", 0))
                    )
        elif event == "straggler_detected":
            st = stragglers.setdefault(
                r.get("client"), {"count": 0, "max_z": 0.0}
            )
            st["count"] += 1
            st["max_z"] = max(st["max_z"], float(r.get("z", 0.0)))
        elif event == "jit_compile":
            compile_events.append(
                {"what": r.get("what"), "seconds": r.get("seconds")}
            )
        elif event == "rpc" and not r.get("ok", True):
            rpc_errors.append(r)
        elif event == "metrics_snapshot":
            # Registries are cumulative, so — PER NODE — the last snapshot
            # mentioning a metric carries its totals. Keying by name alone
            # would let a multi-node stream's nodes clobber each other
            # (client7's local_step_s overwriting client3's); nodes merge
            # exactly below instead.
            node_snaps = per_node_snapshots.setdefault(
                str(r.get("node") or ""), {}
            )
            for name, snap in (r.get("metrics") or {}).items():
                node_snaps[name] = snap
        elif event in ("alert_pending", "alert_firing", "alert_resolved"):
            state = event[len("alert_"):]
            a = alerts.setdefault(
                str(r.get("alert")),
                {"pending": 0, "firing": 0, "resolved": 0,
                 "last_state": "ok", "metric": r.get("metric")},
            )
            a[state] += 1
            a["last_state"] = state
            if r.get("metric") is not None:
                a["metric"] = r.get("metric")
        elif event == "summary":
            summary_event = {
                k: v for k, v in r.items() if k not in ("event", "time")
            }

    # Fleet totals: counters sum, gauges last-wins, histograms add
    # bucket-wise — the same primitive the live FleetRegistry merge uses,
    # so offline summaries and /metrics can never disagree.
    last_snapshots = merge_node_snapshots(per_node_snapshots)

    step_time = {
        name: _hist_stats(snap)
        for name, snap in last_snapshots.items()
        if snap.get("type") == "histogram" and name.endswith("step_s")
        and snap.get("count")
    }
    rpc = {
        name.split("/", 1)[1]: _hist_stats(snap)
        for name, snap in last_snapshots.items()
        if name.startswith("rpc_s/") and snap.get("count")
    }
    # Every other populated histogram (codec encode/decode seconds, bundle
    # bytes, client poll latency, jit dispatch, ...): no histogram this
    # stream records may be write-only in the summary.
    other_hists = {
        name: _hist_stats(snap)
        for name, snap in last_snapshots.items()
        if snap.get("type") == "histogram" and snap.get("count")
        and not (name.endswith("step_s") or name.startswith("rpc_s/"))
    }
    counters = {
        name: snap["value"] for name, snap in last_snapshots.items()
        if snap.get("type") == "counter"
    }
    gauges = {
        name: snap["value"] for name, snap in last_snapshots.items()
        if snap.get("type") == "gauge"
    }

    return {
        "events_total": len(records),
        "wall_seconds": (max(times) - min(times)) if times else 0.0,
        "event_counts": dict(sorted(event_counts.items())),
        "phases": phases,
        "spans": spans,
        "rounds": rounds,
        "slowest_clients": slowest,
        "stragglers": stragglers,
        "step_time": step_time,
        "rpc": rpc,
        "histograms": other_hists,
        "rpc_errors": len(rpc_errors),
        "counters": counters,
        "gauges": gauges,
        "alerts": alerts,
        "compile": compile_events,
        "summary": summary_event,
        "data_plane": collect_data_plane(records),
    }


def _fmt_s(seconds: float | None) -> str:
    if seconds is None:
        return "-"
    if seconds < 1e-3:
        return f"{seconds * 1e6:.0f} µs"
    if seconds < 1.0:
        return f"{seconds * 1e3:.2f} ms"
    return f"{seconds:.2f} s"


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{n:.0f} B"
        n /= 1024
    return f"{n:.1f} GiB"


def format_report(s: dict[str, Any]) -> str:
    """Render a :func:`summarize_metrics` dict as a human-readable report."""
    lines = [
        f"run summary: {s['events_total']} events over "
        f"{s['wall_seconds']:.2f} s wall clock",
    ]

    wall = s["wall_seconds"] or float("inf")
    breakdown = dict(s["phases"])
    for name, g in s["spans"].items():
        breakdown.setdefault(f"span:{name}", g)
    if breakdown:
        lines.append("")
        lines.append("phase breakdown:")
        lines.append(f"  {'phase':<24}{'total':>12}{'count':>8}{'%wall':>8}")
        for name, g in sorted(
            breakdown.items(), key=lambda kv: -kv[1]["total_s"]
        ):
            pct = 100.0 * g["total_s"] / wall if wall else 0.0
            lines.append(
                f"  {name:<24}{_fmt_s(g['total_s']):>12}{g['count']:>8}"
                f"{pct:>7.1f}%"
            )

    if s["step_time"]:
        lines.append("")
        lines.append("step time:")
        lines.append(
            f"  {'source':<24}{'count':>8}{'mean':>12}{'p50':>12}"
            f"{'p95':>12}{'p99':>12}"
        )
        for name, st in sorted(s["step_time"].items()):
            lines.append(
                f"  {name:<24}{st['count']:>8}{_fmt_s(st['mean_s']):>12}"
                f"{_fmt_s(st['p50_s']):>12}{_fmt_s(st['p95_s']):>12}"
                f"{_fmt_s(st['p99_s']):>12}"
            )

    if s["rpc"]:
        lines.append("")
        lines.append("rpc latency:")
        lines.append(
            f"  {'method':<32}{'count':>8}{'mean':>12}{'p50':>12}{'p95':>12}"
        )
        for name, st in sorted(s["rpc"].items()):
            lines.append(
                f"  {name:<32}{st['count']:>8}{_fmt_s(st['mean_s']):>12}"
                f"{_fmt_s(st['p50_s']):>12}{_fmt_s(st['p95_s']):>12}"
            )
        deadline = s["counters"].get("rpc_deadline_expired", 0)
        errors = s["counters"].get("rpc_errors", 0)
        lines.append(
            f"  errors: {errors:.0f} ({deadline:.0f} deadline expiries), "
            f"rpc error events: {s['rpc_errors']}"
        )

    if s.get("histograms"):
        lines.append("")
        lines.append("other distributions (codec, poll, dispatch, ...):")
        lines.append(
            f"  {'name':<32}{'count':>8}{'mean':>12}{'p50':>12}{'p95':>12}"
        )
        for name, st in sorted(s["histograms"].items()):
            fmt = _fmt_bytes if "bytes" in name else _fmt_s
            lines.append(
                f"  {name:<32}{st['count']:>8}{fmt(st['mean_s']):>12}"
                f"{fmt(st['p50_s']):>12}{fmt(st['p95_s']):>12}"
            )

    rounds = s["rounds"]
    if rounds["count"]:
        per = rounds["count"]
        lines.append("")
        lines.append(
            f"federation rounds: {per} "
            f"(mean {_fmt_s(rounds['total_s'] / per)}/round)"
        )
        lines.append(
            f"  bytes moved: {_fmt_bytes(rounds['bytes_pulled'])} pulled, "
            f"{_fmt_bytes(rounds['bytes_pushed'])} pushed "
            f"({_fmt_bytes((rounds['bytes_pulled'] + rounds['bytes_pushed']) / per)}"
            "/round)"
        )
        if s["slowest_clients"]:
            worst = max(
                s["slowest_clients"].items(),
                key=lambda kv: kv[1]["rounds_slowest"],
            )
            lines.append(
                f"  slowest client: {worst[0]} (straggler in "
                f"{worst[1]['rounds_slowest']}/{per} rounds, max poll "
                f"{_fmt_s(worst[1]['max_poll_s'])})"
            )
        for cid, st in sorted(s.get("stragglers", {}).items(),
                              key=lambda kv: -kv[1]["count"]):
            lines.append(
                f"  straggler detected: client {cid} x{st['count']} "
                f"(max z {st['max_z']:.1f})"
            )

    dp = s.get("data_plane") or {}
    if any(dp.get(k) for k in
           ("rejections", "clips", "rollbacks", "quarantines")):
        lines.append("")
        lines.append("data plane (admission gate / guardian):")
        for cid in sorted(
            set(dp.get("rejections", {})) | set(dp.get("clips", {}))
        ):
            by = dp.get("rejections", {}).get(cid, {})
            reasons = ", ".join(
                f"{r}:{n}" for r, n in sorted(by.items())
            ) or "-"
            lines.append(
                f"  client {cid}: {sum(by.values())} rejected ({reasons})"
                f", {dp.get('clips', {}).get(cid, 0)} clipped"
            )
        for rb in dp.get("rollbacks", ()):
            restored = rb.get("restored_round")
            lines.append(
                f"  rollback at round {rb.get('round')} "
                f"({rb.get('reason')}"
                + (f" -> restored round {restored}"
                   if restored is not None else "")
                + ")"
            )
        for cid, n in sorted(dp.get("quarantines", {}).items()):
            lines.append(f"  quarantined: client {cid} x{n}")

    if s.get("alerts"):
        lines.append("")
        lines.append("SLO alerts:")
        for name, a in sorted(s["alerts"].items()):
            metric = f" on {a['metric']}" if a.get("metric") else ""
            lines.append(
                f"  {name}{metric}: fired x{a['firing']} "
                f"(pending x{a['pending']}, resolved x{a['resolved']}), "
                f"last state {a['last_state']}"
            )

    enc = s["counters"].get("codec_encoded_bytes")
    dec = s["counters"].get("codec_decoded_bytes")
    if enc is not None or dec is not None:
        lines.append("")
        lines.append(
            f"codec: {_fmt_bytes(enc or 0)} encoded "
            f"({s['counters'].get('codec_encode_calls', 0):.0f} bundles), "
            f"{_fmt_bytes(dec or 0)} decoded "
            f"({s['counters'].get('codec_decode_calls', 0):.0f} bundles)"
        )

    if s["compile"]:
        lines.append("")
        lines.append("compile capture (first-call trace+compile+run):")
        for c in s["compile"]:
            lines.append(f"  {c['what']}: {_fmt_s(c['seconds'])}")

    if s["summary"]:
        lines.append("")
        lines.append(f"run result: {json.dumps(s['summary'], default=str)}")

    return "\n".join(lines)


# ---- model-quality report (the `report` CLI subcommand's engine) ------------

def summarize_model_quality(
    records: list[dict[str, Any]]
) -> dict[str, Any]:
    """Aggregate a run's model-quality telemetry into a report dict: the
    per-round coherence/diversity/drift trajectory (``quality_computed``
    + ``topic_drift`` events keyed by round), the per-client contribution
    EWMAs (read from the LAST ``metrics_snapshot`` carrying the
    contribution gauges), and the data-plane accounting
    (:func:`collect_data_plane`). Everything comes from the JSONL stream
    alone — the report needs no live server."""
    quality: dict[int, dict[str, Any]] = {}
    last_gauges: dict[str, float] = {}
    topics_last: list[list[str]] | None = None
    alerts: dict[str, dict[str, Any]] = {}
    for r in records:
        event = r.get("event")
        if event in ("alert_pending", "alert_firing", "alert_resolved"):
            state = event[len("alert_"):]
            a = alerts.setdefault(
                str(r.get("alert")),
                {"pending": 0, "firing": 0, "resolved": 0,
                 "last_state": "ok", "metric": r.get("metric")},
            )
            a[state] += 1
            a["last_state"] = state
        elif event == "quality_computed":
            row = quality.setdefault(int(r.get("round", -1)), {})
            row.update(
                npmi=r.get("npmi"), diversity=r.get("diversity"),
                irbo=r.get("irbo"), n_topics=r.get("n_topics"),
            )
            if r.get("topics"):
                topics_last = r["topics"]
        elif event == "topic_drift":
            row = quality.setdefault(int(r.get("round", -1)), {})
            row.update(
                mean_drift=r.get("mean_drift"),
                max_drift=r.get("max_drift"),
                mean_js=r.get("mean_js"), churn=r.get("churn"),
            )
        elif event == "metrics_snapshot":
            for name, snap in (r.get("metrics") or {}).items():
                if snap.get("type") == "gauge" and snap["value"] is not None:
                    last_gauges[name] = snap["value"]

    contributions: dict[str, dict[str, Any]] = {}
    for name, value in last_gauges.items():
        base, _, key = name.partition("/")
        if base in ("client_contribution_cos", "client_contribution_share"):
            cid = key.removeprefix("client")
            field = (
                "cos_ewma" if base == "client_contribution_cos"
                else "share_ewma"
            )
            contributions.setdefault(cid, {})[field] = value

    return {
        "quality": [
            {"round": rnd, **row} for rnd, row in sorted(quality.items())
        ],
        "contributions": contributions,
        "pairwise": {
            "cos_mean": last_gauges.get("contribution_pairwise_cos_mean"),
            "cos_min": last_gauges.get("contribution_pairwise_cos_min"),
        },
        "topics": topics_last,
        "alerts": alerts,
        "data_plane": collect_data_plane(records),
    }


def check_monotone_coherence(
    summary: dict[str, Any], tolerance: float
) -> list[str]:
    """CI gate: verify NPMI coherence never drops more than ``tolerance``
    below its running maximum over the quality trajectory. Returns the
    violations (empty = pass) — the ``report`` CLI exits non-zero on any,
    so the scenario harness can gate on model quality, not just on step
    time."""
    violations: list[str] = []
    best: float | None = None
    best_round: int | None = None
    for row in summary.get("quality", ()):
        npmi = row.get("npmi")
        if npmi is None:
            continue
        if best is not None and npmi < best - tolerance:
            violations.append(
                f"round {row['round']}: npmi {npmi:.4f} fell "
                f"{best - npmi:.4f} below the round-{best_round} peak "
                f"{best:.4f} (tolerance {tolerance:g})"
            )
        if best is None or npmi > best:
            best, best_round = npmi, row["round"]
    if not summary.get("quality"):
        violations.append(
            "no quality_computed events in the stream (was the run "
            "launched with --quality_every > 0 and --quality_ref?)"
        )
    elif best is None:
        # Quality rounds exist but NPMI was never computed (no reference
        # corpus): a gate that checked nothing must not report green.
        violations.append(
            "quality rounds carry no NPMI values — coherence was never "
            "measured (was the run launched with --quality_ref?)"
        )
    return violations


def _fmt_opt(value: Any, spec: str = "{:.3f}") -> str:
    return "-" if value is None else spec.format(value)


def format_quality_report(s: dict[str, Any]) -> str:
    """Render a :func:`summarize_model_quality` dict as a human-readable
    round-by-round model-health report."""
    lines: list[str] = []
    quality = s.get("quality") or []
    lines.append(
        f"model-quality report: {len(quality)} quality rounds"
    )

    if quality:
        lines.append("")
        lines.append(
            f"  {'round':>6}{'npmi':>9}{'diversity':>11}{'irbo':>8}"
            f"{'drift':>8}{'max':>8}{'churn':>7}"
        )
        for row in quality:
            lines.append(
                f"  {row['round']:>6}"
                f"{_fmt_opt(row.get('npmi')):>9}"
                f"{_fmt_opt(row.get('diversity')):>11}"
                f"{_fmt_opt(row.get('irbo')):>8}"
                f"{_fmt_opt(row.get('mean_drift')):>8}"
                f"{_fmt_opt(row.get('max_drift')):>8}"
                f"{_fmt_opt(row.get('churn'), '{:d}'):>7}"
            )

    contributions = s.get("contributions") or {}
    dp = s.get("data_plane") or {}
    if contributions or dp.get("rejections"):
        lines.append("")
        lines.append("per-client contributions (EWMA):")
        lines.append(
            f"  {'client':<8}{'cos->agg':>10}{'share':>8}{'rejected':>10}"
            f"{'clipped':>9}{'quarantined':>13}"
        )
        clients = sorted(
            set(contributions) | set(dp.get("rejections", {}))
            | set(dp.get("clips", {})) | set(dp.get("quarantines", {})),
            key=str,
        )
        for cid in clients:
            c = contributions.get(cid, {})
            rejected = sum(dp.get("rejections", {}).get(cid, {}).values())
            lines.append(
                f"  {cid:<8}{_fmt_opt(c.get('cos_ewma')):>10}"
                f"{_fmt_opt(c.get('share_ewma')):>8}"
                f"{rejected:>10}{dp.get('clips', {}).get(cid, 0):>9}"
                f"{dp.get('quarantines', {}).get(cid, 0):>13}"
            )

    pairwise = s.get("pairwise") or {}
    if pairwise.get("cos_mean") is not None:
        lines.append("")
        lines.append(
            f"cohort dispersion: pairwise cosine mean "
            f"{pairwise['cos_mean']:.3f}, min "
            f"{_fmt_opt(pairwise.get('cos_min'))} "
            "(low mean = heterogeneous / non-IID update directions)"
        )

    for rb in dp.get("rollbacks", ()):
        restored = rb.get("restored_round")
        lines.append(
            f"rollback at round {rb.get('round')} ({rb.get('reason')}"
            + (f" -> restored round {restored}"
               if restored is not None else "")
            + ")"
        )

    if s.get("alerts"):
        lines.append("")
        lines.append("SLO alerts:")
        for name, a in sorted(s["alerts"].items()):
            metric = f" on {a['metric']}" if a.get("metric") else ""
            lines.append(
                f"  {name}{metric}: fired x{a['firing']} "
                f"(pending x{a['pending']}, resolved x{a['resolved']}), "
                f"last state {a['last_state']}"
            )

    if s.get("topics"):
        lines.append("")
        lines.append("final topics (top words):")
        for i, words in enumerate(s["topics"]):
            lines.append(f"  topic {i}: {' '.join(words[:10])}")

    privacy = s.get("privacy")
    if privacy:
        lines.append("")
        lines.append(format_privacy_line(privacy))

    return "\n".join(lines)


def summarize_privacy(
    records: "list[dict[str, Any]]",
) -> "dict[str, Any] | None":
    """Fold a stream's ``privacy_budget`` ledger into its final state
    (the accountant's running (eps, delta) — README "Differential
    privacy & posterior sampling"); ``None`` when the run carried no
    ledger (``--dp off``)."""
    last: dict[str, Any] | None = None
    rounds = 0
    exceeded = 0
    for r in records:
        event = r.get("event")
        if event == "privacy_budget":
            rounds += 1
            last = r
        elif event == "privacy_budget_exceeded":
            exceeded += 1
    if last is None:
        return None
    return {
        "mode": last.get("mode"),
        "eps": float(last.get("eps", 0.0)),
        "delta": float(last.get("delta", 0.0)),
        "sigma": float(last.get("sigma", 0.0)),
        "steps": int(last.get("steps", rounds)),
        "budget": float(last.get("budget", 0.0)),
        "rounds": rounds,
        "exceeded_events": exceeded,
    }


def format_privacy_line(p: "dict[str, Any]") -> str:
    """One-line rendering of a :func:`summarize_privacy` dict."""
    budget = (
        f"budget {p['budget']:g}"
        + (f", EXCEEDED x{p['exceeded_events']}"
           if p.get("exceeded_events") else "")
        if p.get("budget") else "budget untracked"
    )
    return (
        f"privacy: dp={p['mode']} eps {p['eps']:.4g} at delta "
        f"{p['delta']:g} after {p['steps']} noised round(s) "
        f"(sigma {p['sigma']:g}, {budget})"
    )


# ---- Prometheus exposition + live ops endpoint ------------------------------

_PROM_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    name = _PROM_NAME_OK.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _prom_label(value: str) -> str:
    return (
        value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def render_prometheus(snapshot: dict[str, Any],
                      prefix: str = "gfedntm",
                      max_series: int = 256) -> str:
    """Render a :meth:`MetricRegistry.snapshot` dict as Prometheus text
    exposition (version 0.0.4). Registry names like
    ``rpc_s/FederationClient.TrainStep`` split at the first ``/`` into the
    metric family (sanitized) plus a ``key`` label, so per-client and
    per-method series stay one scrapeable family.

    ``max_series`` caps the label cardinality per family: per-client
    series (poll latency, contribution EWMAs) grow with client churn, and
    an unbounded exposition would eventually dominate every scrape. A
    family over the cap exports its first ``max_series`` keys (sorted —
    stable across scrapes) plus one ``<prefix>_series_overflow_total``
    counter recording how many series were withheld, so the truncation is
    itself observable instead of silent. ``max_series=0`` disables the
    cap."""
    families: dict[str, list[tuple[str, dict[str, Any]]]] = {}
    for name, snap in snapshot.items():
        base, _, key = name.partition("/")
        families.setdefault(_prom_name(base), []).append((key, snap))

    overflow: dict[str, int] = {}
    lines: list[str] = []
    for base in sorted(families):
        series = sorted(families[base])
        if max_series and len(series) > max_series:
            overflow[base] = len(series) - max_series
            series = series[:max_series]
        kind = series[0][1].get("type")
        full = f"{prefix}_{base}"
        if kind == "counter":
            full += "_total"
        if kind in ("counter", "gauge", "histogram"):
            lines.append(f"# TYPE {full} {kind}")
        for key, snap in series:
            label = f'{{key="{_prom_label(key)}"}}' if key else ""
            if kind == "counter":
                lines.append(f"{full}{label} {snap['value']}")
            elif kind == "gauge":
                if snap["value"] is not None:
                    lines.append(f"{full}{label} {snap['value']}")
            elif kind == "histogram":
                base_label = (
                    f'key="{_prom_label(key)}",' if key else ""
                )
                cum = 0
                for edge, count in zip(snap["edges"], snap["counts"]):
                    cum += count
                    lines.append(
                        f'{full}_bucket{{{base_label}le="{edge}"}} {cum}'
                    )
                cum += snap["counts"][-1]
                lines.append(
                    f'{full}_bucket{{{base_label}le="+Inf"}} {cum}'
                )
                lines.append(f"{full}_sum{label} {snap['sum']}")
                lines.append(f"{full}_count{label} {snap['count']}")
    if overflow:
        full = f"{prefix}_series_overflow_total"
        lines.append(f"# TYPE {full} counter")
        for base in sorted(overflow):
            lines.append(
                f'{full}{{family="{_prom_label(base)}"}} {overflow[base]}'
            )
    return "\n".join(lines) + "\n"


def _accepts_kwarg(fn, name: str) -> bool:
    """True when ``fn`` can be called with keyword ``name``."""
    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):  # builtins / C callables: no signature
        return False
    p = params.get(name)
    if p is not None:
        return p.kind is not inspect.Parameter.VAR_POSITIONAL
    return any(
        q.kind is inspect.Parameter.VAR_KEYWORD for q in params.values()
    )


class OpsServer:
    """Live ops endpoint: a stdlib ``ThreadingHTTPServer`` on a daemon
    thread serving

    - ``/healthz`` — liveness probe (``200 ok``): the ops thread exists;
    - ``/ready`` — readiness probe, distinct from liveness (README
      "Serving"): 200 only when ``ready_fn`` returns truthy — for the
      serving plane that means "a model is loaded and the encoder is
      warm", which a load balancer must gate on before routing traffic;
      503 otherwise. Without a ``ready_fn`` the route mirrors
      ``/healthz`` (a process with no warm-up phase is ready when alive);
    - ``/metrics`` — Prometheus text exposition of the registry
      (:func:`render_prometheus`);
    - ``/status`` — JSON from ``status_fn`` (the federation server's live
      round / membership / codec view). ``/status?full=1`` passes
      ``full=True`` through to ``status_fn`` (the federation server then
      serves the complete per-client roster instead of the bounded
      summary); a ``status_fn`` that takes no ``full`` kwarg is called
      plain — older callers keep working.

    ``routes`` mounts additional POST handlers (the serving plane's JSON
    ``/infer``): a dict of path -> ``fn(body_bytes, query_string)``
    returning ``(http_code, content_type, body_bytes)``. Handler
    exceptions surface as 500s, never kill the serving thread.

    Fleet telemetry (README "Fleet telemetry & SLOs"): passing a
    :class:`FleetRegistry` as ``fleet`` extends ``/metrics`` with the
    fleet-merged ``<prefix>_fleet_*`` families plus node-labeled
    ``<prefix>_node_*`` series, and mounts ``/status.fleet`` (the bounded
    top-k :meth:`FleetRegistry.summary`). An ``alerts_fn`` mounts
    ``/alerts`` (the SLO engine's live alert states). Every ``/metrics``
    scrape also refreshes the process self-gauges
    (:func:`sample_process_metrics`), so each ops plane exposes
    ``gfedntm_process_{rss_bytes,uptime_s,threads}`` for free.

    Entirely out of the training hot path: no thread is started unless
    :meth:`start` is called, and GET handlers only *read* registry
    snapshots.
    """

    def __init__(self, registry: MetricRegistry | None = None,
                 status_fn=None, host: str = "127.0.0.1", port: int = 0,
                 ready_fn=None, routes: dict | None = None,
                 fleet: "FleetRegistry | None" = None, alerts_fn=None):
        self.registry = registry or MetricRegistry()
        self.status_fn = status_fn
        self.ready_fn = ready_fn
        self.routes = dict(routes or {})
        self.fleet = fleet
        self.alerts_fn = alerts_fn
        self.host = host
        self.port = port
        self._httpd = None
        self._thread = None

    def start(self) -> int:
        """Bind + serve on a daemon thread; returns the actual port
        (``port=0`` binds an ephemeral one)."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        ops = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - http.server API
                path, _, query = self.path.partition("?")
                try:
                    if path == "/healthz":
                        code, ctype, body = 200, "text/plain", b"ok\n"
                    elif path == "/ready":
                        # Readiness is not liveness: a serving process is
                        # alive the moment its ops thread binds, but must
                        # not receive traffic until a model is loaded and
                        # warm (README "Serving").
                        ready = (
                            bool(ops.ready_fn()) if ops.ready_fn is not None
                            else True
                        )
                        code = 200 if ready else 503
                        ctype = "text/plain"
                        body = b"ready\n" if ready else b"not ready\n"
                    elif path == "/metrics":
                        sample_process_metrics(ops.registry)
                        text = render_prometheus(ops.registry.snapshot())
                        if ops.fleet is not None:
                            text += render_fleet_prometheus(
                                ops.fleet.node_snapshots()
                            )
                        code = 200
                        ctype = "text/plain; version=0.0.4"
                        body = text.encode()
                    elif path == "/status.fleet" and ops.fleet is not None:
                        code, ctype = 200, "application/json"
                        body = json.dumps(
                            ops.fleet.summary(), default=str, indent=1,
                        ).encode()
                    elif path == "/alerts" and ops.alerts_fn is not None:
                        code, ctype = 200, "application/json"
                        body = json.dumps(
                            ops.alerts_fn(), default=str, indent=1,
                        ).encode()
                    elif path == "/status":
                        full = "full=1" in query.split("&")
                        if ops.status_fn is None:
                            status = {}
                        elif full and _accepts_kwarg(ops.status_fn, "full"):
                            # Detected by signature, not by calling and
                            # catching TypeError — that would also eat a
                            # TypeError raised INSIDE status_fn and
                            # silently serve the summary view instead.
                            status = ops.status_fn(full=True)
                        else:
                            # status_fn without a full kwarg (older
                            # callers / test fixtures) serves its one view
                            status = ops.status_fn()
                        code, ctype = 200, "application/json"
                        body = json.dumps(
                            status, default=str, indent=1
                        ).encode()
                    else:
                        code, ctype, body = 404, "text/plain", b"not found\n"
                except Exception as err:  # never kill the serving thread
                    code, ctype = 500, "text/plain"
                    body = f"error: {err}\n".encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):  # noqa: N802 - http.server API
                path, _, query = self.path.partition("?")
                handler = ops.routes.get(path)
                try:
                    if handler is None:
                        code, ctype, body = 404, "text/plain", b"not found\n"
                    else:
                        length = int(self.headers.get("Content-Length", 0))
                        payload = self.rfile.read(length) if length else b""
                        code, ctype, body = handler(payload, query)
                except Exception as err:  # never kill the serving thread
                    code, ctype = 500, "text/plain"
                    body = f"error: {err}\n".encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-request stderr
                pass

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="ops-server", daemon=True,
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


# ---- straggler analytics ----------------------------------------------------

class StragglerDetector:
    """Rolling per-client step-time EWMAs with z-score outlier flagging.

    Each round the server reports the warmed clients' poll latencies
    (:meth:`observe_round`); the detector updates one EWMA gauge per client
    (``client_step_ewma_s/clientN``) and flags any client whose EWMA sits
    more than ``z_threshold`` standard deviations above the population
    mean — provided the population is large enough to make a z-score
    meaningful (``min_clients``), the client has enough history
    (``min_rounds``), AND its EWMA exceeds ``min_ratio`` × the mean: a
    z-score alone is scale-invariant, so in a tightly-clustered fleet a
    client microseconds slower than its peers would otherwise flag.
    :meth:`status` serves the current per-client view to the ops
    endpoint's ``/status``.
    """

    def __init__(self, registry: MetricRegistry | None = None,
                 z_threshold: float = 2.0, alpha: float = 0.3,
                 min_clients: int = 3, min_rounds: int = 3,
                 min_ratio: float = 1.5):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.registry = registry
        self.z_threshold = float(z_threshold)
        self.alpha = float(alpha)
        self.min_clients = int(min_clients)
        self.min_rounds = int(min_rounds)
        self.min_ratio = float(min_ratio)
        self._ewma: dict[Any, float] = {}
        self._rounds: dict[Any, int] = {}
        self._current: dict[Any, dict[str, Any]] = {}
        self._lock = threading.Lock()

    def observe_round(
        self, latencies: dict[Any, float]
    ) -> list[dict[str, Any]]:
        """Fold one round's per-client latencies in; returns the newly
        computed stragglers as ``{"client", "z", "ewma_s"}`` dicts."""
        with self._lock:
            for cid, lat in latencies.items():
                prev = self._ewma.get(cid)
                self._ewma[cid] = (
                    float(lat) if prev is None
                    else self.alpha * float(lat) + (1 - self.alpha) * prev
                )
                self._rounds[cid] = self._rounds.get(cid, 0) + 1
                if self.registry is not None:
                    self.registry.gauge(
                        f"client_step_ewma_s/client{cid}"
                    ).set(self._ewma[cid])
            mature = {
                cid: e for cid, e in self._ewma.items()
                if self._rounds[cid] >= self.min_rounds
            }
            self._current = {
                cid: {"ewma_s": e, "z": None, "straggler": False}
                for cid, e in self._ewma.items()
            }
            if len(mature) < self.min_clients:
                return []
            values = list(mature.values())
            mean = sum(values) / len(values)
            var = sum((v - mean) ** 2 for v in values) / len(values)
            std = var ** 0.5
            if std <= 1e-12:
                return []
            flagged = []
            for cid, e in mature.items():
                z = (e - mean) / std
                self._current[cid]["z"] = z
                if (
                    z > self.z_threshold and e > self.min_ratio * mean
                    and cid in latencies
                ):
                    self._current[cid]["straggler"] = True
                    flagged.append({"client": cid, "z": z, "ewma_s": e})
            return flagged

    def ewma_view(self) -> dict[Any, float]:
        """Snapshot of the per-client poll-latency EWMAs — the live input
        to the pacing engines' adaptive poll deadline (a warmed client's
        deadline derives from these instead of the fixed 120 + 2E
        population-scale constant)."""
        with self._lock:
            return dict(self._ewma)

    def forget(self, client_id: Any) -> None:
        """Evict a departed client: a dropped client's frozen EWMA would
        otherwise skew the population mean/std forever (inflating std so
        genuine new stragglers stop flagging) and haunt ``/status``. Its
        gauge is dropped from the registry too — per-client series must
        not accumulate one ghost per client that ever churned through
        the federation. A rejoin re-warms from scratch, like the
        server's poll warm-up."""
        with self._lock:
            self._ewma.pop(client_id, None)
            self._rounds.pop(client_id, None)
            self._current.pop(client_id, None)
        if self.registry is not None:
            self.registry.drop(f"client_step_ewma_s/client{client_id}")

    def status(self) -> dict[str, dict[str, Any]]:
        """JSON-safe per-client view for the ops endpoint."""
        with self._lock:
            return {
                str(cid): dict(state)
                for cid, state in sorted(self._current.items(), key=str)
            }

    def summary(self, top_k: int = 5) -> dict[str, Any]:
        """Bounded view for the default ``/status`` scrape: counts plus
        the ``top_k`` slowest EWMAs. One heap pass over the live map —
        the full per-client materialize-and-sort that :meth:`status`
        does would stall the ops thread at 10⁴ clients (ISSUE 11
        satellite); only the ``top_k`` winners are copied out."""
        with self._lock:
            top = heapq.nlargest(
                top_k, self._current.items(),
                key=lambda kv: (kv[1].get("ewma_s") or 0.0, str(kv[0])),
            )
            return {
                "observed": len(self._current),
                "flagged": sum(
                    1 for v in self._current.values() if v.get("straggler")
                ),
                "top_slowest": [
                    {"client": str(cid), **state} for cid, state in top
                ],
            }


# ---- cross-node trace merge (the `trace` CLI subcommand's engine) -----------

def _serve_offset_samples(
    records: list[dict[str, Any]], remote: str
) -> list[float]:
    """``recv - send`` deltas of ``serve`` spans received FROM ``remote``:
    each sample is (receiver clock − sender clock) + network latency, so
    the minimum over many samples approaches the clock offset plus the
    latency floor."""
    out = []
    for r in records:
        if (
            r.get("event") == "span" and r.get("name") == "serve"
            and r.get("remote_node") == remote
            and isinstance(r.get("rpc_send_time"), (int, float))
            and isinstance(r.get("rpc_recv_time"), (int, float))
        ):
            out.append(float(r["rpc_recv_time"]) - float(r["rpc_send_time"]))
    return out


def estimate_clock_offset(
    node_records: list[dict[str, Any]],
    ref_records: list[dict[str, Any]],
    node: str, ref: str,
) -> float:
    """Seconds by which ``node``'s wall clock leads the reference's,
    NTP-style from the paired RPC send/recv stamps: with both directions
    available the latency floors cancel (``(min fwd − min rev) / 2``); a
    single direction degrades to the one-way bound."""
    fwd = _serve_offset_samples(node_records, ref)   # offset + latency
    rev = _serve_offset_samples(ref_records, node)   # -offset + latency
    if fwd and rev:
        return (min(fwd) - min(rev)) / 2.0
    if fwd:
        return min(fwd)
    if rev:
        return -min(rev)
    return 0.0


def merge_chrome_trace(
    node_records: dict[str, list[dict[str, Any]]],
    reference: str | None = None,
) -> dict[str, Any]:
    """Merge per-node telemetry streams into one Chrome trace-event JSON
    (load in Perfetto / chrome://tracing).

    One pid per node (the reference — the node owning the ``round`` spans —
    first), one tid per emitting thread, every ``span`` event an ``X``
    slice whose wall-clock start is shifted onto the reference clock by
    :func:`estimate_clock_offset`. ``serve`` spans carrying a
    ``remote_parent_id`` additionally get flow arrows from the sender's
    span, so a round renders as one connected tree across all processes.
    """
    if not node_records:
        raise ValueError("no node records to merge")
    if reference is None or reference not in node_records:
        if reference is not None:
            raise ValueError(
                f"reference node {reference!r} not among "
                f"{sorted(node_records)}"
            )
        reference = next(
            (
                node for node, recs in sorted(node_records.items())
                if any(
                    r.get("event") == "span" and r.get("name") == "round"
                    for r in recs
                )
            ),
            sorted(node_records)[0],
        )

    offsets = {
        node: (
            0.0 if node == reference else estimate_clock_offset(
                recs, node_records[reference], node, reference
            )
        )
        for node, recs in node_records.items()
    }

    # Wall-clock zero: earliest aligned span start across all nodes.
    starts = [
        float(r["time"]) - float(r.get("seconds", 0.0)) - offsets[node]
        for node, recs in node_records.items()
        for r in recs
        if r.get("event") == "span" and isinstance(r.get("time"), (int, float))
    ]
    t0 = min(starts) if starts else 0.0

    order = [reference] + sorted(n for n in node_records if n != reference)
    events: list[dict[str, Any]] = []
    # (node, span_id) -> (pid, tid, start_us) for flow binding
    span_index: dict[tuple[str, int], tuple[int, int, float]] = {}
    flows: list[tuple[str, dict[str, Any], float]] = []

    for pid, node in enumerate(order):
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": node},
        })
        tids: dict[Any, int] = {}
        for r in node_records[node]:
            if r.get("event") != "span":
                continue
            seconds = float(r.get("seconds", 0.0))
            start_us = (
                float(r["time"]) - seconds - offsets[node] - t0
            ) * 1e6
            tid = tids.setdefault(r.get("thread", 0), len(tids))
            args = {
                k: v for k, v in r.items()
                if k not in ("event", "time", "seconds", "thread", "name")
                and v is not None
            }
            events.append({
                "name": str(r.get("name", "span")), "cat": "span",
                "ph": "X", "pid": pid, "tid": tid,
                "ts": round(start_us, 3),
                "dur": round(max(seconds, 1e-6) * 1e6, 3),
                "args": args,
            })
            if isinstance(r.get("span_id"), int):
                span_index[(node, r["span_id"])] = (pid, tid, start_us)
            if (
                r.get("name") == "serve"
                and isinstance(r.get("remote_parent_id"), int)
                and isinstance(r.get("remote_node"), str)
            ):
                flows.append((node, r, start_us))

    flow_id = 0
    for node, r, child_start_us in flows:
        parent = span_index.get((r["remote_node"], r["remote_parent_id"]))
        if parent is None:
            continue
        flow_id += 1
        p_pid, p_tid, p_start_us = parent
        c_pid, c_tid, _ = span_index[(node, r["span_id"])]
        events.append({
            "name": "rpc", "cat": "trace", "ph": "s", "id": flow_id,
            "pid": p_pid, "tid": p_tid,
            "ts": round(max(p_start_us, 0.0) + 0.5, 3),
        })
        events.append({
            "name": "rpc", "cat": "trace", "ph": "f", "bp": "e",
            "id": flow_id, "pid": c_pid, "tid": c_tid,
            "ts": round(child_start_us, 3),
        })

    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "reference": reference,
            "clock_offsets_s": {n: offsets[n] for n in order},
            "epoch_origin_unix_s": t0,
        },
    }
