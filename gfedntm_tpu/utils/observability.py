"""Structured metrics + profiling (first-class, unlike the reference).

The reference's telemetry is log-line based (per-minibatch loss strings,
``federated_avitm.py:109``) with a vestigial ``GRPC_TRACE`` constant and no
profiler hooks (SURVEY.md §5). Here:

- :class:`MetricsLogger` — structured JSONL event stream (one object per
  line: step/epoch metrics, phase timings) plus an in-memory record, so
  experiments and dashboards read one format.
- :func:`phase_timer` — wall-clock timing of named phases (consensus,
  compile, train, inference) pushed into the logger.
- :func:`trace` — ``jax.profiler`` trace context for TPU timeline capture
  (view in TensorBoard / xprof).
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Any, Iterator


class MetricsLogger:
    """Append-only structured metrics. ``path=None`` keeps records in memory
    only (tests); otherwise each event is one JSON line, flushed eagerly so
    a crashed run keeps its telemetry."""

    def __init__(self, path: str | None = None):
        self.path = path
        self.records: list[dict[str, Any]] = []
        self._fh = None
        if path is not None:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._fh = open(path, "a")

    def log(self, event: str, **fields: Any) -> dict[str, Any]:
        record = {"event": event, "time": time.time(), **fields}
        self.records.append(record)
        if self._fh is not None:
            self._fh.write(json.dumps(record, default=float) + "\n")
            self._fh.flush()
        return record

    def events(self, event: str) -> list[dict[str, Any]]:
        return [r for r in self.records if r["event"] == event]

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


@contextlib.contextmanager
def phase_timer(
    logger: MetricsLogger | None, phase: str, **fields: Any
) -> Iterator[None]:
    """Time a named phase; logs ``{"event": "phase", "phase": ..., "seconds": ...}``."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        elapsed = time.perf_counter() - t0
        if logger is not None:
            logger.log("phase", phase=phase, seconds=elapsed, **fields)


@contextlib.contextmanager
def trace(log_dir: str | None) -> Iterator[None]:
    """``jax.profiler.trace`` context when ``log_dir`` is set; no-op
    otherwise (so call sites need no branching)."""
    if log_dir is None:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield
