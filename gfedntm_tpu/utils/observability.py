"""Federation-wide telemetry: structured events, spans, metrics, reports.

The reference's telemetry is log-line based (per-minibatch loss strings,
``federated_avitm.py:109``) with a vestigial ``GRPC_TRACE`` constant and no
profiler hooks (SURVEY.md §5). Here telemetry is a first-class subsystem —
the substrate every perf/robustness PR reports against:

- :class:`MetricsLogger` — thread-safe structured JSONL event stream (one
  object per line), flushed eagerly so a crashed run keeps its telemetry.
  Every logger carries a :class:`MetricRegistry` whose cumulative state
  snapshots into the same stream (``metrics_snapshot`` events).
- :func:`span` — hierarchical timing contexts (parent/child ids, monotonic
  durations) so a run decomposes into round → client → {poll, average,
  push, local_step}. Nesting is implicit within a thread (contextvars) and
  explicit (``parent=``) across threads.
- :class:`MetricRegistry` — counters, gauges, and fixed-bucket histograms
  (step time, RPC latency, payload bytes) with percentile estimation.
- :func:`validate_record` — schema lint for the event stream, so new events
  can't silently drift from the documented schema (README "Telemetry").
- :func:`summarize_metrics` / :func:`format_report` — the ``summarize`` CLI
  subcommand's engine: phase breakdown, p50/p95/p99 step time, bytes moved
  per round, slowest client.
- :func:`phase_timer` — wall-phase timing (consensus, compile, train).
- :func:`trace` — ``jax.profiler`` trace context for TPU timeline capture
  (view in TensorBoard / xprof).

Every hook is a no-op when no logger is passed (``logger=None``), so
un-instrumented hot paths pay nothing. Durations come from
``time.perf_counter`` (monotonic — NTP steps cannot produce negative phase
times); wall-clock ``time.time()`` appears only as the ``time`` event
timestamp field.
"""

from __future__ import annotations

import bisect
import contextlib
import contextvars
import itertools
import json
import os
import threading
import time
from typing import Any, Iterator

# ---- event schema -----------------------------------------------------------

#: Required fields per event name, beyond the implicit ``event`` + ``time``.
#: Extra fields are always allowed; MISSING required fields (or an event name
#: absent from this table, under strict validation) are schema drift.
EVENT_SCHEMAS: dict[str, frozenset[str]] = {
    # timing
    "phase": frozenset({"phase", "seconds"}),
    "span": frozenset({"name", "span_id", "parent_id", "seconds"}),
    "jit_compile": frozenset({"what", "seconds"}),
    # registry state
    "metrics_snapshot": frozenset({"metrics"}),
    # RPC failures (successes aggregate into registry histograms only)
    "rpc": frozenset({"service", "method", "seconds", "ok"}),
    # resilience lifecycle (federation probation / quorum / checkpoint /
    # client watchdog; see README "Fault tolerance")
    "client_suspect": frozenset({"client", "failures", "status"}),
    "client_recovered": frozenset({"client"}),
    "quorum_skip": frozenset({"round", "got", "needed"}),
    "checkpoint": frozenset({"round"}),
    "watchdog_fired": frozenset({"client", "idle_s"}),
    # wire codec negotiation + delta-reference discipline (federation
    # compression subsystem; see README "Aggregation strategies & wire
    # compression")
    "codec_negotiated": frozenset({"client", "codec"}),
    "codec_mismatch": frozenset({"client", "server_codec", "client_codec"}),
    "codec_ref_miss": frozenset({"client", "ref_round"}),
    # training progress
    "resume": frozenset({"step"}),
    "epoch": frozenset({"epoch"}),
    "federated_segment": frozenset({"step", "mean_loss"}),
    "federated_iteration": frozenset({"iteration", "mean_loss"}),
    "summary": frozenset(),
    # bench stream (bench.py emits through the same logger/schema)
    "bench_summary": frozenset({"backend"}),
    "bench_result": frozenset({"metric", "value", "unit", "backend"}),
}


def validate_record(record: Any, strict: bool = True) -> dict[str, Any]:
    """Schema-lint one event record; returns it unchanged or raises
    ``ValueError``. ``strict=False`` lets unknown event names pass (their
    ``event``/``time`` envelope is still checked)."""
    if not isinstance(record, dict):
        raise ValueError(f"record must be a dict, got {type(record).__name__}")
    event = record.get("event")
    if not isinstance(event, str) or not event:
        raise ValueError(f"record needs a non-empty 'event' str: {record!r}")
    if not isinstance(record.get("time"), (int, float)):
        raise ValueError(f"record {event!r} needs a numeric 'time' field")
    required = EVENT_SCHEMAS.get(event)
    if required is None:
        if strict:
            raise ValueError(
                f"unknown event {event!r}: register it in "
                "observability.EVENT_SCHEMAS (and README 'Telemetry')"
            )
        return record
    missing = required - record.keys()
    if missing:
        raise ValueError(
            f"event {event!r} missing required fields {sorted(missing)}"
        )
    return record


# ---- metric registry --------------------------------------------------------

#: Exponential-ish latency edges, 100 µs .. 5 min (upper-inclusive buckets).
DEFAULT_TIME_BUCKETS_S: tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)

#: Payload-size edges, 256 B .. 256 MB (the gRPC message cap).
DEFAULT_BYTE_BUCKETS: tuple[float, ...] = tuple(
    256.0 * 4.0 ** i for i in range(11)
)


class Counter:
    """Monotonic counter."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n

    def snapshot(self) -> dict[str, Any]:
        return {"type": "counter", "value": self.value}


class Gauge:
    """Last-value-wins gauge."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value: float | None = None
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def snapshot(self) -> dict[str, Any]:
        return {"type": "gauge", "value": self.value}


class Histogram:
    """Fixed-bucket histogram with upper-inclusive edges.

    ``counts[i]`` counts observations ``v <= edges[i]`` (first matching
    bucket); ``counts[-1]`` is the overflow bucket. Percentiles are
    estimated by linear interpolation inside the selected bucket, clamped
    to the observed [min, max] — exact at the tracked extremes, bucket-
    resolution elsewhere.
    """

    __slots__ = ("name", "edges", "counts", "count", "sum", "min", "max",
                 "_lock")

    def __init__(self, name: str, buckets: tuple[float, ...] | None = None):
        self.name = name
        self.edges = tuple(sorted(buckets or DEFAULT_TIME_BUCKETS_S))
        self.counts = [0] * (len(self.edges) + 1)
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        i = bisect.bisect_left(self.edges, v)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            if not self.count:
                return {
                    "type": "histogram", "count": 0, "sum": 0.0,
                    "edges": list(self.edges), "counts": list(self.counts),
                }
            return {
                "type": "histogram",
                "count": self.count,
                "sum": self.sum,
                "min": self.min,
                "max": self.max,
                "edges": list(self.edges),
                "counts": list(self.counts),
            }

    def quantile(self, q: float) -> float | None:
        return quantile_from_snapshot(self.snapshot(), q)


def quantile_from_snapshot(snap: dict[str, Any], q: float) -> float | None:
    """Estimate the ``q``-quantile (0..1) from a histogram snapshot dict
    (the serialized form inside ``metrics_snapshot`` events)."""
    n = snap.get("count", 0)
    if not n:
        return None
    edges, counts = snap["edges"], snap["counts"]
    lo_all, hi_all = snap["min"], snap["max"]
    target = max(q, 0.0) * n
    cum = 0.0
    for i, c in enumerate(counts):
        if c and cum + c >= target:
            lo = lo_all if i == 0 else edges[i - 1]
            hi = edges[i] if i < len(edges) else hi_all
            lo = min(max(lo, lo_all), hi_all)
            hi = max(min(hi, hi_all), lo)
            frac = (target - cum) / c
            return lo + frac * (hi - lo)
        cum += c
    return hi_all


class MetricRegistry:
    """Get-or-create store of named counters/gauges/histograms; thread-safe.

    The first creation fixes a histogram's buckets; later ``histogram``
    calls for the same name return the existing instance (their ``buckets``
    argument is ignored).
    """

    def __init__(self):
        self._metrics: dict[str, Any] = {}
        self._lock = threading.Lock()

    def _get(self, name: str, cls, *args):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, *args)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}"
                )
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(
        self, name: str, buckets: tuple[float, ...] | None = None
    ) -> Histogram:
        return self._get(name, Histogram, buckets)

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            metrics = list(self._metrics.items())
        return {name: m.snapshot() for name, m in sorted(metrics)}


# ---- structured event log ---------------------------------------------------

class MetricsLogger:
    """Append-only structured metrics. ``path=None`` keeps records in memory
    only (tests); otherwise each event is one JSON line, flushed eagerly so
    a crashed run keeps its telemetry.

    Thread-safe: the federation server's training loop drives one logger
    from many poll/push worker threads, and interleaved JSONL lines would
    corrupt the stream. ``validate=True`` schema-lints every record at log
    time (tests; see :func:`validate_record`).
    """

    def __init__(self, path: str | None = None, validate: bool = False,
                 mode: str = "a", keep_records: bool | None = None):
        self.path = path
        self.validate = validate
        # In-memory retention is for in-process consumers (.events(), tests,
        # bench phase accounting). Default: retain only when there is no
        # file — a long path-backed server run would otherwise accumulate
        # every round's span events for the process lifetime.
        self.keep_records = (
            path is None if keep_records is None else bool(keep_records)
        )
        self.records: list[dict[str, Any]] = []
        self.registry = MetricRegistry()
        self._lock = threading.Lock()
        self._fh = None
        if path is not None:
            if mode not in ("a", "w"):
                raise ValueError(f"mode must be 'a' or 'w', got {mode!r}")
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._fh = open(path, mode)

    def log(self, event: str, **fields: Any) -> dict[str, Any]:
        record = {"event": event, "time": time.time(), **fields}
        if self.validate:
            validate_record(record)
        # Serialize outside the lock; append + write inside it so lines
        # never interleave and records keeps file order.
        line = (
            json.dumps(record, default=float) if self.path is not None
            else None
        )
        with self._lock:
            if self.keep_records:
                self.records.append(record)
            if self._fh is not None and line is not None:
                self._fh.write(line + "\n")
                self._fh.flush()
        return record

    def events(self, event: str) -> list[dict[str, Any]]:
        if not self.keep_records:
            raise RuntimeError(
                "events() needs in-memory retention: construct with "
                "keep_records=True (or path=None), or read the JSONL file "
                "via read_metrics()"
            )
        return [r for r in self.records if r["event"] == event]

    def snapshot_registry(self, **fields: Any) -> dict[str, Any] | None:
        """Dump the registry's cumulative state into the event stream."""
        snap = self.registry.snapshot()
        if not snap:
            return None
        return self.log("metrics_snapshot", metrics=snap, **fields)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ---- hierarchical spans -----------------------------------------------------

_SPAN_IDS = itertools.count(1)
_CURRENT_SPAN: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "gfedntm_current_span", default=None
)


class Span:
    """One timed region of a run. Logs a ``span`` event on exit with its
    monotonic duration, id, parent id, and any annotated attributes.

    Within a thread, nesting is implicit (contextvars). Work handed to a
    pool thread does NOT inherit the submitting thread's context — pass the
    enclosing span explicitly: ``span(logger, "poll", parent=round_span)``.
    """

    __slots__ = ("logger", "name", "fields", "span_id", "parent_id",
                 "_parent", "_token", "_t0")

    def __init__(self, logger: MetricsLogger, name: str, parent: Any,
                 fields: dict[str, Any]):
        self.logger = logger
        self.name = name
        self.fields = dict(fields)
        self.span_id = next(_SPAN_IDS)
        self.parent_id: int | None = None
        self._parent = parent
        self._token = None
        self._t0 = 0.0

    def annotate(self, **fields: Any) -> "Span":
        """Attach attributes that become fields of the logged span event."""
        self.fields.update(fields)
        return self

    def __enter__(self) -> "Span":
        if self._parent is not None:
            self.parent_id = getattr(self._parent, "span_id", self._parent)
        else:
            cur = _CURRENT_SPAN.get()
            self.parent_id = cur.span_id if cur is not None else None
        self._token = _CURRENT_SPAN.set(self)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        seconds = time.perf_counter() - self._t0
        _CURRENT_SPAN.reset(self._token)
        self.logger.log(
            "span", name=self.name, span_id=self.span_id,
            parent_id=self.parent_id, seconds=seconds,
            ok=exc_type is None, **self.fields,
        )


class _NullSpan:
    """No-op span returned for ``logger=None`` call sites (zero overhead)."""

    __slots__ = ()
    span_id = None
    parent_id = None

    def annotate(self, **fields: Any) -> "_NullSpan":
        return self

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        return None


_NULL_SPAN = _NullSpan()


def span(logger: MetricsLogger | None, name: str, parent: Any = None,
         **fields: Any):
    """Hierarchical timing context; a no-op when ``logger`` is None."""
    if logger is None:
        return _NULL_SPAN
    return Span(logger, name, parent, fields)


# ---- jit wrappers -----------------------------------------------------------

def timed_jit(fn, logger: MetricsLogger | None, what: str):
    """Wrap a jitted callable for compile-time capture: the FIRST call
    (trace + compile dominated) is logged as a ``jit_compile`` event; later
    calls feed the ``jit_dispatch_s/<what>`` histogram. Note that jax's
    async dispatch means post-compile durations measure dispatch, not device
    execution, and a later re-specialization (new shapes) is not separated
    out. Passthrough when ``logger`` is None."""
    if logger is None:
        return fn
    hist = logger.registry.histogram(f"jit_dispatch_s/{what}")
    state = {"first": True}
    lock = threading.Lock()

    def wrapper(*args, **kwargs):
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        with lock:
            first, state["first"] = state["first"], False
        if first:
            logger.log("jit_compile", what=what, seconds=dt)
        else:
            hist.observe(dt)
        return out

    return wrapper


# ---- phase timing + profiler ------------------------------------------------

@contextlib.contextmanager
def phase_timer(
    logger: MetricsLogger | None, phase: str, **fields: Any
) -> Iterator[None]:
    """Time a named phase; logs ``{"event": "phase", "phase": ..., "seconds": ...}``."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        elapsed = time.perf_counter() - t0
        if logger is not None:
            logger.log("phase", phase=phase, seconds=elapsed, **fields)


@contextlib.contextmanager
def trace(log_dir: str | None) -> Iterator[None]:
    """``jax.profiler.trace`` context when ``log_dir`` is set; no-op
    otherwise (so call sites need no branching)."""
    if log_dir is None:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield


# ---- run summaries (the `summarize` CLI subcommand's engine) ----------------

def read_metrics(path: str) -> list[dict[str, Any]]:
    """Parse a ``metrics.jsonl`` file; blank lines are skipped, malformed
    lines raise (a corrupt stream should be loud, not silently partial)."""
    records = []
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError as err:
                raise ValueError(f"{path}:{lineno}: bad JSONL line: {err}")
    return records


def _agg(groups: dict, key: str, seconds: float) -> None:
    g = groups.setdefault(key, {"count": 0, "total_s": 0.0, "max_s": 0.0})
    g["count"] += 1
    g["total_s"] += seconds
    g["max_s"] = max(g["max_s"], seconds)


def _hist_stats(snap: dict[str, Any]) -> dict[str, Any]:
    count = snap.get("count", 0)
    out: dict[str, Any] = {"count": count}
    if count:
        out["mean_s"] = snap["sum"] / count
        for q, label in ((0.5, "p50_s"), (0.95, "p95_s"), (0.99, "p99_s")):
            out[label] = quantile_from_snapshot(snap, q)
        out["min_s"], out["max_s"] = snap["min"], snap["max"]
    return out


def summarize_metrics(records: list[dict[str, Any]]) -> dict[str, Any]:
    """Aggregate a run's event stream into a report dict (see
    :func:`format_report` for the rendered form)."""
    times = [r["time"] for r in records
             if isinstance(r.get("time"), (int, float))]
    event_counts: dict[str, int] = {}
    phases: dict[str, dict] = {}
    spans: dict[str, dict] = {}
    rounds = {"count": 0, "total_s": 0.0, "bytes_pulled": 0.0,
              "bytes_pushed": 0.0}
    slowest: dict[Any, dict] = {}
    compile_events: list[dict[str, Any]] = []
    rpc_errors: list[dict[str, Any]] = []
    last_snapshots: dict[str, dict] = {}
    summary_event: dict[str, Any] | None = None

    for r in records:
        event = r.get("event", "?")
        event_counts[event] = event_counts.get(event, 0) + 1
        if event == "phase":
            _agg(phases, str(r.get("phase", "?")), float(r.get("seconds", 0)))
        elif event == "span":
            name = str(r.get("name", "?"))
            secs = float(r.get("seconds", 0))
            _agg(spans, name, secs)
            if name == "round":
                rounds["count"] += 1
                rounds["total_s"] += secs
                rounds["bytes_pulled"] += float(r.get("bytes_pulled", 0))
                rounds["bytes_pushed"] += float(r.get("bytes_pushed", 0))
                cid = r.get("slowest_client")
                if cid is not None:
                    s = slowest.setdefault(
                        cid, {"rounds_slowest": 0, "max_poll_s": 0.0}
                    )
                    s["rounds_slowest"] += 1
                    s["max_poll_s"] = max(
                        s["max_poll_s"], float(r.get("slowest_s", 0))
                    )
        elif event == "jit_compile":
            compile_events.append(
                {"what": r.get("what"), "seconds": r.get("seconds")}
            )
        elif event == "rpc" and not r.get("ok", True):
            rpc_errors.append(r)
        elif event == "metrics_snapshot":
            # Registries are cumulative, so the LAST snapshot mentioning a
            # metric carries its totals.
            for name, snap in (r.get("metrics") or {}).items():
                last_snapshots[name] = snap
        elif event == "summary":
            summary_event = {
                k: v for k, v in r.items() if k not in ("event", "time")
            }

    step_time = {
        name: _hist_stats(snap)
        for name, snap in last_snapshots.items()
        if snap.get("type") == "histogram" and name.endswith("step_s")
        and snap.get("count")
    }
    rpc = {
        name.split("/", 1)[1]: _hist_stats(snap)
        for name, snap in last_snapshots.items()
        if name.startswith("rpc_s/") and snap.get("count")
    }
    # Every other populated histogram (codec encode/decode seconds, bundle
    # bytes, client poll latency, jit dispatch, ...): no histogram this
    # stream records may be write-only in the summary.
    other_hists = {
        name: _hist_stats(snap)
        for name, snap in last_snapshots.items()
        if snap.get("type") == "histogram" and snap.get("count")
        and not (name.endswith("step_s") or name.startswith("rpc_s/"))
    }
    counters = {
        name: snap["value"] for name, snap in last_snapshots.items()
        if snap.get("type") == "counter"
    }
    gauges = {
        name: snap["value"] for name, snap in last_snapshots.items()
        if snap.get("type") == "gauge"
    }

    return {
        "events_total": len(records),
        "wall_seconds": (max(times) - min(times)) if times else 0.0,
        "event_counts": dict(sorted(event_counts.items())),
        "phases": phases,
        "spans": spans,
        "rounds": rounds,
        "slowest_clients": slowest,
        "step_time": step_time,
        "rpc": rpc,
        "histograms": other_hists,
        "rpc_errors": len(rpc_errors),
        "counters": counters,
        "gauges": gauges,
        "compile": compile_events,
        "summary": summary_event,
    }


def _fmt_s(seconds: float | None) -> str:
    if seconds is None:
        return "-"
    if seconds < 1e-3:
        return f"{seconds * 1e6:.0f} µs"
    if seconds < 1.0:
        return f"{seconds * 1e3:.2f} ms"
    return f"{seconds:.2f} s"


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{n:.0f} B"
        n /= 1024
    return f"{n:.1f} GiB"


def format_report(s: dict[str, Any]) -> str:
    """Render a :func:`summarize_metrics` dict as a human-readable report."""
    lines = [
        f"run summary: {s['events_total']} events over "
        f"{s['wall_seconds']:.2f} s wall clock",
    ]

    wall = s["wall_seconds"] or float("inf")
    breakdown = dict(s["phases"])
    for name, g in s["spans"].items():
        breakdown.setdefault(f"span:{name}", g)
    if breakdown:
        lines.append("")
        lines.append("phase breakdown:")
        lines.append(f"  {'phase':<24}{'total':>12}{'count':>8}{'%wall':>8}")
        for name, g in sorted(
            breakdown.items(), key=lambda kv: -kv[1]["total_s"]
        ):
            pct = 100.0 * g["total_s"] / wall if wall else 0.0
            lines.append(
                f"  {name:<24}{_fmt_s(g['total_s']):>12}{g['count']:>8}"
                f"{pct:>7.1f}%"
            )

    if s["step_time"]:
        lines.append("")
        lines.append("step time:")
        lines.append(
            f"  {'source':<24}{'count':>8}{'mean':>12}{'p50':>12}"
            f"{'p95':>12}{'p99':>12}"
        )
        for name, st in sorted(s["step_time"].items()):
            lines.append(
                f"  {name:<24}{st['count']:>8}{_fmt_s(st['mean_s']):>12}"
                f"{_fmt_s(st['p50_s']):>12}{_fmt_s(st['p95_s']):>12}"
                f"{_fmt_s(st['p99_s']):>12}"
            )

    if s["rpc"]:
        lines.append("")
        lines.append("rpc latency:")
        lines.append(
            f"  {'method':<32}{'count':>8}{'mean':>12}{'p50':>12}{'p95':>12}"
        )
        for name, st in sorted(s["rpc"].items()):
            lines.append(
                f"  {name:<32}{st['count']:>8}{_fmt_s(st['mean_s']):>12}"
                f"{_fmt_s(st['p50_s']):>12}{_fmt_s(st['p95_s']):>12}"
            )
        deadline = s["counters"].get("rpc_deadline_expired", 0)
        errors = s["counters"].get("rpc_errors", 0)
        lines.append(
            f"  errors: {errors:.0f} ({deadline:.0f} deadline expiries), "
            f"rpc error events: {s['rpc_errors']}"
        )

    if s.get("histograms"):
        lines.append("")
        lines.append("other distributions (codec, poll, dispatch, ...):")
        lines.append(
            f"  {'name':<32}{'count':>8}{'mean':>12}{'p50':>12}{'p95':>12}"
        )
        for name, st in sorted(s["histograms"].items()):
            fmt = _fmt_bytes if "bytes" in name else _fmt_s
            lines.append(
                f"  {name:<32}{st['count']:>8}{fmt(st['mean_s']):>12}"
                f"{fmt(st['p50_s']):>12}{fmt(st['p95_s']):>12}"
            )

    rounds = s["rounds"]
    if rounds["count"]:
        per = rounds["count"]
        lines.append("")
        lines.append(
            f"federation rounds: {per} "
            f"(mean {_fmt_s(rounds['total_s'] / per)}/round)"
        )
        lines.append(
            f"  bytes moved: {_fmt_bytes(rounds['bytes_pulled'])} pulled, "
            f"{_fmt_bytes(rounds['bytes_pushed'])} pushed "
            f"({_fmt_bytes((rounds['bytes_pulled'] + rounds['bytes_pushed']) / per)}"
            "/round)"
        )
        if s["slowest_clients"]:
            worst = max(
                s["slowest_clients"].items(),
                key=lambda kv: kv[1]["rounds_slowest"],
            )
            lines.append(
                f"  slowest client: {worst[0]} (straggler in "
                f"{worst[1]['rounds_slowest']}/{per} rounds, max poll "
                f"{_fmt_s(worst[1]['max_poll_s'])})"
            )

    enc = s["counters"].get("codec_encoded_bytes")
    dec = s["counters"].get("codec_decoded_bytes")
    if enc is not None or dec is not None:
        lines.append("")
        lines.append(
            f"codec: {_fmt_bytes(enc or 0)} encoded "
            f"({s['counters'].get('codec_encode_calls', 0):.0f} bundles), "
            f"{_fmt_bytes(dec or 0)} decoded "
            f"({s['counters'].get('codec_decode_calls', 0):.0f} bundles)"
        )

    if s["compile"]:
        lines.append("")
        lines.append("compile capture (first-call trace+compile+run):")
        for c in s["compile"]:
            lines.append(f"  {c['what']}: {_fmt_s(c['seconds'])}")

    if s["summary"]:
        lines.append("")
        lines.append(f"run result: {json.dumps(s['summary'], default=str)}")

    return "\n".join(lines)
