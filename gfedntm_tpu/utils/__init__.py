from gfedntm_tpu.utils import observability as observability
from gfedntm_tpu.utils import serialization as serialization
from gfedntm_tpu.utils.observability import MetricsLogger, phase_timer, trace
from gfedntm_tpu.utils.serialization import (
    load_variables,
    save_model_as_npz,
    save_variables,
)
