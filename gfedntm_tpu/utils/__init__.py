from gfedntm_tpu.utils import observability as observability
from gfedntm_tpu.utils import serialization as serialization
from gfedntm_tpu.utils.observability import (
    MetricRegistry,
    MetricsLogger,
    format_report,
    phase_timer,
    span,
    summarize_metrics,
    timed_jit,
    trace,
    validate_record,
)
from gfedntm_tpu.utils.serialization import (
    load_variables,
    save_model_as_npz,
    save_variables,
)
