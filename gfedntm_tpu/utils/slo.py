"""Declarative SLOs + a pending→firing→resolved alerting state machine.

An SLO spec names an objective over any registry metric — counter,
gauge, or histogram — in the fleet-merged snapshot the telemetry plane
maintains (README "Fleet telemetry & SLOs"):

    {"name": "serve-p99", "metric": "serve_latency_s", "agg": "p99",
     "op": "<=", "threshold": 0.25, "window_s": 60, "for_s": 10}

``op threshold`` states the OBJECTIVE ("p99 <= 250 ms"); an evaluation
where it does not hold is a violation. ``window_s`` evaluates over the
trailing window (burn rate, windowed percentiles) by subtracting the
cumulative snapshot at the window start — the same fixed-bucket /
monotone-counter structure that makes fleet merges exact makes windowed
deltas exact too; ``window_s = 0`` evaluates the all-time cumulative
state. ``for_s`` is the pending dwell: a violation must persist that
long before the alert fires (0 = fire immediately).

Aggregations: ``p50``/``p95``/``p99``/``mean`` (histograms), ``value``
(gauges, or a counter/histogram-count level), ``rate`` (counter or
histogram-count increase per second — requires ``window_s > 0``).

The state machine is evaluated inline from hooks the federation and
serving planes already own (the pacing engines' per-aggregation tick,
the serving watcher's poll loop) — no new threads. Transitions emit
``alert_pending`` / ``alert_firing`` / ``alert_resolved`` events into
the JSONL stream, surface live at the ops ``/alerts`` endpoint, and the
``slo`` CLI subcommand replays recorded ``metrics_snapshot`` streams
through this same engine as an offline CI gate (exit 1 if any spec ever
fired) — the ``--assert-monotone-coherence`` pattern, generalized.
"""

from __future__ import annotations

import json
import os
from collections import deque
from typing import Any

from gfedntm_tpu.utils import flightrec
from gfedntm_tpu.utils.observability import (
    FleetRegistry,
    MetricsLogger,
    quantile_from_snapshot,
)

__all__ = [
    "SLOSpec",
    "SLOEngine",
    "load_slo_specs",
    "evaluate_stream",
]

_AGGS = ("p50", "p95", "p99", "mean", "value", "rate")
_OPS = {
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
}


class SLOSpec:
    """One validated SLO: ``name: metric agg op threshold`` over a
    trailing ``window_s`` with a ``for_s`` pending dwell."""

    __slots__ = ("name", "metric", "agg", "op", "threshold", "window_s",
                 "for_s")

    def __init__(self, name: str, metric: str, agg: str, op: str,
                 threshold: float, window_s: float = 0.0,
                 for_s: float = 0.0):
        if not name or not metric:
            raise ValueError("an SLO spec needs a name and a metric")
        if agg not in _AGGS:
            raise ValueError(
                f"SLO {name!r}: agg must be one of {_AGGS}, got {agg!r}"
            )
        if op not in _OPS:
            raise ValueError(
                f"SLO {name!r}: op must be one of {tuple(_OPS)}, got {op!r}"
            )
        if agg == "rate" and not window_s:
            raise ValueError(
                f"SLO {name!r}: agg 'rate' needs window_s > 0 (a rate over "
                "all time is just value/uptime)"
            )
        self.name = str(name)
        self.metric = str(metric)
        self.agg = str(agg)
        self.op = str(op)
        self.threshold = float(threshold)
        self.window_s = float(window_s or 0.0)
        self.for_s = float(for_s or 0.0)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "SLOSpec":
        unknown = set(d) - {"name", "metric", "agg", "op", "threshold",
                            "window_s", "for_s"}
        if unknown:
            raise ValueError(
                f"SLO spec {d.get('name', '?')!r}: unknown keys "
                f"{sorted(unknown)}"
            )
        try:
            return cls(
                name=d["name"], metric=d["metric"],
                agg=d.get("agg", "value"), op=d["op"],
                threshold=d["threshold"],
                window_s=d.get("window_s", 0.0), for_s=d.get("for_s", 0.0),
            )
        except KeyError as err:
            raise ValueError(
                f"SLO spec {d.get('name', '?')!r}: missing key {err}"
            )

    def objective(self) -> str:
        win = f" over {self.window_s:g}s" if self.window_s else ""
        return (
            f"{self.agg}({self.metric}){win} {self.op} {self.threshold:g}"
        )


def load_slo_specs(spec: str) -> list[SLOSpec]:
    """Parse ``--slo``: a path to a JSON file, or inline JSON — either a
    list of spec objects or ``{"slos": [...]}``."""
    text = spec
    if os.path.exists(spec):
        with open(spec) as fh:
            text = fh.read()
    try:
        data = json.loads(text)
    except json.JSONDecodeError as err:
        raise ValueError(
            f"--slo is neither an existing file nor valid JSON: {err}"
        )
    if isinstance(data, dict):
        data = data.get("slos", [])
    if not isinstance(data, list):
        raise ValueError("--slo JSON must be a list of specs (or {'slos': "
                         "[...]})")
    return [SLOSpec.from_dict(d) for d in data]


def _window_delta(cur: dict[str, Any], base: dict[str, Any] | None
                  ) -> dict[str, Any]:
    """The histogram observed INSIDE the window: cumulative-at-now minus
    cumulative-at-window-start, bucket-wise (exact for fixed buckets).
    Window min/max are not tracked, so they are synthesized from the
    occupied bucket span — percentile interpolation then clamps to
    bucket resolution, which is the histogram's native precision anyway.
    A negative delta (registry restarted mid-window) falls back to the
    cumulative snapshot."""
    if base is None or base.get("type") != "histogram":
        delta = dict(cur)
    else:
        counts = [a - b for a, b in zip(cur["counts"], base["counts"])]
        count = cur.get("count", 0) - base.get("count", 0)
        if count < 0 or any(c < 0 for c in counts):
            delta = dict(cur)
        else:
            delta = {
                "type": "histogram", "count": count,
                "sum": cur.get("sum", 0.0) - base.get("sum", 0.0),
                "edges": list(cur["edges"]), "counts": counts,
            }
    if delta.get("count") and "min" not in delta:
        edges, counts = delta["edges"], delta["counts"]
        occupied = [i for i, c in enumerate(counts) if c]
        lo_i, hi_i = occupied[0], occupied[-1]
        delta["min"] = edges[lo_i - 1] if lo_i > 0 else 0.0
        delta["max"] = edges[hi_i] if hi_i < len(edges) else edges[-1]
    return delta


class _AlertState:
    __slots__ = ("state", "since", "value", "ever_fired", "history")

    def __init__(self):
        self.state = "ok"  # ok | pending | firing | resolved
        self.since: float | None = None
        self.value: float | None = None
        self.ever_fired = False
        # (time, metric snapshot) baselines for windowed evaluation.
        self.history: deque[tuple[float, dict[str, Any]]] = deque()


class SLOEngine:
    """Evaluates SLO specs against a snapshot source and runs the alert
    state machine. ``snapshot_fn`` returns a metric-name → snapshot dict
    (a single :meth:`MetricRegistry.snapshot`, or the fleet-merged
    :meth:`FleetRegistry.merged` view). Not thread-safe by design: call
    :meth:`evaluate` from the one loop that owns the plane (the pacing
    engine's aggregation tick / the serving watcher); :meth:`status` only
    reads plain attributes and is safe to serve from the ops thread."""

    def __init__(self, specs, snapshot_fn,
                 metrics: MetricsLogger | None = None):
        self.specs = [
            s if isinstance(s, SLOSpec) else SLOSpec.from_dict(s)
            for s in (specs or ())
        ]
        names = [s.name for s in self.specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO spec names in {names}")
        self.snapshot_fn = snapshot_fn
        self.metrics = metrics
        self._alerts = {s.name: _AlertState() for s in self.specs}

    # -- value extraction ----------------------------------------------------

    def _measure(self, spec: SLOSpec, snap: dict[str, Any],
                 st: _AlertState, now: float) -> float | None:
        kind = snap.get("type")
        if spec.window_s > 0:
            # Keep the newest baseline at least window_s old (plus one
            # younger entry so the window never over-stretches once
            # enough history exists).
            st.history.append((now, snap))
            while (len(st.history) > 1
                   and now - st.history[1][0] >= spec.window_s):
                st.history.popleft()
            base_t, base = st.history[0]
        else:
            base_t, base = now, None

        if kind == "gauge":
            return snap.get("value")
        if kind == "counter":
            if spec.agg == "rate":
                dt = now - base_t
                if dt <= 0 or base is None:
                    return None
                return (float(snap.get("value") or 0.0)
                        - float(base.get("value") or 0.0)) / dt
            return float(snap.get("value") or 0.0)
        if kind == "histogram":
            if spec.agg == "rate":
                dt = now - base_t
                if dt <= 0 or base is None:
                    return None
                return (snap.get("count", 0) - base.get("count", 0)) / dt
            window = (
                _window_delta(snap, base) if spec.window_s > 0
                else dict(snap)
            )
            if not window.get("count"):
                return None
            if spec.agg == "mean":
                return window["sum"] / window["count"]
            if spec.agg == "value":
                return float(window["count"])
            q = {"p50": 0.5, "p95": 0.95, "p99": 0.99}[spec.agg]
            return quantile_from_snapshot(window, q)
        return None

    # -- state machine -------------------------------------------------------

    def _fields(self, spec: SLOSpec, st: _AlertState,
                **extra: Any) -> dict[str, Any]:
        fields: dict[str, Any] = dict(
            alert=spec.name, metric=spec.metric,
            threshold=spec.threshold, value=st.value,
            objective=spec.objective(),
        )
        fields.update(extra)
        return fields

    def evaluate(self, now: float | None = None) -> list[dict[str, Any]]:
        """One evaluation pass; returns the transitions that happened
        (``[{"alert", "from", "to"}]``). A missing metric or an empty
        window is "no data", which never fires (and resolves a firing
        alert only when data returns and meets the objective)."""
        if now is None:
            import time as _time

            now = _time.time()
        snapshot = self.snapshot_fn() or {}
        transitions: list[dict[str, Any]] = []
        firing = 0
        for spec in self.specs:
            st = self._alerts[spec.name]
            snap = snapshot.get(spec.metric)
            value = (
                self._measure(spec, snap, st, now)
                if isinstance(snap, dict) else None
            )
            st.value = value
            # Flight-ring breadcrumb (README "Incident forensics"): every
            # evaluated sample, not just transitions — when alert_firing
            # triggers a bundle, the ring shows the measured series
            # walking toward the threshold. No-op without a recorder.
            flightrec.note(
                self.metrics, "slo_eval", alert=spec.name,
                metric=spec.metric, value=value,
                threshold=spec.threshold, state=st.state,
            )
            met = (
                _OPS[spec.op](value, spec.threshold)
                if value is not None else None
            )
            prev = st.state
            if met is False:
                if st.state in ("ok", "resolved"):
                    st.state, st.since = "pending", now
                    if self.metrics is not None:
                        self.metrics.log(
                            "alert_pending", **self._fields(spec, st)
                        )
                if st.state == "pending" and now - st.since >= spec.for_s:
                    pending_s = now - st.since
                    st.state, st.since = "firing", now
                    st.ever_fired = True
                    if self.metrics is not None:
                        self.metrics.log(
                            "alert_firing",
                            **self._fields(spec, st, pending_s=pending_s),
                        )
            elif met is True:  # no data (None) holds the current state
                if st.state == "firing":
                    st.state, st.since = "resolved", now
                    if self.metrics is not None:
                        self.metrics.log(
                            "alert_resolved", **self._fields(spec, st)
                        )
                elif st.state == "pending":
                    # A violation that never dwelt long enough to fire
                    # clears silently — pending is not an alert yet.
                    st.state, st.since = "ok", None
            if st.state == "firing":
                firing += 1
            if st.state != prev:
                transitions.append(
                    {"alert": spec.name, "from": prev, "to": st.state}
                )
        if self.metrics is not None:
            self.metrics.registry.gauge("slo_alerts_firing").set(firing)
        return transitions

    # -- views ---------------------------------------------------------------

    def status(self) -> dict[str, Any]:
        """The live ``/alerts`` view (JSON-ready)."""
        alerts = []
        for spec in self.specs:
            st = self._alerts[spec.name]
            alerts.append({
                "alert": spec.name,
                "objective": spec.objective(),
                "state": st.state,
                "since": st.since,
                "value": st.value,
                "threshold": spec.threshold,
                "ever_fired": st.ever_fired,
            })
        return {
            "alerts": alerts,
            "firing": sum(
                1 for a in self._alerts.values() if a.state == "firing"
            ),
        }

    def ever_fired(self) -> list[str]:
        """Names of the specs that ever reached firing (the CI gate)."""
        return [name for name, st in self._alerts.items() if st.ever_fired]


def evaluate_stream(
    node_records: "dict[str, list[dict[str, Any]]]",
    specs, metrics: MetricsLogger | None = None,
) -> SLOEngine:
    """Offline SLO evaluation: replay each node's ``metrics_snapshot``
    events in global time order through a :class:`FleetRegistry` and the
    SAME :class:`SLOEngine` the live planes run — the ``slo`` CLI
    subcommand's engine. Returns the engine (query :meth:`ever_fired` /
    :meth:`status` for the verdict)."""
    fleet = FleetRegistry(metrics=metrics)
    engine = SLOEngine(specs, snapshot_fn=fleet.merged, metrics=metrics)
    timeline: list[tuple[float, str, dict[str, Any]]] = []
    for node, records in node_records.items():
        for r in records:
            if r.get("event") != "metrics_snapshot":
                continue
            t = r.get("time")
            if not isinstance(t, (int, float)):
                continue
            timeline.append((float(t), str(r.get("node") or node), r))
    timeline.sort(key=lambda item: item[0])
    for t, node, r in timeline:
        fleet.ingest(node, r.get("metrics") or {}, full=True)
        engine.evaluate(now=t)
    return engine
