"""Typed configuration for models, training, and federation.

One dataclass-based config system replacing the reference's two mechanisms
(argparse flags in ``main.py:187-205`` + hand-typed INI coercion in
``src/utils/auxiliary_functions.py:387-438``). Defaults mirror
``config/dft_params.cf`` exactly; ``from_ini`` reads the reference's INI
format for drop-in compatibility.

The reference's ``grads_to_share`` (CSV of torch state-dict keys,
``dft_params.cf:50``) generalizes here to a *pytree filter*: the same key
strings are accepted and mapped onto the Flax param/batch-stats tree (see
``gfedntm_tpu.models.params``).
"""

from __future__ import annotations

import configparser
import dataclasses
from dataclasses import dataclass, field
from typing import Any

# The reference's operative default: federate the FULL model state — all
# encoder weights, priors, beta, and batch-norm running stats
# (config/dft_params.cf:50). "SHARE_ALL" selects every param/stat leaf.
SHARE_ALL = ("__all__",)
# The reference's code-level default (server.py:71, client.py:205).
SHARE_MINIMAL = ("prior_mean", "prior_variance", "beta")


@dataclass(frozen=True)
class ModelConfig:
    """NTM hyperparameters (reference: ``[ntms]`` in dft_params.cf:6-31)."""

    n_components: int = 50
    model_type: str = "prodLDA"  # 'prodLDA' | 'LDA'
    hidden_sizes: tuple[int, ...] = (50, 50)
    activation: str = "softplus"
    dropout: float = 0.2
    learn_priors: bool = True
    topic_prior_mean: float = 0.0
    topic_prior_variance: float | None = None
    # CTM-only:
    ctm_model_type: str = "CombinedTM"  # 'CombinedTM' | 'ZeroShotTM'
    contextual_size: int = 768
    label_size: int = 0
    loss_beta_weight: float = 1.0  # ctm.py:148 weights["beta"]

    def inference_type(self, family: str) -> str:
        if family == "avitm":
            return "bow"
        return "combined" if self.ctm_model_type.lower() == "combinedtm" else "zeroshot"


@dataclass(frozen=True)
class TrainConfig:
    """Optimizer / loop hyperparameters (dft_params.cf:8-29)."""

    batch_size: int = 64
    lr: float = 2e-3
    momentum: float = 0.99  # Adam beta1 (avitm.py:142-143: betas=(momentum, 0.99))
    solver: str = "adam"  # adam | sgd | adagrad | adadelta | rmsprop
    num_epochs: int = 100
    num_samples: int = 20  # MC passes for theta inference
    reduce_on_plateau: bool = False
    thetas_thr: float = 3e-3  # federated_model.py:172 threshold
    seed: int = 0
    # TPU-specific:
    compute_dtype: str = "float32"  # 'float32' | 'bfloat16'


@dataclass(frozen=True)
class FederationConfig:
    """Federation topology + sharing policy (dft_params.cf:46-50)."""

    n_clients: int = 1
    grads_to_share: tuple[str, ...] = SHARE_ALL
    max_iters: int = 25_000  # server-driven global step cap (main.py:204)
    mesh_axis: str = "clients"


@dataclass(frozen=True)
class DataConfig:
    """Vocabulary / vectorization settings (dft_params.cf:31, client.py:358-376)."""

    max_features: int = 2000
    lowercase: bool = True
    stop_words: str | None = None  # 'english' for prepare_dataset parity
    val_fraction: float = 0.25  # data_preparation.py:30 train/val split
    split_seed: int = 42


@dataclass(frozen=True)
class GfedConfig:
    model: ModelConfig = field(default_factory=ModelConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    federation: FederationConfig = field(default_factory=FederationConfig)
    data: DataConfig = field(default_factory=DataConfig)

    def replace(self, **sections) -> "GfedConfig":
        return dataclasses.replace(self, **sections)


def _coerce(value: str) -> Any:
    """Typed coercion matching ``read_config_experiments``
    (auxiliary_functions.py:387-438): int, float, bool, tuple, None, str."""
    s = value.strip()
    if s == "":
        return None
    low = s.lower()
    if low in ("true", "false"):
        return low == "true"
    if s.startswith("(") and s.endswith(")"):
        inner = [p.strip() for p in s[1:-1].split(",") if p.strip()]
        return tuple(int(p) for p in inner)
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        pass
    return s


def from_ini(path: str) -> GfedConfig:
    """Read a reference-format INI file (``config/dft_params.cf``)."""
    cp = configparser.ConfigParser()
    with open(path) as f:
        cp.read_file(f)

    raw: dict[str, Any] = {}
    for section in cp.sections():
        for key, val in cp.items(section):
            raw[key] = _coerce(val)

    def pick(cls, **overrides):
        names = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: v for k, v in raw.items() if k in names and v is not None}
        kwargs.update(overrides)
        return cls(**kwargs)

    model = pick(ModelConfig)
    train = pick(TrainConfig)
    data = pick(DataConfig)

    gts = raw.get("grads_to_share")
    fed_kwargs: dict[str, Any] = {}
    if isinstance(gts, str):
        fed_kwargs["grads_to_share"] = tuple(
            t.strip() for t in gts.split(",") if t.strip()
        )
    federation = pick(FederationConfig, **fed_kwargs)
    return GfedConfig(model=model, train=train, federation=federation, data=data)
