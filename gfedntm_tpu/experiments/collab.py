"""Collaborative-vs-non-collaborative real-corpus experiment.

Rebuilds `experiments/collab_vs_non_collab/train.py:22-158`: given a corpus
partitioned by category (the reference's Semantic Scholar parquet partitioned
by its ``fos`` field), train **centralized** models on the full corpus over a
grid of topic counts and **non-collaborative** models per category, and score
every model with topic diversity + inverted RBO (and NPMI when a reference
corpus is supplied). The reference delegates training to TMWrapper/Mallet;
here the native :class:`gfedntm_tpu.experiments.tm_wrapper.TMWrapper` trains
the framework's own models.
"""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Mapping, Sequence

from gfedntm_tpu.experiments.tm_wrapper import TMWrapper

logger = logging.getLogger(__name__)


@dataclass
class CollabExperimentConfig:
    """Sweep configuration (reference defaults: K in {10,20,30,40,50},
    `train.py:36-38`)."""

    n_topics_grid: tuple[int, ...] = (10, 20, 30, 40, 50)
    model_type: str = "avitm"
    compute_npmi: bool = False
    model_kwargs: dict[str, Any] = field(default_factory=dict)


def run_collab_experiment(
    partitions: Mapping[str, Sequence[str]],
    models_root: str | Path,
    cfg: CollabExperimentConfig | None = None,
    results_path: str | Path | None = None,
) -> dict[str, Any]:
    """``partitions`` maps category → list of documents (the reference's
    per-``fos`` split, obtainable via
    :func:`gfedntm_tpu.data.loaders.partition_corpus`).

    Returns ``{"centralized": {K: metrics}, "non_collab": {category: {K:
    metrics}}}`` and optionally writes it as JSON."""
    cfg = cfg or CollabExperimentConfig()
    wrapper = TMWrapper(models_root)
    full_corpus = [doc for docs in partitions.values() for doc in docs]
    # Tokenize the reference corpus ONCE; every model in the sweep scores
    # against the same token lists.
    reference_corpus = (
        [doc.split() for doc in full_corpus] if cfg.compute_npmi else None
    )

    results: dict[str, Any] = {"centralized": {}, "non_collab": {}}
    for k in cfg.n_topics_grid:
        logger.info("centralized model, K=%d, %d docs", k, len(full_corpus))
        model, _ = wrapper.train_model(
            f"centralized_k{k}", full_corpus,
            model_type=cfg.model_type, n_topics=k,
            model_kwargs=cfg.model_kwargs,
        )
        results["centralized"][k] = wrapper.evaluate_model(
            model, reference_corpus
        )

    for category, docs in partitions.items():
        results["non_collab"][category] = {}
        for k in cfg.n_topics_grid:
            logger.info(
                "non-collab model %r, K=%d, %d docs", category, k, len(docs)
            )
            model, _ = wrapper.train_model(
                f"{category}_k{k}", list(docs),
                model_type=cfg.model_type, n_topics=k,
                model_kwargs=cfg.model_kwargs,
            )
            results["non_collab"][category][k] = wrapper.evaluate_model(
                model, reference_corpus
            )

    if results_path is not None:
        results_path = Path(results_path)
        results_path.parent.mkdir(parents=True, exist_ok=True)
        serializable = {
            "centralized": {
                str(k): v for k, v in results["centralized"].items()
            },
            "non_collab": {
                cat: {str(k): v for k, v in by_k.items()}
                for cat, by_k in results["non_collab"].items()
            },
        }
        with open(results_path, "w", encoding="utf8") as f:
            json.dump(serializable, f, indent=2)
    return results
