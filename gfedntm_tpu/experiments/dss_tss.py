"""DSS/TSS ground-truth recovery simulations.

Rebuilds the reference experiment `experiments/dss_tss/run_simulation.py`:
per sweep point (eta or number-of-frozen-topics), repeat ``iters`` times:
generate a synthetic multi-node LDA corpus with known topic-word
(``topic_vectors``) and doc-topic (``doc_topics``) distributions, then score

- a **centralized** model trained on the union of all node corpora,
- **non-collaborative** per-node models (scores averaged over nodes),
- a **random baseline** (Dirichlet-random betas / thetas),

with TSS (topic similarity, `run_simulation.py:321-334`) on betas reprojected
onto the full synthetic vocabulary and DSS (doc-similarity error,
`run_simulation.py:337-355`) on thetas inferred for a held-out global
inference corpus. Results aggregate to mean/std per sweep point
(`run_simulation.py:618-734`) and are saved as JSON (+ pickle of a pandas
DataFrame matching the reference artifact schema when pandas is available).
"""

from __future__ import annotations

import hashlib
import json
import logging
import pickle
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

import numpy as np

from gfedntm_tpu.data.datasets import BowDataset
from gfedntm_tpu.data.preparation import prepare_dataset
from gfedntm_tpu.data.synthetic import generate_synthetic_corpus
from gfedntm_tpu.data.vocab import vectorize
from gfedntm_tpu.eval.metrics import (
    convert_topic_word_to_init_size,
    document_similarity_score,
    topic_similarity_score,
)
from gfedntm_tpu.models.avitm import AVITM

logger = logging.getLogger(__name__)


def _jax_backend_name() -> str:
    try:
        import jax

        return jax.default_backend()
    except Exception:  # noqa: BLE001 - metadata only
        return "unknown"


@dataclass
class SimulationConfig:
    """Mirror of the reference's ``config.json`` schema
    (`experiments/dss_tss/config/*/config.json`)."""

    vocab_size: int = 5000
    n_topics: int = 50
    beta: float = 0.01          # eta: topic-word Dirichlet prior
    alpha: float = 0.1          # doc-topic Dirichlet prior (config.json)
    n_docs: int = 10000         # training docs per node
    n_docs_global_inf: int = 1000   # held-out inference docs per node
    n_nodes: int = 5
    frozen_topics: int = 5      # config.json (eta sweep regime)
    nwords: tuple[int, int] = (150, 250)
    experiment: int = 1         # 0: sweep frozen topics; 1: sweep eta
    frozen_topics_list: tuple[int, ...] = (5, 10, 15, 20, 25, 30, 35, 40)
    eta_list: tuple[float, ...] = (1e-2, 0.02, 0.03, 0.04, 0.08, 1.0)
    iters: int = 20
    # model hyperparameters (reference train_avitm: hidden (100,100), 100 ep)
    hidden_sizes: tuple[int, ...] = (100, 100)
    num_epochs: int = 100
    batch_size: int = 64
    lr: float = 2e-3
    seed: int = 0
    model_kwargs: dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_json(cls, path: str | Path) -> "SimulationConfig":
        with open(path, encoding="utf8") as f:
            info = json.load(f)
        kwargs: dict[str, Any] = {}
        for key in (
            "vocab_size", "n_topics", "beta", "alpha", "n_docs",
            "n_docs_global_inf", "n_nodes", "frozen_topics", "experiment",
            "iters",
        ):
            if key in info:
                kwargs[key] = info[key]
        if "nwords" in info:
            nw = info["nwords"]
            kwargs["nwords"] = (
                tuple(nw.values()) if isinstance(nw, dict) else tuple(nw)
            )
        for key in ("frozen_topics_list", "eta_list"):
            if key in info:
                v = info[key]
                v = v.split() if isinstance(v, str) else v
                cast = int if key == "frozen_topics_list" else float
                kwargs[key] = tuple(cast(x) for x in v)
        return cls(**kwargs)


def _train_avitm(
    corpus: list[str], cfg: SimulationConfig, seed: int
) -> tuple[AVITM, Any, dict[int, str]]:
    """Reference ``train_avitm`` (`run_simulation.py:271-318`): 25% val
    split, CountVectorizer vocab, prodLDA fit with early stopping."""
    train_data, val_data, input_size, id2token, _docs, vocab = prepare_dataset(
        corpus
    )
    model = AVITM(
        input_size=input_size,
        n_components=cfg.n_topics,
        hidden_sizes=cfg.hidden_sizes,
        batch_size=cfg.batch_size,
        num_epochs=cfg.num_epochs,
        lr=cfg.lr,
        seed=seed,
        **cfg.model_kwargs,
    )
    model.fit(train_data, val_data)
    return model, vocab, id2token


def refmap_project(
    beta: np.ndarray, id2token: dict[int, str], vocab_size: int
) -> np.ndarray:
    """The reference's ``convert_topic_word_to_init_size`` semantics,
    off-by-one included (`run_simulation.py:225-268`): the corpus generator
    names words ``wd0..wd{V-1}`` (`run_simulation.py:170-179`) but the
    scorer matches them against ``all_words = wd1..wdV``
    (`run_simulation.py:433-436`), so token ``wdN`` lands in full-vocab
    column ``N-1``, ``wd0``'s mass is silently dropped, and rows are
    L1-renormalized. Every reference TSS artifact is computed under this
    mapping; replicating it is the only way to band this repo's numbers
    against the published pickles (see results/noncollab_probe/probe.json:
    the unmodified reference implementation scores 8.28 under the correct
    mapping and 7.15 under its own — the published non-collab "gap" is this
    bug, not a model difference)."""
    out = np.zeros((beta.shape[0], vocab_size), dtype=np.float64)
    for j in range(beta.shape[1]):
        n = int(id2token[j][2:])
        if n >= 1:
            out[:, n - 1] = beta[:, j]
    out /= np.maximum(out.sum(axis=1, keepdims=True), 1e-300)
    return out


def _score_model(
    model: AVITM,
    vocab,
    id2token: dict[int, str],
    cfg: SimulationConfig,
    inf_docs: list[str],
    topic_vectors: np.ndarray,
    inf_doc_topics: np.ndarray,
) -> tuple[float, float, float]:
    """TSS on reprojected betas + DSS on inferred thetas for ``inf_docs``,
    plus TSS under the reference's shifted word mapping (``refmap``).

    Deliberate reference-replication note: the reference experiment applies
    ``softmax`` ON TOP of ``get_topic_word_distribution()`` — which is
    already row-softmaxed (``run_simulation.py:428-429`` over
    ``avitm.py:539-551``) — so its published TSS envelope (8.679 +/- 0.042,
    BASELINE.md) is computed on *double-softmaxed* (near-uniform) betas.
    The second softmax is replicated here so scores are comparable to the
    committed reference artifacts. The off-by-one word mapping is NOT
    replicated in the primary ``tss`` (it is a scoring bug, see
    :func:`refmap_project`); the ``tss_refmap`` value replicates it so the
    envelope can be banded against the reference's published numbers."""
    betas = model.get_topic_word_distribution()
    e = np.exp(betas - betas.max(axis=1, keepdims=True))
    betas = e / e.sum(axis=1, keepdims=True)  # ref's second softmax
    betas_full = convert_topic_word_to_init_size(
        cfg.vocab_size, betas, id2token
    )
    tss = topic_similarity_score(betas_full, topic_vectors)
    tss_refmap = topic_similarity_score(
        refmap_project(betas, id2token, cfg.vocab_size), topic_vectors
    )

    val_bow = vectorize(inf_docs, vocab)
    val_data = BowDataset(X=val_bow, idx2token=id2token)
    thetas_inf = model.get_doc_topic_distribution(val_data)
    dss = document_similarity_score(thetas_inf, inf_doc_topics)
    return tss, dss, tss_refmap


def run_iter_simulation(
    cfg: SimulationConfig, seed: int
) -> dict[str, dict[str, float]]:
    """One simulation iteration (`run_simulation.py:361-512`): generate,
    train all three arms, score. Returns
    ``{arm: {"betas": TSS, "thetas": DSS}}``."""
    # Independent stream for the baseline arm: the corpus generator is
    # seeded with ``seed`` and its FIRST draw is the ground-truth
    # topic_vectors, so a same-seeded generator here would "randomly" draw
    # the exact ground truth (TSS = K). The reference avoids this via the
    # global np.random stream position; here an offset seed does it
    # deterministically.
    rng = np.random.default_rng(seed + 990_001)
    docs_per_node = cfg.n_docs + cfg.n_docs_global_inf
    corpus = generate_synthetic_corpus(
        vocab_size=cfg.vocab_size,
        n_topics=cfg.n_topics,
        beta=cfg.beta,
        alpha=cfg.alpha,
        n_docs=docs_per_node,
        nwords=cfg.nwords,
        n_nodes=cfg.n_nodes,
        frozen_topics=cfg.frozen_topics,
        seed=seed,
    )
    topic_vectors = corpus.topic_vectors

    train_docs = [node.documents[: cfg.n_docs] for node in corpus.nodes]
    inf_docs = [
        doc
        for node in corpus.nodes
        for doc in node.documents[cfg.n_docs : docs_per_node]
    ]
    inf_doc_topics = np.concatenate(
        [node.doc_topics[cfg.n_docs : docs_per_node] for node in corpus.nodes]
    )

    result: dict[str, dict[str, float]] = {}

    # Baseline arm (`run_simulation.py:396-400,510-516`): betas are a fresh
    # Dirichlet(eta) draw; thetas are a fresh ``just_inf`` draw of
    # doc-topics from the SAME rotating node priors the corpus used
    # (generateSynthetic(True, False, ...)) — not a flat-alpha Dirichlet.
    random_betas = rng.dirichlet(
        np.full(cfg.vocab_size, cfg.beta), cfg.n_topics
    )
    prior_frozen = [cfg.alpha] * cfg.frozen_topics
    own = (cfg.n_topics - cfg.frozen_topics) // max(cfg.n_nodes, 1)
    prior_nofrozen = [cfg.alpha] * own + [cfg.alpha / 10000.0] * (
        cfg.n_topics - cfg.frozen_topics - own
    )
    thetas_bas = []
    for _node in range(cfg.n_nodes):
        thetas_bas.append(
            rng.dirichlet(
                np.array(prior_frozen + prior_nofrozen),
                cfg.n_docs_global_inf,
            )
        )
        prior_nofrozen = prior_nofrozen[own:] + prior_nofrozen[:own]
    random_thetas = np.concatenate(thetas_bas)
    result["baseline"] = {
        "betas": topic_similarity_score(random_betas, topic_vectors),
        "thetas": document_similarity_score(random_thetas, inf_doc_topics),
    }

    # The baseline arm draws betas directly on the full vocabulary — no
    # token-name projection is involved, so the reference's off-by-one
    # mapping cannot affect it and refmap == correct map by construction.
    result["baseline"]["betas_refmap"] = result["baseline"]["betas"]

    # Centralized arm: one model on the union of node corpora.
    logger.info("simulation: centralized arm (seed=%d)", seed)
    central_corpus = [doc for docs in train_docs for doc in docs]
    model, vocab, id2token = _train_avitm(central_corpus, cfg, seed)
    tss, dss, tss_ref = _score_model(
        model, vocab, id2token, cfg, inf_docs, topic_vectors, inf_doc_topics
    )
    result["centralized"] = {
        "betas": tss, "thetas": dss, "betas_refmap": tss_ref,
    }

    # Non-collaborative arm: per-node models, scores averaged.
    tss_nodes, dss_nodes, tss_ref_nodes = [], [], []
    for node_id in range(cfg.n_nodes):
        logger.info("simulation: non-collab node %d (seed=%d)", node_id, seed)
        model, vocab, id2token = _train_avitm(
            train_docs[node_id], cfg, seed + node_id + 1
        )
        tss, dss, tss_ref = _score_model(
            model, vocab, id2token, cfg, inf_docs, topic_vectors,
            inf_doc_topics,
        )
        tss_nodes.append(tss)
        dss_nodes.append(dss)
        tss_ref_nodes.append(tss_ref)
    result["non_colab"] = {
        "betas": float(np.mean(tss_nodes)),
        "thetas": float(np.mean(dss_nodes)),
        "betas_refmap": float(np.mean(tss_ref_nodes)),
    }
    return result


def run_simulation(
    cfg: SimulationConfig, results_dir: str | Path | None = None
) -> dict[str, Any]:
    """Full sweep (`run_simulation.py:618-734`): for each sweep point run
    ``cfg.iters`` iterations and aggregate mean/std per arm/statistic.

    Returns ``{"index": [...], "index_name": ..., "columns":
    {"<arm>_<stat>_<mean|std>": [...]}}`` and, when ``results_dir`` is given,
    writes ``results.json`` plus — if pandas is importable — the reference's
    ``results.pickle`` DataFrame artifact.

    With ``results_dir`` set, each completed iteration is also checkpointed
    to ``results_dir/iters/`` and skipped on re-run: a multi-hour sweep
    interrupted mid-way (the TPU tunnel can hang a device call indefinitely;
    the caller's watchdog kills and relaunches) resumes at the first
    unfinished iteration instead of redoing the run. Iteration results are
    seed-deterministic (``cfg.seed + 1000 * it``), so a resumed sweep equals
    an uninterrupted one."""
    if cfg.experiment == 0:
        sweep = list(cfg.frozen_topics_list)
        index_name = "Nr frozen topics"
    else:
        sweep = list(cfg.eta_list)
        index_name = "Eta"
        # The reference's eta sweep runs at frozen_topics_list[1] — NOT the
        # config.json's frozen_topics (`run_simulation.py:694-696`:
        # ``frozen_topics = frozen_topics_list[1]`` inside the eta loop).
        # With the published lists this is 10. Round <=3 artifacts ran at
        # the config value 5, which fully explains the baseline-arm DSS
        # divergence (frozen=5 random-theta DSS = 765 vs the published
        # 834.6 +/- 4.5; frozen=10 gives 833.7) and part of the non-collab
        # divergence. The override is applied to the effective base config
        # BEFORE stamping so checkpoints from the wrong regime can never be
        # silently aggregated into a corrected sweep.
        if len(cfg.frozen_topics_list) > 1:
            cfg = SimulationConfig(**{**cfg.__dict__})
            cfg.frozen_topics = int(cfg.frozen_topics_list[1])

    arms = ("centralized", "non_colab", "baseline")
    stats = ("betas", "thetas", "betas_refmap")
    columns: dict[str, list[float]] = {
        f"{arm}_{stat}_{agg}": []
        for arm in arms for stat in stats for agg in ("mean", "std")
    }
    t_start = time.perf_counter()
    # elapsed_s must record cumulative compute cost, not this process's
    # wall time: a full checkpoint-resume replays a multi-hour sweep in
    # seconds, and overwriting the field with ~0 erases the only record of
    # what the artifact cost to produce (round-4 review finding).
    prior_elapsed = 0.0
    if results_dir is not None:
        prior_json = Path(results_dir) / "results.json"
        if prior_json.exists():
            try:
                with open(prior_json, encoding="utf8") as f:
                    prior_meta = json.load(f).get("meta", {})
                # Accumulate only if the prior run is THIS experiment/regime
                # (round-4 advisor finding: a from-scratch rerun or a
                # different experiment written into the same dir would
                # inherit and compound an unrelated elapsed_s, overstating
                # the artifact's compute-cost provenance).
                if (
                    prior_meta.get("experiment") == cfg.experiment
                    and prior_meta.get("seed") == cfg.seed
                ):
                    prior_elapsed = float(prior_meta.get("elapsed_s", 0.0))
            except (ValueError, OSError):
                prior_elapsed = 0.0
    iter_backends: list[str] = []
    stat_counts: dict[str, list[int]] = {
        f"{arm}_{stat}": [] for arm in arms for stat in stats
    }

    for point in sweep:
        point_cfg = SimulationConfig(**{**cfg.__dict__})
        if cfg.experiment == 0:
            point_cfg.frozen_topics = int(point)
        else:
            point_cfg.beta = float(point)
        per_iter = {arm: {stat: [] for stat in stats} for arm in arms}
        ckpt_dir = None
        if results_dir is not None:
            # Namespace checkpoints by a config digest (everything that
            # changes iteration results except the per-point overrides and
            # the iteration count): a re-run with a different seed/regime
            # lands in a fresh subdirectory instead of silently loading the
            # old config's numbers.
            stamp_cfg = {
                k: v for k, v in sorted(cfg.__dict__.items())
                if k not in ("iters", "eta_list", "frozen_topics_list",
                             "model_kwargs")
            }
            stamp_cfg["model_kwargs"] = sorted(cfg.model_kwargs.items())
            digest = hashlib.sha256(
                repr(stamp_cfg).encode()
            ).hexdigest()[:12]
            ckpt_dir = Path(results_dir) / "iters" / digest
            ckpt_dir.mkdir(parents=True, exist_ok=True)
            stamp_path = ckpt_dir / "config_stamp.json"
            if not stamp_path.exists():
                with open(stamp_path, "w", encoding="utf8") as f:
                    json.dump(
                        {k: repr(v) for k, v in stamp_cfg.items()}, f,
                        indent=2,
                    )
        for it in range(cfg.iters):
            ckpt = (
                ckpt_dir / f"point{point}_it{it}.json"
                if ckpt_dir is not None else None
            )
            if ckpt is not None and ckpt.exists():
                with open(ckpt, encoding="utf8") as f:
                    res = json.load(f)
                logger.info("simulation: resume point=%s it=%d", point, it)
            else:
                res = run_iter_simulation(
                    point_cfg, seed=cfg.seed + 1000 * it
                )
                # Per-iteration provenance: a resumed sweep may aggregate
                # checkpoints produced on a different backend (each is a
                # legitimate sample of the same seeded experiment).
                res["_backend"] = _jax_backend_name()
                if ckpt is not None:
                    tmp = ckpt.with_suffix(".tmp")
                    with open(tmp, "w", encoding="utf8") as f:
                        json.dump(res, f)
                    tmp.rename(ckpt)
            iter_backends.append(res.get("_backend", "unknown"))
            for arm in arms:
                for stat in stats:
                    # Checkpoints written before the refmap stat existed lack
                    # it; aggregate each stat over the iterations that have
                    # it (count recorded in meta) instead of discarding
                    # banked multi-hour iterations.
                    if stat in res[arm]:
                        per_iter[arm][stat].append(res[arm][stat])
        for arm in arms:
            for stat in stats:
                vals = np.asarray(per_iter[arm][stat])
                columns[f"{arm}_{stat}_mean"].append(
                    float(vals.mean()) if vals.size else None
                )
                columns[f"{arm}_{stat}_std"].append(
                    float(vals.std()) if vals.size else None
                )
                stat_counts[f"{arm}_{stat}"].append(int(vals.size))

    backend = _jax_backend_name()
    out = {
        "index": sweep,
        "index_name": index_name,
        "columns": columns,
        # Run provenance (VERDICT r2 Weak #3: the artifact must say how it
        # was produced, not just what the numbers are).
        "meta": {
            "backend": backend,
            # Which backend actually produced each aggregated iteration
            # (checkpointed iterations may predate this process).
            "iter_backends": iter_backends,
            # Per-point sample counts per aggregated stat (refmap columns
            # can be shallower than betas/thetas when banked pre-refmap
            # checkpoints were aggregated).
            "stat_counts": stat_counts,
            "iters": cfg.iters,
            "seed": cfg.seed,
            "experiment": cfg.experiment,
            "elapsed_s": round(
                prior_elapsed + time.perf_counter() - t_start, 1
            ),
            "regime": {
                "vocab_size": cfg.vocab_size,
                "n_topics": cfg.n_topics,
                "n_nodes": cfg.n_nodes,
                "n_docs_per_node": cfg.n_docs,
                "n_docs_global_inf": cfg.n_docs_global_inf,
                # experiment 0 sweeps frozen_topics (the artifact's index);
                # recording the base config's value there would misstate how
                # the run was produced.
                "frozen_topics": (
                    list(sweep) if cfg.experiment == 0 else cfg.frozen_topics
                ),
                "alpha": cfg.alpha,
            },
        },
    }
    if results_dir is not None:
        results_dir = Path(results_dir)
        results_dir.mkdir(parents=True, exist_ok=True)
        with open(results_dir / "results.json", "w", encoding="utf8") as f:
            json.dump(out, f, indent=2)
        try:
            import pandas as pd

            df = pd.DataFrame(columns, index=pd.Index(sweep, name=index_name))
            with open(results_dir / "results.pickle", "wb") as f:
                pickle.dump(df, f)
        except ImportError:
            pass
    return out
