"""Native centralized-baseline driver (the reference's TMWrapper).

The reference's `src/aux_modules/tmWrapper/tm_wrapper.py:15-400` shells out to
the external ``topicmodeler`` git submodule (Java Mallet / torch CTM) to train
centralized baseline models, manages model folders with backup semantics
(`tm_wrapper.py:226-241`), writes train-config JSONs
(`tm_wrapper.py:123-169`), and computes post-hoc quality metrics — NPMI
coherence vs a reference corpus, RBO, topic diversity
(`tm_wrapper.py:358-400`).

This rebuild trains the framework's own TPU-native AVITM/CTM models in
process — no subprocesses, no Java — while keeping the same workflow surface:
named model folders, persisted train configs, timing, and the same metric set
(computed by :mod:`gfedntm_tpu.eval.metrics`).
"""

from __future__ import annotations

import json
import logging
import shutil
import time
from pathlib import Path
from typing import Any, Sequence

import numpy as np

from gfedntm_tpu.data.preparation import prepare_dataset, prepare_ctm_dataset
from gfedntm_tpu.eval.metrics import (
    inverted_rbo,
    npmi_coherence,
    topic_diversity,
)
from gfedntm_tpu.models.avitm import AVITM
from gfedntm_tpu.models.ctm import CombinedTM, ZeroShotTM

logger = logging.getLogger(__name__)


class TMWrapper:
    """Train/evaluate centralized topic models with managed output folders."""

    def __init__(self, models_root: str | Path):
        self.models_root = Path(models_root)
        self.models_root.mkdir(parents=True, exist_ok=True)

    # ---- folder management (`tm_wrapper.py:226-241`) -----------------------
    def _prepare_model_dir(self, name: str, overwrite: bool = True) -> Path:
        """Create the model folder; an existing one is moved aside to
        ``<name>_old`` first (reference backup semantics)."""
        model_dir = self.models_root / name
        if model_dir.exists():
            if not overwrite:
                raise FileExistsError(str(model_dir))
            backup = self.models_root / f"{name}_old"
            if backup.exists():
                shutil.rmtree(backup)
            model_dir.rename(backup)
        model_dir.mkdir(parents=True)
        return model_dir

    # ---- training ----------------------------------------------------------
    def train_model(
        self,
        name: str,
        corpus: Sequence[str],
        model_type: str = "avitm",
        n_topics: int = 25,
        embeddings: np.ndarray | None = None,
        model_kwargs: dict[str, Any] | None = None,
    ) -> tuple[Any, Path]:
        """Train one centralized model; persists the train config JSON and
        the trained model under ``models_root/name`` and returns
        ``(model, model_dir)``.

        ``model_type``: ``avitm`` (prodLDA), ``lda`` (NeuralLDA),
        ``zeroshot`` or ``combined`` (CTM — needs ``embeddings``)."""
        model_kwargs = dict(model_kwargs or {})
        model_dir = self._prepare_model_dir(name)
        t0 = time.perf_counter()

        if model_type in ("avitm", "lda", "prodlda"):
            train_data, val_data, input_size, id2token, _docs, vocab = (
                prepare_dataset(corpus)
            )
            model = AVITM(
                input_size=input_size,
                n_components=n_topics,
                model_type="LDA" if model_type == "lda" else "prodLDA",
                **model_kwargs,
            )
            model.fit(train_data, val_data)
        elif model_type in ("zeroshot", "combined"):
            if embeddings is None:
                raise ValueError(
                    f"model_type={model_type!r} needs precomputed contextual "
                    "embeddings"
                )
            (train_data, val_data, input_size, id2token, qt, _emb_train,
             _emb_all, _docs) = prepare_ctm_dataset(
                list(corpus), custom_embeddings=embeddings
            )
            cls = ZeroShotTM if model_type == "zeroshot" else CombinedTM
            model = cls(
                input_size=input_size,
                contextual_size=train_data.contextual_size,
                n_components=n_topics,
                **model_kwargs,
            )
            model.fit(train_data, val_data)
        else:
            raise ValueError(f"unknown model_type: {model_type!r}")

        elapsed = time.perf_counter() - t0
        config = {
            "name": name,
            "model_type": model_type,
            "n_topics": n_topics,
            "n_docs": len(corpus),
            "train_seconds": elapsed,
            "model_kwargs": {
                k: v for k, v in model_kwargs.items()
                if isinstance(v, (int, float, str, bool, list, tuple))
            },
        }
        with open(model_dir / "trainconfig.json", "w", encoding="utf8") as f:
            json.dump(config, f, indent=2)
        model.save(str(model_dir))
        logger.info("trained %s (%s) in %.1fs", name, model_type, elapsed)
        return model, model_dir

    # ---- hierarchical training (`tm_wrapper.py:278-357`) -------------------
    def train_htm_submodel(
        self,
        version: str,
        father_model: Any,
        father_dir: str | Path,
        corpus: Sequence[str],
        name: str,
        expansion_topic: int,
        thr: float | None = None,
        model_type: str = "avitm",
        n_topics: int = 10,
        model_kwargs: dict[str, Any] | None = None,
    ) -> tuple[Any, Path, list[str]]:
        """Train a second-level (child) model under a father model's folder.

        The reference's ``train_htm_submodel`` (`tm_wrapper.py:298-357`)
        delegates child-corpus construction to the external ``topicmodeler``
        submodule (not vendored in the reference repo) via
        ``topicmodeling.py --hierarchical``; the two HTM versions it selects
        are implemented natively here:

        - **HTM-WS** (word selection): each word occurrence in each document
          is assigned to its most responsible father topic
          (``argmax_k theta[d,k] * beta[k,w]``); the child corpus keeps, per
          document, only the words assigned to ``expansion_topic``.
          Documents left empty are dropped.
        - **HTM-DS** (document selection): the child corpus keeps the full
          text of documents whose father doc-topic weight on
          ``expansion_topic`` exceeds ``thr`` (default ``1/K_father``).

        The child model trains on the reduced corpus with its own fitted
        vocabulary and is saved under ``father_dir/name`` with a
        ``config.json`` recording ``hierarchy_level=1``, the HTM version,
        the expansion topic and the threshold (reference
        ``_get_model_config(hierarchy_level=1, ...)``,
        `tm_wrapper.py:331-341`).

        Returns ``(child_model, child_dir, child_corpus)``.
        """
        version = version.upper()
        if version not in ("HTM-WS", "HTM-DS"):
            raise ValueError(
                f"version must be 'HTM-WS' or 'HTM-DS', got {version!r}"
            )
        corpus = list(corpus)
        k_father = father_model.n_components

        # Father posteriors over ITS OWN training vocabulary: re-prepare the
        # corpus (prepare_dataset is deterministic: 75/25 split seed 42,
        # CountVectorizer vocab) so beta columns align with token ids.
        from gfedntm_tpu.data.datasets import BowDataset
        from gfedntm_tpu.data.vocab import vectorize

        _tr, _va, _size, id2token, _docs, vocab = prepare_dataset(corpus)
        bow = vectorize(corpus, vocab)
        data = BowDataset(X=bow, idx2token=id2token)
        thetas = np.asarray(father_model.get_doc_topic_distribution(data))
        betas = np.asarray(father_model.get_topic_word_distribution())
        if betas.shape[1] != bow.shape[1]:
            raise ValueError(
                f"corpus re-vectorizes to {bow.shape[1]} tokens but the "
                f"father model was trained on {betas.shape[1]} — pass the "
                "father's training corpus"
            )

        if version == "HTM-DS":
            thr = (1.0 / k_father) if thr is None else float(thr)
            keep = thetas[:, expansion_topic] > thr
            child_corpus = [corpus[i] for i in np.flatnonzero(keep)]
        else:  # HTM-WS
            tokens = [id2token[j] for j in range(len(id2token))]
            child_corpus = []
            for d in range(bow.shape[0]):
                present = np.flatnonzero(bow[d] > 0)
                if present.size == 0:
                    continue
                # responsibility argmax over father topics, per present word
                resp = thetas[d][:, None] * betas[:, present]  # [K, n_w]
                assigned = present[resp.argmax(axis=0) == expansion_topic]
                if assigned.size == 0:
                    continue
                counts = bow[d, assigned].astype(int)
                child_corpus.append(
                    " ".join(
                        " ".join([tokens[w]] * c)
                        for w, c in zip(assigned, counts)
                    )
                )
        if len(child_corpus) < 8:
            raise ValueError(
                f"{version} selected only {len(child_corpus)} documents for "
                f"topic {expansion_topic} (thr={thr}) — not enough to train "
                "a child model"
            )

        # Child folder lives inside the father's folder; train_model's
        # _prepare_model_dir supplies the reference backup semantics
        # (`tm_wrapper.py:332-346`).
        father_dir = Path(father_dir)
        child_wrapper = TMWrapper(father_dir)
        child_model, child_dir = child_wrapper.train_model(
            name, child_corpus, model_type=model_type, n_topics=n_topics,
            model_kwargs=model_kwargs,
        )
        hier_config = {
            "trainer": model_type,
            "TMparam": {
                k: v for k, v in (model_kwargs or {}).items()
                if isinstance(v, (int, float, str, bool, list, tuple))
            },
            "hierarchy_level": 1,
            "htm_version": version,
            "expansion_tpc": int(expansion_topic),
            "thr": thr,
            "father_model": str(father_dir),
            "n_child_docs": len(child_corpus),
        }
        with open(child_dir / "config.json", "w", encoding="utf8") as f:
            json.dump(hier_config, f, indent=2)
        logger.info(
            "trained %s child %s on %d docs (topic %d)",
            version, name, len(child_corpus), expansion_topic,
        )
        return child_model, child_dir, child_corpus

    # ---- metrics (`tm_wrapper.py:358-400`) ---------------------------------
    def evaluate_model(
        self,
        model: Any,
        reference_corpus: Sequence[str] | Sequence[list[str]] | None = None,
        topn: int = 10,
    ) -> dict[str, float]:
        """NPMI coherence (vs reference corpus), inverted RBO, and topic
        diversity of the trained model's topics.

        ``reference_corpus`` may be raw strings or pre-tokenized token
        lists — sweeps that score many models against one corpus should
        tokenize once and pass the token lists."""
        n_take = min(max(topn, 25), model.input_size)
        topics = model.get_topics(n_take)
        metrics: dict[str, float] = {
            "topic_diversity": topic_diversity(topics, topn=n_take),
            "inverted_rbo": inverted_rbo(topics, topn=topn),
        }
        if reference_corpus is not None:
            tokenized = [
                doc.split() if isinstance(doc, str) else doc
                for doc in reference_corpus
            ]
            metrics["npmi"] = npmi_coherence(topics, tokenized, topn=topn)
        return metrics
