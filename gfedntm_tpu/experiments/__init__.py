"""Experiment harnesses — the reference's L5 layer, rebuilt natively.

Rebuilds `experiments/dss_tss/run_simulation.py` (DSS/TSS simulations),
`experiments/collab_vs_non_collab/train.py` (real-corpus comparisons),
`src/aux_modules/tmWrapper/tm_wrapper.py` (centralized-baseline driver) and
`aux_scripts/evaluation/wmd.py` (word-mover's-distance evaluation) on top of
the TPU-native model stack — no Java Mallet, Spark, or subprocess drivers.
"""

from gfedntm_tpu.experiments.dss_tss import (  # noqa: F401
    SimulationConfig,
    run_iter_simulation,
    run_simulation,
)
from gfedntm_tpu.experiments.tm_wrapper import TMWrapper  # noqa: F401
from gfedntm_tpu.experiments.collab import (  # noqa: F401
    CollabExperimentConfig,
    run_collab_experiment,
)
from gfedntm_tpu.experiments.wmd import (  # noqa: F401
    topic_set_wmd_matrix,
    wmd_centralized_vs_nodes,
)
