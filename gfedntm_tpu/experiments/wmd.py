"""Word-mover's-distance evaluation between topic sets.

Rebuilds `aux_scripts/evaluation/wmd.py:13-110`: for every topic of a node
model, the WMD to each topic of a centralized model, summarized as the mean of
per-topic minima. The reference computes WMD with gensim's
``KeyedVectors.wmdistance`` over ``word2vec-google-news-300``; this rebuild
computes the same relaxed word-mover's distance natively from any
``{word: vector}`` mapping (numpy), and only *loading* pretrained gensim
vectors is gated on gensim being installed (it is not part of the baked
environment — SURVEY.md §2.4 treats this evaluation as an optional external
baseline).
"""

from __future__ import annotations

import logging
from typing import Mapping, Sequence

import numpy as np

logger = logging.getLogger(__name__)


def _topic_vectors(
    topic: Sequence[str], embeddings: Mapping[str, np.ndarray]
) -> np.ndarray | None:
    vecs = [np.asarray(embeddings[w]) for w in topic if w in embeddings]
    if not vecs:
        return None
    return np.stack(vecs)


def relaxed_wmd(
    words1: Sequence[str],
    words2: Sequence[str],
    embeddings: Mapping[str, np.ndarray],
) -> float:
    """Relaxed WMD (Kusner et al. 2015's RWMD lower bound, symmetrized):
    each word travels to its nearest counterpart; the distance is the max of
    the two directed means. Out-of-vocabulary words are skipped, matching
    gensim's handling; returns inf when either side is fully OOV."""
    v1 = _topic_vectors(words1, embeddings)
    v2 = _topic_vectors(words2, embeddings)
    if v1 is None or v2 is None:
        return float("inf")
    # pairwise euclidean distances [n1, n2]
    d = np.sqrt(
        np.maximum(
            (v1 * v1).sum(1)[:, None]
            - 2.0 * (v1 @ v2.T)
            + (v2 * v2).sum(1)[None, :],
            0.0,
        )
    )
    return float(max(d.min(axis=1).mean(), d.min(axis=0).mean()))


def topic_set_wmd_matrix(
    topics_a: Sequence[Sequence[str]],
    topics_b: Sequence[Sequence[str]],
    embeddings: Mapping[str, np.ndarray],
) -> np.ndarray:
    """[len(topics_a), len(topics_b)] matrix of pairwise topic WMDs
    (`wmd.py:36-57`)."""
    out = np.zeros((len(topics_a), len(topics_b)))
    for i, ta in enumerate(topics_a):
        for j, tb in enumerate(topics_b):
            out[i, j] = relaxed_wmd(ta, tb, embeddings)
    return out


def wmd_centralized_vs_nodes(
    centralized_topics: Sequence[Sequence[str]],
    node_topics: Mapping[str, Sequence[Sequence[str]]],
    embeddings: Mapping[str, np.ndarray],
) -> dict[str, float]:
    """Per node model: mean over its topics of the minimum WMD to any
    centralized topic (`wmd.py:59-80` mean-min summary). Lower = the node's
    topics are better covered by the centralized model."""
    results: dict[str, float] = {}
    for node, topics in node_topics.items():
        mat = topic_set_wmd_matrix(topics, centralized_topics, embeddings)
        mins = mat.min(axis=1)
        finite = mins[np.isfinite(mins)]
        results[node] = float(finite.mean()) if finite.size else float("inf")
    return results


def load_gensim_embeddings(
    name: str = "word2vec-google-news-300",
) -> Mapping[str, np.ndarray]:
    """Load pretrained vectors via gensim's downloader (`wmd.py:13-20`).
    Gated: raises ImportError with guidance when gensim is unavailable."""
    try:
        import gensim.downloader  # type: ignore[import-not-found]
    except ImportError as e:  # pragma: no cover - env without gensim
        raise ImportError(
            "gensim is not installed in this environment; pass any "
            "{word: vector} mapping to the WMD functions instead"
        ) from e
    return gensim.downloader.load(name)  # pragma: no cover
