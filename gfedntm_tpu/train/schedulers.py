"""Host-side learning-rate scheduling.

The reference constructs ``ReduceLROnPlateau(self.optimizer, patience=10)``
when ``reduce_on_plateau`` is set (``avitm.py:155-157``, ``ctm.py:170-172``)
but never calls ``scheduler.step`` — a vestigial wiring (SURVEY.md §2.5
policy: implement *intended* semantics). Here the torch semantics are
implemented for real: on a monitored metric plateau of ``patience`` epochs,
multiply the LR by ``factor``. The LR lives inside the optax state (via
``optax.inject_hyperparams``) so changing it between epochs does not
recompile the train program.
"""

from __future__ import annotations


class ReduceLROnPlateau:
    """torch.optim.lr_scheduler.ReduceLROnPlateau (mode='min') semantics:
    factor=0.1, patience=10, threshold=1e-4 (relative), min_lr=0."""

    def __init__(
        self,
        initial_lr: float,
        factor: float = 0.1,
        patience: int = 10,
        threshold: float = 1e-4,
        min_lr: float = 0.0,
    ):
        self.lr = float(initial_lr)
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.min_lr = min_lr
        self.best = float("inf")
        self.num_bad_epochs = 0

    def step(self, metric: float) -> float:
        """Record one epoch's monitored metric; returns the (possibly
        reduced) learning rate."""
        if metric < self.best * (1.0 - self.threshold):
            self.best = metric
            self.num_bad_epochs = 0
        else:
            self.num_bad_epochs += 1
        if self.num_bad_epochs > self.patience:
            self.lr = max(self.lr * self.factor, self.min_lr)
            self.num_bad_epochs = 0
        return self.lr


def set_learning_rate(opt_state, lr: float):
    """Write a new LR into an ``inject_hyperparams`` optax state in place
    (the state is host-side between compiled epoch programs)."""
    import jax.numpy as jnp

    if hasattr(opt_state, "hyperparams"):
        opt_state.hyperparams["learning_rate"] = jnp.asarray(
            lr, dtype=opt_state.hyperparams["learning_rate"].dtype
        )
    return opt_state
