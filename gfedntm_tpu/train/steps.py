"""Jitted training/eval/inference programs shared by centralized and
federated trainers.

The reference's per-batch Python loop (``avitm.py:231-277``) becomes a single
``lax.scan`` over a precomputed index schedule with the corpus resident in
device memory — one XLA program per epoch instead of per-batch dispatch, so
step time is dominated by the MXU matmuls, not host orchestration
(SURVEY.md §3.3 observation (a): the reference's wall-clock is orchestration-
bound).

All functions here are *factories* closing over the model/optimizer so the
returned callables are pure and jittable.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax

from gfedntm_tpu.models.losses import (
    avitm_loss,
    cross_entropy_with_logits,
    ctm_loss,
    gaussian_kl,
)
from gfedntm_tpu.models.networks import DecoderNetwork
from gfedntm_tpu.utils.observability import timed_jit

#: bfloat16 has an 8-bit significand: integers are exactly representable
#: only up to 2**8 = 256. BoW term counts above that are silently rounded
#: when x_bow rides the fused kernel's bf16 storage path (ADVICE r5).
BF16_EXACT_COUNT_MAX = 256.0


def check_bf16_bow_counts(x_bow, logger=None) -> bool:
    """Host-side screen for the bf16-storage precision hazard: returns True
    (and warns loudly through ``logger``) when ``x_bow`` carries counts
    the bf16 fused-loss storage path cannot represent exactly — i.e.
    ``max > 256``. Call it ONCE per corpus, outside jit, wherever the BoW
    matrix is staged to the device; the jitted programs cannot warn."""
    import numpy as np

    x_max = float(np.max(x_bow)) if np.size(x_bow) else 0.0
    if x_max <= BF16_EXACT_COUNT_MAX:
        return False
    if logger is not None:
        logger.warning(
            "compute_dtype='bfloat16' with BoW counts up to %.0f: bf16 "
            "represents integers exactly only up to %.0f, so the most "
            "frequent terms of long documents will be silently quantized "
            "in the fused reconstruction loss. Use compute_dtype='float32'"
            " (or cap counts in preprocessing) if exact counts matter.",
            x_max, BF16_EXACT_COUNT_MAX,
        )
    return True


def _gather_batch(data: dict[str, Any], idx: jax.Array) -> dict[str, Any]:
    return {k: jnp.take(v, idx, axis=0) for k, v in data.items() if v is not None}


def _apply_dshard(batch: dict[str, Any], mask, dshard):
    """Constrain a gathered batch's row axis onto the data mesh.

    ``dshard=(mesh, axis_name)`` is the GSPMD data-parallel hook of the
    multi-chip local-training path (``parallel.sharded.fit_data_sharded``,
    the mesh-enabled ``FederatedStepper``): the program's semantics are
    untouched — full-batch loss, full-batch (masked) BatchNorm statistics
    — and only the *placement* of the per-step batch changes, so XLA
    splits the row-wise compute across the mesh and inserts the psums the
    batch statistics need. Parity with the single-device program is
    therefore reduction-order-only (the 1e-4 band the multichip tests
    pin). The batch axis must divide the mesh (callers bucket-pad the
    schedule with :func:`pad_batch_axis` first)."""
    if dshard is None:
        return batch, mask
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh, axis = dshard

    def constrain(v):
        spec = P(axis, *([None] * (v.ndim - 1)))
        return jax.lax.with_sharding_constraint(v, NamedSharding(mesh, spec))

    return {k: constrain(v) for k, v in batch.items()}, constrain(mask)


def pad_batch_axis(indices, mask, multiple: int):
    """Bucket-pad an ``[S, B]`` epoch schedule's batch axis up to a
    multiple of ``multiple`` with masked no-op rows.

    Two jobs at once for the data-sharded training paths: (a) every
    per-step batch divides evenly over the mesh, and (b) every step of
    every epoch shares ONE padded shape, so the steady state never
    recompiles on ragged final batches. Masked rows are exact no-ops —
    the mask-aware loss and BatchNorm already guarantee this for the
    ragged-final-batch padding the schedules carry; this adds more of the
    same. The padded rows gather doc 0 (a real row, so no out-of-bounds
    clamp paths), masked to zero contribution. The first ``B`` rows of
    every step are byte-identical to the unpadded schedule, and jax's
    counter-based PRNG draws per flattened element, so the kept rows'
    dropout/reparam draws match the unpadded program's exactly."""
    import numpy as np

    from gfedntm_tpu.parallel.mesh import pad_to_multiple

    b = int(indices.shape[1])
    b_pad = pad_to_multiple(b, multiple)
    if b_pad == b:
        return indices, mask
    s = indices.shape[0]
    idx_out = np.zeros((s, b_pad), dtype=indices.dtype)
    idx_out[:, :b] = indices
    mask_out = np.zeros((s, b_pad), dtype=mask.dtype)
    mask_out[:, :b] = mask
    return idx_out, mask_out


def donation_argnums(
    argnums: tuple[int, ...], donate: bool = True
) -> tuple[int, ...]:
    """Buffer-donation argnums for the jitted training programs, gated on
    the backend: the carried state (params / batch_stats / opt_state)
    flows linearly call-to-call, so donating it lets XLA reuse the input
    HBM for the outputs instead of double-buffering the whole model+Adam
    state. On CPU the gate returns ``()`` — CPU either ignores donation
    (warning spam) or callers there legitimately re-read old state in
    parity tests — so tier-1 semantics are untouched."""
    if not donate:
        return ()
    try:
        if jax.default_backend() in ("cpu",):
            return ()
    except RuntimeError:  # no backend at all
        return ()
    return argnums


def _fused_batch_loss(module, family, beta_weight, params, batch_stats, batch,
                      mask, rngs, vshard=None):
    """Training loss via the Pallas fused decode+reconstruction kernel
    (ops/fused_decoder.py): the [B, V] word distribution never exists; the
    decoder BatchNorm's running stats are updated here from the kernel's
    batch statistics with MaskedBatchNorm's torch semantics (momentum 0.1,
    unbiased running variance).

    ``vshard=(mesh, data_axis_or_None, model_axis)`` composes the kernel
    with a GSPMD-sharded model (VERDICT r2 task 5): the loss runs inside a
    *nested* ``shard_map`` over the mesh, each device streaming its local V
    shard through the kernel, with only [B, 1]-sized online-softmax merges
    crossing the model axis (see ``prodlda_recon_loss_vsharded``). The
    encoder stays on the plain GSPMD path outside the shard_map — XLA
    already inserts its V-axis collectives."""
    from gfedntm_tpu.ops.fused_decoder import (
        prodlda_recon_loss,
        prodlda_recon_loss_vsharded,
    )

    out, mutated = module.apply(
        {"params": params, "batch_stats": batch_stats},
        batch["x_bow"],
        batch.get("x_ctx"),
        batch.get("labels"),
        train=True,
        mask=mask,
        mutable=["batch_stats"],
        rngs=rngs,
        method="encode_theta",
    )
    m = mask.astype(jnp.float32)
    bn = batch_stats["beta_batchnorm"]
    # bf16-compute models stream beta/x through the kernel in bf16 storage
    # too (f32 accumulation — see _pad_core): the loss is bandwidth-bound,
    # so halving its HBM traffic is where compute_dtype actually pays.
    # Precision assumption (ADVICE r5): bf16 storage keeps x_bow counts
    # exact only up to BF16_EXACT_COUNT_MAX — AVITM._device_data screens
    # the corpus host-side and warns once when that is violated.
    storage = (
        "bfloat16"
        if getattr(module, "dtype", jnp.float32) == jnp.bfloat16
        else "float32"
    )
    if vshard is None:
        rl, b_mean, b_var = prodlda_recon_loss(
            out.theta, params["beta"], batch["x_bow"],
            bn["running_mean"], bn["running_var"], m, True,
            1e-5, 1e-10, None, storage,
        )
    else:
        from functools import partial

        from jax.sharding import PartitionSpec as P

        from gfedntm_tpu.parallel.mesh import shard_map_compat

        mesh, data_axis, model_axis = vshard
        rl, b_mean, b_var = shard_map_compat(
            partial(
                prodlda_recon_loss_vsharded,
                model_axis=model_axis, data_axis=data_axis, training=True,
                storage_dtype=storage,
            ),
            mesh,
            in_specs=(
                P(data_axis, None),           # theta [B, K]
                P(None, model_axis),          # beta [K, V]
                P(data_axis, model_axis),     # x_bow [B, V]
                P(model_axis),                # running mean [V]
                P(model_axis),                # running var [V]
                P(data_axis),                 # mask [B]
            ),
            out_specs=(P(data_axis), P(model_axis), P(model_axis)),
            check=False,
        )(
            out.theta, params["beta"], batch["x_bow"],
            bn["running_mean"], bn["running_var"], m,
        )
    kl = gaussian_kl(
        out.prior_mean, out.prior_variance, out.posterior_mean,
        out.posterior_variance, out.posterior_log_variance,
    )
    if family == "avitm":
        loss = jnp.sum((kl + rl) * m)
    else:
        loss = jnp.sum((beta_weight * kl + rl) * m)
        if out.estimated_labels is not None:
            loss = loss + cross_entropy_with_logits(
                out.estimated_labels,
                jnp.argmax(batch["labels"], axis=1),
                sample_mask=m,
            )

    cnt = jnp.maximum(jnp.sum(m), 1.0)
    var_unbiased = b_var * (cnt / jnp.maximum(cnt - 1.0, 1.0))
    momentum = 0.1
    new_bs = dict(mutated["batch_stats"])
    new_bs["beta_batchnorm"] = {
        "running_mean": (1 - momentum) * bn["running_mean"]
        + momentum * b_mean,
        "running_var": (1 - momentum) * bn["running_var"]
        + momentum * var_unbiased,
        "num_batches_tracked": bn["num_batches_tracked"] + 1,
    }
    return loss, new_bs


def _batch_loss(module, family, beta_weight, params, batch_stats, batch, mask,
                rngs, train: bool, vshard=None):
    """Forward + reference loss on one (padded, masked) batch."""
    if (
        train
        and getattr(module, "fused_decoder", False)
        and module.model_type.lower() == "prodlda"
    ):
        return _fused_batch_loss(
            module, family, beta_weight, params, batch_stats, batch, mask,
            rngs, vshard=vshard,
        )
    out, mutated = module.apply(
        {"params": params, "batch_stats": batch_stats},
        batch["x_bow"],
        batch.get("x_ctx"),
        batch.get("labels"),
        train=train,
        mask=mask if train else None,
        mutable=["batch_stats"] if train else [],
        rngs=rngs,
    ) if train else (
        module.apply(
            {"params": params, "batch_stats": batch_stats},
            batch["x_bow"],
            batch.get("x_ctx"),
            batch.get("labels"),
            train=False,
            rngs=rngs,
        ),
        {"batch_stats": batch_stats},
    )
    # Masked (padding) rows contribute exact zeros: the network clamps
    # posterior log-variance at the source (DecoderNetwork._encode), so every
    # per-row loss term is finite and `loss * mask` has finite gradients even
    # for the all-masked zero batches of padding clients.
    m = mask.astype(jnp.float32)
    if family == "avitm":
        loss = avitm_loss(
            batch["x_bow"], out.word_dist, out.prior_mean, out.prior_variance,
            out.posterior_mean, out.posterior_variance,
            out.posterior_log_variance, sample_mask=m,
        )
    else:
        loss = ctm_loss(
            batch["x_bow"], out.word_dist, out.prior_mean, out.prior_variance,
            out.posterior_mean, out.posterior_variance,
            out.posterior_log_variance, beta_weight=beta_weight,
            estimated_labels=out.estimated_labels,
            labels_onehot=batch.get("labels"),
            sample_mask=m,
        )
    return loss, mutated["batch_stats"]


def grad_step(module, tx, family, beta_weight, params, batch_stats, opt_state,
              batch, mask, rngs, vshard=None):
    """One forward/backward/optimizer update — the single implementation of
    the training-step semantics shared by the epoch scan, the one-minibatch
    federation step, and the SPMD federated program."""

    def loss_fn(p):
        return _batch_loss(
            module, family, beta_weight, p, batch_stats, batch, mask, rngs,
            train=True, vshard=vshard,
        )

    (loss, new_bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    updates, new_opt = tx.update(grads, opt_state, params)
    new_params = optax.apply_updates(params, updates)
    return new_params, new_bs, new_opt, loss


def build_train_epoch(
    module: DecoderNetwork,
    tx: optax.GradientTransformation,
    family: str = "avitm",
    beta_weight: float = 1.0,
    vshard=None,
    metrics=None,
    label: str = "train_epoch",
    donate: bool = True,
    dshard=None,
):
    """Returns jitted ``(params, batch_stats, opt_state, data, indices, masks,
    rng) -> (params, batch_stats, opt_state, losses[S])``.

    ``data`` is a dict of device arrays ({'x_bow': [N,V], optional 'x_ctx',
    'labels'}); ``indices``/``masks`` are [S, B] (see
    ``data.datasets.make_epoch_schedule``).

    ``metrics`` (an observability MetricsLogger) wraps the returned program
    for compile capture: the first call is logged as a ``jit_compile``
    event, later dispatch latencies feed ``jit_dispatch_s/<label>``.

    ``donate`` (accelerator backends only — see :func:`donation_argnums`)
    donates the carried state buffers (params/batch_stats/opt_state) so
    the epoch program updates the model in place in HBM; callers must
    treat the state they passed in as consumed, which every in-repo
    caller already does (state is reassigned from the outputs).

    ``dshard=(mesh, axis_name)`` (see :func:`_apply_dshard`) runs the SAME
    program data-parallel over a mesh: each gathered batch's rows are
    sharding-constrained onto the mesh, XLA splits the row-wise compute
    and inserts the batch-statistic psums. Mutually exclusive with the
    fused Pallas loss (which composes with meshes via ``vshard`` instead).
    """
    if dshard is not None and getattr(module, "fused_decoder", False):
        raise ValueError(
            "dshard (GSPMD data-parallel) does not compose with the fused "
            "Pallas decoder; use the V-sharded vshard path "
            "(parallel.sharded.fit_sharded) or fused_decoder=False"
        )

    def train_epoch(params, batch_stats, opt_state, data, indices, masks, rng):
        def body(carry, xs):
            params, batch_stats, opt_state = carry
            idx, mask, i = xs
            step_rng = jax.random.fold_in(rng, i)
            rngs = {
                "dropout": jax.random.fold_in(step_rng, 0),
                "reparam": jax.random.fold_in(step_rng, 1),
            }
            batch = _gather_batch(data, idx)
            batch, mask = _apply_dshard(batch, mask, dshard)
            new_params, new_bs, new_opt, loss = grad_step(
                module, tx, family, beta_weight, params, batch_stats,
                opt_state, batch, mask, rngs, vshard=vshard,
            )
            return (new_params, new_bs, new_opt), loss

        steps = indices.shape[0]
        (params, batch_stats, opt_state), losses = jax.lax.scan(
            body,
            (params, batch_stats, opt_state),
            (indices, masks, jnp.arange(steps)),
        )
        return params, batch_stats, opt_state, losses

    return timed_jit(
        jax.jit(
            train_epoch,
            donate_argnums=donation_argnums((0, 1, 2), donate),
        ),
        metrics, label,
    )


def build_train_step(
    module: DecoderNetwork,
    tx: optax.GradientTransformation,
    family: str = "avitm",
    beta_weight: float = 1.0,
    metrics=None,
    label: str = "train_step",
    donate: bool = False,
    dshard=None,
):
    """Jitted ONE-minibatch step: ``(params, batch_stats, opt_state, data,
    idx[B], mask[B], rng) -> (params, batch_stats, opt_state, loss)``.

    The externally-stepped federation protocol (``train_mb_delta``,
    ``federated_avitm.py:51-83``) drives this once per server poll; the
    whole-epoch ``lax.scan`` programs above stay the fast path for
    single-program training. ``metrics`` adds first-call compile capture
    (see :func:`~gfedntm_tpu.utils.observability.timed_jit`). ``donate``
    defaults OFF here (unlike the epoch program): the stepper snapshots
    shared parameters between steps, so in-place state is opt-in.
    ``dshard=(mesh, axis)`` data-shards the minibatch over a mesh (the
    federation client's multi-chip local step — see :func:`_apply_dshard`;
    the caller bucket-pads ``idx``/``mask`` with :func:`pad_batch_axis`)."""
    if dshard is not None and getattr(module, "fused_decoder", False):
        raise ValueError(
            "dshard (GSPMD data-parallel) does not compose with the fused "
            "Pallas decoder; use fused_decoder=False for mesh-sharded "
            "federation clients"
        )

    def train_step(params, batch_stats, opt_state, data, idx, mask, rng):
        rngs = {
            "dropout": jax.random.fold_in(rng, 0),
            "reparam": jax.random.fold_in(rng, 1),
        }
        batch = _gather_batch(data, idx)
        batch, mask = _apply_dshard(batch, mask, dshard)
        return grad_step(
            module, tx, family, beta_weight, params, batch_stats, opt_state,
            batch, mask, rngs,
        )

    return timed_jit(
        jax.jit(
            train_step,
            donate_argnums=donation_argnums((0, 1, 2), donate),
        ),
        metrics, label,
    )


def build_eval_epoch(
    module: DecoderNetwork, family: str = "avitm", beta_weight: float = 1.0,
    metrics=None, label: str = "eval_epoch",
):
    """Jitted validation epoch: eval-mode forward (running BN stats, fresh
    reparam draws — ``avitm.py:295-319`` semantics), per-step summed losses."""

    def eval_epoch(params, batch_stats, data, indices, masks, rng):
        def body(carry, xs):
            idx, mask, i = xs
            step_rng = jax.random.fold_in(rng, i)
            rngs = {"reparam": jax.random.fold_in(step_rng, 1)}
            batch = _gather_batch(data, idx)
            loss, _ = _batch_loss(
                module, family, beta_weight, params, batch_stats, batch, mask,
                rngs, train=False,
            )
            return carry, loss

        steps = indices.shape[0]
        _, losses = jax.lax.scan(
            body, None, (indices, masks, jnp.arange(steps))
        )
        return losses

    return timed_jit(jax.jit(eval_epoch), metrics, label)


def build_infer_theta(module: DecoderNetwork, n_samples: int = 20,
                      metrics=None, label: str = "infer_theta"):
    """Jitted MC doc-topic inference (``avitm.py:470-523``): average of
    ``n_samples`` reparameterized theta draws per document, batched via scan,
    samples via vmap (all MC passes share one data load — the reference
    re-reads the corpus n_samples times)."""

    def infer(params, batch_stats, data, indices, rng):
        variables = {"params": params, "batch_stats": batch_stats}

        def body(carry, xs):
            idx, i = xs
            batch = _gather_batch(data, idx)

            def one_sample(s):
                return module.apply(
                    variables,
                    batch["x_bow"],
                    batch.get("x_ctx"),
                    batch.get("labels"),
                    method=DecoderNetwork.get_theta,
                    rngs={"reparam": jax.random.fold_in(jax.random.fold_in(rng, i), s)},
                )

            thetas = jax.vmap(one_sample)(jnp.arange(n_samples))
            return carry, jnp.mean(thetas, axis=0)

        steps = indices.shape[0]
        _, thetas = jax.lax.scan(body, None, (indices, jnp.arange(steps)))
        return thetas.reshape(-1, thetas.shape[-1])

    return timed_jit(jax.jit(infer), metrics, label)


def init_variables(
    module: DecoderNetwork,
    batch_size: int,
    vocab_size: int,
    contextual_size: int = 0,
    label_size: int = 0,
    seed: int = 0,
):
    """Initialize {params, batch_stats} with dummy batches (shape-only)."""
    x_bow = jnp.zeros((batch_size, vocab_size), jnp.float32)
    x_ctx = (
        jnp.zeros((batch_size, contextual_size), jnp.float32)
        if contextual_size
        else None
    )
    labels = (
        jnp.zeros((batch_size, label_size), jnp.float32) if label_size else None
    )
    key = jax.random.PRNGKey(seed)
    k_param, k_rep, k_drop = jax.random.split(key, 3)
    variables = module.init(
        {"params": k_param, "reparam": k_rep, "dropout": k_drop},
        x_bow,
        x_ctx,
        labels,
        train=True,
    )
    return variables["params"], variables.get("batch_stats", {})


def full_batch_indices(n_docs: int, batch_size: int) -> tuple:
    """Unshuffled padded index/mask arrays covering a dataset once
    (inference order, DataLoader(shuffle=False) — avitm.py:489-491)."""
    import numpy as np

    steps = max(1, -(-n_docs // batch_size))
    idx = np.zeros(steps * batch_size, dtype=np.int32)
    idx[:n_docs] = np.arange(n_docs)
    mask = np.zeros(steps * batch_size, dtype=bool)
    mask[:n_docs] = True
    return idx.reshape(steps, batch_size), mask.reshape(steps, batch_size)
