"""Orbax checkpointing of train state — including federated resume.

The reference has NO resume path for the federated loop (SURVEY.md §5: a
restart redoes consensus and training from scratch; its initial-NN/Adam-state
transfer at ``server.py:303-311`` only *starts* clients identically). Here
the whole federation state — per-client params, batch stats, optimizer state,
and the global step counter — is one pytree, checkpointed atomically with
orbax and restored onto the same mesh sharding.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp


class CheckpointManager:
    """Thin orbax wrapper: numbered step checkpoints under one directory."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, state: Any, force: bool = False) -> None:
        self._mgr.save(
            step, args=ocp.args.StandardSave(_to_numpy(state)), force=force
        )
        self._mgr.wait_until_finished()

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore(self, target: Any, step: int | None = None) -> Any:
        """Restore into the structure/shardings of ``target`` (a live state
        pytree — e.g. the freshly initialized one)."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, target)
        return self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract)
        )

    def close(self) -> None:
        self._mgr.close()


def _to_numpy(tree: Any) -> Any:
    return jax.tree.map(np.asarray, tree)
