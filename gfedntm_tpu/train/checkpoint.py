"""Orbax checkpointing of train state — including federated resume.

The reference has NO resume path for the federated loop (SURVEY.md §5: a
restart redoes consensus and training from scratch; its initial-NN/Adam-state
transfer at ``server.py:303-311`` only *starts* clients identically). Here
the whole federation state — per-client params, batch stats, optimizer state,
and the global step counter — is one pytree, checkpointed atomically with
orbax and restored onto the same mesh sharding.

:class:`FederationCheckpointer` extends the same machinery to the NETWORK
server's round state (``last_average`` + round counter + membership
snapshot + consensus vocabulary), so a crashed
:class:`~gfedntm_tpu.federation.server.FederatedServer` resumes from its
last checkpointed round instead of round 0.
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp


class CheckpointIntegrityError(RuntimeError):
    """A federation checkpoint is unusable (truncated/corrupt sidecar JSON,
    or the sidecar and the orbax round directories disagree). Raised with
    an actionable message instead of letting a raw ``JSONDecodeError`` /
    ``KeyError`` traceback surface mid ``--resume``."""


def _fsync_dir(path: str) -> None:
    """fsync the directory entry so a rename survives a power cut — on
    filesystems without O_DIRECTORY fsync (or exotic mounts) this is
    best-effort, the data-file fsync below is the hard guarantee."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Crash-safe file replacement: write a temp sibling, fsync it, then
    ``os.replace`` over the target and fsync the directory. A kill at ANY
    point leaves either the old complete file or the new complete file —
    never the truncated half-write PR 5's ``CheckpointIntegrityError``
    detects after the fact. The temp name is pid-suffixed so two processes
    racing the same target cannot corrupt each other's staging file."""
    tmp = f"{path}.tmp.{os.getpid()}"
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
    try:
        with os.fdopen(fd, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        # The staging file must not accumulate on failure; the original
        # target is untouched either way.
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(os.path.dirname(os.path.abspath(path)))


def atomic_write_json(path: str, obj: Any) -> None:
    atomic_write_bytes(path, json.dumps(obj).encode("utf-8"))


def _load_sidecar_meta(path: str, what: str, hint: str) -> dict[str, Any] | None:
    """Shared loader for the JSON halves of federation recovery state
    (checkpoint sidecar + round journal): ``None`` when absent; corrupt
    JSON or missing required keys (``round``, ``average_keys``) raise
    :class:`CheckpointIntegrityError` carrying ``what``/``hint`` — one
    integrity contract, two callers."""
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        try:
            meta = json.load(fh)
        except json.JSONDecodeError as err:
            raise CheckpointIntegrityError(
                f"{what} {path} is truncated or corrupt ({err}); {hint}"
            ) from err
    missing = [k for k in ("round", "average_keys") if k not in meta]
    if missing:
        raise CheckpointIntegrityError(
            f"{what} {path} is missing required keys {missing}; {hint}"
        )
    return meta


class CheckpointManager:
    """Thin orbax wrapper: numbered step checkpoints under one directory."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, state: Any, force: bool = False) -> None:
        self._mgr.save(
            step, args=ocp.args.StandardSave(_to_numpy(state)), force=force
        )
        self._mgr.wait_until_finished()

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def all_steps(self) -> list[int]:
        return sorted(self._mgr.all_steps())

    def restore(self, target: Any, step: int | None = None) -> Any:
        """Restore into the structure/shardings of ``target`` (a live state
        pytree — e.g. the freshly initialized one)."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, target)
        return self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract)
        )

    def close(self) -> None:
        self._mgr.close()


def _to_numpy(tree: Any) -> Any:
    return jax.tree.map(np.asarray, tree)


class FederationCheckpointer:
    """Round-state checkpoints for the network federation server.

    The numeric state — the shared-subset ``last_average`` — rides the
    orbax :class:`CheckpointManager` (as a list of arrays: flat-dict keys
    contain ``/`` which orbax would misread as tree structure, so the key
    order is pinned in the JSON sidecar instead). Everything orbax cannot
    hold — the consensus vocabulary, the sorted average keys, and the
    membership snapshot — lives in an atomically-replaced
    ``federation.json`` next to the round directories. The orbax
    ``latest_step`` is the authoritative resume round; the sidecar is
    rewritten after each array save, and :meth:`restore_round` verifies the
    two agree — after a crash between the writes it falls back (loudly) to
    the round the sidecar describes when that round is still on disk,
    while a corrupt/truncated sidecar or an unreconcilable mismatch
    surfaces as :class:`CheckpointIntegrityError` with a recovery hint,
    never as a raw traceback mid ``--resume``.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = CheckpointManager(
            os.path.join(self.directory, "rounds"), max_to_keep=max_to_keep
        )
        self.meta_path = os.path.join(self.directory, "federation.json")
        self.aggregator_path = os.path.join(
            self.directory, "aggregator_state.npz"
        )

    def save_round(
        self,
        round_idx: int,
        average: dict[str, np.ndarray],
        membership: list[dict[str, Any]],
        vocab: list[str] | None = None,
        extra: dict[str, Any] | None = None,
        aggregator_state: dict[str, np.ndarray] | None = None,
    ) -> None:
        keys = sorted(average)
        # Idempotent per round: the server's final checkpoint can land on
        # the same round as the last periodic one (orbax raises
        # StepAlreadyExistsError on a re-save, even with force=True), and
        # a given round's state is the same state.
        if self._mgr.latest_step() == int(round_idx):
            return
        self._mgr.save(
            int(round_idx), [np.asarray(average[k]) for k in keys],
            force=True,
        )
        # Server-aggregator optimizer state (FedAvgM/FedAdam momenta — a
        # flat npz-able array dict, see aggregation.ServerAggregator):
        # saved NEXT TO the orbax rounds, tagged with its round so a crash
        # between the two writes is detected at restore instead of pairing
        # round-R parameters with round-R' moments.
        if aggregator_state:
            atomic_write_bytes(
                self.aggregator_path,
                _npz_bytes(aggregator_state, round_idx),
            )
        elif os.path.exists(self.aggregator_path):
            # Stateless aggregator now: a stale state file from an earlier
            # configuration must not survive to poison a later resume.
            os.remove(self.aggregator_path)
        meta = {
            "round": int(round_idx),
            "average_keys": keys,
            "membership": membership,
            **(extra or {}),
        }
        if vocab is not None:
            meta["vocab"] = list(vocab)
        atomic_write_json(self.meta_path, meta)

    def load_aggregator_state(
        self,
    ) -> "tuple[int, dict[str, np.ndarray]] | None":
        """The ``(round, arrays)`` saved by the last :meth:`save_round`, or
        ``None`` when the aggregator was stateless (no file)."""
        if not os.path.exists(self.aggregator_path):
            return None
        try:
            with np.load(self.aggregator_path) as data:
                arrays = {k: data[k] for k in data.files if k != "__round__"}
                return int(data["__round__"]), arrays
        except (OSError, ValueError, KeyError) as err:
            raise CheckpointIntegrityError(
                f"aggregator state {self.aggregator_path} is corrupt "
                f"({err}); delete it to restart the server optimizer cold"
            ) from err

    def latest_round(self) -> int | None:
        return self._mgr.latest_step()

    def load_meta(self) -> dict[str, Any] | None:
        """The sidecar metadata, or ``None`` when absent. A sidecar that
        exists but cannot be parsed (truncated write, disk corruption) or
        lacks its required keys raises :class:`CheckpointIntegrityError`
        with a recovery hint rather than a raw traceback."""
        return _load_sidecar_meta(
            self.meta_path, "federation sidecar",
            f"restore it from a backup, or delete the checkpoint "
            f"directory {self.directory} to start the federation fresh",
        )

    def restore_round(
        self, template: dict[str, np.ndarray], step: int | None = None
    ) -> tuple[int, dict[str, np.ndarray]]:
        """Restore ``(round_idx, average)``; ``template`` supplies the
        expected key set and array shapes (e.g. the shared flat subset of a
        freshly built template model)."""
        meta = self.load_meta()
        if meta is None:
            raise FileNotFoundError(f"no federation meta at {self.meta_path}")
        keys = meta["average_keys"]
        missing = [k for k in keys if k not in template]
        if missing:
            raise ValueError(
                f"checkpoint avg keys not in template (model config "
                f"changed since the checkpoint?): {missing[:3]}"
            )
        explicit_step = step is not None
        step = self.latest_round() if step is None else step
        if step is None:
            raise FileNotFoundError(
                f"no round checkpoint under {self.directory}"
            )
        meta_round = int(meta["round"])
        if not explicit_step and meta_round != int(step):
            # The two halves are written orbax-first, sidecar-second, so a
            # crash between the writes leaves the sidecar one checkpoint
            # behind the newest orbax round. The round the sidecar DOES
            # describe is usually still on disk (max_to_keep > 1): resume
            # from it — loudly — instead of pairing round-R arrays with
            # round-R' metadata or demanding manual surgery.
            if meta_round in self._mgr.all_steps():
                import logging

                logging.getLogger("FederationCheckpointer").warning(
                    "checkpoint sidecar describes round %d but the newest "
                    "orbax round is %d (crash between the two writes?); "
                    "resuming from round %d, whose halves agree",
                    meta_round, int(step), meta_round,
                )
                step = meta_round
            else:
                raise CheckpointIntegrityError(
                    f"checkpoint round mismatch under {self.directory}: "
                    f"the orbax rounds are {self._mgr.all_steps()} but "
                    f"the sidecar {self.meta_path} describes round "
                    f"{meta_round}, which is not among them (mixed runs "
                    "or corruption); delete the checkpoint directory to "
                    "start fresh"
                )
        arrays = self._mgr.restore(
            [np.asarray(template[k]) for k in keys], step=step
        )
        return int(step), dict(zip(keys, (np.asarray(a) for a in arrays)))

    def close(self) -> None:
        self._mgr.close()


def _npz_bytes(arrays: dict[str, np.ndarray], round_idx: int) -> bytes:
    import io

    buf = io.BytesIO()
    np.savez(buf, __round__=np.int64(round_idx), **arrays)
    return buf.getvalue()


#: npz key prefix separating journaled aggregator slots from average keys.
_AGG_PREFIX = "__agg__/"


class RoundJournal:
    """Per-round crash-recovery journal for the federation server.

    The orbax :class:`FederationCheckpointer` is the *rollback-quality*
    store: guardian-gated, written every ``checkpoint_every`` rounds, the
    target a divergence rollback restores. This journal is the *crash
    recovery* store: one cheap atomic write per pushed round (a flat npz
    of the broadcast average + aggregator slots, and a JSON record of the
    round, key order, membership — session tokens included — and
    consensus vocabulary), so a SIGKILLed server restarted with NO
    operator flags resumes from the last fully-pushed round and replays
    at most the one round that was in flight at the kill.

    Both files go through :func:`atomic_write_bytes` (temp + fsync +
    ``os.replace`` + directory fsync): a kill mid-write can never produce
    a truncated journal. The npz is written first, the JSON second; the
    JSON's ``round`` must match the npz's ``__round__`` tag, so a kill
    between the two writes is detected at load (the stale JSON describes
    the previous round whose npz was just overwritten) and reported as
    :class:`CheckpointIntegrityError` — the caller degrades to the orbax
    checkpoint.
    """

    STATE_NAME = "journal_state.npz"
    META_NAME = "journal.json"

    def __init__(self, directory: str):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.state_path = os.path.join(self.directory, self.STATE_NAME)
        self.meta_path = os.path.join(self.directory, self.META_NAME)

    def record(
        self,
        round_idx: int,
        average: dict[str, np.ndarray],
        membership: list[dict[str, Any]],
        vocab: list[str] | None = None,
        extra: dict[str, Any] | None = None,
        aggregator_state: dict[str, np.ndarray] | None = None,
    ) -> None:
        """Journal one fully-pushed round (arrays first, meta second)."""
        keys = sorted(average)
        arrays = {k: np.asarray(average[k]) for k in keys}
        for name, arr in (aggregator_state or {}).items():
            arrays[_AGG_PREFIX + name] = np.asarray(arr)
        atomic_write_bytes(self.state_path, _npz_bytes(arrays, round_idx))
        meta = {
            "round": int(round_idx),
            "average_keys": keys,
            "membership": membership,
            **(extra or {}),
        }
        if vocab is not None:
            meta["vocab"] = list(vocab)
        atomic_write_json(self.meta_path, meta)

    def mark_finished(self) -> None:
        """Stamp the journal after a normal stop broadcast: a finished
        federation must not be resurrected by the next server start's
        auto-recovery probe."""
        meta = None
        try:
            meta = self.load_meta()
        except CheckpointIntegrityError:
            meta = None
        if meta is None:
            meta = {"round": -1, "average_keys": [], "membership": []}
        meta["finished"] = True
        atomic_write_json(self.meta_path, meta)

    def load_meta(self) -> dict[str, Any] | None:
        """The journal's JSON record, or ``None`` when absent; corrupt or
        key-incomplete JSON raises :class:`CheckpointIntegrityError` with
        a recovery hint (same contract as the checkpoint sidecar)."""
        return _load_sidecar_meta(
            self.meta_path, "round journal",
            "delete it to fall back to the latest orbax checkpoint",
        )

    def load(self, include_finished: bool = False) -> "dict[str, Any] | None":
        """Load the journaled round: a dict with ``round``, ``average``,
        ``aggregator_state``, ``membership``, ``vocab``, and every extra
        key the writer recorded — or ``None`` when no journal exists (or
        it is marked finished — ``include_finished=True`` loads it
        anyway: the SERVING plane wants a cleanly-finished run's final
        model, which only auto-recovery must never resurrect). Integrity
        failures (corrupt JSON/npz, or a round tag disagreement from a
        kill between the two writes) raise
        :class:`CheckpointIntegrityError`."""
        meta = self.load_meta()
        if meta is None or (meta.get("finished") and not include_finished):
            return None
        if not os.path.exists(self.state_path):
            raise CheckpointIntegrityError(
                f"round journal {self.meta_path} describes round "
                f"{meta['round']} but {self.state_path} is missing; "
                "delete the journal to fall back to the latest checkpoint"
            )
        try:
            with np.load(self.state_path) as data:
                state_round = int(data["__round__"])
                arrays = {
                    k: np.asarray(data[k])
                    for k in data.files if k != "__round__"
                }
        except (OSError, ValueError, KeyError, EOFError) as err:
            raise CheckpointIntegrityError(
                f"round journal state {self.state_path} is corrupt "
                f"({err}); delete the journal to fall back to the latest "
                "checkpoint"
            ) from err
        if state_round != int(meta["round"]):
            raise CheckpointIntegrityError(
                f"round journal halves disagree under {self.directory}: "
                f"meta describes round {meta['round']} but the state file "
                f"is round {state_round} (kill between the two writes); "
                "delete the journal to fall back to the latest checkpoint"
            )
        average: dict[str, np.ndarray] = {}
        agg_state: dict[str, np.ndarray] = {}
        for key, arr in arrays.items():
            if key.startswith(_AGG_PREFIX):
                agg_state[key[len(_AGG_PREFIX):]] = arr
            else:
                average[key] = arr
        missing = [k for k in meta["average_keys"] if k not in average]
        if missing:
            raise CheckpointIntegrityError(
                f"round journal state {self.state_path} lacks average "
                f"keys {missing[:3]} its meta declares; delete the "
                "journal to fall back to the latest checkpoint"
            )
        out = dict(meta)
        out["round"] = state_round
        out["average"] = average
        out["aggregator_state"] = agg_state
        return out
