"""Global-model divergence detection + rollback bookkeeping.

The admission gate (:mod:`gfedntm_tpu.federation.sanitize`) and the robust
aggregators (:mod:`gfedntm_tpu.federation.aggregation`) screen *individual*
updates, but a federation can still diverge: a coordinated majority, a bad
hyperparameter interaction with an adaptive server optimizer, or a slow
poisoning below every per-round threshold all corrupt the global model
*gradually*. :class:`DivergenceGuardian` is the backstop — it watches the
aggregate itself and tells the server when to roll back to the last good
:class:`~gfedntm_tpu.train.checkpoint.FederationCheckpointer` round.

Health signals, per averaged round:

- **finiteness** of the new global average — a NaN/Inf global is
  *immediately* divergent (no patience): pushing it once poisons every
  client irrecoverably under per-minibatch averaging;
- **round loss** (the *median* of the accepted replies' ``StepReply.loss``
  — the loss scalar is client-reported and attacker-controlled, so a mean
  would let one byzantine reply force rollbacks at will; non-finite
  reports are ignored unless they are ALL non-finite) against its own
  EWMA: ``loss > loss_factor * EWMA`` for ``patience`` consecutive rounds
  is a divergence;
- **global parameter norm** against its EWMA, same patience rule — loss can
  look flat while parameters silently blow up (the classic softmax
  saturation failure).

The EWMAs only absorb *healthy* rounds, so a slowly exploding loss cannot
drag its own baseline along with it. The guardian also remembers which
clients' accepted updates (by admitted weight) dominated the unhealthy
streak, so the server can quarantine the likely culprits at rollback time.
"""

from __future__ import annotations

import logging
from typing import Any, Iterable, Mapping

import numpy as np

__all__ = ["DivergenceGuardian"]

# Divergence reason codes (the `divergence_rollback` event vocabulary).
NONFINITE_GLOBAL = "nonfinite_global"
LOSS_EXPLOSION = "loss_explosion"
NORM_EXPLOSION = "norm_explosion"


def _global_norm(average: Mapping[str, np.ndarray]) -> float:
    total = 0.0
    for value in average.values():
        arr = np.asarray(value, np.float64).ravel()
        total += float(np.dot(arr, arr))
    return float(np.sqrt(total))


class DivergenceGuardian:
    """Rolling health watch over the server's round aggregates.

    ``patience`` consecutive unhealthy rounds (or one non-finite global)
    constitute a divergence; ``loss_factor`` / ``norm_factor`` set how far
    above its EWMA a signal must move to count as unhealthy. ``observe``
    returns the divergence reason (or ``None``); the caller performs the
    actual rollback and then calls :meth:`note_rollback` to reset the
    baselines against the restored state.
    """

    def __init__(
        self,
        patience: int = 3,
        loss_factor: float = 4.0,
        norm_factor: float = 10.0,
        ewma_alpha: float = 0.3,
        dominance_factor: float = 2.0,
        metrics: Any = None,
        logger: logging.Logger | None = None,
    ):
        if patience < 1:
            raise ValueError(f"patience must be >= 1, got {patience}")
        if loss_factor <= 1.0 or norm_factor <= 1.0:
            raise ValueError(
                "loss_factor/norm_factor must be > 1 (an explosion "
                "threshold at or below the baseline flags every round)"
            )
        if not 0.0 < ewma_alpha <= 1.0:
            raise ValueError(
                f"ewma_alpha must be in (0, 1], got {ewma_alpha}"
            )
        self.patience = int(patience)
        self.loss_factor = float(loss_factor)
        self.norm_factor = float(norm_factor)
        self.ewma_alpha = float(ewma_alpha)
        self.dominance_factor = float(dominance_factor)
        self.metrics = metrics
        self.logger = logger or logging.getLogger("DivergenceGuardian")
        self._loss_ewma: float | None = None
        self._norm_ewma: float | None = None
        self._streak = 0
        # Admitted weight per client over the CURRENT unhealthy streak —
        # the attribution base for the rollback quarantine.
        self._streak_weight: dict[int, float] = {}

    # ---- health state -------------------------------------------------------
    @property
    def healthy(self) -> bool:
        """True while no unhealthy streak is open — the server only writes
        round checkpoints in this state, so the checkpoint it would roll
        back to can never itself be mid-divergence."""
        return self._streak == 0

    def _ewma(self, current: float | None, value: float) -> float:
        if current is None:
            return value
        return (1.0 - self.ewma_alpha) * current + self.ewma_alpha * value

    # ---- per-round observation ----------------------------------------------
    def observe(
        self,
        round_idx: int,
        losses: Iterable[float],
        average: Mapping[str, np.ndarray],
        contributors: "Iterable[tuple[int, float]]" = (),
    ) -> str | None:
        """Digest one averaged round; returns a divergence reason code or
        None. ``losses`` are the accepted replies' reported losses (the
        gate already dropped rejected clients — their losses must not move
        the health baseline); ``contributors`` are ``(client_id,
        admitted_weight)`` pairs for quarantine attribution."""
        for key in sorted(average):
            arr = np.asarray(average[key])
            if arr.dtype.kind == "f" and not np.all(np.isfinite(arr)):
                self._streak = max(self._streak, 1)
                self._note_contributors(contributors)
                self.logger.error(
                    "round %d: global average tensor %r is non-finite",
                    round_idx, key,
                )
                return NONFINITE_GLOBAL

        losses = [float(v) for v in losses]
        finite = [v for v in losses if np.isfinite(v)]
        # Median, not mean: StepReply.loss is client-reported. A single
        # byzantine client whose tensors pass the gate could still report
        # loss=NaN/1e30 and, under a mean, trip a rollback every `patience`
        # rounds forever — a training-denial loop. The median moves only
        # when a majority of the admitted cohort reports an explosion.
        round_loss = float(np.median(finite)) if finite else float("nan")
        norm = _global_norm(average)
        reason = None
        if losses and not finite:
            # every admitted contributor reports a non-finite loss: the
            # fleet already computes on poisoned state
            reason = LOSS_EXPLOSION
        elif (
            self._loss_ewma is not None
            and np.isfinite(round_loss)
            and round_loss > self.loss_factor * abs(self._loss_ewma)
        ):
            reason = LOSS_EXPLOSION
        elif (
            self._norm_ewma is not None
            and norm > self.norm_factor * max(self._norm_ewma, 1e-12)
        ):
            reason = NORM_EXPLOSION

        if reason is None:
            self._streak = 0
            self._streak_weight.clear()
            if np.isfinite(round_loss):
                self._loss_ewma = self._ewma(self._loss_ewma, round_loss)
            self._norm_ewma = self._ewma(self._norm_ewma, norm)
            return None

        self._streak += 1
        self._note_contributors(contributors)
        self.logger.warning(
            "round %d unhealthy (%s: loss %.4g vs EWMA %s, norm %.4g vs "
            "EWMA %s) — streak %d/%d",
            round_idx, reason, round_loss, self._loss_ewma, norm,
            self._norm_ewma, self._streak, self.patience,
        )
        if self.metrics is not None:
            self.metrics.registry.counter("unhealthy_rounds").inc()
        if self._streak >= self.patience:
            return reason
        return None

    def _note_contributors(self, contributors) -> None:
        for client_id, weight in contributors:
            self._streak_weight[client_id] = (
                self._streak_weight.get(client_id, 0.0) + float(weight)
            )

    # ---- rollback support ----------------------------------------------------
    def dominant_contributors(self) -> list[int]:
        """Clients whose admitted weight over the unhealthy streak exceeds
        ``dominance_factor`` x the equal share — the quarantine candidates.
        Empty when influence was evenly spread (quarantining everyone is
        quarantining no one)."""
        total = sum(self._streak_weight.values())
        n = len(self._streak_weight)
        if n < 2 or total <= 0:
            return []
        cutoff = self.dominance_factor * total / n
        return sorted(
            cid for cid, w in self._streak_weight.items() if w > cutoff
        )

    def note_rollback(self) -> None:
        """Reset every baseline after the server restored a checkpoint:
        the EWMAs describe the diverged trajectory, not the restored one."""
        self._loss_ewma = None
        self._norm_ewma = None
        self._streak = 0
        self._streak_weight.clear()
