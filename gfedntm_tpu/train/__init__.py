from gfedntm_tpu.train import checkpoint as checkpoint
from gfedntm_tpu.train import early_stopping as early_stopping
from gfedntm_tpu.train import optimizers as optimizers
from gfedntm_tpu.train import schedulers as schedulers
from gfedntm_tpu.train import steps as steps
from gfedntm_tpu.train.checkpoint import CheckpointManager
from gfedntm_tpu.train.early_stopping import EarlyStopping
from gfedntm_tpu.train.optimizers import build_optimizer
from gfedntm_tpu.train.schedulers import ReduceLROnPlateau, set_learning_rate
