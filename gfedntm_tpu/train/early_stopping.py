"""Early stopping on validation loss (reference ``pytorchtools.py:4-55``).

Semantics preserved: score = -val_loss; an epoch "improves" when
``score >= best + delta``; otherwise a patience counter increments and
training stops when it reaches ``patience``. On improvement an optional
checkpoint callback fires (the reference calls ``model.save(path)``).
"""

from __future__ import annotations

from typing import Callable


class EarlyStopping:
    def __init__(
        self,
        patience: int = 5,
        delta: float = 0.0,
        checkpoint_fn: Callable[[], None] | None = None,
        verbose: bool = False,
    ):
        self.patience = patience
        self.delta = delta
        self.checkpoint_fn = checkpoint_fn
        self.verbose = verbose
        self.counter = 0
        self.best_score: float | None = None
        self.early_stop = False
        self.val_loss_min = float("inf")

    def __call__(self, val_loss: float) -> None:
        score = -val_loss
        if self.best_score is None:
            self.best_score = score
            self._checkpoint(val_loss)
        elif score < self.best_score + self.delta:
            self.counter += 1
            if self.counter >= self.patience:
                self.early_stop = True
        else:
            self.best_score = score
            self._checkpoint(val_loss)
            self.counter = 0

    def _checkpoint(self, val_loss: float) -> None:
        if self.checkpoint_fn is not None:
            self.checkpoint_fn()
        self.val_loss_min = val_loss
