"""Optimizer factory matching the reference's torch solvers.

Reference: ``avitm.py:140-153`` / ``ctm.py:158-168`` build one of
{adam, sgd, adagrad, adadelta, rmsprop}; Adam notably uses
``betas=(momentum, 0.99)`` with the config default momentum=0.99
(``dft_params.cf:15``). optax's adam matches torch's bias-corrected update
for identical (b1, b2, eps).
"""

from __future__ import annotations

import optax


def build_optimizer(
    solver: str = "adam", lr: float = 2e-3, momentum: float = 0.99
) -> optax.GradientTransformation:
    solver = solver.lower()
    if solver == "adam":
        return optax.adam(lr, b1=momentum, b2=0.99, eps=1e-8)
    if solver == "sgd":
        return optax.sgd(lr, momentum=momentum)
    if solver == "adagrad":
        # torch Adagrad: lr_decay=0, eps=1e-10
        return optax.adagrad(lr, eps=1e-10)
    if solver == "adadelta":
        # torch Adadelta defaults: rho=0.9, eps=1e-6
        return optax.adadelta(lr, rho=0.9, eps=1e-6)
    if solver == "rmsprop":
        # torch RMSprop defaults: alpha=0.99, eps=1e-8
        return optax.rmsprop(lr, decay=0.99, eps=1e-8, momentum=momentum)
    raise ValueError(
        "solver must be 'adam', 'adadelta', 'sgd', 'rmsprop' or 'adagrad', "
        f"got {solver!r}"
    )
