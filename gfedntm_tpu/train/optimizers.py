"""Optimizer factory matching the reference's torch solvers.

Reference: ``avitm.py:140-153`` / ``ctm.py:158-168`` build one of
{adam, sgd, adagrad, adadelta, rmsprop}; Adam notably uses
``betas=(momentum, 0.99)`` with the config default momentum=0.99
(``dft_params.cf:15``). optax's adam matches torch's bias-corrected update
for identical (b1, b2, eps).
"""

from __future__ import annotations

import optax


def copy_for_donation(tree):
    """Device-side copy of a carried-state tree (params / batch_stats /
    optimizer state) that is about to be fed to a donating program.

    The donation seam of the multi-chip training paths: donating epoch
    programs CONSUME their state inputs on accelerators
    (``train.steps.donation_argnums``), so any caller that must keep its
    copy alive across the call — a trainer's cached initial state, a
    model object whose ``opt_state`` is also read by the host-side LR
    scheduler after the epoch returns fresh outputs, a bench that re-fits
    from the same init — hands the program this copy instead. A
    state-sized device copy is ~free next to corpus staging, and on CPU
    (where donation is gated off) ``jnp.copy`` is still correct, just
    unnecessary. Non-array leaves pass through untouched."""
    import jax
    import jax.numpy as jnp

    return jax.tree.map(
        lambda leaf: jnp.copy(leaf) if hasattr(leaf, "shape") else leaf,
        tree,
    )


def build_optimizer(
    solver: str = "adam",
    lr: float = 2e-3,
    momentum: float = 0.99,
    inject_lr: bool = False,
) -> optax.GradientTransformation:
    """``inject_lr`` wraps the solver in ``optax.inject_hyperparams`` so the
    learning rate becomes part of the optimizer state and can be changed
    between epochs (ReduceLROnPlateau) without recompiling."""
    solver = solver.lower()
    if solver == "adam":
        fn = lambda learning_rate: optax.adam(  # noqa: E731
            learning_rate, b1=momentum, b2=0.99, eps=1e-8
        )
    elif solver == "sgd":
        fn = lambda learning_rate: optax.sgd(  # noqa: E731
            learning_rate, momentum=momentum
        )
    elif solver == "adagrad":
        # torch Adagrad: lr_decay=0, eps=1e-10
        fn = lambda learning_rate: optax.adagrad(  # noqa: E731
            learning_rate, eps=1e-10
        )
    elif solver == "adadelta":
        # torch Adadelta defaults: rho=0.9, eps=1e-6
        fn = lambda learning_rate: optax.adadelta(  # noqa: E731
            learning_rate, rho=0.9, eps=1e-6
        )
    elif solver == "rmsprop":
        # torch RMSprop defaults: alpha=0.99, eps=1e-8
        fn = lambda learning_rate: optax.rmsprop(  # noqa: E731
            learning_rate, decay=0.99, eps=1e-8, momentum=momentum
        )
    else:
        raise ValueError(
            "solver must be 'adam', 'adadelta', 'sgd', 'rmsprop' or "
            f"'adagrad', got {solver!r}"
        )
    if inject_lr:
        return optax.inject_hyperparams(fn)(learning_rate=lr)
    return fn(lr)
