"""Wire-compression strategies for the federation hot path.

The protocol exchanges full client parameter bundles every round, so the
wire moves ``2 x clients x |theta|`` float32 bytes per round — the dominant
cost of the network path (the FL communication survey, arXiv:2405.20431,
names update compression the highest-leverage lever for exactly this
shape). This module layers a :class:`WireCodec` strategy *under*
:mod:`gfedntm_tpu.federation.codec`: the proto schema is unchanged except
for three additive ``TensorRecord`` fields (``codec``/``aux``/``wire_dtype``)
and a ``TensorBundle.ref_round`` tag.

Three composable stages, spec'd as a ``+``-joined string (the **codec id**
negotiated at join time):

- ``delta`` — encode values relative to the last *broadcast aggregate* both
  endpoints hold. One optimizer step moves parameters a little; the delta's
  dynamic range is tiny, which is what makes the lossy stages cheap.
- ``topk:<frac>`` — keep only the largest-magnitude ``frac`` of each
  tensor's (delta) entries, shipping ``uint32`` indices + values. Lossy;
  the dropped mass goes into a per-endpoint **error-feedback residual**
  that is added back before the next selection, so nothing is lost
  permanently — only delayed. Implies ``delta`` (top-k of raw parameters
  would zero most of the model).
- ``fp16`` / ``bf16`` — quantize the transmitted values buffer; decode
  upcasts to the logical dtype recorded on the wire.

Reference discipline (the part that makes delta safe): every delta-encoded
bundle carries ``ref_round`` = 1 + the round whose broadcast it is relative
to. Decoders that do not hold that exact reference **fail loudly**
(:class:`ReferenceMismatch`) instead of mis-decoding; the server keeps a
small cache of recent broadcast views so a client that missed one push
still decodes, and only delta-encodes a push when every recipient of the
previous one acked it.

Integer/bool tensors and zero-size arrays always ride raw records — the
lossy stages are float-only by construction.
"""

from __future__ import annotations

import math
import time
from collections import OrderedDict
from typing import Any, Mapping

import numpy as np

from gfedntm_tpu.federation import codec
from gfedntm_tpu.federation.protos import federated_pb2 as pb

__all__ = [
    "CodecError",
    "ReferenceMismatch",
    "WireCodec",
    "make_codec",
    "UplinkEncoder",
    "UplinkDecoder",
    "DownlinkEncoder",
    "DownlinkDecoder",
]


class CodecError(ValueError):
    """A bundle could not be decoded under the negotiated codec."""


class ReferenceMismatch(CodecError):
    """A delta bundle references a broadcast this endpoint does not hold."""


_QUANT_DTYPES = {"fp16": "float16", "bf16": "bfloat16"}


class WireCodec:
    """Parsed, canonicalized compression spec (the negotiated codec id).

    ``spec`` is ``None``/``""``/``"none"``/``"identity"`` for the identity
    codec, else a ``+``-joined subset of ``delta``, ``topk:<frac>``,
    ``fp16``/``bf16`` in any order. ``topk`` implies ``delta``.
    """

    def __init__(self, spec: str | None = None):
        self.delta = False
        self.topk_frac: float | None = None
        self.quant: str | None = None  # wire dtype name or None
        raw = (spec or "none").strip().lower()
        if raw not in ("none", "identity"):
            for stage in raw.split("+"):
                stage = stage.strip()
                if stage == "delta":
                    self.delta = True
                elif stage.startswith("topk:"):
                    frac = float(stage.split(":", 1)[1])
                    if not 0.0 < frac <= 1.0:
                        raise ValueError(
                            f"topk fraction must be in (0, 1], got {frac}"
                        )
                    self.topk_frac = frac
                elif stage in _QUANT_DTYPES:
                    if self.quant is not None:
                        raise ValueError(f"duplicate quantize stage in {raw!r}")
                    self.quant = _QUANT_DTYPES[stage]
                else:
                    raise ValueError(
                        f"unknown codec stage {stage!r} in {raw!r} "
                        "(want delta, topk:<frac>, fp16, bf16)"
                    )
            if self.topk_frac is not None:
                self.delta = True  # top-k without a base zeroes the model

    @property
    def codec_id(self) -> str:
        """Canonical spec string — the value negotiated on the wire."""
        stages = []
        if self.delta:
            stages.append("delta")
        if self.topk_frac is not None:
            stages.append(f"topk:{self.topk_frac:g}")
        if self.quant is not None:
            stages.append("fp16" if self.quant == "float16" else "bf16")
        return "+".join(stages) or "none"

    @property
    def identity(self) -> bool:
        return not (self.delta or self.topk_frac is not None or self.quant)

    @property
    def lossy(self) -> bool:
        return self.topk_frac is not None or self.quant is not None

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"WireCodec({self.codec_id!r})"


def make_codec(spec: "str | WireCodec | None") -> WireCodec:
    return spec if isinstance(spec, WireCodec) else WireCodec(spec)


def _compressible(arr: np.ndarray) -> bool:
    """Lossy/delta stages apply to non-empty float tensors only."""
    return arr.dtype.kind == "f" and arr.size > 0


def _note_wire(metrics, op: str, raw_bytes: int, wire_bytes: int) -> None:
    """Registry-only wire telemetry: cumulative raw-vs-compressed bytes and
    the running compression ratio (ISSUE knobs: ``compressed_bytes`` +
    compression-ratio gauge). Per-call JSONL events would dominate the
    stream at one encode/decode per client per round."""
    reg = metrics.registry
    raw_c = reg.counter(f"uncompressed_bytes_{op}")
    cmp_c = reg.counter(f"compressed_bytes_{op}")
    raw_c.inc(raw_bytes)
    cmp_c.inc(wire_bytes)
    reg.counter("compressed_bytes").inc(wire_bytes)
    total_raw = raw_c.value
    total_cmp = cmp_c.value
    if total_cmp > 0:
        reg.gauge(f"compression_ratio_{op}").set(total_raw / total_cmp)


class _Session:
    """Shared encode/decode machinery for one direction of the wire.

    Holds no policy about *which* reference to use — subclasses manage
    reference lifetime (single last-applied aggregate client-side, a small
    round-keyed cache server-side) and whether an error-feedback residual
    is carried.
    """

    def __init__(self, codec_: WireCodec, metrics=None, role: str = ""):
        self.codec = make_codec(codec_)
        self.metrics = metrics
        self.role = role
        self.residual: dict[str, np.ndarray] | None = (
            {} if self.codec.lossy else None
        )

    def reset(self) -> None:
        """Forget every delta reference and error-feedback residual this
        session carries — the divergence-rollback path (README "Robust
        aggregation & divergence recovery"): after the server restores a
        checkpointed round, references derived from the diverged trajectory
        must not be decoded (or deltaed) against, and residuals holding
        un-delivered diverged mass must not be re-injected into the
        restored state. The next encode after a reset is self-contained."""
        if self.residual is not None:
            self.residual = {}
        if self.metrics is not None:
            self.metrics.registry.counter("codec_resets").inc()

    # ---- encode ------------------------------------------------------------
    def _encode(
        self,
        tensors: Mapping[str, np.ndarray],
        reference: "dict[str, np.ndarray] | None",
        ref_round: int,
    ) -> tuple[pb.TensorBundle, dict[str, np.ndarray]]:
        """Encode ``tensors`` into a bundle; returns ``(bundle, view)``
        where ``view`` is exactly what the decoder will reconstruct (the
        residual bookkeeping and reference chains are built from it)."""
        c = self.codec
        t0 = time.perf_counter() if self.metrics is not None else 0.0
        use_delta = c.delta and reference is not None
        records = []
        view: dict[str, np.ndarray] = {}
        raw_bytes = 0
        for name in sorted(tensors):
            arr = np.asarray(tensors[name])
            raw_bytes += arr.nbytes
            if not _compressible(arr) or c.identity:
                records.append(codec.array_to_record(name, arr))
                view[name] = arr
                continue
            base = None
            if use_delta:
                if name not in reference:
                    raise CodecError(
                        f"delta encode: no reference for tensor {name!r}"
                    )
                base = np.asarray(reference[name])
            d = (arr - base) if base is not None else arr
            if self.residual is not None:
                prev = self.residual.get(name)
                if prev is not None:
                    d = d + prev
            # Top-k is only meaningful on *deltas* — sparsifying raw
            # parameters (first round, or a push to a not-yet-synced
            # fleet) would zero most of the model. Without a base the
            # tensor ships dense (still quantized).
            rec, recon_d = self._compress_values(
                name, arr, d, sparse_ok=base is not None
            )
            if self.residual is not None:
                self.residual[name] = d - recon_d
            records.append(rec)
            view[name] = (
                (base + recon_d) if base is not None else recon_d
            ).astype(arr.dtype)
        bundle = pb.TensorBundle(
            tensors=records,
            # proto3 cannot distinguish 0 from unset, so the wire carries
            # round + 1; 0 means "self-contained bundle".
            ref_round=(ref_round + 1) if use_delta else 0,
        )
        if self.metrics is not None:
            self.metrics.registry.histogram(
                f"wire_encode_s/{self.role or 'wire'}"
            ).observe(time.perf_counter() - t0)
            _note_wire(self.metrics, "sent", raw_bytes, bundle.ByteSize())
        return bundle, view

    def _compress_values(
        self, name: str, arr: np.ndarray, d: np.ndarray,
        sparse_ok: bool = True,
    ) -> tuple[pb.TensorRecord, np.ndarray]:
        """Top-k select + quantize the (delta) values ``d``; returns the
        wire record and the dense reconstruction the decoder will see."""
        c = self.codec
        flat = np.ascontiguousarray(d).reshape(-1)
        wire_dtype = c.quant  # None = ship at logical dtype
        if sparse_ok and c.topk_frac is not None and c.topk_frac < 1.0:
            k = max(1, math.ceil(c.topk_frac * flat.size))
            if k < flat.size:
                idx = np.argpartition(np.abs(flat), flat.size - k)[-k:]
            else:
                idx = np.arange(flat.size)
            idx = np.sort(idx).astype(np.uint32)
            values = flat[idx]
            if wire_dtype is not None:
                values = values.astype(codec.np_dtype(wire_dtype))
            recon_flat = np.zeros_like(flat)
            recon_flat[idx] = values.astype(flat.dtype)
            rec = pb.TensorRecord(
                name=name, shape=list(arr.shape), dtype=arr.dtype.name,
                codec="topk", data=values.tobytes(), aux=idx.tobytes(),
                wire_dtype=wire_dtype or "",
            )
            return rec, recon_flat.reshape(d.shape)
        values = flat
        if wire_dtype is not None:
            values = values.astype(codec.np_dtype(wire_dtype))
        recon = values.astype(flat.dtype).reshape(d.shape)
        rec = pb.TensorRecord(
            name=name, shape=list(arr.shape), dtype=arr.dtype.name,
            codec="dense", data=values.tobytes(),
            wire_dtype=wire_dtype or "",
        )
        return rec, recon

    # ---- decode ------------------------------------------------------------
    def _decode(
        self,
        bundle: pb.TensorBundle,
        reference: "dict[str, np.ndarray] | None",
    ) -> dict[str, np.ndarray]:
        t0 = time.perf_counter() if self.metrics is not None else 0.0
        delta_bundle = bundle.ref_round > 0
        if delta_bundle and reference is None:
            raise ReferenceMismatch(
                f"bundle references broadcast round {bundle.ref_round - 1} "
                "but this endpoint holds no such reference"
            )
        out: dict[str, np.ndarray] = {}
        raw_bytes = 0
        for rec in bundle.tensors:
            if rec.codec in ("", "raw"):
                arr = codec.record_to_array(rec)
            elif rec.codec in ("dense", "topk"):
                arr = self._decode_values(rec)
                if delta_bundle:
                    base = reference.get(rec.name)
                    if base is None:
                        raise ReferenceMismatch(
                            f"delta bundle tensor {rec.name!r} has no "
                            "reference entry"
                        )
                    arr = (np.asarray(base) + arr).astype(arr.dtype)
            else:
                raise CodecError(
                    f"unknown record codec {rec.codec!r} for {rec.name!r}"
                )
            out[rec.name] = arr
            raw_bytes += arr.nbytes
        if self.metrics is not None:
            self.metrics.registry.histogram(
                f"wire_decode_s/{self.role or 'wire'}"
            ).observe(time.perf_counter() - t0)
            _note_wire(self.metrics, "recv", raw_bytes, bundle.ByteSize())
        return out

    @staticmethod
    def _decode_values(rec: pb.TensorRecord) -> np.ndarray:
        if rec.dtype not in codec.ALLOWED_DTYPES:
            raise CodecError(f"dtype {rec.dtype!r} not allowed on the wire")
        wire = rec.wire_dtype or rec.dtype
        if wire not in codec.WIRE_DTYPES:
            raise CodecError(f"wire dtype {wire!r} not allowed on the wire")
        values = np.frombuffer(rec.data, dtype=codec.np_dtype(wire))
        values = values.astype(codec.np_dtype(rec.dtype))
        shape = tuple(rec.shape)
        if rec.codec == "dense":
            return values.reshape(shape)
        idx = np.frombuffer(rec.aux, dtype=np.uint32)
        if idx.size != values.size:
            raise CodecError(
                f"topk record {rec.name!r}: {idx.size} indices for "
                f"{values.size} values"
            )
        numel = int(np.prod(shape, dtype=np.int64)) if shape else 1
        if idx.size and int(idx.max()) >= numel:
            raise CodecError(
                f"topk record {rec.name!r}: index {int(idx.max())} out of "
                f"range for {numel} elements"
            )
        dense = np.zeros(numel, dtype=values.dtype)
        dense[idx] = values
        return dense.reshape(shape)


class UplinkEncoder(_Session):
    """Client side of the StepReply path: encodes post-step snapshots
    relative to the last *applied* aggregate, carrying the error-feedback
    residual across rounds."""

    def __init__(self, codec_: WireCodec, metrics=None, role: str = "uplink"):
        super().__init__(codec_, metrics=metrics, role=role)
        self._ref: dict[str, np.ndarray] | None = None
        self._ref_round = -1

    def reset(self) -> None:
        """Drop the applied-aggregate reference AND the error-feedback
        residual (a rollback re-broadcast's ``reset_session``): the next
        snapshot is encoded self-contained and carries no mass from the
        discarded trajectory."""
        self._ref = None
        self._ref_round = -1
        super().reset()

    def note_aggregate(
        self, tensors: Mapping[str, np.ndarray], round_idx: int
    ) -> None:
        """Record the aggregate this client just applied — the reference the
        next snapshot is delta-encoded against."""
        if self.codec.delta:
            self._ref = {k: np.asarray(v) for k, v in tensors.items()}
            self._ref_round = int(round_idx)

    def encode(self, snapshot: Mapping[str, np.ndarray]) -> pb.TensorBundle:
        bundle, _view = self._encode(snapshot, self._ref, self._ref_round)
        return bundle


class UplinkDecoder(_Session):
    """Server side of the StepReply path. Keeps a small round-keyed cache of
    *client-held views* of recent broadcasts (what :class:`DownlinkEncoder`
    reports each push reconstructs to), so a client whose last push was one
    or two rounds stale still decodes; anything older raises
    :class:`ReferenceMismatch` — loud, and healed by the next push."""

    def __init__(self, codec_: WireCodec, metrics=None, max_refs: int = 8,
                 role: str = "uplink"):
        super().__init__(codec_, metrics=metrics, role=role)
        self.max_refs = int(max_refs)
        self._refs: "OrderedDict[int, dict[str, np.ndarray]]" = OrderedDict()
        self.residual = None  # decode side carries no residual

    def note_push(
        self, round_idx: int, client_view: Mapping[str, np.ndarray]
    ) -> None:
        if not self.codec.delta:
            return
        self._refs[int(round_idx)] = dict(client_view)
        while len(self._refs) > self.max_refs:
            self._refs.popitem(last=False)

    def reset(self) -> None:
        """Drop the whole broadcast-view cache (divergence rollback): an
        uplink deltaed against a pre-rollback broadcast now raises
        :class:`ReferenceMismatch` — loud, and healed by the rolled-back
        re-broadcast."""
        self._refs.clear()
        super().reset()

    def decode(self, bundle: pb.TensorBundle) -> dict[str, np.ndarray]:
        reference = None
        if bundle.ref_round > 0:
            reference = self._refs.get(bundle.ref_round - 1)
            if reference is None:
                raise ReferenceMismatch(
                    f"no cached broadcast view for round "
                    f"{bundle.ref_round - 1} (cache holds "
                    f"{sorted(self._refs)})"
                )
        return self._decode(bundle, reference)


class DownlinkEncoder(_Session):
    """Server side of the Aggregate push path. Deltas against the previous
    *broadcast view* — but only when the caller says every recipient holds
    it (``allow_delta``; the server tracks push acks). Carries the broadcast
    error-feedback residual so lossy pushes never lose mass permanently."""

    def __init__(self, codec_: WireCodec, metrics=None,
                 role: str = "downlink"):
        super().__init__(codec_, metrics=metrics, role=role)
        self._last_view: dict[str, np.ndarray] | None = None
        self._last_round = -1

    def reset(self) -> None:
        """Forget the last broadcast view (divergence rollback): the next
        push is encoded self-contained regardless of ``allow_delta``."""
        self._last_view = None
        self._last_round = -1
        super().reset()

    @property
    def last_round(self) -> int:
        """Round of the broadcast a delta push would reference (-1 =
        none yet). Under cohort/async pacing recipients hold broadcasts
        of different rounds, so the server's ``allow_delta`` check
        compares each recipient's last-acked round to THIS — not merely
        membership in an acked set."""
        return self._last_round

    def encode(
        self,
        average: Mapping[str, np.ndarray],
        round_idx: int,
        allow_delta: bool = False,
    ) -> tuple[pb.TensorBundle, dict[str, np.ndarray]]:
        """Returns ``(bundle, client_view)`` — feed ``client_view`` to
        :meth:`UplinkDecoder.note_push` (it is the exact tensor set every
        client that applies this push will hold)."""
        reference = self._last_view if allow_delta else None
        ref_round = self._last_round if allow_delta else -1
        bundle, view = self._encode(average, reference, ref_round)
        self._last_view = view
        self._last_round = int(round_idx)
        return bundle, view


class DownlinkDecoder(_Session):
    """Client side of the Aggregate push path: holds the single last-applied
    broadcast view as the delta reference."""

    def __init__(self, codec_: WireCodec, metrics=None,
                 role: str = "downlink"):
        super().__init__(codec_, metrics=metrics, role=role)
        self._ref: dict[str, np.ndarray] | None = None
        self._ref_round = -1
        self.residual = None

    def reset(self) -> None:
        """Drop the last-applied broadcast reference (a rollback
        re-broadcast's ``reset_session``); the incoming push must then be
        self-contained."""
        self._ref = None
        self._ref_round = -1
        super().reset()

    def decode(
        self, bundle: pb.TensorBundle, round_idx: int
    ) -> dict[str, np.ndarray]:
        if bundle.ref_round > 0 and bundle.ref_round - 1 != self._ref_round:
            raise ReferenceMismatch(
                f"push deltas against broadcast round {bundle.ref_round - 1} "
                f"but this client last applied round {self._ref_round}"
            )
        out = self._decode(
            bundle, self._ref if bundle.ref_round > 0 else None
        )
        if self.codec.delta:
            self._ref = dict(out)
            self._ref_round = int(round_idx)
        return out
