"""Wire-compression strategies for the federation hot path.

The protocol exchanges full client parameter bundles every round, so the
wire moves ``2 x clients x |theta|`` float32 bytes per round — the dominant
cost of the network path (the FL communication survey, arXiv:2405.20431,
names update compression the highest-leverage lever for exactly this
shape). This module layers a :class:`WireCodec` strategy *under*
:mod:`gfedntm_tpu.federation.codec`: the proto schema is unchanged except
for three additive ``TensorRecord`` fields (``codec``/``aux``/``wire_dtype``)
and a ``TensorBundle.ref_round`` tag.

Three composable stages, spec'd as a ``+``-joined string (the **codec id**
negotiated at join time):

- ``delta`` — encode values relative to the last *broadcast aggregate* both
  endpoints hold. One optimizer step moves parameters a little; the delta's
  dynamic range is tiny, which is what makes the lossy stages cheap.
- ``topk:<frac>`` — keep only the largest-magnitude ``frac`` of each
  tensor's (delta) entries, shipping ``uint32`` indices + values. Lossy;
  the dropped mass goes into a per-endpoint **error-feedback residual**
  that is added back before the next selection, so nothing is lost
  permanently — only delayed. Implies ``delta`` (top-k of raw parameters
  would zero most of the model).
- ``fp16`` / ``bf16`` — quantize the transmitted values buffer; decode
  upcasts to the logical dtype recorded on the wire.

Reference discipline (the part that makes delta safe): every delta-encoded
bundle carries ``ref_round`` = 1 + the round whose broadcast it is relative
to. Decoders that do not hold that exact reference **fail loudly**
(:class:`ReferenceMismatch`) instead of mis-decoding; the server keeps a
small cache of recent broadcast views so a client that missed one push
still decodes.

Per-recipient push encoding (README "Hierarchical federation & wire
efficiency"): the downlink maintains one **canonical view chain** —
``view_i = view_{i-1} + recon(compress(avg_i - view_{i-1} + residual))``,
exactly the PR 3 consecutive-round delta stream — and every recipient of a
push converges onto the round's canonical view regardless of how far
behind it was:

- a recipient holding the immediately-previous view gets the canonical
  chain bundle (computed once per round, shared);
- a recipient holding an older cached view gets an exact **catch-up**
  bundle: the entries where the canonical view changed since its round,
  shipped as *assignment* records (``sparse_set``: uint32 indices + values
  at the logical dtype) so the reconstruction is bit-exact — additive
  float deltas would drift by an ulp and silently corrupt the uplink
  reference chain;
- a recipient with no usable reference (fresh join, or its view was
  evicted from the bounded cache) gets a self-contained view bundle (raw
  records of the canonical view) — degraded compression, never an error.

Both reference caches (uplink broadcast views, downlink canonical views)
are bounded LRU keyed by round; evictions are instrumented
(``codec_refs_evicted`` counter, eviction-age gauge, ``codec_ref_evicted``
events) and degrade to self-contained pushes / loud
:class:`ReferenceMismatch` heals, never to mis-decodes.

Integer/bool tensors and zero-size arrays always ride raw records — the
lossy stages are float-only by construction.
"""

from __future__ import annotations

import math
import time
from collections import OrderedDict
from typing import Any, Mapping

import numpy as np

from gfedntm_tpu.federation import codec
from gfedntm_tpu.federation.protos import federated_pb2 as pb

__all__ = [
    "CodecError",
    "ReferenceMismatch",
    "WireCodec",
    "make_codec",
    "UplinkEncoder",
    "UplinkDecoder",
    "DownlinkEncoder",
    "DownlinkDecoder",
]


class CodecError(ValueError):
    """A bundle could not be decoded under the negotiated codec."""


class ReferenceMismatch(CodecError):
    """A delta bundle references a broadcast this endpoint does not hold."""


_QUANT_DTYPES = {"fp16": "float16", "bf16": "bfloat16"}


class WireCodec:
    """Parsed, canonicalized compression spec (the negotiated codec id).

    ``spec`` is ``None``/``""``/``"none"``/``"identity"`` for the identity
    codec, else a ``+``-joined subset of ``delta``, ``topk:<frac>``,
    ``fp16``/``bf16`` in any order. ``topk`` implies ``delta``.
    """

    def __init__(self, spec: str | None = None):
        self.delta = False
        self.topk_frac: float | None = None
        self.quant: str | None = None  # wire dtype name or None
        raw = (spec or "none").strip().lower()
        if raw not in ("none", "identity"):
            for stage in raw.split("+"):
                stage = stage.strip()
                if stage == "delta":
                    self.delta = True
                elif stage.startswith("topk:"):
                    frac = float(stage.split(":", 1)[1])
                    if not 0.0 < frac <= 1.0:
                        raise ValueError(
                            f"topk fraction must be in (0, 1], got {frac}"
                        )
                    self.topk_frac = frac
                elif stage in _QUANT_DTYPES:
                    if self.quant is not None:
                        raise ValueError(f"duplicate quantize stage in {raw!r}")
                    self.quant = _QUANT_DTYPES[stage]
                else:
                    raise ValueError(
                        f"unknown codec stage {stage!r} in {raw!r} "
                        "(want delta, topk:<frac>, fp16, bf16)"
                    )
            if self.topk_frac is not None:
                self.delta = True  # top-k without a base zeroes the model

    @property
    def codec_id(self) -> str:
        """Canonical spec string — the value negotiated on the wire."""
        stages = []
        if self.delta:
            stages.append("delta")
        if self.topk_frac is not None:
            stages.append(f"topk:{self.topk_frac:g}")
        if self.quant is not None:
            stages.append("fp16" if self.quant == "float16" else "bf16")
        return "+".join(stages) or "none"

    @property
    def identity(self) -> bool:
        return not (self.delta or self.topk_frac is not None or self.quant)

    @property
    def lossy(self) -> bool:
        return self.topk_frac is not None or self.quant is not None

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"WireCodec({self.codec_id!r})"


def make_codec(spec: "str | WireCodec | None") -> WireCodec:
    return spec if isinstance(spec, WireCodec) else WireCodec(spec)


def _compressible(arr: np.ndarray) -> bool:
    """Lossy/delta stages apply to non-empty float tensors only."""
    return arr.dtype.kind == "f" and arr.size > 0


def _note_eviction(
    metrics, direction: str, evicted_round: int, now_round: int
) -> None:
    """Reference-cache eviction telemetry (ISSUE 11 satellite): cumulative
    eviction counter, the age (in rounds) of the view just evicted — a
    rising age means the cache is cycling faster than the fleet rotates —
    and one JSONL event per eviction (bounded at one per push round)."""
    age = max(0, int(now_round) - int(evicted_round))
    if metrics is not None:
        metrics.registry.counter("codec_refs_evicted").inc()
        metrics.registry.gauge(
            f"codec_ref_evicted_age_rounds/{direction}"
        ).set(age)
        metrics.log(
            "codec_ref_evicted", direction=direction,
            round=int(evicted_round), age=age,
        )


def _note_wire(metrics, op: str, raw_bytes: int, wire_bytes: int) -> None:
    """Registry-only wire telemetry: cumulative raw-vs-compressed bytes and
    the running compression ratio (ISSUE knobs: ``compressed_bytes`` +
    compression-ratio gauge). Per-call JSONL events would dominate the
    stream at one encode/decode per client per round."""
    reg = metrics.registry
    raw_c = reg.counter(f"uncompressed_bytes_{op}")
    cmp_c = reg.counter(f"compressed_bytes_{op}")
    raw_c.inc(raw_bytes)
    cmp_c.inc(wire_bytes)
    reg.counter("compressed_bytes").inc(wire_bytes)
    total_raw = raw_c.value
    total_cmp = cmp_c.value
    if total_cmp > 0:
        reg.gauge(f"compression_ratio_{op}").set(total_raw / total_cmp)


class _Session:
    """Shared encode/decode machinery for one direction of the wire.

    Holds no policy about *which* reference to use — subclasses manage
    reference lifetime (single last-applied aggregate client-side, a small
    round-keyed cache server-side) and whether an error-feedback residual
    is carried.
    """

    def __init__(self, codec_: WireCodec, metrics=None, role: str = ""):
        self.codec = make_codec(codec_)
        self.metrics = metrics
        self.role = role
        self.residual: dict[str, np.ndarray] | None = (
            {} if self.codec.lossy else None
        )

    def reset(self) -> None:
        """Forget every delta reference and error-feedback residual this
        session carries — the divergence-rollback path (README "Robust
        aggregation & divergence recovery"): after the server restores a
        checkpointed round, references derived from the diverged trajectory
        must not be decoded (or deltaed) against, and residuals holding
        un-delivered diverged mass must not be re-injected into the
        restored state. The next encode after a reset is self-contained."""
        if self.residual is not None:
            self.residual = {}
        if self.metrics is not None:
            self.metrics.registry.counter("codec_resets").inc()

    # ---- encode ------------------------------------------------------------
    def _encode(
        self,
        tensors: Mapping[str, np.ndarray],
        reference: "dict[str, np.ndarray] | None",
        ref_round: int,
    ) -> tuple[pb.TensorBundle, dict[str, np.ndarray]]:
        """Encode ``tensors`` into a bundle; returns ``(bundle, view)``
        where ``view`` is exactly what the decoder will reconstruct (the
        residual bookkeeping and reference chains are built from it)."""
        c = self.codec
        t0 = time.perf_counter() if self.metrics is not None else 0.0
        use_delta = c.delta and reference is not None
        records = []
        view: dict[str, np.ndarray] = {}
        raw_bytes = 0
        for name in sorted(tensors):
            arr = np.asarray(tensors[name])
            raw_bytes += arr.nbytes
            if not _compressible(arr) or c.identity:
                records.append(codec.array_to_record(name, arr))
                view[name] = arr
                continue
            base = None
            if use_delta:
                if name not in reference:
                    raise CodecError(
                        f"delta encode: no reference for tensor {name!r}"
                    )
                base = np.asarray(reference[name])
            d = (arr - base) if base is not None else arr
            if self.residual is not None:
                prev = self.residual.get(name)
                if prev is not None:
                    d = d + prev
            # Top-k is only meaningful on *deltas* — sparsifying raw
            # parameters (first round, or a push to a not-yet-synced
            # fleet) would zero most of the model. Without a base the
            # tensor ships dense (still quantized).
            rec, recon_d = self._compress_values(
                name, arr, d, sparse_ok=base is not None
            )
            if self.residual is not None:
                self.residual[name] = d - recon_d
            records.append(rec)
            view[name] = (
                (base + recon_d) if base is not None else recon_d
            ).astype(arr.dtype)
        bundle = pb.TensorBundle(
            tensors=records,
            # proto3 cannot distinguish 0 from unset, so the wire carries
            # round + 1; 0 means "self-contained bundle".
            ref_round=(ref_round + 1) if use_delta else 0,
        )
        if self.metrics is not None:
            self.metrics.registry.histogram(
                f"wire_encode_s/{self.role or 'wire'}"
            ).observe(time.perf_counter() - t0)
            _note_wire(self.metrics, "sent", raw_bytes, bundle.ByteSize())
        return bundle, view

    def _compress_values(
        self, name: str, arr: np.ndarray, d: np.ndarray,
        sparse_ok: bool = True,
    ) -> tuple[pb.TensorRecord, np.ndarray]:
        """Top-k select + quantize the (delta) values ``d``; returns the
        wire record and the dense reconstruction the decoder will see."""
        c = self.codec
        flat = np.ascontiguousarray(d).reshape(-1)
        wire_dtype = c.quant  # None = ship at logical dtype
        if sparse_ok and c.topk_frac is not None and c.topk_frac < 1.0:
            k = max(1, math.ceil(c.topk_frac * flat.size))
            if k < flat.size:
                idx = np.argpartition(np.abs(flat), flat.size - k)[-k:]
            else:
                idx = np.arange(flat.size)
            idx = np.sort(idx).astype(np.uint32)
            values = flat[idx]
            if wire_dtype is not None:
                values = values.astype(codec.np_dtype(wire_dtype))
            recon_flat = np.zeros_like(flat)
            recon_flat[idx] = values.astype(flat.dtype)
            rec = pb.TensorRecord(
                name=name, shape=list(arr.shape), dtype=arr.dtype.name,
                codec="topk", data=values.tobytes(), aux=idx.tobytes(),
                wire_dtype=wire_dtype or "",
            )
            return rec, recon_flat.reshape(d.shape)
        values = flat
        if wire_dtype is not None:
            values = values.astype(codec.np_dtype(wire_dtype))
        recon = values.astype(flat.dtype).reshape(d.shape)
        rec = pb.TensorRecord(
            name=name, shape=list(arr.shape), dtype=arr.dtype.name,
            codec="dense", data=values.tobytes(),
            wire_dtype=wire_dtype or "",
        )
        return rec, recon

    # ---- decode ------------------------------------------------------------
    def _decode(
        self,
        bundle: pb.TensorBundle,
        reference: "dict[str, np.ndarray] | None",
    ) -> dict[str, np.ndarray]:
        t0 = time.perf_counter() if self.metrics is not None else 0.0
        delta_bundle = bundle.ref_round > 0
        if delta_bundle and reference is None:
            raise ReferenceMismatch(
                f"bundle references broadcast round {bundle.ref_round - 1} "
                "but this endpoint holds no such reference"
            )
        out: dict[str, np.ndarray] = {}
        raw_bytes = 0
        for rec in bundle.tensors:
            if rec.codec in ("", "raw"):
                arr = codec.record_to_array(rec)
            elif rec.codec == "sparse_set":
                # Catch-up assignment record (per-recipient push encoding):
                # copy the reference tensor and OVERWRITE the listed
                # entries with the shipped values — bit-exact convergence
                # onto the canonical view (an additive float delta would
                # round). Only legal inside a delta bundle.
                if not delta_bundle:
                    raise CodecError(
                        f"sparse_set record {rec.name!r} outside a delta "
                        "bundle"
                    )
                base = reference.get(rec.name)
                if base is None:
                    raise ReferenceMismatch(
                        f"catch-up bundle tensor {rec.name!r} has no "
                        "reference entry"
                    )
                arr = self._apply_sparse_set(rec, np.asarray(base))
            elif rec.codec in ("dense", "topk"):
                arr = self._decode_values(rec)
                if delta_bundle:
                    base = reference.get(rec.name)
                    if base is None:
                        raise ReferenceMismatch(
                            f"delta bundle tensor {rec.name!r} has no "
                            "reference entry"
                        )
                    arr = (np.asarray(base) + arr).astype(arr.dtype)
            else:
                raise CodecError(
                    f"unknown record codec {rec.codec!r} for {rec.name!r}"
                )
            out[rec.name] = arr
            raw_bytes += arr.nbytes
        if self.metrics is not None:
            self.metrics.registry.histogram(
                f"wire_decode_s/{self.role or 'wire'}"
            ).observe(time.perf_counter() - t0)
            _note_wire(self.metrics, "recv", raw_bytes, bundle.ByteSize())
        return out

    @staticmethod
    def _apply_sparse_set(rec: pb.TensorRecord, base: np.ndarray) -> np.ndarray:
        """Decode one ``sparse_set`` record onto its reference tensor."""
        if rec.dtype not in codec.ALLOWED_DTYPES:
            raise CodecError(f"dtype {rec.dtype!r} not allowed on the wire")
        if rec.wire_dtype:
            raise CodecError(
                f"sparse_set record {rec.name!r} must ship logical-dtype "
                "values (exact reconstruction)"
            )
        values = np.frombuffer(rec.data, dtype=codec.np_dtype(rec.dtype))
        idx = np.frombuffer(rec.aux, dtype=np.uint32)
        if idx.size != values.size:
            raise CodecError(
                f"sparse_set record {rec.name!r}: {idx.size} indices for "
                f"{values.size} values"
            )
        shape = tuple(rec.shape)
        if tuple(base.shape) != shape:
            raise CodecError(
                f"sparse_set record {rec.name!r}: reference shape "
                f"{tuple(base.shape)} != record shape {shape}"
            )
        numel = int(np.prod(shape, dtype=np.int64)) if shape else 1
        if idx.size and int(idx.max()) >= numel:
            raise CodecError(
                f"sparse_set record {rec.name!r}: index {int(idx.max())} "
                f"out of range for {numel} elements"
            )
        out = np.array(base, dtype=codec.np_dtype(rec.dtype), copy=True)
        flat = out.reshape(-1)
        flat[idx] = values
        return out

    @staticmethod
    def _decode_values(rec: pb.TensorRecord) -> np.ndarray:
        if rec.dtype not in codec.ALLOWED_DTYPES:
            raise CodecError(f"dtype {rec.dtype!r} not allowed on the wire")
        wire = rec.wire_dtype or rec.dtype
        if wire not in codec.WIRE_DTYPES:
            raise CodecError(f"wire dtype {wire!r} not allowed on the wire")
        values = np.frombuffer(rec.data, dtype=codec.np_dtype(wire))
        values = values.astype(codec.np_dtype(rec.dtype))
        shape = tuple(rec.shape)
        if rec.codec == "dense":
            return values.reshape(shape)
        idx = np.frombuffer(rec.aux, dtype=np.uint32)
        if idx.size != values.size:
            raise CodecError(
                f"topk record {rec.name!r}: {idx.size} indices for "
                f"{values.size} values"
            )
        numel = int(np.prod(shape, dtype=np.int64)) if shape else 1
        if idx.size and int(idx.max()) >= numel:
            raise CodecError(
                f"topk record {rec.name!r}: index {int(idx.max())} out of "
                f"range for {numel} elements"
            )
        dense = np.zeros(numel, dtype=values.dtype)
        dense[idx] = values
        return dense.reshape(shape)


class UplinkEncoder(_Session):
    """Client side of the StepReply path: encodes post-step snapshots
    relative to the last *applied* aggregate, carrying the error-feedback
    residual across rounds."""

    def __init__(self, codec_: WireCodec, metrics=None, role: str = "uplink"):
        super().__init__(codec_, metrics=metrics, role=role)
        self._ref: dict[str, np.ndarray] | None = None
        self._ref_round = -1

    def reset(self) -> None:
        """Drop the applied-aggregate reference AND the error-feedback
        residual (a rollback re-broadcast's ``reset_session``): the next
        snapshot is encoded self-contained and carries no mass from the
        discarded trajectory."""
        self._ref = None
        self._ref_round = -1
        super().reset()

    def note_aggregate(
        self, tensors: Mapping[str, np.ndarray], round_idx: int
    ) -> None:
        """Record the aggregate this client just applied — the reference the
        next snapshot is delta-encoded against."""
        if self.codec.delta:
            self._ref = {k: np.asarray(v) for k, v in tensors.items()}
            self._ref_round = int(round_idx)

    def encode(self, snapshot: Mapping[str, np.ndarray]) -> pb.TensorBundle:
        bundle, _view = self._encode(snapshot, self._ref, self._ref_round)
        return bundle


class UplinkDecoder(_Session):
    """Server side of the StepReply path. Keeps a small round-keyed cache of
    *client-held views* of recent broadcasts (what :class:`DownlinkEncoder`
    reports each push reconstructs to), so a client whose last push was one
    or two rounds stale still decodes; anything older raises
    :class:`ReferenceMismatch` — loud, and healed by the next push."""

    def __init__(self, codec_: WireCodec, metrics=None, max_refs: int = 8,
                 role: str = "uplink"):
        super().__init__(codec_, metrics=metrics, role=role)
        self.max_refs = int(max_refs)
        self._refs: "OrderedDict[int, dict[str, np.ndarray]]" = OrderedDict()
        self.residual = None  # decode side carries no residual

    def note_push(
        self, round_idx: int, client_view: Mapping[str, np.ndarray]
    ) -> None:
        if not self.codec.delta:
            return
        self._refs[int(round_idx)] = dict(client_view)
        while len(self._refs) > self.max_refs:
            evicted_round, _view = self._refs.popitem(last=False)
            # Bounded-cache eviction (ISSUE 11 satellite): an uplink that
            # still deltas against this round will raise a loud
            # ReferenceMismatch (codec_ref_miss) and heal on its next
            # push — degraded, never a mis-decode.
            _note_eviction(
                self.metrics, "uplink", evicted_round, round_idx
            )

    def reset(self) -> None:
        """Drop the whole broadcast-view cache (divergence rollback): an
        uplink deltaed against a pre-rollback broadcast now raises
        :class:`ReferenceMismatch` — loud, and healed by the rolled-back
        re-broadcast."""
        self._refs.clear()
        super().reset()

    def decode(self, bundle: pb.TensorBundle) -> dict[str, np.ndarray]:
        reference = None
        if bundle.ref_round > 0:
            reference = self._refs.get(bundle.ref_round - 1)
            if reference is None:
                raise ReferenceMismatch(
                    f"no cached broadcast view for round "
                    f"{bundle.ref_round - 1} (cache holds "
                    f"{sorted(self._refs)})"
                )
        return self._decode(bundle, reference)


class DownlinkEncoder(_Session):
    """Server side of the Aggregate push path.

    Maintains the **canonical view chain**: each :meth:`advance` encodes
    the round's aggregate as a delta against the previous canonical view
    (the EF residual carries any lossy-stage mass forward), caches the
    reconstruction view in a bounded round-keyed LRU, and
    :meth:`bundle_for` then serves *per-recipient* bundles — the shared
    canonical chain bundle for up-to-date recipients, exact catch-up
    bundles for recipients holding an older cached view, and a
    self-contained view bundle when no usable reference exists (README
    "Hierarchical federation & wire efficiency"). The legacy
    :meth:`encode` (fleet-consensus ``allow_delta``) remains for
    single-stream callers."""

    def __init__(self, codec_: WireCodec, metrics=None,
                 role: str = "downlink", max_views: int = 8):
        super().__init__(codec_, metrics=metrics, role=role)
        self._last_view: dict[str, np.ndarray] | None = None
        self._last_round = -1
        self.max_views = int(max_views)
        # Canonical client views by round (bounded LRU) + this round's
        # chain bundle. The view dicts are shared by reference with the
        # uplink decoder's cache — one copy of each round's tensors.
        self._views: "OrderedDict[int, dict[str, np.ndarray]]" = OrderedDict()
        self._canonical: pb.TensorBundle | None = None
        # Served-bundle memo for the CURRENT round, keyed by acked_round
        # (-1 = the self-contained view bundle). bundle_for runs under
        # the server's codec lock with one call per concurrent pusher —
        # without this, N stale recipients cost N identical O(model)
        # encodes serialized on that lock.
        self._served: dict[int, pb.TensorBundle] = {}

    def reset(self) -> None:
        """Forget the last broadcast view AND the whole canonical view
        cache (divergence rollback): the next push is encoded
        self-contained regardless of what any recipient claims to hold."""
        self._last_view = None
        self._last_round = -1
        self._views.clear()
        self._canonical = None
        self._served.clear()
        super().reset()

    @property
    def last_round(self) -> int:
        """Round of the broadcast a delta push would reference (-1 =
        none yet). Under cohort/async pacing recipients hold broadcasts
        of different rounds, so the server's ``allow_delta`` check
        compares each recipient's last-acked round to THIS — not merely
        membership in an acked set."""
        return self._last_round

    def encode(
        self,
        average: Mapping[str, np.ndarray],
        round_idx: int,
        allow_delta: bool = False,
    ) -> tuple[pb.TensorBundle, dict[str, np.ndarray]]:
        """Returns ``(bundle, client_view)`` — feed ``client_view`` to
        :meth:`UplinkDecoder.note_push` (it is the exact tensor set every
        client that applies this push will hold)."""
        reference = self._last_view if allow_delta else None
        ref_round = self._last_round if allow_delta else -1
        bundle, view = self._encode(average, reference, ref_round)
        self._note_view(view, int(round_idx))
        self._canonical = bundle
        return bundle, view

    def advance(
        self, average: Mapping[str, np.ndarray], round_idx: int
    ) -> tuple[pb.TensorBundle, dict[str, np.ndarray]]:
        """Advance the canonical view chain one round: encode ``average``
        as a delta against the previous canonical view whenever one exists
        (self-contained otherwise — first round, or after :meth:`reset`),
        cache the reconstruction view, and return ``(chain_bundle, view)``.
        Call once per pushed round, then :meth:`bundle_for` per
        recipient."""
        bundle, view = self._encode(
            average, self._last_view, self._last_round
        )
        self._note_view(view, int(round_idx))
        self._canonical = bundle
        return bundle, view

    def _note_view(self, view: dict[str, np.ndarray], round_idx: int) -> None:
        self._last_view = view
        self._last_round = round_idx
        self._served.clear()  # memoized bundles describe the prior round
        if not self.codec.delta:
            return
        self._views[round_idx] = view
        while len(self._views) > max(1, self.max_views):
            evicted_round, _view = self._views.popitem(last=False)
            # A recipient still holding this round falls back to a
            # self-contained view bundle on its next push (degraded
            # compression, not an error).
            _note_eviction(
                self.metrics, "downlink", evicted_round, round_idx
            )

    def bundle_for(self, acked_round: "int | None") -> pb.TensorBundle:
        """The push bundle for one recipient, keyed by the round of the
        last broadcast that recipient acked (``None`` = no reference).
        Must follow an :meth:`advance` for the current round.

        - the chain bundle is self-contained → everyone shares it;
        - ``acked_round`` is the chain bundle's own reference → the shared
          chain bundle;
        - ``acked_round`` still cached → an exact catch-up bundle onto the
          canonical view;
        - otherwise (never acked, or evicted) → a self-contained view
          bundle."""
        if self._canonical is None or self._last_view is None:
            raise CodecError("bundle_for before the first advance()")
        chain_ref = int(self._canonical.ref_round) - 1  # -1 = self-contained
        if chain_ref < 0:
            return self._canonical
        if acked_round is not None and int(acked_round) == chain_ref:
            return self._canonical
        if acked_round is not None and int(acked_round) in self._views:
            key = int(acked_round)
            if key not in self._served:
                self._served[key] = self._catchup_bundle(key)
            return self._served[key]
        if -1 not in self._served:
            self._served[-1] = self._view_bundle()
        return self._served[-1]

    def _catchup_bundle(self, acked_round: int) -> pb.TensorBundle:
        """Exact catch-up onto the canonical view for a recipient holding
        the cached view of ``acked_round``: per float tensor, the entries
        that changed since then as ``sparse_set`` assignment records
        (uint32 indices + logical-dtype values — bit-exact, see
        :meth:`_Session._apply_sparse_set`), falling back to a raw dense
        record when the change is too dense for sparse framing to win."""
        t0 = time.perf_counter() if self.metrics is not None else 0.0
        old = self._views[acked_round]
        records = []
        raw_bytes = 0
        for name in sorted(self._last_view):
            arr = np.asarray(self._last_view[name])
            raw_bytes += arr.nbytes
            base = old.get(name)
            if (
                not _compressible(arr) or base is None
                or np.asarray(base).shape != arr.shape
            ):
                records.append(codec.array_to_record(name, arr))
                continue
            flat = np.ascontiguousarray(arr).reshape(-1)
            base_flat = np.ascontiguousarray(np.asarray(base)).reshape(-1)
            idx = np.flatnonzero(flat != base_flat)
            sparse_bytes = idx.size * (4 + arr.dtype.itemsize)
            if sparse_bytes >= flat.size * arr.dtype.itemsize:
                records.append(codec.array_to_record(name, arr))
                continue
            idx32 = idx.astype(np.uint32)
            records.append(pb.TensorRecord(
                name=name, shape=list(arr.shape), dtype=arr.dtype.name,
                codec="sparse_set", data=flat[idx].tobytes(),
                aux=idx32.tobytes(),
            ))
        bundle = pb.TensorBundle(
            tensors=records, ref_round=acked_round + 1
        )
        if self.metrics is not None:
            self.metrics.registry.counter("codec_catchup_pushes").inc()
            self.metrics.registry.histogram(
                f"wire_encode_s/{self.role or 'wire'}"
            ).observe(time.perf_counter() - t0)
            _note_wire(self.metrics, "sent", raw_bytes, bundle.ByteSize())
        return bundle

    def _view_bundle(self) -> pb.TensorBundle:
        """Self-contained raw encoding of the canonical view — the bounded
        fallback when a recipient has no usable reference. Raw records are
        exact by construction, so the recipient still converges onto the
        canonical view and its future uplinks decode against the shared
        round-keyed cache."""
        t0 = time.perf_counter() if self.metrics is not None else 0.0
        records = [
            codec.array_to_record(name, np.asarray(self._last_view[name]))
            for name in sorted(self._last_view)
        ]
        bundle = pb.TensorBundle(tensors=records, ref_round=0)
        if self.metrics is not None:
            raw = sum(
                np.asarray(v).nbytes for v in self._last_view.values()
            )
            self.metrics.registry.counter("codec_selfcontained_pushes").inc()
            self.metrics.registry.histogram(
                f"wire_encode_s/{self.role or 'wire'}"
            ).observe(time.perf_counter() - t0)
            _note_wire(self.metrics, "sent", raw, bundle.ByteSize())
        return bundle


class DownlinkDecoder(_Session):
    """Client side of the Aggregate push path: holds the single last-applied
    broadcast view as the delta reference."""

    def __init__(self, codec_: WireCodec, metrics=None,
                 role: str = "downlink"):
        super().__init__(codec_, metrics=metrics, role=role)
        self._ref: dict[str, np.ndarray] | None = None
        self._ref_round = -1
        self.residual = None

    def reset(self) -> None:
        """Drop the last-applied broadcast reference (a rollback
        re-broadcast's ``reset_session``); the incoming push must then be
        self-contained."""
        self._ref = None
        self._ref_round = -1
        super().reset()

    def decode(
        self, bundle: pb.TensorBundle, round_idx: int
    ) -> dict[str, np.ndarray]:
        if bundle.ref_round > 0 and bundle.ref_round - 1 != self._ref_round:
            raise ReferenceMismatch(
                f"push deltas against broadcast round {bundle.ref_round - 1} "
                f"but this client last applied round {self._ref_round}"
            )
        out = self._decode(
            bundle, self._ref if bundle.ref_round > 0 else None
        )
        if self.codec.delta:
            self._ref = dict(out)
            self._ref_round = int(round_idx)
        return out


def encode_push_for_recipients(
    downlink_enc: "DownlinkEncoder | None",
    uplink_dec: "UplinkDecoder | None",
    average: "Mapping[str, np.ndarray]",
    round_idx: int,
    recipients: "list[int]",
    acked: "Mapping[int, int]",
    reset: bool,
    metrics: Any = None,
) -> "dict[int, pb.Aggregate]":
    """One round's push encoded **per recipient** (README "Hierarchical
    federation & wire efficiency"): advance the canonical view chain
    once, then serve each recipient the bundle matched to its own
    last-acked reference — the shared chain bundle when up to date, a
    catch-up bundle for an older cached view, a self-contained view
    bundle when it holds nothing usable. Recipients sharing a reference
    share one encoded bundle, so encode cost is O(distinct references),
    not O(cohort). ``downlink_enc=None`` is the identity-codec path: one
    raw bundle for everyone.

    This is the ONE implementation of the reference/reset rules, shared
    by ``FederatedServer._encode_push`` and
    ``RelayNode._fanout_aggregate`` — the two tiers must not drift. The
    caller holds whatever lock guards the codec sessions."""
    if downlink_enc is None:
        agg = pb.Aggregate(
            shared=codec.flatdict_to_bundle(average, metrics=metrics),
            round=round_idx, reset_session=reset,
        )
        return {cid: agg for cid in recipients}
    _bundle, view = downlink_enc.advance(average, round_idx=round_idx)
    if uplink_dec is not None:
        uplink_dec.note_push(round_idx, view)
    out: dict[int, pb.Aggregate] = {}
    by_ref: "dict[int | None, pb.Aggregate]" = {}
    for cid in recipients:
        # A session reset deliberately severs every reference chain: the
        # recipient drops its codec state before applying, so its bundle
        # must not assume one.
        ref = None if reset else acked.get(cid)
        agg = by_ref.get(ref)
        if agg is None:
            agg = pb.Aggregate(
                shared=downlink_enc.bundle_for(ref),
                round=round_idx, reset_session=reset,
            )
            by_ref[ref] = agg
        out[cid] = agg
    return out
