"""Update admission gate: the data-plane trust boundary of the federation.

PR 2 hardened the *transport* plane (retry/probation/quorum); until PR 5 the
*data* plane was fully trusting — any tensor a client returned flowed
straight into the round average, and the per-minibatch exchange (the
reference gFedNTM design) makes that a one-round total poisoning: a single
NaN coordinate, exploded norm, or adversarially scaled payload is averaged
in and re-broadcast to every client. Practical-FL surveys name unreliable
client updates a first-class failure mode alongside stragglers
(arXiv:2405.20431 §4), and the FALD analysis (arXiv:2112.05120) shows how
sensitive the averaged model is to heavy-tailed per-client noise.

:class:`UpdateGate` screens every decoded client snapshot before it can
enter the aggregate step:

1. **conformance** — key set, per-tensor shape AND dtype must match the
   server's shared template (the skew-skip logic that used to live inline
   in ``server._collect_snapshots``);
2. **finiteness** — every tensor must be NaN/Inf-free;
3. **norm screening** — the update norm ``||snapshot - current_global||``
   is tested against the round cohort's ``median + k * MAD`` (a robust
   outlier test that needs no tuning against absolute scales), and
   optionally hard-clipped to ``max_update_norm`` (gradient-clipping
   semantics: the direction is kept, the influence is bounded).

Rejected updates are excluded from the average, logged as
``update_rejected`` telemetry events with a machine-readable reason code,
and counted per client; ``consecutive(client)`` lets the server feed
repeat offenders into the PR 2 probation machinery
(``Federation.mark_suspect(reason="poisoned")``) so a persistently
poisonous client is backed off and eventually dropped exactly like a
persistently unreachable one.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Any, Mapping

import numpy as np

from gfedntm_tpu.utils import flightrec

__all__ = ["Rejection", "GateResult", "UpdateGate", "update_norm"]

# Reason codes (the `update_rejected` event's `reason` field vocabulary).
KEY_SKEW = "key_skew"
SHAPE_SKEW = "shape_skew"
DTYPE_SKEW = "dtype_skew"
NONFINITE = "nonfinite"
NORM_OUTLIER = "norm_outlier"

#: MAD → sigma for normally distributed data (the usual robust-scale
#: consistency constant).
_MAD_SIGMA = 1.4826


def update_norm(
    snapshot: Mapping[str, np.ndarray],
    reference: Mapping[str, np.ndarray],
) -> float:
    """Global L2 norm of ``snapshot - reference`` over the shared subset
    (float64 accumulation — a poisoned float32 update can overflow a
    same-dtype square)."""
    total = 0.0
    for key, value in snapshot.items():
        d = (
            np.asarray(value, np.float64)
            - np.asarray(reference[key], np.float64)
        )
        total += float(np.dot(d.ravel(), d.ravel()))
    return float(np.sqrt(total))


@dataclass
class Rejection:
    """One gated-out update: who, why, and with what norm (NaN when the
    rejection happened before the norm stage)."""

    client_id: int
    reason: str
    detail: str
    norm: float = float("nan")


@dataclass
class GateResult:
    """Outcome of one round's admission pass.

    ``stacked`` is only set by the device backend (see
    :meth:`UpdateGate.set_engine`): the accepted cohort as a
    ``device_agg.StackedRound`` — clip already applied on the plane — for
    the aggregator to consume without ever round-tripping through
    per-key host dicts."""

    accepted: list  # [(client_id, weight, snapshot)]
    rejected: list  # [Rejection]
    clipped: list  # [(client_id, norm, max_norm)]
    stacked: Any = None


class UpdateGate:
    """Per-round admission screening of decoded client snapshots.

    ``mad_k <= 0`` disables the cohort outlier test; ``max_update_norm``
    ``None`` disables the hard clip; ``check_finite=False`` turns the gate
    into a pure conformance check (the pre-PR 5 behaviour — used by tests
    that need to demonstrate unprotected poisoning). The MAD test only
    runs on cohorts of at least ``min_cohort`` candidates: a median over
    one or two updates is not a statistic.
    """

    def __init__(
        self,
        *,
        check_finite: bool = True,
        mad_k: float = 4.0,
        mad_rel_floor: float = 0.5,
        max_update_norm: float | None = None,
        min_cohort: int = 3,
        suspect_after: int = 2,
        metrics: Any = None,
        logger: logging.Logger | None = None,
    ):
        if mad_rel_floor < 0:
            raise ValueError(
                f"mad_rel_floor must be >= 0, got {mad_rel_floor}"
            )
        if max_update_norm is not None and max_update_norm <= 0:
            raise ValueError(
                f"max_update_norm must be > 0, got {max_update_norm}"
            )
        if suspect_after < 1:
            raise ValueError(
                f"suspect_after must be >= 1, got {suspect_after}"
            )
        self.check_finite = bool(check_finite)
        self.mad_k = float(mad_k)
        # Scale floor as a fraction of the median norm: with a tiny cohort
        # the MAD collapses toward 0 and every deviation would read as an
        # outlier; the floor keeps the rejection threshold at least
        # (1 + mad_k * mad_rel_floor) x the median.
        self.mad_rel_floor = float(mad_rel_floor)
        self.max_update_norm = (
            None if max_update_norm is None else float(max_update_norm)
        )
        self.min_cohort = int(min_cohort)
        self.suspect_after = int(suspect_after)
        self.metrics = metrics
        self.logger = logger or logging.getLogger("UpdateGate")
        self._expected_keys: frozenset[str] | None = None
        self._expected_shapes: dict[str, tuple] = {}
        self._expected_dtypes: dict[str, np.dtype] = {}
        # Device-resident backend (README "Device-resident aggregation"):
        # when an engine is attached, finiteness/norms/clip run as one
        # fused sharded XLA pass over the stacked cohort instead of host
        # numpy per tensor. Decisions are identical by contract
        # (tests/test_device_agg.py).
        self._engine: Any = None
        self._template: dict[str, np.ndarray] | None = None
        self._plane: Any = None
        # Consecutive rejection streak per client (reset on acceptance):
        # the "repeated offender" signal the server feeds into probation.
        self._streak: dict[int, int] = {}
        self.total_rejections: dict[int, int] = {}

    # ---- template ----------------------------------------------------------
    def set_template(self, template: Mapping[str, np.ndarray]) -> None:
        """Pin the authoritative key/shape/dtype contract (the server's
        shared template subset)."""
        self._expected_keys = frozenset(template)
        self._expected_shapes = {
            k: tuple(np.asarray(v).shape) for k, v in template.items()
        }
        self._expected_dtypes = {
            k: np.asarray(v).dtype for k, v in template.items()
        }
        self._template = {k: np.asarray(v) for k, v in template.items()}
        self._plane = None  # re-derived lazily from the new template

    def set_engine(self, engine: Any) -> None:
        """Attach a ``device_agg.DeviceAggEngine``: subsequent rounds run
        the data plane (finiteness, norms, clip) on device and hand the
        aggregator a stacked cohort (``GateResult.stacked``). ``None``
        restores the pure-numpy path."""
        self._engine = engine

    def consecutive(self, client_id: int) -> int:
        """Current consecutive-rejection streak for one client."""
        return self._streak.get(client_id, 0)

    # ---- per-candidate checks ----------------------------------------------
    def _conformance(self, client_id: int, snap: Mapping) -> Rejection | None:
        if self._expected_keys is None:
            return None
        if frozenset(snap) != self._expected_keys:
            missing = sorted(self._expected_keys - set(snap))[:3]
            unexpected = sorted(set(snap) - self._expected_keys)[:3]
            return Rejection(
                client_id, KEY_SKEW,
                f"missing={missing}, unexpected={unexpected}",
            )
        for key in snap:
            arr = np.asarray(snap[key])
            want = self._expected_shapes[key]
            if tuple(arr.shape) != want:
                return Rejection(
                    client_id, SHAPE_SKEW,
                    f"{key}: {tuple(arr.shape)} != {want}",
                )
            if arr.dtype != self._expected_dtypes[key]:
                return Rejection(
                    client_id, DTYPE_SKEW,
                    f"{key}: {arr.dtype} != {self._expected_dtypes[key]}",
                )
        return None

    @staticmethod
    def _nonfinite(client_id: int, snap: Mapping) -> Rejection | None:
        for key in sorted(snap):
            arr = np.asarray(snap[key])
            if arr.dtype.kind == "f" and not np.all(np.isfinite(arr)):
                bad = int(arr.size - np.isfinite(arr).sum())
                return Rejection(
                    client_id, NONFINITE,
                    f"{key}: {bad}/{arr.size} non-finite values",
                )
        return None

    def _outlier_threshold(self, norms: list[float]) -> float | None:
        """The cohort's rejection threshold, or None when the MAD test
        cannot run (disabled, or cohort too small)."""
        if self.mad_k <= 0 or len(norms) < self.min_cohort:
            return None
        arr = np.asarray(norms, np.float64)
        med = float(np.median(arr))
        mad = float(np.median(np.abs(arr - med)))
        scale = max(_MAD_SIGMA * mad, self.mad_rel_floor * med, 1e-12)
        return med + self.mad_k * scale

    # ---- the round pass ----------------------------------------------------
    @staticmethod
    def _screen_norm(
        norm: float, client_id: int, staleness: "Mapping[int, int] | None"
    ) -> float:
        """The norm the MAD outlier screen judges: raw, divided by
        ``1 + staleness``. Under cohort/async pacing a client steps from
        the broadcast it last applied, so its raw update-vs-current-global
        norm carries the drift of ``s`` intervening aggregations — honest
        stale members would read as outliers against fresh peers. The
        first-order normalization makes the cohort statistics compare
        like with like (the gate's cohort-awareness, ISSUE 9); with no
        staleness map (sync pacing) the division is by exactly 1.0 and
        decisions are bit-identical to the historical screen. The hard
        clip deliberately still uses the RAW norm — influence on the
        aggregate is bounded in absolute terms no matter how stale the
        update claims to be."""
        if staleness is None:
            return norm
        return norm / (1.0 + max(0, int(staleness.get(client_id, 0))))

    def admit_round(
        self,
        candidates: "list[tuple[int, float, dict[str, np.ndarray]]]",
        current_global: Mapping[str, np.ndarray],
        round_idx: int,
        staleness: "Mapping[int, int] | None" = None,
    ) -> GateResult:
        """Screen one round's ``(client_id, weight, snapshot)`` candidates.

        Order matters: conformance and finiteness run per candidate; norms
        are then computed for the structurally-sound survivors ONLY (a
        shape-skewed or NaN update must not pollute the cohort statistics
        it is judged against); MAD outliers are rejected on staleness-
        normalized norms (see :meth:`_screen_norm`; raw norms when no
        ``staleness`` map is given); finally the hard clip bounds whoever
        remains on RAW norms. Telemetry and streak bookkeeping happen
        here so every caller gets identical accounting.

        With a device engine attached (:meth:`set_engine`) the same pass
        runs on the stacked device plane — identical decisions, and the
        result additionally carries ``stacked`` for the device-resident
        aggregator.
        """
        if self._engine is not None and self._template is not None:
            return self._admit_round_device(
                candidates, current_global, round_idx, staleness
            )
        rejected: list[Rejection] = []
        clipped: list[tuple[int, float, float]] = []
        sound: list[tuple[int, float, dict, float]] = []
        for client_id, weight, snap in candidates:
            rej = self._conformance(client_id, snap)
            if rej is None and self.check_finite:
                rej = self._nonfinite(client_id, snap)
            if rej is not None:
                rejected.append(rej)
                continue
            norm = (
                update_norm(snap, current_global)
                if (self.mad_k > 0 or self.max_update_norm is not None)
                and self.check_finite
                else float("nan")
            )
            sound.append((client_id, weight, snap, norm))

        threshold = self._outlier_threshold([
            self._screen_norm(n, c, staleness)
            for c, _w, _s, n in sound if np.isfinite(n)
        ])
        accepted: list[tuple[int, float, dict]] = []
        for client_id, weight, snap, norm in sound:
            screen = self._screen_norm(norm, client_id, staleness)
            if threshold is not None and screen > threshold:
                rejected.append(Rejection(
                    client_id, NORM_OUTLIER,
                    f"update norm {norm:.3e} (screened {screen:.3e}) > "
                    f"cohort threshold {threshold:.3e}",
                    norm=norm,
                ))
                continue
            if (
                self.max_update_norm is not None
                and np.isfinite(norm) and norm > self.max_update_norm
            ):
                factor = self.max_update_norm / norm
                snap = {
                    k: np.asarray(
                        np.asarray(current_global[k], np.float64)
                        + factor * (
                            np.asarray(v, np.float64)
                            - np.asarray(current_global[k], np.float64)
                        ),
                        dtype=np.asarray(v).dtype,
                    )
                    for k, v in snap.items()
                }
                clipped.append((client_id, norm, self.max_update_norm))
            accepted.append((client_id, weight, snap))

        self._account(accepted, rejected, clipped, round_idx)
        return GateResult(accepted=accepted, rejected=rejected,
                          clipped=clipped)

    def _admit_round_device(
        self,
        candidates: "list[tuple[int, float, dict[str, np.ndarray]]]",
        current_global: Mapping[str, np.ndarray],
        round_idx: int,
        staleness: "Mapping[int, int] | None" = None,
    ) -> GateResult:
        """The admission pass on the device plane: conformance stays host
        metadata work, then the structurally-sound candidates are stacked
        ONCE and a single fused sharded program computes every row's
        non-finite count and update norm; MAD screening is O(N) host
        arithmetic over those norms; the clip is one more device pass
        with per-row factors. Semantics mirror the numpy branch above
        decision-for-decision (tests/test_device_agg.py pins this): a row
        whose norm overflows the f32 plane accumulator (values ~1e19+,
        finite in their own dtype) gets its norm recomputed with the
        numpy f64 accumulator on the host, so even those extreme rows
        take the oracle's screen/clip/admit path."""
        from gfedntm_tpu.federation.device_agg import FlatPlane, StackedRound

        if self._plane is None:
            self._plane = FlatPlane(self._template)
        plane, engine = self._plane, self._engine

        # Phase-1 rejections (conformance + finiteness) are collected with
        # their candidate index and emitted in candidate order — the exact
        # accounting order of the numpy branch, whose single loop
        # interleaves both checks.
        phase1: list[tuple[int, Rejection]] = []
        sound: list[tuple[int, float, dict]] = []
        sound_src: list[int] = []
        for ci, (client_id, weight, snap) in enumerate(candidates):
            rej = self._conformance(client_id, snap)
            if rej is not None:
                phase1.append((ci, rej))
                continue
            sound.append((client_id, weight, snap))
            sound_src.append(ci)

        if not sound:
            rejected = [rej for _ci, rej in phase1]
            self._account([], rejected, [], round_idx)
            return GateResult(accepted=[], rejected=rejected, clipped=[])

        mat = engine.stack(plane, [s for _c, _w, s in sound])
        gvec = engine.put_vector(plane, current_global)
        need_norm = (
            self.mad_k > 0 or self.max_update_norm is not None
        ) and self.check_finite
        if self.check_finite or need_norm:
            counts, norms = engine.gate_stats(mat, gvec)
        else:
            # Gate fully disabled (pre-PR 5 semantics): the numpy branch
            # computes nothing here — skip the device pass too.
            counts = np.zeros(len(sound), np.int64)
            norms = np.full(len(sound), np.nan)
        finite_rows: list[int] = []
        for i, (client_id, _w, snap) in enumerate(sound):
            if self.check_finite and counts[i] > 0:
                # The per-key host scan only runs for the (rare) flagged
                # row, to reproduce the numpy rejection detail. A row the
                # host finds finite in its own dtype (values that only
                # overflowed the f32 *plane* — possible for wider-dtype
                # templates) is NOT a numpy-path NONFINITE: let it fall
                # through to the norm stage, where its infinite plane
                # norm rejects it as the documented overflow outlier.
                rej = self._nonfinite(client_id, snap)
                if rej is not None:
                    phase1.append((sound_src[i], rej))
                    continue
            finite_rows.append(i)
        rejected = [rej for _ci, rej in sorted(phase1, key=lambda t: t[0])]
        if need_norm:
            for i in finite_rows:
                # f32 plane overflow (values finite in their own dtype
                # whose squares exceed f32 range): recompute THIS row's
                # norm with the numpy f64 accumulator so the decision —
                # screen, clip, or admit — is exactly the oracle's.
                # Rare path, O(overflowed rows) host work.
                if not np.isfinite(norms[i]):
                    norms[i] = update_norm(sound[i][2], current_global)

        threshold = (
            self._outlier_threshold([
                self._screen_norm(float(norms[i]), sound[i][0], staleness)
                for i in finite_rows if np.isfinite(norms[i])
            ])
            if need_norm else None
        )
        accepted_rows: list[int] = []
        accepted: list[tuple[int, float, dict]] = []
        clipped: list[tuple[int, float, float]] = []
        factors = np.ones(len(sound), np.float32)
        clip_rows: set[int] = set()
        for i in finite_rows:
            client_id, weight, snap = sound[i]
            norm = float(norms[i]) if need_norm else float("nan")
            screen = self._screen_norm(norm, client_id, staleness)
            if threshold is not None and screen > threshold:
                rejected.append(Rejection(
                    client_id, NORM_OUTLIER,
                    f"update norm {norm:.3e} (screened {screen:.3e}) > "
                    f"cohort threshold {threshold:.3e}",
                    norm=norm,
                ))
                continue
            if (
                self.max_update_norm is not None
                and np.isfinite(norm) and norm > self.max_update_norm
            ):
                factors[i] = self.max_update_norm / norm
                clip_rows.add(i)
                clipped.append((client_id, norm, self.max_update_norm))
            accepted_rows.append(i)
            accepted.append((client_id, weight, snap))

        if clip_rows:
            mat = engine.clip(mat, gvec, factors)
            # Keep the host dicts consistent with the clipped plane: the
            # stacked rows are authoritative for the aggregate, but the
            # dicts feed the non-f32 remainder and any numpy fallback.
            # Only the clipped rows round-trip to host.
            for pos, i in enumerate(accepted_rows):
                if i in clip_rows:
                    client_id, weight, _snap = sound[i]
                    row = np.asarray(mat[i])[:plane.dim].copy()
                    accepted[pos] = (
                        client_id, weight, plane.unflatten(row),
                    )

        stacked = None
        if accepted_rows:
            rows = (
                mat if len(accepted_rows) == len(sound)
                else mat[np.asarray(accepted_rows, np.int32)]
            )
            stacked = StackedRound(
                engine, plane,
                [w for _c, w, _s in accepted], rows,
                [s for _c, _w, s in accepted],
                gvec=gvec,
            )
        self._account(accepted, rejected, clipped, round_idx)
        return GateResult(accepted=accepted, rejected=rejected,
                          clipped=clipped, stacked=stacked)

    def _account(self, accepted, rejected, clipped, round_idx: int) -> None:
        m = self.metrics
        for client_id, _w, _s in accepted:
            self._streak.pop(client_id, None)
            # Flight-ring context (README "Incident forensics"): the
            # JSONL stream records rejections only; a postmortem needs
            # the full per-client verdict history leading into an
            # incident — acceptances included.
            flightrec.note(
                m, "gate_verdict", client=client_id, round=round_idx,
                verdict="accepted",
            )
        for rej in rejected:
            flightrec.note(
                m, "gate_verdict", client=rej.client_id, round=round_idx,
                verdict="rejected", reason=rej.reason, detail=rej.detail,
            )
            self._streak[rej.client_id] = (
                self._streak.get(rej.client_id, 0) + 1
            )
            self.total_rejections[rej.client_id] = (
                self.total_rejections.get(rej.client_id, 0) + 1
            )
            self.logger.warning(
                "round %d: rejecting client %d update (%s: %s); excluding "
                "it from the average", round_idx, rej.client_id, rej.reason,
                rej.detail,
            )
            if m is not None:
                m.registry.counter("updates_rejected").inc()
                m.registry.counter(f"updates_rejected/{rej.reason}").inc()
                if rej.reason in (KEY_SKEW, SHAPE_SKEW, DTYPE_SKEW):
                    # Historical conformance counter, kept for dashboard
                    # continuity with the PR 2 skew-skip logic.
                    m.registry.counter("key_skew_excluded").inc()
                event = dict(
                    client=rej.client_id, round=round_idx,
                    reason=rej.reason, detail=rej.detail,
                )
                if np.isfinite(rej.norm):
                    event["norm"] = rej.norm
                m.log("update_rejected", **event)
        for client_id, norm, max_norm in clipped:
            flightrec.note(
                m, "gate_verdict", client=client_id, round=round_idx,
                verdict="clipped", norm=norm, max_norm=max_norm,
            )
            self.logger.warning(
                "round %d: clipping client %d update norm %.3e -> %.3e",
                round_idx, client_id, norm, max_norm,
            )
            if m is not None:
                m.registry.counter("updates_clipped").inc()
                m.log(
                    "update_clipped", client=client_id, round=round_idx,
                    norm=norm, max_norm=max_norm,
                )


def decode_and_admit(
    replies: "list[tuple[Any, Any]]",
    decode: "Any",
    gate: UpdateGate,
    current_global: Mapping[str, np.ndarray],
    round_idx: int,
    *,
    metrics: Any = None,
    was_suspect: frozenset = frozenset(),
    weight_scale: "Mapping[int, float] | None" = None,
    staleness: "Mapping[int, int] | None" = None,
    on_decode_error: "Any",
    on_poisoned: "Any",
    on_recovered: "Any",
) -> "tuple[GateResult, dict[int, float], dict[int, tuple[Any, Any]]]":
    """Decode one round's ``(member_record, StepReply)`` pairs and pass
    them through ``gate`` — the ONE decode-and-gate pipeline shared by the
    root server (``FederatedServer._collect_snapshots``) and the relay
    tier (``RelayNode._train_round``), the uplink twin of
    ``compression.encode_push_for_recipients``: a gate-policy change
    (rejection reasons, staleness normalization, recovery semantics) made
    on one tier MUST apply at the other, or a poisoner behind a relay is
    screened by stale rules.

    Shared here: the decode attempt with ``codec_ref_miss``
    counter/event accounting (a reply the codec cannot decode costs the
    round one contributor, never an error), FedAvg weight assembly
    (``reply.nr_samples`` falling back to the member's join-time corpus
    size, optionally scaled by ``weight_scale`` — the async staleness
    discount), the admission call itself, the repeat-offender screen
    (``gate.consecutive() >= gate.suspect_after``), and admission-scoped
    probation recovery (a ``was_suspect`` member only clears when its
    update is *accepted*). Tier-specific policy stays with the caller via
    the three hooks: ``on_decode_error(rec, err)`` (logging),
    ``on_poisoned(rec, rejection)`` (probation entry), and
    ``on_recovered(client_id)``.

    Returns ``(gate_result, losses_by_id, records_by_id)`` where
    ``records_by_id`` maps member id to its ``(record, reply)`` pair for
    the decodable replies.
    """
    from gfedntm_tpu.federation.compression import CodecError

    records: "dict[int, tuple[Any, Any]]" = {}
    losses: "dict[int, float]" = {}
    candidates: "list[tuple[int, float, dict[str, np.ndarray]]]" = []
    for rec, reply in replies:
        try:
            snap = decode(reply.shared)
        except CodecError as err:
            if metrics is not None:
                metrics.registry.counter("codec_ref_miss").inc()
                metrics.log(
                    "codec_ref_miss", client=rec.client_id,
                    ref_round=int(reply.shared.ref_round) - 1,
                    round=round_idx,
                )
            on_decode_error(rec, err)
            continue
        records[rec.client_id] = (rec, reply)
        losses[rec.client_id] = float(reply.loss)
        weight = float(reply.nr_samples) or rec.nr_samples
        if weight_scale is not None:
            weight *= float(weight_scale.get(rec.client_id, 1.0))
        candidates.append((rec.client_id, weight, snap))

    result = gate.admit_round(
        candidates, current_global, round_idx, staleness=staleness,
    )
    for rej in result.rejected:
        rec, _reply = records[rej.client_id]
        if gate.consecutive(rej.client_id) >= gate.suspect_after:
            on_poisoned(rec, rej)
    for client_id, _w, _s in result.accepted:
        if client_id in was_suspect:
            on_recovered(client_id)
    return result, losses, records
