"""Update admission gate: the data-plane trust boundary of the federation.

PR 2 hardened the *transport* plane (retry/probation/quorum); until PR 5 the
*data* plane was fully trusting — any tensor a client returned flowed
straight into the round average, and the per-minibatch exchange (the
reference gFedNTM design) makes that a one-round total poisoning: a single
NaN coordinate, exploded norm, or adversarially scaled payload is averaged
in and re-broadcast to every client. Practical-FL surveys name unreliable
client updates a first-class failure mode alongside stragglers
(arXiv:2405.20431 §4), and the FALD analysis (arXiv:2112.05120) shows how
sensitive the averaged model is to heavy-tailed per-client noise.

:class:`UpdateGate` screens every decoded client snapshot before it can
enter the aggregate step:

1. **conformance** — key set, per-tensor shape AND dtype must match the
   server's shared template (the skew-skip logic that used to live inline
   in ``server._collect_snapshots``);
2. **finiteness** — every tensor must be NaN/Inf-free;
3. **norm screening** — the update norm ``||snapshot - current_global||``
   is tested against the round cohort's ``median + k * MAD`` (a robust
   outlier test that needs no tuning against absolute scales), and
   optionally hard-clipped to ``max_update_norm`` (gradient-clipping
   semantics: the direction is kept, the influence is bounded).

Rejected updates are excluded from the average, logged as
``update_rejected`` telemetry events with a machine-readable reason code,
and counted per client; ``consecutive(client)`` lets the server feed
repeat offenders into the PR 2 probation machinery
(``Federation.mark_suspect(reason="poisoned")``) so a persistently
poisonous client is backed off and eventually dropped exactly like a
persistently unreachable one.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Any, Mapping

import numpy as np

__all__ = ["Rejection", "GateResult", "UpdateGate", "update_norm"]

# Reason codes (the `update_rejected` event's `reason` field vocabulary).
KEY_SKEW = "key_skew"
SHAPE_SKEW = "shape_skew"
DTYPE_SKEW = "dtype_skew"
NONFINITE = "nonfinite"
NORM_OUTLIER = "norm_outlier"

#: MAD → sigma for normally distributed data (the usual robust-scale
#: consistency constant).
_MAD_SIGMA = 1.4826


def update_norm(
    snapshot: Mapping[str, np.ndarray],
    reference: Mapping[str, np.ndarray],
) -> float:
    """Global L2 norm of ``snapshot - reference`` over the shared subset
    (float64 accumulation — a poisoned float32 update can overflow a
    same-dtype square)."""
    total = 0.0
    for key, value in snapshot.items():
        d = (
            np.asarray(value, np.float64)
            - np.asarray(reference[key], np.float64)
        )
        total += float(np.dot(d.ravel(), d.ravel()))
    return float(np.sqrt(total))


@dataclass
class Rejection:
    """One gated-out update: who, why, and with what norm (NaN when the
    rejection happened before the norm stage)."""

    client_id: int
    reason: str
    detail: str
    norm: float = float("nan")


@dataclass
class GateResult:
    """Outcome of one round's admission pass."""

    accepted: list  # [(client_id, weight, snapshot)]
    rejected: list  # [Rejection]
    clipped: list  # [(client_id, norm, max_norm)]


class UpdateGate:
    """Per-round admission screening of decoded client snapshots.

    ``mad_k <= 0`` disables the cohort outlier test; ``max_update_norm``
    ``None`` disables the hard clip; ``check_finite=False`` turns the gate
    into a pure conformance check (the pre-PR 5 behaviour — used by tests
    that need to demonstrate unprotected poisoning). The MAD test only
    runs on cohorts of at least ``min_cohort`` candidates: a median over
    one or two updates is not a statistic.
    """

    def __init__(
        self,
        *,
        check_finite: bool = True,
        mad_k: float = 4.0,
        mad_rel_floor: float = 0.5,
        max_update_norm: float | None = None,
        min_cohort: int = 3,
        suspect_after: int = 2,
        metrics: Any = None,
        logger: logging.Logger | None = None,
    ):
        if mad_rel_floor < 0:
            raise ValueError(
                f"mad_rel_floor must be >= 0, got {mad_rel_floor}"
            )
        if max_update_norm is not None and max_update_norm <= 0:
            raise ValueError(
                f"max_update_norm must be > 0, got {max_update_norm}"
            )
        if suspect_after < 1:
            raise ValueError(
                f"suspect_after must be >= 1, got {suspect_after}"
            )
        self.check_finite = bool(check_finite)
        self.mad_k = float(mad_k)
        # Scale floor as a fraction of the median norm: with a tiny cohort
        # the MAD collapses toward 0 and every deviation would read as an
        # outlier; the floor keeps the rejection threshold at least
        # (1 + mad_k * mad_rel_floor) x the median.
        self.mad_rel_floor = float(mad_rel_floor)
        self.max_update_norm = (
            None if max_update_norm is None else float(max_update_norm)
        )
        self.min_cohort = int(min_cohort)
        self.suspect_after = int(suspect_after)
        self.metrics = metrics
        self.logger = logger or logging.getLogger("UpdateGate")
        self._expected_keys: frozenset[str] | None = None
        self._expected_shapes: dict[str, tuple] = {}
        self._expected_dtypes: dict[str, np.dtype] = {}
        # Consecutive rejection streak per client (reset on acceptance):
        # the "repeated offender" signal the server feeds into probation.
        self._streak: dict[int, int] = {}
        self.total_rejections: dict[int, int] = {}

    # ---- template ----------------------------------------------------------
    def set_template(self, template: Mapping[str, np.ndarray]) -> None:
        """Pin the authoritative key/shape/dtype contract (the server's
        shared template subset)."""
        self._expected_keys = frozenset(template)
        self._expected_shapes = {
            k: tuple(np.asarray(v).shape) for k, v in template.items()
        }
        self._expected_dtypes = {
            k: np.asarray(v).dtype for k, v in template.items()
        }

    def consecutive(self, client_id: int) -> int:
        """Current consecutive-rejection streak for one client."""
        return self._streak.get(client_id, 0)

    # ---- per-candidate checks ----------------------------------------------
    def _conformance(self, client_id: int, snap: Mapping) -> Rejection | None:
        if self._expected_keys is None:
            return None
        if frozenset(snap) != self._expected_keys:
            missing = sorted(self._expected_keys - set(snap))[:3]
            unexpected = sorted(set(snap) - self._expected_keys)[:3]
            return Rejection(
                client_id, KEY_SKEW,
                f"missing={missing}, unexpected={unexpected}",
            )
        for key in snap:
            arr = np.asarray(snap[key])
            want = self._expected_shapes[key]
            if tuple(arr.shape) != want:
                return Rejection(
                    client_id, SHAPE_SKEW,
                    f"{key}: {tuple(arr.shape)} != {want}",
                )
            if arr.dtype != self._expected_dtypes[key]:
                return Rejection(
                    client_id, DTYPE_SKEW,
                    f"{key}: {arr.dtype} != {self._expected_dtypes[key]}",
                )
        return None

    @staticmethod
    def _nonfinite(client_id: int, snap: Mapping) -> Rejection | None:
        for key in sorted(snap):
            arr = np.asarray(snap[key])
            if arr.dtype.kind == "f" and not np.all(np.isfinite(arr)):
                bad = int(arr.size - np.isfinite(arr).sum())
                return Rejection(
                    client_id, NONFINITE,
                    f"{key}: {bad}/{arr.size} non-finite values",
                )
        return None

    def _outlier_threshold(self, norms: list[float]) -> float | None:
        """The cohort's rejection threshold, or None when the MAD test
        cannot run (disabled, or cohort too small)."""
        if self.mad_k <= 0 or len(norms) < self.min_cohort:
            return None
        arr = np.asarray(norms, np.float64)
        med = float(np.median(arr))
        mad = float(np.median(np.abs(arr - med)))
        scale = max(_MAD_SIGMA * mad, self.mad_rel_floor * med, 1e-12)
        return med + self.mad_k * scale

    # ---- the round pass ----------------------------------------------------
    def admit_round(
        self,
        candidates: "list[tuple[int, float, dict[str, np.ndarray]]]",
        current_global: Mapping[str, np.ndarray],
        round_idx: int,
    ) -> GateResult:
        """Screen one round's ``(client_id, weight, snapshot)`` candidates.

        Order matters: conformance and finiteness run per candidate; norms
        are then computed for the structurally-sound survivors ONLY (a
        shape-skewed or NaN update must not pollute the cohort statistics
        it is judged against); MAD outliers are rejected on raw norms;
        finally the hard clip bounds whoever remains. Telemetry and streak
        bookkeeping happen here so every caller gets identical accounting.
        """
        rejected: list[Rejection] = []
        clipped: list[tuple[int, float, float]] = []
        sound: list[tuple[int, float, dict, float]] = []
        for client_id, weight, snap in candidates:
            rej = self._conformance(client_id, snap)
            if rej is None and self.check_finite:
                rej = self._nonfinite(client_id, snap)
            if rej is not None:
                rejected.append(rej)
                continue
            norm = (
                update_norm(snap, current_global)
                if (self.mad_k > 0 or self.max_update_norm is not None)
                and self.check_finite
                else float("nan")
            )
            sound.append((client_id, weight, snap, norm))

        threshold = self._outlier_threshold(
            [n for _c, _w, _s, n in sound if np.isfinite(n)]
        )
        accepted: list[tuple[int, float, dict]] = []
        for client_id, weight, snap, norm in sound:
            if threshold is not None and norm > threshold:
                rejected.append(Rejection(
                    client_id, NORM_OUTLIER,
                    f"update norm {norm:.3e} > cohort threshold "
                    f"{threshold:.3e}",
                    norm=norm,
                ))
                continue
            if (
                self.max_update_norm is not None
                and np.isfinite(norm) and norm > self.max_update_norm
            ):
                factor = self.max_update_norm / norm
                snap = {
                    k: np.asarray(
                        np.asarray(current_global[k], np.float64)
                        + factor * (
                            np.asarray(v, np.float64)
                            - np.asarray(current_global[k], np.float64)
                        ),
                        dtype=np.asarray(v).dtype,
                    )
                    for k, v in snap.items()
                }
                clipped.append((client_id, norm, self.max_update_norm))
            accepted.append((client_id, weight, snap))

        self._account(accepted, rejected, clipped, round_idx)
        return GateResult(accepted=accepted, rejected=rejected,
                          clipped=clipped)

    def _account(self, accepted, rejected, clipped, round_idx: int) -> None:
        m = self.metrics
        for client_id, _w, _s in accepted:
            self._streak.pop(client_id, None)
        for rej in rejected:
            self._streak[rej.client_id] = (
                self._streak.get(rej.client_id, 0) + 1
            )
            self.total_rejections[rej.client_id] = (
                self.total_rejections.get(rej.client_id, 0) + 1
            )
            self.logger.warning(
                "round %d: rejecting client %d update (%s: %s); excluding "
                "it from the average", round_idx, rej.client_id, rej.reason,
                rej.detail,
            )
            if m is not None:
                m.registry.counter("updates_rejected").inc()
                m.registry.counter(f"updates_rejected/{rej.reason}").inc()
                if rej.reason in (KEY_SKEW, SHAPE_SKEW, DTYPE_SKEW):
                    # Historical conformance counter, kept for dashboard
                    # continuity with the PR 2 skew-skip logic.
                    m.registry.counter("key_skew_excluded").inc()
                event = dict(
                    client=rej.client_id, round=round_idx,
                    reason=rej.reason, detail=rej.detail,
                )
                if np.isfinite(rej.norm):
                    event["norm"] = rej.norm
                m.log("update_rejected", **event)
        for client_id, norm, max_norm in clipped:
            self.logger.warning(
                "round %d: clipping client %d update norm %.3e -> %.3e",
                round_idx, client_id, norm, max_norm,
            )
            if m is not None:
                m.registry.counter("updates_clipped").inc()
                m.log(
                    "update_clipped", client=client_id, round=round_idx,
                    norm=norm, max_norm=max_norm,
                )
