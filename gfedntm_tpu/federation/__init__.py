"""Network federation for genuinely-remote clients (gRPC over DCN/WAN).

In-pod federation never touches this package — it is one SPMD program with
``lax.psum`` over ICI (:mod:`gfedntm_tpu.federated.trainer`). This package
exists for the reference's actual deployment shape — one process per
organization/container (``docker-compose.yaml:21-149``) — and bridges such
remote clients into the same stepper protocol.
"""

from gfedntm_tpu.federation import codec as codec
from gfedntm_tpu.federation import rpc as rpc
from gfedntm_tpu.federation.client import Client, FederatedClientServicer
from gfedntm_tpu.federation.pacing import PacingSpec, parse_pacing
from gfedntm_tpu.federation.registry import ClientRecord, Federation
from gfedntm_tpu.federation.resilience import (
    FaultInjector,
    FaultSpec,
    RetryPolicy,
)
from gfedntm_tpu.federation.server import FederatedServer, build_template_model
