"""Federated server for the cross-datacenter network path.

Rebuilds ``src/federation/server.py:37-553`` (``FederatedServer``): phase-1
vocabulary consensus as a gRPC servicer, phase-2 per-minibatch orchestration
where the server polls every client for its post-step shared parameters,
computes the sample-weighted average, and pushes it back
(``server.py:408-553``). Used only for genuinely-remote clients — inside a
pod the SPMD :class:`~gfedntm_tpu.federated.trainer.FederatedTrainer`
replaces all of this with one ``lax.psum``.

Deliberate mechanics changes (the reference's orchestration floor was ≥3 s
sleep × N clients per step plus 2N fresh channels, SURVEY.md §3.3):
- persistent channels per client, opened once at training start;
- clients are polled **concurrently** (ThreadPoolExecutor), not round-robin;
- no inter-client sleeps;
- quorum waits are condition-variable driven with configurable timeouts
  instead of the 120 s poll-expiry (§2.5 item 9);
- a client whose RPC fails enters **probation** (``SUSPECT``): it is
  re-polled with per-round backoff for ``probation_rounds`` rounds before
  the drop becomes permanent — recovery, not fail-soft, and several layers
  beyond the reference's §5 "no retry" crash;
- transient RPC errors are additionally retried in-call with decorrelated
  jitter (:class:`~gfedntm_tpu.federation.resilience.RetryPolicy`);
- a configurable round **quorum fraction** skips (rather than averages)
  rounds where too few clients answered, so the weighted average never
  silently degenerates to one straggler's parameters;
- round state (``last_average`` + round counter + membership) is
  **checkpointed** every ``checkpoint_every`` rounds, and a crashed server
  restarted with :meth:`FederatedServer.restore_from_checkpoint` continues
  from the checkpointed round while clients rejoin;
- the data plane is hardened too (README "Robust aggregation & divergence
  recovery"): every decoded reply passes an **update admission gate**
  (conformance, finiteness, cohort norm screening) before it can enter the
  aggregate, the mean stage may be **byzantine-robust**
  (trimmed-mean/median/Krum), and a **divergence guardian** rolls the
  global model back to the last good checkpoint when it diverges anyway;
- the round *control plane* lives in
  :mod:`~gfedntm_tpu.federation.pacing` (README "Federation pacing"):
  this module keeps the data plane (decode + admission, aggregation
  strategies, guardian, quality plane, codec sessions, checkpointing)
  and the gRPC servicer surface, while the pacing engine decides who is
  polled when — the all-clients ``sync`` barrier (default, bitwise the
  historical trajectory), seeded ``cohort:<K>`` sampling with unbiased
  reweighting, or ``async:<B>`` FedBuff-style buffered aggregation with
  staleness-discounted updates.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import logging
import math
import threading
import time
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import Any

import numpy as np

from gfedntm_tpu.config import SHARE_ALL
from gfedntm_tpu.data.vocab import Vocabulary
from gfedntm_tpu.federation import codec, pacing, rpc
from gfedntm_tpu.federation.aggregation import make_aggregator
from gfedntm_tpu.federation.compression import (
    DownlinkEncoder,
    UplinkDecoder,
    encode_push_for_recipients,
    make_codec,
)
from gfedntm_tpu.federation.protos import federated_pb2 as pb
from gfedntm_tpu.eval.monitor import COHERENCE_COLLAPSE, ContributionTracker
from gfedntm_tpu.federation.registry import (
    DROPPED,
    Federation,
    looks_like_session_token as _looks_like_session_token,
)
from gfedntm_tpu.federation.resilience import RetryPolicy
from gfedntm_tpu.federation.sanitize import UpdateGate, decode_and_admit
from gfedntm_tpu.models.avitm import AVITM
from gfedntm_tpu.train.guardian import DivergenceGuardian
from gfedntm_tpu.models.ctm import CTM
from gfedntm_tpu.utils import flightrec
from gfedntm_tpu.utils.observability import (
    FleetRegistry,
    OpsServer,
    RoundProfiler,
    StragglerDetector,
    new_trace_id,
)


#: Default additive NPMI slack the coherence-collapse guard gets under
#: any DP mode (README "Differential privacy & posterior sampling"):
#: wide enough that per-round noise jitter at the published scales never
#: false-triggers a rollback, narrow enough that a genuine collapse
#: (NPMI cliffs are several tenths) still fires. Operators override via
#: quality_monitor_kwargs={"noise_floor": ...}.
DP_GUARD_NOISE_FLOOR = 0.05


def build_template_model(
    family: str, vocab_size: int, model_kwargs: dict[str, Any]
) -> AVITM:
    """Construct the global template model (server-side init that every
    client replicates, ``server.py:290-331``)."""
    kwargs = dict(model_kwargs)
    kwargs["input_size"] = int(vocab_size)
    if "hidden_sizes" in kwargs:
        kwargs["hidden_sizes"] = tuple(kwargs["hidden_sizes"])
    if family == "avitm":
        return AVITM(**kwargs)
    if family == "ctm":
        return CTM(**kwargs)
    raise ValueError(f"unknown model family {family!r}")


class FederatedServer:
    """gRPC servicer + training orchestrator.

    Parameters mirror the reference CLI surface (``main.py:187-205``):
    ``min_clients`` (= --min_clients_federation), ``family`` + ``model_kwargs``
    (= --model_type + INI hyperparams), ``max_iters``.

    ``metrics`` is an optional
    :class:`~gfedntm_tpu.utils.observability.MetricsLogger`: each round then
    emits nested ``round → {poll, average, push}`` spans (bytes moved,
    slowest client), per-client poll-latency histograms and staleness
    gauges, RPC/codec registry metrics, and a final ``metrics_snapshot``.
    The logger is driven from poll/push worker threads — it is thread-safe.
    """

    def __init__(
        self,
        min_clients: int,
        family: str = "avitm",
        model_kwargs: dict[str, Any] | None = None,
        grads_to_share: tuple[str, ...] = SHARE_ALL,
        max_iters: int = 25_000,
        save_dir: str | None = None,
        logger: logging.Logger | None = None,
        metrics=None,
        poll_workers: int = 16,
        local_steps: int = 1,
        retry_policy: RetryPolicy | None = None,
        probation_rounds: int = 3,
        quorum_fraction: float = 0.5,
        checkpoint_every: int = 25,
        round_backoff_s: float = 0.5,
        fault_injector=None,
        aggregator="fedavg",
        aggregator_kwargs: dict[str, Any] | None = None,
        robust_aggregator: str | None = None,
        aggregation_backend: str = "auto",
        sanitize: bool = True,
        max_update_norm: float | None = None,
        outlier_mad_k: float = 4.0,
        divergence_patience: int = 3,
        divergence_loss_factor: float = 4.0,
        wire_codec: str = "none",
        codec_ref_cache: int = 8,
        codec_ref_cache_max: int = 64,
        ops_port: int | None = None,
        ops_host: str = "127.0.0.1",
        profiler: RoundProfiler | None = None,
        straggler_z: float = 2.0,
        quality_every: int = 0,
        quality_ref: str | None = None,
        quality_topn: int = 10,
        quality_guard: bool = False,
        quality_history: int = 64,
        quality_monitor_kwargs: dict[str, Any] | None = None,
        pacing_policy: str = "sync",
        cohort_size: int | None = None,
        async_buffer: int | None = None,
        staleness_alpha: float = 0.5,
        pacing_seed: int = 0,
        journal_every: int = 1,
        reconnect_grace_s: float = 120.0,
        relay_grace_rounds: int = 0,
        slo_specs=None,
        fleet_max_nodes: int = 512,
        fleet_max_series: int = 512,
        dp: str = "off",
        dp_clip: float = 1.0,
        dp_sigma: float = 0.0,
        dp_delta: float = 1e-5,
        dp_budget: float = 0.0,
        dp_seed: int = 0,
        dump_dir: str | None = None,
        flightrec_entries: int = 2048,
        flightrec_seconds: float = 300.0,
        flightrec_debounce_s: float = 30.0,
        flightrec_max_bundles: int = 32,
    ):
        if local_steps < 1:
            raise ValueError(f"local_steps must be >= 1, got {local_steps}")
        if probation_rounds < 1:
            raise ValueError(
                f"probation_rounds must be >= 1, got {probation_rounds}"
            )
        if not 0.0 <= quorum_fraction <= 1.0:
            raise ValueError(
                f"quorum_fraction must be in [0, 1], got {quorum_fraction}"
            )
        self.family = family
        self.model_kwargs = dict(model_kwargs or {})
        self.grads_to_share = tuple(grads_to_share)
        self.max_iters = max_iters
        self.save_dir = save_dir
        self.logger = logger or logging.getLogger("FederatedServer")
        self.metrics = metrics
        self.poll_workers = poll_workers
        # Round pacing (README "Federation pacing"): "sync" preserves the
        # historical all-clients barrier bitwise; "cohort:<K>" samples a
        # seeded K-of-N roster per round with unbiased inverse-inclusion-
        # probability reweighting; "async:<B>" is FedBuff-style buffered
        # aggregation with staleness-discounted updates. Parsed eagerly so
        # a bad spec fails at construction, not mid-federation; the engine
        # itself is built when the training loop starts.
        self.pacing = pacing.parse_pacing(
            pacing_policy, cohort_size=cohort_size,
            async_buffer=async_buffer, staleness_alpha=staleness_alpha,
            seed=pacing_seed,
        )
        self._engine: pacing.RoundEngine | None = None
        # FedAvg exchange period in local minibatches (1 = the reference's
        # per-minibatch averaging; E>1 = FedAvg proper — the same knob as
        # FederatedTrainer.local_steps, carried to clients per StepRequest).
        self.local_steps = int(local_steps)
        # Resilience knobs (README "Fault tolerance"): in-call RPC retry,
        # round-scoped probation before a permanent drop, minimum fraction
        # of the round's unfinished membership that must answer for the
        # average to
        # count, round checkpoint period (0 disables; needs save_dir), and
        # the wall-clock pause after a reply-less / below-quorum round.
        self.retry_policy = retry_policy or RetryPolicy(metrics=metrics)
        self.probation_rounds = int(probation_rounds)
        self.quorum_fraction = float(quorum_fraction)
        self.checkpoint_every = int(checkpoint_every)
        self.round_backoff_s = float(round_backoff_s)
        self.fault_injector = fault_injector
        # Aggregation strategy (README "Aggregation strategies & wire
        # compression"): the round's aggregate step is a strategy call —
        # FedAvg reproduces the historical inline average bit-for-bit;
        # FedAvgM/FedAdam/FedYogi carry server-optimizer state across
        # rounds (checkpointed with the round state, so --resume keeps it).
        self.aggregator = make_aggregator(
            aggregator, robust=robust_aggregator,
            **(aggregator_kwargs or {})
        )
        # Aggregation data-plane backend (README "Device-resident
        # aggregation"): "device" stacks each round's snapshots into one
        # sharded device array and runs the gate statistics + robust mean
        # stage as jitted XLA programs; "numpy" is the host reference
        # oracle; "auto" (default) picks device exactly when an
        # accelerator backend is present, so CPU deployments (and tier-1)
        # are bit-for-bit unchanged. Resolved lazily at first use
        # (_ensure_template) so constructing a server never initializes
        # jax's backend on its own.
        if aggregation_backend not in ("auto", "device", "numpy"):
            raise ValueError(
                f"aggregation_backend must be auto|device|numpy, got "
                f"{aggregation_backend!r}"
            )
        self.aggregation_backend = aggregation_backend
        self._agg_backend_resolved: str | None = None
        # Data-plane defense (README "Robust aggregation & divergence
        # recovery"), three layers: (1) the update admission gate screens
        # every decoded reply (conformance always; finiteness + norm
        # screening unless sanitize=False) and feeds repeat offenders into
        # probation; (2) the aggregator above may carry a byzantine-robust
        # mean stage; (3) the divergence guardian watches the aggregate
        # itself and triggers a checkpoint rollback when the global model
        # diverges anyway (divergence_patience=0 disables it).
        self.update_gate = UpdateGate(
            check_finite=bool(sanitize),
            mad_k=float(outlier_mad_k) if sanitize else 0.0,
            max_update_norm=max_update_norm if sanitize else None,
            metrics=metrics, logger=self.logger,
        )
        self.guardian = (
            DivergenceGuardian(
                patience=divergence_patience,
                loss_factor=divergence_loss_factor,
                metrics=metrics, logger=self.logger,
            )
            if divergence_patience > 0 else None
        )
        # Privacy plane (README "Differential privacy & posterior
        # sampling"): ``--dp off`` (the default) constructs NOTHING —
        # no noiser, no accountant — so every existing trajectory is
        # bitwise unchanged. ``--dp server`` injects FedLD noise into
        # the aggregate after the (possibly robust) mean stage and
        # tightens the admission gate's clip to the DP clip (that clip
        # IS the sensitivity bound the noise is calibrated to);
        # ``--dp client`` expects clients to sanitize locally and only
        # runs the server-side ledger, charged conservatively at q = 1
        # with the declared mechanism parameters.
        from gfedntm_tpu.privacy.mechanisms import parse_dp

        self.dp = parse_dp(
            dp, clip=dp_clip, sigma=dp_sigma, delta=dp_delta,
            budget=dp_budget, seed=dp_seed,
        )
        self.privacy_accountant = None
        self._dp_noiser = None
        if self.dp.enabled:
            from gfedntm_tpu.privacy import PrivacyAccountant, ServerNoiser

            self.privacy_accountant = PrivacyAccountant(
                sigma=self.dp.sigma, delta=self.dp.delta,
                budget=self.dp.budget, mode=self.dp.mode,
            )
            if self.dp.mode == "server":
                self._dp_noiser = ServerNoiser(self.dp, metrics=metrics)
                self.aggregator.noiser = self._dp_noiser
                if sanitize:
                    gate = self.update_gate
                    gate.max_update_norm = (
                        self.dp.clip if gate.max_update_norm is None
                        else min(gate.max_update_norm, self.dp.clip)
                    )
                else:
                    self.logger.warning(
                        "--dp server with sanitize off: the admission "
                        "gate is not enforcing the DP clip, so the "
                        "declared sensitivity bound rests on clients "
                        "clipping honestly",
                    )
        # Wire codec, negotiated with every client at join time: the
        # GlobalSetup advertises this id, ReadyForTraining verifies the
        # client runs the same one (mismatch = Ack code 2, loud on both
        # ends — never a silent mis-decode).
        self.wire_codec = make_codec(wire_codec)
        self._uplink_dec = UplinkDecoder(
            self.wire_codec, metrics=metrics, max_refs=codec_ref_cache,
        )
        self._downlink_enc = DownlinkEncoder(
            self.wire_codec, metrics=metrics, max_views=codec_ref_cache,
        )
        # Hard cap on both reference caches (ISSUE 11 satellite): the
        # rotation-aware auto-size below (~4N/K) is unbounded in N at
        # fixed K — the cap bounds server tensor memory; past it, a
        # long-unsampled client degrades to a self-contained push /
        # loud ReferenceMismatch heal instead of growing the cache.
        self.codec_ref_cache_max = int(codec_ref_cache_max)
        # Wire-codec sessions are single-threaded under poll pacing (the
        # round loop owns them); push pacing adds gRPC PushUpdate threads
        # encoding per-recipient replies concurrently with the engine
        # advancing the canonical chain — every session touch holds this.
        self._codec_lock = threading.Lock()
        # Per-client round of the last acked push — a push may only be
        # delta-encoded when every recipient holds the encoder's delta
        # reference (the immediately-previous broadcast). Under cohort and
        # async pacing, different clients legitimately hold broadcasts of
        # different rounds, so this is a round-tagged map rather than the
        # historical single-round set; sync semantics are unchanged (the
        # allow_delta check compares each recipient's tag to the encoder's
        # reference round). Written by the training loop (round push
        # results, rollback clears) AND by gRPC servicer threads (a
        # rejoiner is discarded in ReadyForTraining), so every mutation
        # holds _push_lock: a lost discard would let the next push
        # delta-encode against a broadcast the fresh process never held.
        self._push_lock = threading.Lock()
        self._push_acked: dict[int, int] = {}  # guarded-by: _push_lock
        # Push pacing bookkeeping: the round of the last broadcast each
        # client was SENT in a PushUpdate reply (caps base_round claims —
        # a client cannot "ack" a round it was never given), and, after a
        # divergence rollback, the rollback round each member still owes
        # a session reset for (the reset rides every PushUpdate reply
        # until the member demonstrably applied a post-rollback round).
        self._push_sent: dict[int, int] = {}  # guarded-by: _push_lock
        self._reset_owed: dict[int, int] = {}  # guarded-by: _push_lock
        # Receipt-time replay guard for client-minted PushUpdate seqs —
        # deliberately SEPARATE from `_reply_seen` (which the drain-time
        # _collect_snapshots check reads and records): recording a push
        # seq at receipt would make its own drain read as a replay.
        self._push_seen: dict[int, int] = {}
        # Identity-codec PushUpdate reply memo: (average object, round,
        # encoded bundle) — see the PushUpdate identity branch.
        self._push_identity_memo: "tuple[Any, int, pb.TensorBundle] | None" \
            = None
        # Set by a divergence rollback (and by crash recovery): the NEXT
        # push carries Aggregate.reset_session so every recipient drops
        # its wire-codec session state (delta refs + error-feedback
        # residuals) before applying — no mass from the discarded
        # trajectory (or from a dead server pairing) survives client-side.
        self._session_reset_pending = False

        # Idempotent-RPC plane (README "Crash recovery & sessions"):
        # every TrainStep delivery carries a server-minted sequence
        # number, monotonic ACROSS restarts (wall-clock epoch base) —
        # clients answer a replayed seq from their cache, and
        # `_reply_seen` (last seen reply seq per client; written by the
        # training loop, cleared by join-time servicer threads — CPython
        # dict ops are atomic and a lost clear only widens the replay
        # guard) drops duplicate StepReplies before they can
        # double-count a client in the average or corrupt delta-codec
        # ack state. Client stubs therefore run an idempotent retry
        # policy: DEADLINE_EXCEEDED — "the call may have executed" —
        # becomes safely retryable.
        self._seq_epoch = int(time.time()) << 20
        self._seq_counter = itertools.count(1)
        self._reply_seen: dict[int, int] = {}
        self.client_retry_policy = dataclasses.replace(
            self.retry_policy, idempotent=True
        )

        # Crash-recovery plane: a per-pushed-round journal (atomic npz +
        # JSON under save_dir/checkpoints) lets a SIGKILLed server
        # restarted with NO flags resume from the last fully-pushed
        # round — `journal_every` rounds of work at risk (default 1; 0
        # disables journaling and auto-recovery).
        self.journal_every = int(journal_every)
        self._round_journal = None
        # Set by the first journal write that hits the filesystem's
        # failure surface (ENOSPC/EIO): training continues, journaling
        # (and therefore crash autorecovery) is off for the rest of the
        # run — see _note_journal_write_failure.
        self._journal_disabled = False
        self._recovered_from: int | None = None
        self._recovered_source: str | None = None
        # Wall-clock timestamp of the autorecovery restore, consumed by
        # the recovery_time_s gauge the moment the post-recovery quorum
        # re-forms (the metric the `recovery_time` SLO example bounds).
        self._recovered_at: float | None = None
        # Shard supervision (README "Crash recovery & sessions"): when
        # this server's members are RELAYS (a hierarchy root), a relay
        # silent for this many rounds leaves the quorum denominator —
        # quorum is denominated over live shards instead of stalling
        # until the dead relay's probation budget runs out. 0 keeps the
        # flat-fleet semantics bitwise.
        self.relay_grace_rounds = int(relay_grace_rounds)
        # After recovery the original min_clients bar may be unreachable
        # (some members died for good): training restarts once
        # quorum_fraction of the restored unfinished membership is back.
        self._resume_ready_needed: int | None = None
        # Restored members that have not reconnected yet hold the round
        # loop open for this long after training resumes: a recovered
        # fleet whose fast members finish in seconds must not declare
        # the federation over before slower members' watchdogs have even
        # fired. Bounded — a member gone for good cannot stall forever.
        self.reconnect_grace_s = float(reconnect_grace_s)
        self._recovery_deadline: float | None = None

        # Clients whose compile-dominated first poll has been seen (and
        # excluded from the poll-latency/straggler stats).
        self._poll_warmed: set[int] = set()

        # Cross-process observability plane (README "Distributed tracing &
        # ops endpoint"): one trace id per training run (every poll/push
        # carries it in gRPC metadata, so client-side serve spans land in
        # the same tree), an optional live ops endpoint (/metrics, /healthz,
        # /status; port=0 binds ephemeral, None disables — no thread), an
        # optional jax.profiler round window, and rolling straggler
        # analytics over the warmed poll latencies.
        self.trace_id: str | None = None
        self.ops_port = ops_port
        self.ops_host = ops_host
        self.ops_actual_port: int | None = None
        self._ops_server: OpsServer | None = None
        self.profiler = profiler
        self.straggler = StragglerDetector(
            registry=metrics.registry if metrics is not None else None,
            z_threshold=straggler_z,
        )

        # Fleet telemetry plane + SLO engine (README "Fleet telemetry &
        # SLOs"): clients piggyback delta-encoded registry reports on the
        # replies/pushes/rejoins they already send; the FleetRegistry
        # holds the per-node latest behind a cardinality guard, and the
        # pacing engines tick the SLO state machine once per aggregation
        # (_fleet_tick) — no extra threads, no extra round-trips.
        self.fleet = FleetRegistry(
            metrics=metrics, max_nodes=fleet_max_nodes,
            max_series_per_node=fleet_max_series,
        )
        if slo_specs:
            from gfedntm_tpu.utils.slo import SLOEngine

            self.slo = SLOEngine(
                slo_specs, snapshot_fn=self.fleet.merged, metrics=metrics,
            )
        else:
            self.slo = None

        # Incident-forensics plane (README "Incident forensics"): with a
        # --dump_dir, a FlightRecorder rings every logger record at full
        # fidelity and the IncidentTrigger seam dumps atomic postmortem
        # bundles when a detector fires — plus solicits flight-record
        # snapshots from implicated members on the next RPC exchange
        # (on_capture -> capture_token riding polls / push replies).
        # Unset (the default) constructs NOTHING: no recorder on the
        # logger, no trigger, bitwise-identical round loop.
        self.dump_dir = dump_dir
        self._incident_trigger: "flightrec.IncidentTrigger | None" = None
        self._flightrec_solicit: "tuple[str, float] | None" = None
        if dump_dir is not None and metrics is not None:
            recorder = flightrec.FlightRecorder(
                max_entries=flightrec_entries,
                max_seconds=flightrec_seconds,
                registry=metrics.registry,
            )
            metrics.recorder = recorder
            self._incident_trigger = flightrec.IncidentTrigger(
                recorder, dump_dir, metrics=metrics,
                node=metrics.node or "server",
                status_cb=lambda: self._status(full=False),
                debounce_s=flightrec_debounce_s,
                max_bundles=flightrec_max_bundles,
                on_capture=self._solicit_flightrec,
            )

        # Model-quality observability plane (README "Model-quality
        # observability"): with quality_every > 0, every quality round
        # extracts topic words from the global beta, computes NPMI
        # coherence against the server-held --quality_ref corpus plus
        # diversity and round-over-round drift, and the per-round
        # contribution analytics (cosine to the accepted aggregate,
        # pairwise cohort similarity) run on every averaged round. The
        # default (0) keeps the round loop bit-identical: no monitor is
        # ever constructed, no extra device pass runs, no events appear.
        if quality_every < 0:
            raise ValueError(
                f"quality_every must be >= 0, got {quality_every}"
            )
        self.quality_every = int(quality_every)
        self.quality_ref = quality_ref
        self.quality_topn = int(quality_topn)
        self.quality_guard = bool(quality_guard)
        self.quality_history = int(quality_history)
        # Extra TopicQualityMonitor knobs (guard_drop/guard_patience/
        # churn_cos/...) for operators and the scenario harness; the CLI
        # exposes only the common surface.
        self.quality_monitor_kwargs = dict(quality_monitor_kwargs or {})
        self._quality_mon = None
        self.contributions = ContributionTracker(
            registry=metrics.registry if metrics is not None else None
        )

        self.federation = Federation(min_clients=min_clients)
        self.template: AVITM | None = None
        self.global_vocab: Vocabulary | None = None
        self.last_average: dict[str, np.ndarray] | None = None
        self.global_betas: np.ndarray | None = None
        self.global_iterations = 0

        self._setup_lock = threading.Lock()
        # Built exactly once under _setup_lock — every joiner blocked in
        # GetGlobalSetup must receive the SAME consensus reply.
        self._setup_reply: pb.GlobalSetup | None = None  # guarded-by: _setup_lock
        self._train_lock = threading.Lock()
        # Started exactly once under _train_lock by whichever
        # ReadyForTraining completes quorum.
        self._train_thread: threading.Thread | None = None  # guarded-by: _train_lock
        # _stopping is set BEFORE the stop-broadcast client snapshot so a
        # ReadyForTraining that lands in the shutdown window (after the
        # snapshot, before training_done) is turned away with code=1 instead
        # of blocking forever on a stop that will never be sent.
        self._stopping = threading.Event()
        # _aborted models a hard server crash (tests/emergencies): the loop
        # exits WITHOUT the stop broadcast or finalization, leaving clients
        # to their liveness watchdogs — exactly like a SIGKILL.
        self._aborted = threading.Event()
        self.training_done = threading.Event()
        self._grpc_server = None
        self._expected_keys: frozenset[str] | None = None
        self._expected_shapes: dict[str, tuple] | None = None
        self._ckpt = None
        # Bookkeeping of the most recent admitted cohort, written by
        # _collect_snapshots and read by the guardian step: (client_id,
        # weight, reported loss) per accepted reply. Single-threaded use —
        # only the training loop touches it.
        self._round_accepted: list[tuple[int, float, float]] = []

    # ---- lifecycle ---------------------------------------------------------
    def start(self, address: str = "[::]:50051") -> str:
        # Every client parks one worker thread inside GetGlobalSetup until
        # quorum; size the pool so intake RPCs can still be dispatched.
        self._grpc_server = rpc.make_server(
            max_workers=max(
                self.poll_workers, 2 * self.federation.min_clients + 4
            )
        )
        rpc.add_service(
            self._grpc_server, "gfedntm.Federation", self,
            metrics=self.metrics,
        )
        port = self._grpc_server.add_insecure_port(address)
        self._grpc_server.start()
        self.logger.info("federation server listening on port %d", port)
        if self.ops_port is not None:
            self._ops_server = OpsServer(
                registry=(
                    self.metrics.registry if self.metrics is not None
                    else None
                ),
                status_fn=self._status,
                host=self.ops_host, port=self.ops_port,
                fleet=self.fleet,
                alerts_fn=self.slo.status if self.slo is not None else None,
            )
            self.ops_actual_port = self._ops_server.start()
            self.logger.info(
                "ops endpoint on http://%s:%d (/metrics /healthz /status)",
                self.ops_host, self.ops_actual_port,
            )
            if self.metrics is not None:
                self.metrics.log(
                    "ops_server_started", port=self.ops_actual_port,
                )
        return f"localhost:{port}" if address.startswith("[::]") else address

    def stop(self, grace: float = 1.0, join_timeout: float = 10.0) -> None:
        """Graceful shutdown: signal the training loop (waking any backoff
        waits), give it ``join_timeout`` seconds to run its stop broadcast
        and finalization, then stop the gRPC server. Without the join, the
        training thread would keep polling against a stopped server."""
        self._stopping.set()
        t = self._train_thread
        if t is not None and t.is_alive():
            t.join(join_timeout)
            if t.is_alive():
                self.logger.warning(
                    "training thread still running after %.1fs; stopping "
                    "the gRPC server anyway", join_timeout,
                )
        if self._grpc_server is not None:
            self._grpc_server.stop(grace)
        self._stop_ops_server()

    def abort(self) -> None:
        """Hard-crash simulation: kill the gRPC server NOW and abandon the
        training loop with no stop broadcast and no finalization — clients
        are left to their liveness watchdogs, and a later server process
        can :meth:`restore_from_checkpoint`."""
        self._aborted.set()
        self._stopping.set()
        if self._grpc_server is not None:
            self._grpc_server.stop(0)
        self._stop_ops_server()

    def _stop_ops_server(self) -> None:
        if self._ops_server is not None:
            self._ops_server.stop()
            self._ops_server = None

    def _status(self, full: bool = False) -> dict[str, Any]:
        """The live ops endpoint's ``/status`` payload: round progress,
        membership, negotiated codec + compression ratios, and the
        straggler view — all JSON-safe reads, no training-loop locks held
        across RPC work.

        The default view is a bounded *summary* (ISSUE 11 satellite):
        per-state membership counts plus top-k failing/slowest members —
        at 10⁴ clients the full per-client dict build stalls the ops
        thread on every scrape. ``full=True`` (``/status?full=1``)
        restores the complete roster and per-client straggler/
        contribution series."""
        reg = self.metrics.registry if self.metrics is not None else None

        def gauge(name):
            metric = reg.get(name) if reg is not None else None
            return metric.value if metric is not None else None

        def count(name):
            metric = reg.get(name) if reg is not None else None
            return int(metric.value) if metric is not None else 0

        return {
            "round": int(self.global_iterations),
            "max_iters": int(self.max_iters),
            "min_clients": int(self.federation.min_clients),
            "training_started": self._train_thread is not None,
            "training_done": self.training_done.is_set(),
            "stopping": self._stopping.is_set(),
            "trace_id": self.trace_id,
            "codec": self.wire_codec.codec_id,
            "aggregator": self.aggregator.name,
            "local_steps": self.local_steps,
            "quorum_fraction": self.quorum_fraction,
            # Pacing view (README "Federation pacing"): policy, the last
            # polled roster, and the policy-specific extras (inclusion
            # scale / buffer depth / staleness).
            "pacing": (
                self._engine.status() if self._engine is not None
                else {"policy": self.pacing.spec_id}
            ),
            "clients": (
                self.federation.membership_snapshot() if full
                else self.federation.membership_summary()
            ),
            # Crash-survival plane (README "Crash recovery & sessions"):
            # where (and from what) this process recovered, journal
            # cadence, and the durable-session/idempotency counters.
            "recovery": {
                "recovered_from": self._recovered_from,
                "source": self._recovered_source,
                "journal_every": self.journal_every,
                "session_restores": count("session_restores"),
                "rpcs_deduplicated": count("rpcs_deduplicated"),
            },
            "compression": {
                "ratio_sent": gauge("compression_ratio_sent"),
                "ratio_recv": gauge("compression_ratio_recv"),
            },
            "stragglers": (
                self.straggler.status() if full
                else self.straggler.summary()
            ),
            # Data-plane defense view (README "Robust aggregation &
            # divergence recovery"): every rejection/clip/rollback is
            # visible here as well as in the JSONL stream.
            "data_plane": {
                "agg_backend": (
                    self._agg_backend_resolved or self.aggregation_backend
                ),
                "sanitize": self.update_gate.check_finite,
                "outlier_mad_k": self.update_gate.mad_k,
                "max_update_norm": self.update_gate.max_update_norm,
                "updates_rejected": count("updates_rejected"),
                "updates_clipped": count("updates_clipped"),
                "rejections_by_client": dict(
                    self.update_gate.total_rejections
                ),
                "divergence_rollbacks": count("divergence_rollbacks"),
                "clients_quarantined": count("clients_quarantined"),
                "guardian_healthy": (
                    self.guardian.healthy if self.guardian is not None
                    else None
                ),
            },
            # Model-quality plane (README "Model-quality observability"):
            # coherence/diversity/drift ring buffer + per-client
            # contribution EWMAs; None when the plane is off.
            "model_quality": self._model_quality_status(full=full),
            # Privacy plane (README "Differential privacy & posterior
            # sampling"): the live (eps, delta) ledger; None when
            # --dp off (the plane constructs nothing).
            "privacy": (
                self.privacy_accountant.status()
                if self.privacy_accountant is not None else None
            ),
            # Fleet telemetry plane (README "Fleet telemetry & SLOs"):
            # headline counts only — the bounded deep view is
            # /status.fleet, live alert detail is /alerts.
            "fleet": {
                "nodes": len(self.fleet.node_snapshots()),
                "reports_invalid": count("fleet_reports_invalid"),
                "reports_dropped": count("fleet_reports_dropped"),
                "alerts_firing": (
                    self.slo.status()["firing"]
                    if self.slo is not None else None
                ),
            },
        }

    def _model_quality_status(self, full: bool = False) -> dict[str, Any] | None:
        if self.quality_every <= 0:
            return None
        out: dict[str, Any] = {
            "every": self.quality_every,
            "guard": self.quality_guard,
            "reference": self.quality_ref,
        }
        if self._quality_mon is not None:
            out.update(self._quality_mon.status())
        out["contributions"] = (
            self.contributions.status() if full
            else self.contributions.summary()
        )
        return out

    def wait_done(self, timeout: float | None = None) -> bool:
        return self.training_done.wait(timeout)

    # ---- Federation service (client -> server) -----------------------------
    def OfferVocab(self, request: pb.VocabOffer, context) -> pb.Ack:
        """Phase-1 vocabulary intake (``sendLocalDic``, ``server.py:175-210``)."""
        self.federation.connect_vocab(
            request.client_id, tuple(request.tokens), request.nr_samples
        )
        self.logger.info(
            "client %d offered %d tokens (%.0f samples)",
            request.client_id, len(request.tokens), request.nr_samples,
        )
        return pb.Ack(code=0, detail=f"vocab of {len(request.tokens)} accepted")

    def GetGlobalSetup(self, request: pb.JoinRequest, context) -> pb.GlobalSetup:
        """Blocks for vocabulary quorum, then returns the agreed vocabulary +
        replicated initial model/optimizer state
        (``sendGlobalDicAndInitialNN``, ``server.py:212-331``), plus a
        freshly minted durable-session token (README "Crash recovery &
        sessions")."""
        self.federation.wait_vocab_quorum()
        with self._setup_lock:
            if self._setup_reply is None:
                self._setup_reply = self._build_setup_reply()
            base = self._setup_reply
        return self._mint_session(int(request.client_id), base)

    def _mint_session(
        self, client_id: int, base: pb.GlobalSetup
    ) -> pb.GlobalSetup:
        """Per-client GlobalSetup: the shared consensus reply plus a fresh
        session token. Passing through GetGlobalSetup is what defines a
        client as a NEW process, so every piece of server-side state
        describing the OLD process is discarded here — push-ack/codec
        posture, reply-seq replay guard, poll warm-up, straggler and
        contribution EWMAs. ReadyForTraining presenting a still-current
        token is then, by construction, a live-process reconnect and
        keeps all of it."""
        if client_id <= 0:
            return base
        token = uuid.uuid4().hex
        self.federation.set_session_token(client_id, token)
        with self._push_lock:
            self._push_acked.pop(client_id, None)
            self._push_sent.pop(client_id, None)
            self._reset_owed.pop(client_id, None)
        self._reply_seen.pop(client_id, None)
        self._push_seen.pop(client_id, None)
        self._poll_warmed.discard(client_id)
        self.straggler.forget(client_id)
        self.contributions.forget(client_id)
        reply = pb.GlobalSetup()
        reply.CopyFrom(base)
        reply.session_token = token
        return reply

    def _build_setup_reply(self) -> pb.GlobalSetup:
        from gfedntm_tpu.data.vocab import union_vocabularies

        vocabs = [
            Vocabulary(c.vocab) for c in self.federation.get_clients()
            if c.vocab_sent
        ]
        self.global_vocab = union_vocabularies(vocabs)
        self.template = build_template_model(
            self.family, len(self.global_vocab), self.model_kwargs
        )
        self.logger.info(
            "consensus: %d clients, global vocabulary %d tokens",
            len(vocabs), len(self.global_vocab),
        )
        return self._setup_reply_from_template()

    def _setup_reply_from_template(self) -> pb.GlobalSetup:
        """The GlobalSetup message for the CURRENT vocab + template state —
        shared by the consensus path and the checkpoint-resume path (where
        the template carries the restored average instead of fresh init)."""
        hyper = {
            "family": self.family,
            "kwargs": {**self.model_kwargs, "input_size": len(self.global_vocab)},
            "grads_to_share": list(self.grads_to_share),
        }
        return pb.GlobalSetup(
            vocab=list(self.global_vocab.tokens),
            model_family=self.family,
            codec_id=self.wire_codec.codec_id,
            # Pacing negotiation: push-paced clients stream PushUpdate
            # rounds of `local_steps` on their own clock instead of
            # waiting for polls.
            pacing_id=self.pacing.spec_id,
            local_steps=self.local_steps,
            hyperparams_json=json.dumps(hyper),
            init_variables=codec.tree_to_bundle(
                {"params": self.template.params,
                 "batch_stats": self.template.batch_stats},
                metrics=self.metrics,
            ),
            init_opt_state=codec.tree_to_bundle(
                self.template.opt_state, metrics=self.metrics
            ),
        )

    # ---- shared-key template + round checkpointing -------------------------
    def _shared_template(self) -> dict[str, np.ndarray]:
        """The template model's shared flat subset — the authoritative key
        set (and shapes) every client reply must match."""
        from flax.traverse_util import flatten_dict

        from gfedntm_tpu.models.params import build_share_mask

        variables = {
            "params": self.template.params,
            "batch_stats": self.template.batch_stats,
        }
        mask = flatten_dict(
            build_share_mask(variables, self.grads_to_share), sep="/"
        )
        flat = flatten_dict(variables, sep="/")
        return {k: np.asarray(v) for k, v in flat.items() if mask.get(k)}

    def _checkpointer(self):
        """Lazily constructed FederationCheckpointer under
        ``save_dir/checkpoints`` (round checkpointing needs a save_dir)."""
        if self._ckpt is None:
            if self.save_dir is None:
                raise ValueError("round checkpointing requires save_dir")
            import os

            from gfedntm_tpu.train.checkpoint import FederationCheckpointer

            self._ckpt = FederationCheckpointer(
                os.path.join(self.save_dir, "checkpoints")
            )
        return self._ckpt

    def _membership_state(self) -> list[dict[str, Any]]:
        """JSON-able membership snapshot persisted with checkpoints and
        the round journal — session tokens included, so a restarted
        server can re-admit live-process reconnects."""
        return [
            {
                "client_id": c.client_id,
                "nr_samples": c.nr_samples,
                "current_mb": c.current_mb,
                "current_epoch": c.current_epoch,
                "finished": bool(c.finished),
                "status": c.status,
                "session_token": c.session_token,
            }
            for c in self.federation.get_clients()
        ]

    def _state_extra(self) -> dict[str, Any]:
        """JSON-able run descriptors persisted with checkpoints and the
        round journal. ``model_kwargs`` makes the recovery state
        self-describing for the SERVING plane (README "Serving"): a
        ``serve`` process can rebuild the exact template model from the
        journal alone, no operator model flags. ``quality`` is the PR 7
        coherence guard's verdict on the journaled round — the serving
        plane refuses to hot-swap in a candidate whose quality round the
        guard flagged (``flagged`` = a live unhealthy streak at journal
        time), keeping the last good model instead."""
        extra: dict[str, Any] = {
            "family": self.family,
            "aggregator": self.aggregator.name,
            "wire_codec": self.wire_codec.codec_id,
            "model_kwargs": dict(self.model_kwargs),
        }
        mon = self._quality_mon
        if mon is not None:
            view = mon.status()
            streak = int(view.get("unhealthy_streak") or 0)
            last = view.get("last") or {}
            extra["quality"] = {
                "flagged": streak > 0,
                "unhealthy_streak": streak,
                "npmi": last.get("npmi"),
                "round": last.get("round"),
            }
        # Privacy ledger (README "Differential privacy & posterior
        # sampling"): the spent-budget RDP curve rides every journal
        # write and checkpoint, so crash-autorecovery RESUMES epsilon —
        # a restart must never hand the adversary a fresh budget.
        if self.privacy_accountant is not None:
            extra["privacy"] = self.privacy_accountant.state_dict()
        return extra

    def _save_round_checkpoint(self) -> None:
        """Persist round state (never lets a checkpoint failure kill
        training — the checkpoint is the recovery path, not the workload)."""
        try:
            self._checkpointer().save_round(
                self.global_iterations, self.last_average,
                self._membership_state(),
                vocab=list(self.global_vocab.tokens),
                extra=self._state_extra(),
                aggregator_state=self.aggregator.state_dict(),
            )
        except Exception:
            self.logger.exception(
                "round checkpoint at %d failed", self.global_iterations
            )
            return
        if self.metrics is not None:
            self.metrics.registry.counter("checkpoints_saved").inc()
            self.metrics.log("checkpoint", round=self.global_iterations)

    # ---- crash-recovery journal (README "Crash recovery & sessions") -------
    def _journal(self):
        if self._round_journal is None:
            if self.save_dir is None:
                raise ValueError("the round journal requires save_dir")
            import os

            from gfedntm_tpu.train.checkpoint import RoundJournal

            self._round_journal = RoundJournal(
                os.path.join(self.save_dir, "checkpoints")
            )
        return self._round_journal

    def _note_journal_write_failure(self, iteration: int,
                                    err: Exception) -> None:
        """A journal write hit the filesystem's failure surface (ENOSPC,
        EIO, a yanked volume): degrade LOUDLY — ``journal_write_failed``
        event + counter — and disable journaling for the rest of the run.
        Training continues; only crash autorecovery is lost, and a stale
        half-written journal must never masquerade as current state."""
        self._journal_disabled = True
        self.logger.error(
            "round journal write at %d failed (%s); journaling disabled "
            "for the rest of this run — training continues WITHOUT crash "
            "autorecovery", iteration, err,
        )
        if self.metrics is not None:
            self.metrics.registry.counter("journal_write_failures").inc()
            self.metrics.log(
                "journal_write_failed", round=iteration, error=str(err),
            )

    def _journal_round(self, iteration: int) -> None:
        """Journal one fully-pushed round (called by the engines after the
        push completes). Like checkpointing, a journal failure is loud but
        never kills training — an I/O failure (ENOSPC/EIO) additionally
        disables journaling for the run, other failures only widen the
        recovery replay."""
        if (
            self.journal_every <= 0 or self.save_dir is None
            or self._journal_disabled
            or self.last_average is None
            or iteration % self.journal_every != 0
        ):
            return
        try:
            self._journal().record(
                iteration, self.last_average, self._membership_state(),
                vocab=list(self.global_vocab.tokens),
                extra=self._state_extra(),
                aggregator_state=self.aggregator.state_dict(),
            )
        except OSError as err:
            self._note_journal_write_failure(iteration, err)
        except Exception:
            self.logger.exception(
                "round journal write at %d failed", iteration
            )
            if self.metrics is not None:
                self.metrics.registry.counter("journal_errors").inc()

    def _mark_journal_finished(self) -> None:
        """Stamp the journal after a normal shutdown so the next server
        start's auto-recovery probe does not resurrect a finished run.
        Still attempted when journaling was disabled by a write failure:
        the stamp is what stops the NEXT start from resurrecting the
        stale journal, and the disk may have recovered since."""
        if self.journal_every <= 0 or self.save_dir is None:
            return
        try:
            self._journal().mark_finished()
        except Exception:
            self.logger.exception("marking the round journal finished failed")
            if self.metrics is not None:
                self.metrics.registry.counter("journal_errors").inc()

    def _load_journal_state(self) -> "dict[str, Any] | None":
        """The round journal's recovery state, or ``None`` when absent,
        disabled, or marked finished. A corrupt journal is LOUD
        (``checkpoint_invalid`` event) but degrades to the orbax
        checkpoint rather than blocking recovery."""
        from gfedntm_tpu.train.checkpoint import CheckpointIntegrityError

        if self.journal_every <= 0 or self.save_dir is None:
            return None
        try:
            return self._journal().load()
        except CheckpointIntegrityError as err:
            self.logger.error(
                "round journal unusable (%s); falling back to the latest "
                "checkpoint", err,
            )
            if self.metrics is not None:
                self.metrics.registry.counter("checkpoint_invalid").inc()
                self.metrics.log("checkpoint_invalid", reason=str(err))
            return None

    def restore_from_checkpoint(self) -> int:
        """Rebuild vocabulary, template, ``last_average``, the round
        counter, and the (not-yet-ready) membership from the newest of
        the round journal and the latest orbax checkpoint under
        ``save_dir``; the restored average is applied onto the template
        so rejoining clients replicate the TRAINED state, not a fresh
        init. Call before :meth:`start`. Returns the resume round (the
        round the loop continues FROM); raises ``FileNotFoundError`` when
        there is nothing to resume and
        :class:`~gfedntm_tpu.train.checkpoint.CheckpointIntegrityError`
        (after a ``checkpoint_invalid`` telemetry event) when what exists
        is corrupt — a broken ``--resume`` must say what is broken and how
        to recover, not dump a JSON traceback."""
        from gfedntm_tpu.train.checkpoint import CheckpointIntegrityError

        ckpt = self._checkpointer()
        jstate = self._load_journal_state()
        try:
            meta = ckpt.load_meta()
            ckpt_round = ckpt.latest_round() if meta is not None else None
        except CheckpointIntegrityError as err:
            if jstate is None:
                self.logger.error("cannot resume: %s", err)
                if self.metrics is not None:
                    self.metrics.registry.counter("checkpoint_invalid").inc()
                    self.metrics.log("checkpoint_invalid", reason=str(err))
                raise
            self.logger.error(
                "checkpoint unusable (%s); recovering from the round "
                "journal alone", err,
            )
            meta, ckpt_round = None, None
        # The journal records the last fully-PUSHED round R (resume at
        # R+1); the checkpoint sidecar records the resume round directly.
        # Prefer whichever is further along — a fresh journal beats a
        # stale periodic checkpoint, and a guardian-withheld journal gap
        # falls back to the rollback-quality checkpoint.
        use_journal = jstate is not None and (
            ckpt_round is None
            or int(jstate["round"]) + 1 >= int(ckpt_round)
        )
        if not use_journal and (meta is None or ckpt_round is None):
            raise FileNotFoundError(
                f"no federation checkpoint or round journal under "
                f"{ckpt.directory}"
            )
        source = jstate if use_journal else meta
        vocab = source.get("vocab")
        if not vocab:
            raise CheckpointIntegrityError(
                "recovery state has no consensus vocabulary; delete "
                f"{ckpt.directory} to start the federation fresh"
            )
        self.global_vocab = Vocabulary(tuple(vocab))
        self.template = build_template_model(
            self.family, len(self.global_vocab), self.model_kwargs
        )
        template = self._shared_template()
        self._expected_keys = frozenset(template)
        self._expected_shapes = {k: v.shape for k, v in template.items()}
        self.update_gate.set_template(template)
        if use_journal:
            missing = [
                k for k in jstate["average_keys"] if k not in template
            ]
            if missing:
                raise ValueError(
                    f"journal avg keys not in template (model config "
                    f"changed since the journal?): {missing[:3]}"
                )
            round_idx = int(jstate["round"]) + 1
            average = {
                k: np.asarray(jstate["average"][k], dtype=v.dtype)
                for k, v in template.items() if k in jstate["average"]
            }
            self._restore_journal_aggregator(jstate)
        else:
            try:
                round_idx, average = ckpt.restore_round(template)
            except CheckpointIntegrityError as err:
                self.logger.error("cannot resume: %s", err)
                if self.metrics is not None:
                    self.metrics.registry.counter("checkpoint_invalid").inc()
                    self.metrics.log("checkpoint_invalid", reason=str(err))
                raise
            self._restore_aggregator_state(ckpt, meta, round_idx)
        self.last_average = average
        self.global_iterations = int(round_idx)
        self._restore_privacy(source.get("privacy"))
        self._restore_membership(source.get("membership") or ())
        # Recovered-server wire posture: this process holds no codec
        # session state and no push acks — the next push is
        # self-contained and orders a fleet-wide session reset, and
        # token reconnects of members that held live sessions get the
        # per-client reset order (Ack code 3) at readmission.
        self._session_reset_pending = not self.wire_codec.identity
        if self.pacing.policy == "push" and not self.wire_codec.identity:
            # Reply-delivered resets (the rollback mechanism): a push
            # server is never polled, so _encode_push — the only consumer
            # of _session_reset_pending — never runs, and a surviving
            # client whose channel reconnects within its stub retry
            # window never probes ReadyForTraining for the Ack-3 reset.
            # Without this, its delta uplinks reference pre-crash state
            # this process doesn't hold and ReferenceMismatch forever.
            with self._push_lock:
                self._reset_owed = {
                    c.client_id: int(round_idx)
                    for c in self.federation.get_clients()
                    if not c.finished
                }
        self._recovered_from = int(round_idx)
        self._recovered_source = "journal" if use_journal else "checkpoint"

        from gfedntm_tpu.federated.stepper import FederatedStepper

        FederatedStepper(self.template, self.grads_to_share).set_gradients(
            average
        )
        with self._setup_lock:
            self._setup_reply = self._setup_reply_from_template()
        self.logger.info(
            "resumed federation from round %d via the %s (%d restored "
            "members)", round_idx,
            "round journal" if use_journal else "checkpoint",
            len(source.get("membership", ())),
        )
        if self.metrics is not None:
            self.metrics.log("resume", step=round_idx)
        return round_idx

    def _restore_privacy(self, state) -> None:
        """Resume the (ε, δ) ledger from recovery state: ε continues,
        never resets. The server-noise stream continues too — the noiser
        counter is restored to the ledger's (post-catch-up) step count,
        so recovery never reuses a draw the dead process may already
        have spent. A run
        recovered WITHOUT ``--dp`` while the journal carries a ledger is
        loud: the operator silently dropping the mechanism mid-run is a
        privacy-accounting hole, not a configuration preference."""
        if state is None:
            return
        if self.privacy_accountant is None:
            self.logger.warning(
                "recovery state carries a privacy ledger (%s steps, "
                "mode=%s) but this server runs --dp off; the ledger is "
                "NOT carried forward — rounds from here on are "
                "unaccounted", state.get("steps"), state.get("mode"),
            )
            return
        self.privacy_accountant.load_state_dict(dict(state))
        # The round journal is written BEFORE the round's accountant tick
        # (the journal marks "fully pushed", the tick runs at round end),
        # so the journaled ledger can lag the RELEASED noise by exactly
        # one round. Recovery charges one conservative catch-up step:
        # the ledger never under-counts noise that already left the
        # server (at worst one round is double-charged), and the noise
        # stream index advances past any draw the dead process may have
        # spent.
        self.privacy_accountant.step(
            q=self.privacy_accountant.last_q or 1.0
        )
        if self._dp_noiser is not None:
            self._dp_noiser.applications = self.privacy_accountant.steps
        self.logger.info(
            "resumed privacy ledger: eps=%.4f at delta=%g after %d "
            "noised rounds (incl. one conservative catch-up step for "
            "the possibly-uncharged in-flight round)",
            self.privacy_accountant.epsilon(),
            self.privacy_accountant.delta, self.privacy_accountant.steps,
        )

    def _restore_journal_aggregator(self, jstate: dict) -> None:
        """Reload journaled server-optimizer slots (same name-mismatch
        stance as :meth:`_restore_aggregator_state`)."""
        saved_name = jstate.get("aggregator")
        arrays = jstate.get("aggregator_state") or {}
        if not arrays:
            return
        if saved_name is not None and saved_name != self.aggregator.name:
            self.logger.warning(
                "journal was written by aggregator %r but this server "
                "runs %r; server-optimizer state starts fresh",
                saved_name, self.aggregator.name,
            )
            return
        self.aggregator.load_state_dict(arrays)

    def _restore_membership(self, membership) -> None:
        """Repopulate the registry from a recovery snapshot: members keep
        their identity, FedAvg weight, progress, and session tokens, but
        none are training-ready until they reconnect. The training
        restart bar becomes ``quorum_fraction`` of the restored
        unfinished membership (capped by ``min_clients``) — a member that
        died for good must not stall recovery forever."""
        unfinished = 0
        for m in membership:
            try:
                client_id = int(m["client_id"])
            except (KeyError, TypeError, ValueError):
                continue
            finished = bool(m.get("finished"))
            self.federation.restore_member(
                client_id,
                nr_samples=float(m.get("nr_samples") or 0.0),
                session_token=str(m.get("session_token") or ""),
                finished=finished,
                current_mb=int(m.get("current_mb") or 0),
                current_epoch=int(m.get("current_epoch") or 0),
                needs_codec_reset=not self.wire_codec.identity,
            )
            unfinished += not finished
        if unfinished:
            self._resume_ready_needed = max(
                1, math.ceil(self.quorum_fraction * unfinished)
            )

    def maybe_autorecover(self) -> "int | None":
        """Zero-flag crash recovery: when ``save_dir`` holds a round
        journal (or checkpoint) from an interrupted run, restore it and
        return the resume round; return ``None`` when there is nothing to
        recover (fresh start) or the previous run finished cleanly.
        Corrupt state still raises — silently discarding a recovery
        record an operator may be counting on is worse than stopping."""
        from gfedntm_tpu.train.checkpoint import CheckpointIntegrityError

        if self.save_dir is None or self.journal_every <= 0:
            # No journal ⇒ no auto-recovery (the documented contract of
            # --journal_every 0): without the journal's finished stamp a
            # cleanly-completed run's checkpoints would be resurrected on
            # every restart. Explicit --resume still restores them.
            return None
        try:
            finished = bool(
                (self._journal().load_meta() or {}).get("finished")
            )
        except CheckpointIntegrityError:
            finished = False
        if finished:
            self.logger.info(
                "previous federation under %s finished cleanly; "
                "starting fresh", self.save_dir,
            )
            return None
        try:
            round_idx = self.restore_from_checkpoint()
        except FileNotFoundError:
            return None
        self.logger.warning(
            "auto-recovered an interrupted federation: resuming from "
            "round %d (re-admitting session-token reconnects)", round_idx,
        )
        # Recovery clock for the recovery_time_s gauge: stops the moment
        # the post-recovery quorum re-forms and training restarts.
        self._recovered_at = time.monotonic()
        if self.metrics is not None:
            self.metrics.registry.counter("server_recoveries").inc()
            self.metrics.log(
                "server_recovered", round=round_idx,
                source=self._recovered_source or "checkpoint",
            )
        return round_idx

    def _restore_aggregator_state(self, ckpt, meta: dict, round_idx) -> None:
        """Reload the server aggregator's optimizer state saved with the
        round checkpoint — a resumed FedAdam/FedYogi run must continue its
        moments, not restart them cold. An aggregator-name mismatch (the
        operator changed --aggregator between runs) restarts stateless with
        a loud warning rather than loading foreign moments."""
        saved_name = meta.get("aggregator")
        if saved_name is not None and saved_name != self.aggregator.name:
            self.logger.warning(
                "checkpoint was written by aggregator %r but this server "
                "runs %r; server-optimizer state starts fresh",
                saved_name, self.aggregator.name,
            )
            return
        state = ckpt.load_aggregator_state()
        if state is None:
            return
        state_round, arrays = state
        if int(state_round) != int(round_idx):
            self.logger.warning(
                "aggregator state is from round %d but the round "
                "checkpoint is %d (crash between the two saves); "
                "server-optimizer state starts fresh", state_round, round_idx,
            )
            return
        self.aggregator.load_state_dict(arrays)
        self.logger.info(
            "restored %s aggregator state (%d arrays) from round %d",
            self.aggregator.name, len(arrays), state_round,
        )

    def ReadyForTraining(self, request: pb.JoinRequest, context) -> pb.Ack:
        """Client readiness signal; the training thread starts exactly once
        when quorum is reached (``trainFederatedModel``, ``server.py:365-406``).
        A client (re)joining after the federation already finished gets
        ``code=1`` so it can finalize instead of waiting for polls that will
        never come."""
        if self._stopping.is_set() or self.training_done.is_set():
            return pb.Ack(code=1, detail="federation already finished")
        # Codec negotiation: the training phase moves opaque (possibly
        # delta/sparse/quantized) payloads, so a fleet mixing codecs must
        # fail at join time, not mis-decode at round time. An empty id is
        # a pre-negotiation client — compatible only with the identity
        # codec.
        client_codec = request.codec_id or "none"
        if client_codec != self.wire_codec.codec_id:
            self.logger.error(
                "client %d runs wire codec %r but this federation "
                "negotiated %r; rejecting join",
                request.client_id, client_codec, self.wire_codec.codec_id,
            )
            if self.metrics is not None:
                self.metrics.registry.counter("codec_mismatches").inc()
                self.metrics.log(
                    "codec_mismatch", client=request.client_id,
                    server_codec=self.wire_codec.codec_id,
                    client_codec=client_codec,
                )
            return pb.Ack(
                code=2,
                detail=(
                    f"wire codec mismatch: federation runs "
                    f"{self.wire_codec.codec_id!r}, client offered "
                    f"{client_codec!r}"
                ),
            )
        # Durable sessions (README "Crash recovery & sessions"): a ready
        # presenting a still-current session token is a live process
        # reconnecting after a connection loss — its server-side state
        # (straggler EWMA, push-ack/codec posture, poll warm-up,
        # reply-seq guard) describes THIS process and survives. A
        # token-less/mismatched ready, or the first ready of a
        # just-minted session, is a fresh process and starts clean (the
        # mint already discarded the old process's state).
        kind = self.federation.classify_join(
            request.client_id, request.session_token
        )
        self.federation.connect_ready(request.client_id, request.address)
        if request.telemetry:
            # Rejoin resync (README "Fleet telemetry & SLOs"): the joining
            # client's FULL registry report rides the ready it already
            # sends, healing any deltas lost while it was away.
            self.fleet.ingest_bytes(request.telemetry)
        ack_code, ack_detail = 0, "ready recorded"
        if kind == "restore":
            self.logger.info(
                "client %d reconnected with its session token",
                request.client_id,
            )
            if self.metrics is not None:
                self.metrics.registry.counter("session_restores").inc()
                self.metrics.log(
                    "session_restored", client=request.client_id,
                )
            if (
                self.federation.consume_codec_reset(request.client_id)
                and not self.wire_codec.identity
            ):
                # This server recovered from a crash and holds none of
                # the codec session state the reconnecting client still
                # carries: order a client-side reset so the next
                # exchanged bundles are self-contained on both ends.
                ack_code = 3
                ack_detail = (
                    "session restored by a recovered server; reset "
                    "wire-codec sessions"
                )
            if request.recovered:
                # The PRESENTER crashed and restored itself from its own
                # journal (a respawned relay): same session, same weight
                # — but its wire-codec state died with the old process,
                # so this side's per-recipient push posture must not
                # delta-encode against references it no longer holds.
                # Its first poll re-jits too.
                with self._push_lock:
                    self._push_acked.pop(request.client_id, None)
                    self._push_sent.pop(request.client_id, None)
                    self._reset_owed.pop(request.client_id, None)
                self._reply_seen.pop(request.client_id, None)
                self._push_seen.pop(request.client_id, None)
                self._poll_warmed.discard(request.client_id)
                self.logger.info(
                    "client %d reconnected as a journal-recovered "
                    "process; its wire posture starts self-contained",
                    request.client_id,
                )
        elif kind == "new":
            if _looks_like_session_token(request.session_token):
                # A valid-format token this federation never minted: a
                # member of a dead tier re-homing here (README "Crash
                # recovery & sessions" — cross-tier failover presents
                # the ORIGINAL tier's token). Admit it as a fresh join,
                # but LOUDLY: an operator seeing this has lost a relay.
                self.logger.warning(
                    "client %d presented an unknown session token — "
                    "re-homed member of a dead tier; admitting as a "
                    "fresh join", request.client_id,
                )
                if self.metrics is not None:
                    self.metrics.registry.counter("members_rehomed").inc()
                    self.metrics.log(
                        "member_rehomed", client=request.client_id,
                    )
            # A (re)joining client is a fresh process with no broadcast
            # reference — it must not count as having acked the last
            # push, or the next push could be delta-encoded against
            # state it never held. Its straggler history is a different
            # process's too. ("first" readies were already cleaned by
            # the token mint in GetGlobalSetup.)
            with self._push_lock:
                self._push_acked.pop(request.client_id, None)
                self._push_sent.pop(request.client_id, None)
                self._reset_owed.pop(request.client_id, None)
            self._reply_seen.pop(request.client_id, None)
            self._push_seen.pop(request.client_id, None)
            self._poll_warmed.discard(request.client_id)
            self.straggler.forget(request.client_id)
            self.contributions.forget(request.client_id)
        # Re-check after registering: if the training loop began shutting
        # down concurrently, this client may have missed the stop-broadcast
        # snapshot — tell it to finalize on its own. (If it made the
        # snapshot it gets both the broadcast and code=1; finalization is
        # idempotent.)
        if self._stopping.is_set() or self.training_done.is_set():
            return pb.Ack(code=1, detail="federation already finished")
        with self._train_lock:
            # After crash recovery the original min_clients bar may be
            # unreachable (members can be gone for good): the restored
            # run restarts once quorum_fraction of the restored
            # unfinished membership is back, whichever bar is lower.
            needed = self.federation.min_clients
            if self._resume_ready_needed is not None:
                needed = min(needed, self._resume_ready_needed)
            if (
                self._train_thread is None
                and sum(
                    c.ready_for_training
                    for c in self.federation.get_clients()
                )
                >= needed
            ):
                if self._recovered_at is not None:
                    # Time-to-quorum after a crash: the metric the
                    # shipped `recovery_time` SLO example bounds (README
                    # "Fleet telemetry & SLOs").
                    elapsed = time.monotonic() - self._recovered_at
                    self._recovered_at = None
                    if self.metrics is not None:
                        self.metrics.registry.gauge(
                            "recovery_time_s"
                        ).set(elapsed)
                self._train_thread = threading.Thread(
                    target=self._run_training, name="federated-training",
                    daemon=True,
                )
                self._train_thread.start()
        return pb.Ack(code=ack_code, detail=ack_detail)

    def PushUpdate(self, request: pb.StepReply, context) -> pb.Aggregate:
        """Client-initiated round under push pacing (README "Hierarchical
        federation & wire efficiency"): buffer the streamed update for
        the engine's FedBuff drain and answer with the freshest
        broadcast, per-recipient delta-encoded against the round the
        client reports holding — one RPC moves the update up AND the
        model down, and server work stays O(updates received).

        The durable-session token authenticates the push (a stale
        process's updates must not enter the average); the client's
        ``base_round`` claim is its broadcast ack, clamped to what this
        server actually sent it. The reply is an empty marker when the
        client is already current, and carries ``stop`` once the
        federation is over (the client finalizes)."""
        cid = int(request.client_id)
        m = self.metrics
        if self._stopping.is_set() or self.training_done.is_set():
            return pb.Aggregate(stop=True)
        if self.pacing.policy != "push":
            self.logger.warning(
                "client %d sent PushUpdate but this federation paces %s; "
                "refusing", cid, self.pacing.spec_id,
            )
            if m is not None:
                m.registry.counter("push_updates_refused").inc()
            return pb.Aggregate(stop=True)
        rec = self.federation.get(cid)
        if (
            rec is None or not rec.session_token
            or rec.session_token != request.session_token
        ):
            # An unknown member or a token minted for a different process:
            # the pusher is stale — tell it to finalize rather than let
            # an unauthenticated update into the average.
            self.logger.warning(
                "client %d PushUpdate with a stale/unknown session "
                "token; refusing", cid,
            )
            if m is not None:
                m.registry.counter("push_updates_refused").inc()
            return pb.Aggregate(stop=True)
        engine = self._engine
        if not isinstance(engine, pacing.PushEngine):
            # Training has not started (readiness quorum still forming):
            # a HOLD marker (round=-1, nothing buffered) — the client
            # re-presents the same round later instead of burning its
            # local epoch budget into the void.
            return pb.Aggregate(round=-1)

        # Solicited flight-record pull (README "Incident forensics"):
        # every reply in the solicitation window carries the token; the
        # client dedupes by token so re-rides cost nothing.
        tok = self.flightrec_token()

        # Broadcast-ack bookkeeping from the client's own claim, capped
        # by what this server actually sent it (a claim cannot fabricate
        # a reference we never delivered — the delta encoder would
        # otherwise trust it).
        claimed = int(request.base_round) - 1
        with self._push_lock:
            acked = min(claimed, self._push_sent.get(cid, -1))
            if acked >= 0:
                self._push_acked[cid] = acked
            else:
                self._push_acked.pop(cid, None)
            owed_round = self._reset_owed.get(cid)
            if owed_round is not None and acked >= owed_round:
                # The member demonstrably applied a post-rollback round
                # THIS process delivered (acked is clamped to _push_sent):
                # its reset landed. The raw claim must not clear it — a
                # surviving client's pre-crash base_round can sit past the
                # recovered journal round while this process delivered
                # nothing, and popping the owed reset on that claim leaves
                # the client's pre-crash codec sessions alive: every
                # uplink then ReferenceMismatches and every reply round is
                # dedup-skipped — the no-progress deadlock
                # _session_reset_pending exists to prevent.
                self._reset_owed.pop(cid, None)
                owed_round = None
        reset = owed_round is not None

        # Replay guard: the stub retries UNAVAILABLE automatically, so a
        # push delivered-but-reply-lost would be buffered (and averaged)
        # twice without it. Client-minted per-push seqs dedup here —
        # duplicates still get the freshest broadcast, just no second
        # buffer slot (the TrainStep idempotency stance, inverted).
        seq = int(request.seq)
        duplicate = bool(seq) and self._push_seen.get(cid, 0) >= seq
        if duplicate:
            self.logger.warning(
                "client %d: duplicate PushUpdate seq %d; answering "
                "without re-buffering", cid, seq,
            )
            if m is not None:
                m.registry.counter("rpcs_deduplicated").inc()
                m.log(
                    "rpc_deduplicated", client=cid, method="PushUpdate",
                    seq=seq,
                )
        else:
            if seq:
                self._push_seen[cid] = seq
            if request.telemetry:
                # Piggybacked telemetry (README "Fleet telemetry & SLOs"):
                # deltas ride the push the client already streamed. Only
                # non-duplicate pushes ingest — a replayed push re-ships
                # the same bytes (replace-semantics would make re-ingest
                # harmless, but skipping keeps report ages honest).
                self.fleet.ingest_bytes(request.telemetry)
            if request.flightrec and self._incident_trigger is not None:
                # Solicited flight-record snapshot riding the push
                # (README "Incident forensics", remote capture).
                self._incident_trigger.ingest_remote(request.flightrec)
            self.federation.update_progress(
                cid, int(request.current_mb), int(request.current_epoch),
                float(request.loss), finished=bool(request.finished),
            )
            depth = engine.submit(rec, request)
            if m is not None:
                m.registry.counter("push_updates_received").inc()
                m.registry.gauge("push_buffer_depth").set(depth)

        # Reply with the freshest broadcast the engine has installed. The
        # round tag and the encoded bundle must be read ATOMICALLY vs the
        # engine's chain advance: a reply carrying round K's view labeled
        # K-1 would silently skew the client's uplink reference chain.
        if self.wire_codec.identity:
            # Counter BEFORE payload: racing the engine's install may
            # under-label (client re-applies an identical view later —
            # harmless) but never over-label (which would make the
            # client dedup-skip the real round).
            current = int(self.global_iterations) - 1
            avg = self.last_average
            if avg is None or current < 0 or (
                not reset and acked >= current
            ):
                # Nothing new (or nothing aggregated yet): an empty
                # marker the client recognizes by round <= applied. An
                # owed session reset still rides it (bare reset order).
                return pb.Aggregate(
                    round=max(current, claimed, 0), reset_session=reset,
                    capture_token=tok,
                )
            # One encode per installed average, not one per push: up to
            # N concurrent replies between two aggregations would each
            # rebuild the identical full-model bundle on gRPC threads —
            # O(model bytes) per push at 10^4 clients. Keyed by the
            # average's OBJECT identity, so a rollback/recovery
            # reinstall (always a fresh dict) invalidates naturally;
            # the benign race mirrors the under-label rule above
            # (last-writer-wins, same content). This also matches the
            # delta path's accounting: bundle_for counts encode bytes
            # once per distinct bundle, not per recipient.
            memo = self._push_identity_memo
            if memo is None or memo[0] is not avg or memo[1] != current:
                memo = (avg, current, codec.flatdict_to_bundle(avg, metrics=m))
                self._push_identity_memo = memo
            agg = pb.Aggregate(
                shared=memo[2], round=current, reset_session=reset,
                capture_token=tok,
            )
        else:
            with self._codec_lock:
                # The canonical chain's own round is the authoritative
                # tag for the bundle bundle_for() serves — never the
                # separately-read iteration counter. Also covers crash
                # recovery: a restored server has last_average but a
                # fresh chain (last_round=-1) until its first
                # aggregation — empty markers until then, not a
                # bundle_for-before-advance error.
                current = self._downlink_enc.last_round
                if current < 0 or (not reset and acked >= current):
                    # A bare reset order still rides the empty marker: a
                    # recovered server has nothing aggregated to send
                    # yet, but the client must drop its pre-crash codec
                    # sessions BEFORE its next uplink encode or no
                    # post-recovery update can ever decode (the first
                    # aggregation would wait on an uplink that can only
                    # ReferenceMismatch — a deadlock).
                    return pb.Aggregate(
                        round=max(current, claimed, 0), reset_session=reset,
                        capture_token=tok,
                    )
                bundle = self._downlink_enc.bundle_for(
                    None if reset else (acked if acked >= 0 else None)
                )
            agg = pb.Aggregate(
                shared=bundle, round=current, reset_session=reset,
                capture_token=tok,
            )
        with self._push_lock:
            self._push_sent[cid] = current
        return agg

    def _advance_broadcast(
        self, average: dict[str, np.ndarray], iteration: int
    ) -> None:
        """Push pacing: advance the canonical broadcast chain for a round
        with no immediate recipients — members pick the round up
        (per-recipient encoded) in their next PushUpdate replies."""
        if self.wire_codec.identity:
            return
        with self._codec_lock:
            _bundle, view = self._downlink_enc.advance(
                average, round_idx=iteration
            )
            self._uplink_dec.note_push(iteration, view)

    # ---- phase-2 training loop (server.py:408-553) -------------------------
    def _stub_for(self, stubs: dict, rec) -> rpc.ServiceStub | None:
        """Persistent per-client stub, created on first use so clients that
        become ready after the loop starts still get polled. Keyed by
        (client, address): a rejoining client usually serves on a NEW port,
        so a stale cached channel is closed and replaced, not reused."""
        if not rec.address:
            entry = stubs.get(rec.client_id)
            return entry[2] if entry else None
        entry = stubs.get(rec.client_id)
        if entry is None or entry[0] != rec.address:
            if entry is not None:
                entry[1].close()
            channel = rpc.make_channel(rec.address)
            # Training RPCs are idempotent (seq-numbered TrainStep, round-
            # deduplicated ApplyAggregate), so the per-client stubs run
            # the idempotent retry twin: a timed-out-but-delivered call
            # is safely retried and answered from the client's cache.
            stub = rpc.ServiceStub(
                channel, "gfedntm.FederationClient",
                metrics=self.metrics, peer=f"client{rec.client_id}",
                retry_policy=self.client_retry_policy,
                fault_injector=self.fault_injector,
            )
            entry = (rec.address, channel, stub)
            stubs[rec.client_id] = entry
        return entry[2]

    def _note_client_failure(self, rec, addr: str, round_idx: int,
                             exc: Exception, what: str,
                             reason: str = "rpc") -> None:
        """Round-level failure accounting: probation with per-round backoff
        (``SUSPECT``) for ``probation_rounds`` consecutive failed rounds,
        then the permanent drop. ALL failure classes go through probation —
        a deterministic error simply fails its probation and drops within a
        bounded number of rounds, while a transient one recovers. ``reason``
        distinguishes transport failures ("rpc") from data-plane ones
        ("poisoned" gate rejections, "divergence" quarantines)."""
        status = self.federation.mark_suspect(
            rec.client_id, addr, round_idx,
            probation_rounds=self.probation_rounds, reason=reason,
        )
        if status is None:  # stale: the client rejoined on a new address
            return
        reg = self.metrics.registry if self.metrics is not None else None
        if status == DROPPED:
            self.logger.warning(
                "dropping client %d after %d failed rounds (last %s: %s)",
                rec.client_id, rec.consecutive_failures, what, exc,
            )
            # A rejoin is a fresh process that must re-jit, so its first
            # poll is compile-dominated again; its frozen EWMA must also
            # leave the straggler population or it skews every later
            # round's mean/std. Contribution EWMAs (and their gauges)
            # leave with it — per-client series must not outlive churn.
            self._poll_warmed.discard(rec.client_id)
            self.straggler.forget(rec.client_id)
            self.contributions.forget(rec.client_id)
            if reg is not None:
                reg.counter("client_drops").inc()
        else:
            self.logger.warning(
                "client %d suspect (failure %d/%d, retry at round %d) "
                "after failed %s: %s",
                rec.client_id, rec.consecutive_failures,
                self.probation_rounds, rec.next_retry_round, what, exc,
            )
            if reg is not None:
                reg.counter("client_suspect_rounds").inc()
                self.metrics.log(
                    "client_suspect", client=rec.client_id,
                    failures=rec.consecutive_failures, status=status,
                    round=round_idx, reason=reason,
                )

    def _note_round_poll(self, round_sp, polled, replies, iteration) -> None:
        """Straggler/staleness telemetry for one round's poll results:
        per-client poll-latency histograms, slowest-client gauges (annotated
        onto the round span too), rolling per-client EWMAs with z-score
        ``straggler_detected`` events, per-client staleness-in-minibatches
        gauges, and the round's pulled payload bytes."""
        reg = self.metrics.registry
        slowest_id, slowest_s = None, -1.0
        round_lats: dict[int, float] = {}
        for rec, reply, lat in polled:
            if reply is None:
                # A failed poll's latency is the deadline constant, not a
                # straggler signal; the drop is already recorded via the
                # rpc error event + mark_dropped.
                continue
            if rec.client_id not in self._poll_warmed:
                # The client's first poll carries its jit trace+compile —
                # already captured as a jit_compile event client-side; in
                # the straggler stats it would just name whichever client
                # compiled slowest.
                self._poll_warmed.add(rec.client_id)
                continue
            reg.histogram("client_poll_s").observe(lat)
            reg.histogram(f"client_poll_s/client{rec.client_id}").observe(lat)
            round_lats[rec.client_id] = lat
            if lat > slowest_s:
                slowest_id, slowest_s = rec.client_id, lat
        if slowest_id is not None:
            reg.gauge("round_slowest_client_id").set(slowest_id)
            reg.gauge("round_slowest_client_s").set(slowest_s)
            round_sp.annotate(
                slowest_client=slowest_id, slowest_s=slowest_s
            )
        for flagged in self.straggler.observe_round(round_lats):
            reg.counter("stragglers_detected").inc()
            self.metrics.log(
                "straggler_detected", client=flagged["client"],
                round=iteration, z=flagged["z"], ewma_s=flagged["ewma_s"],
            )
            self.logger.warning(
                "round %d: client %d is a straggler (z=%.1f, "
                "EWMA %.3f s)", iteration, flagged["client"], flagged["z"],
                flagged["ewma_s"],
            )
        if replies:
            max_mb = max(reply.current_mb for _rec, reply in replies)
            for rec, reply in replies:
                reg.gauge(f"client_staleness_mb/client{rec.client_id}").set(
                    max_mb - reply.current_mb
                )
            round_sp.annotate(
                clients=len(replies),
                bytes_pulled=sum(
                    reply.shared.ByteSize() for _rec, reply in replies
                ),
            )

    def _fleet_tick(self, iteration: int) -> None:
        """Per-aggregation telemetry housekeeping (README "Fleet telemetry
        & SLOs"): fold the server's OWN registry into the fleet view (so
        fleet-merged series include coordinator-side metrics), then run
        one SLO evaluation pass over the merged snapshot. Called from the
        pacing engines' aggregation points — no dedicated thread; alert
        latency is bounded by round cadence, which is exactly the clock
        the objectives are written against."""
        if self.metrics is not None:
            node = self.metrics.node or "server"
            self.fleet.ingest(
                node, self.metrics.registry.snapshot(), full=True,
            )
        if self.slo is not None:
            self.slo.evaluate()
        self._privacy_tick(iteration)

    def _privacy_tick(self, iteration: int) -> None:
        """Charge the (ε, δ) ledger for one aggregated round. Called from
        :meth:`_fleet_tick`, which every pacing engine runs exactly once
        per round that actually aggregated — skipped (below-quorum)
        rounds apply no mechanism and are charged nothing, keeping the
        ledger's step count in lock-step with the noiser's application
        counter. q comes from the live engine
        (:meth:`pacing.RoundEngine.inclusion_q`): the cohort sampler's
        actual K/eligible, the conservative 1.0 everywhere else. Budget
        exhaustion is LOUD (event + counter + warning) but never stops
        training — the offline ``privacy`` CLI gate enforces."""
        acct = self.privacy_accountant
        if acct is None:
            return
        q = (
            self._engine.inclusion_q() if self._engine is not None
            else 1.0
        )
        was_exceeded = acct.exceeded
        eps = acct.step(q=q)
        if self.metrics is not None:
            self.metrics.registry.gauge("privacy_eps").set(eps)
            self.metrics.log(
                "privacy_budget", round=iteration, eps=float(eps),
                delta=acct.delta, steps=acct.steps, q=float(q),
                sigma=acct.sigma, mode=acct.mode, budget=acct.budget,
            )
        if acct.exceeded and not was_exceeded:
            self.logger.warning(
                "privacy budget EXCEEDED at round %d: eps=%.4f > "
                "declared budget %.4f (delta=%g); training continues — "
                "the offline `privacy` CLI gate is the enforcement "
                "point", iteration, eps, acct.budget, acct.delta,
            )
            if self.metrics is not None:
                self.metrics.registry.counter(
                    "privacy_budget_exceeded"
                ).inc()
                self.metrics.log(
                    "privacy_budget_exceeded", round=iteration,
                    eps=float(eps), budget=acct.budget,
                    delta=acct.delta,
                )

    # ---- incident forensics (README "Incident forensics") ------------------
    def _solicit_flightrec(self, incident_id: str, reason: str,
                           trigger_record: dict) -> None:
        """Root-side post-capture hook: arm a capture token so the next
        RPC exchange with every implicated member (polls under
        sync/cohort/async, PushUpdate replies under push pacing) asks
        for its flight-record snapshot. Best-effort and loss-tolerant —
        the token simply re-rides exchanges until the window closes."""
        self._flightrec_solicit = (incident_id, time.time() + 120.0)
        if self.metrics is not None:
            self.metrics.log(
                "flightrec_requested", incident_id=incident_id,
                reason=reason,
            )

    def flightrec_token(self) -> str:
        """The live solicitation token ("" when none is armed or the
        window expired) — stamped onto outgoing StepRequests/Aggregates."""
        sol = self._flightrec_solicit
        if sol is None:
            return ""
        token, expires = sol
        if time.time() >= expires:
            self._flightrec_solicit = None
            return ""
        return token

    def _awaiting_reconnect_grace(self) -> bool:
        """True while the post-recovery grace window is open AND some
        restored member has not reconnected — the round engines keep the
        federation alive (wall-clock waits, no rounds burned) instead of
        ending it without the stragglers."""
        if self._recovery_deadline is None:
            return False
        if time.monotonic() >= self._recovery_deadline:
            return False
        return bool(self.federation.awaiting_reconnect())

    def _next_step_seq(self) -> int:
        """Fresh TrainStep delivery sequence number: monotonic within the
        process (itertools.count — atomic under the GIL, the pool's poll
        threads draw concurrently) and ACROSS restarts (wall-clock epoch
        base), so a restarted server's polls can never collide with seqs
        the dead process issued — a collision would make clients answer
        fresh polls from their replay caches."""
        return self._seq_epoch + next(self._seq_counter)

    def _current_global(self) -> dict[str, np.ndarray]:
        """The parameters every client stepped from this round: the last
        broadcast average, or the template init before round 0 — the
        reference point for both the admission gate's update norms and the
        server-optimizer pseudo-gradient."""
        return (
            self.last_average if self.last_average is not None
            else self._shared_template()
        )

    def _ensure_template(self) -> None:
        if self._expected_keys is None:
            template = self._shared_template()
            self._expected_keys = frozenset(template)
            self._expected_shapes = {k: v.shape for k, v in template.items()}
            self.update_gate.set_template(template)
        self._resolve_agg_backend()

    def _resolve_agg_backend(self) -> None:
        """Pick the aggregation data-plane backend at server start (first
        template use): ``device`` when an accelerator is present (or
        forced), ``numpy`` otherwise. A device-engine construction
        failure degrades LOUDLY to numpy — a working round loop beats a
        resident one."""
        if self._agg_backend_resolved is not None:
            return
        mode = self.aggregation_backend
        if mode == "auto":
            try:
                import jax

                mode = (
                    "device"
                    if jax.default_backend() not in ("cpu",)
                    else "numpy"
                )
            except Exception as err:  # no usable jax backend at all
                self.logger.warning(
                    "aggregation backend auto-resolve: jax backend "
                    "probe failed (%r); using numpy", err,
                )
                mode = "numpy"
        if mode == "device":
            try:
                from gfedntm_tpu.federation.device_agg import DeviceAggEngine

                engine = DeviceAggEngine()
                self.update_gate.set_engine(engine)
                if self._dp_noiser is not None:
                    # Noise generation joins the device data plane:
                    # sharded per-device draws on the same mesh the
                    # stacked round lives on (host oracle otherwise).
                    self._dp_noiser.device_engine = engine
                self.logger.info(
                    "aggregation backend: device (%d-way '%s' mesh)",
                    engine.n_shards, engine.axis,
                )
            except Exception as err:  # noqa: BLE001 — degrade, don't die
                self.logger.warning(
                    "device aggregation backend unavailable (%r); "
                    "falling back to numpy", err,
                )
                mode = "numpy"
        if mode == "numpy":
            self.update_gate.set_engine(None)
        self._agg_backend_resolved = mode
        if self.metrics is not None:
            self.metrics.registry.gauge("agg_backend_device").set(
                1.0 if mode == "device" else 0.0
            )

    def _collect_snapshots(
        self, replies: list, iteration: int,
        was_suspect: frozenset = frozenset(),
        weight_scale: "dict[int, float] | None" = None,
        staleness: "dict[int, int] | None" = None,
    ) -> list[tuple[float, dict[str, np.ndarray]]]:
        """Decode a round's replies and pass them through the update
        admission gate (:class:`~gfedntm_tpu.federation.sanitize.UpdateGate`):
        conformance (key set / shapes / dtypes vs the shared template),
        per-tensor finiteness, and the cohort update-norm outlier screen.
        Anything the gate rejects costs the round one contributor — never a
        ``KeyError`` in the average or a poisoned broadcast — and repeat
        offenders are fed into the probation machinery with
        ``reason="poisoned"``.

        Recovery is admission-scoped: a suspect client (``was_suspect``)
        only clears probation when its update is *accepted*, not merely
        because its RPC succeeded — a poisoner that answers politely must
        not oscillate in and out of probation forever.

        The FedAvg weight is the reply's ``nr_samples`` — the samples the
        client actually consumed this round (summed over all E local
        minibatches, ADVICE r5) — falling back to the client's join-time
        corpus size for replies that don't report one. ``weight_scale``
        multiplies individual candidates' weights before admission (the
        async engine's staleness discount); absent entries scale by 1.
        ``staleness`` (rounds since each client's base broadcast) makes
        the gate's MAD outlier screen judge staleness-normalized norms —
        under cohort/async pacing an honest client polled from an old
        broadcast must not read as a poisoner against fresher peers.

        Returns the admitted cohort as ``[(weight, snapshot)]`` on the
        numpy backend, or as a device-resident
        :class:`~gfedntm_tpu.federation.device_agg.StackedRound` on the
        device backend (same ``len``, consumed transparently by every
        aggregator's mean stage)."""
        self._ensure_template()
        m = self.metrics
        deduped: list = []
        for rec, reply in replies:
            # Idempotent-RPC guard (root-only — the relay's upstream seq
            # guard lives in its own servicer): a replayed StepReply (a
            # delivery the client answered from its replay cache, or any
            # duplicate of a seq this loop already consumed) must not
            # enter the average twice — one step, one vote.
            seq = int(reply.seq)
            if seq and self._reply_seen.get(rec.client_id, 0) >= seq:
                self.logger.warning(
                    "round %d: dropping replayed StepReply from client "
                    "%d (seq %d already seen)",
                    iteration, rec.client_id, seq,
                )
                if m is not None:
                    m.registry.counter("rpcs_deduplicated").inc()
                    m.log(
                        "rpc_deduplicated", client=rec.client_id,
                        method="TrainStep", seq=seq, round=iteration,
                    )
                continue
            if seq:
                self._reply_seen[rec.client_id] = seq
            if reply.telemetry:
                # Piggybacked telemetry (README "Fleet telemetry & SLOs"):
                # the node's metric deltas ride the poll reply it already
                # sent. Post-dedup only — a replayed reply re-ships the
                # same report bytes, so one ingest per observation.
                self.fleet.ingest_bytes(reply.telemetry)
            deduped.append((rec, reply))

        if self.wire_codec.identity:
            def decode(bundle):
                return codec.bundle_to_flatdict(bundle, metrics=m)
        else:
            decode = self._uplink_dec.decode

        def on_decode_error(rec, err):
            # A reply the negotiated codec cannot decode (usually a delta
            # against a broadcast older than the reference cache) costs
            # the round one contributor; the client still receives this
            # round's push, which re-syncs its reference.
            self.logger.warning(
                "round %d: client %d reply not decodable (%s); "
                "excluding it from the average",
                iteration, rec.client_id, err,
            )

        def on_poisoned(rec, rej):
            # Repeat offenders enter probation exactly like transport
            # failures: backoff, then the permanent drop — a client that
            # only ever sends poison must leave the federation in bounded
            # time.
            self._note_client_failure(
                rec, rec.address, iteration,
                RuntimeError(f"{rej.reason}: {rej.detail}"),
                "update admission", reason="poisoned",
            )

        def on_recovered(client_id):
            # Admission-scoped recovery (see docstring).
            if self.federation.mark_recovered(client_id):
                self.logger.info(
                    "client %d recovered (update admitted at round %d)",
                    client_id, iteration,
                )
                if m is not None:
                    m.registry.counter("client_recoveries").inc()
                    m.log(
                        "client_recovered", client=client_id,
                        round=iteration,
                    )

        result, losses, _records = decode_and_admit(
            deduped, decode, self.update_gate, self._current_global(),
            iteration, metrics=m, was_suspect=was_suspect,
            weight_scale=weight_scale, staleness=staleness,
            on_decode_error=on_decode_error, on_poisoned=on_poisoned,
            on_recovered=on_recovered,
        )
        self._round_accepted = [
            (client_id, weight, losses[client_id])
            for client_id, weight, _snap in result.accepted
        ]
        if result.stacked is not None:
            # Device backend: the admitted cohort is already stacked (and
            # clipped) on the device plane — the aggregator's mean stage
            # consumes it directly, no per-key host dicts on the hot path.
            return result.stacked
        return [
            (weight, snap) for _client_id, weight, snap in result.accepted
        ]

    def _encode_push(
        self, average: dict[str, np.ndarray], iteration: int, replies: list
    ) -> "dict[int, pb.Aggregate]":
        """Encode one round's push **per recipient** through the negotiated
        wire codec (README "Hierarchical federation & wire efficiency").

        The downlink's canonical view chain advances once per round (the
        consecutive-round delta the PR 3 stream always was); each
        recipient then gets the bundle matched to *its own* last-acked
        reference (``_push_acked``): the shared chain bundle when it is
        up to date, an exact catch-up bundle when it holds an older
        cached view (rotating cohorts keep delta+topk compression), and
        a self-contained view bundle when it holds nothing usable —
        replacing PR 9's fleet-consensus rule, under which one stale
        recipient forced a self-contained push on everyone. Recipients
        sharing a reference share one encoded bundle, so the encode cost
        per round is O(distinct references in the cohort), not O(cohort).
        A pending session reset (divergence rollback / crash recovery)
        rides out on every recipient's ``reset_session`` flag with a
        reference-free bundle."""
        reset_session = self._session_reset_pending
        self._session_reset_pending = False
        recipients = [rec.client_id for rec, _reply in replies]
        if self.wire_codec.identity:
            return encode_push_for_recipients(
                None, None, average, iteration, recipients, {},
                reset_session, metrics=self.metrics,
            )
        with self._push_lock:
            acked = dict(self._push_acked)
        with self._codec_lock:
            return encode_push_for_recipients(
                self._downlink_enc, self._uplink_dec, average, iteration,
                recipients, acked, reset_session, metrics=self.metrics,
            )

    def _divergence_rollback(
        self, iteration: int, verdict: str
    ) -> "dict[str, np.ndarray] | None":
        """Restore the last good checkpointed round after a divergence
        verdict and return its average (the rollback re-broadcast), or
        ``None`` when nothing safe exists to restore.

        Alongside the parameters: the wire-codec sessions are reset (a
        delta-encoded push against the diverged broadcast chain would
        mis-decode on rolled-back state — the re-broadcast is
        self-contained and rebuilds the reference chain), the aggregator's
        optimizer state is rolled back to the same round, clients whose
        admitted weight dominated the unhealthy streak are quarantined via
        probation, and the guardian's baselines are re-anchored."""
        m = self.metrics
        restored: dict[str, np.ndarray] | None = None
        restored_round: int | None = None
        if self.save_dir is not None:
            try:
                ckpt = self._checkpointer()
                if ckpt.latest_round() is not None:
                    self._ensure_template()
                    restored_round, restored = ckpt.restore_round(
                        self._shared_template()
                    )
                    self._restore_aggregator_state(
                        ckpt, ckpt.load_meta() or {}, restored_round
                    )
            except Exception:
                self.logger.exception(
                    "round %d: divergence rollback restore failed",
                    iteration,
                )
                restored, restored_round = None, None
        if restored is None:
            # No checkpoint to return to. A non-finite aggregate must
            # still never reach a client — fall back to the last broadcast
            # state (or the template init); a loss/norm explosion with no
            # checkpoint keeps the computed average (nothing better
            # exists) and the guardian keeps watching.
            if verdict != "nonfinite_global":
                # No reset here: the guardian stays unhealthy, so the
                # periodic checkpoint stays withheld (the diverged state
                # must never become a future rollback target) and the
                # verdict keeps firing — loud every round — until the
                # signals recover on their own or an operator steps in.
                self.logger.error(
                    "round %d: divergence (%s) but no checkpoint to roll "
                    "back to; continuing with the current aggregate",
                    iteration, verdict,
                )
                return None
            restored = self._current_global()
            self.logger.error(
                "round %d: non-finite aggregate and no checkpoint; "
                "re-broadcasting the last finite state instead",
                iteration,
            )
        # The compressed-push reference chains describe the diverged
        # trajectory — drop them all so the rollback re-broadcast (and
        # everything after it) is decoded only against post-rollback state.
        # Clients hold session state too (delta refs AND error-feedback
        # residuals carrying un-delivered diverged mass): the re-broadcast
        # orders them to reset theirs via Aggregate.reset_session.
        with self._push_lock:
            self._push_acked.clear()
            self._push_sent.clear()
            if self.pacing.policy == "push":
                # Reply-delivered resets: every unfinished member owes a
                # session reset that rides its PushUpdate replies until
                # it demonstrably applied a post-rollback round.
                self._reset_owed = {
                    c.client_id: iteration
                    for c in self.federation.get_clients()
                    if not c.finished
                }
        self._session_reset_pending = True
        if not self.wire_codec.identity:
            with self._codec_lock:
                self._uplink_dec.reset()
                self._downlink_enc.reset()
        # A coherence-collapse verdict can arrive with the loss/norm
        # guardian disabled (divergence_patience=0) — there is then no
        # streak-weight attribution, so nobody is quarantined.
        quarantined = (
            self.guardian.dominant_contributors()
            if self.guardian is not None else []
        )
        for client_id in quarantined:
            rec = next(
                (c for c in self.federation.get_clients()
                 if c.client_id == client_id), None,
            )
            if rec is None:
                continue
            self._note_client_failure(
                rec, rec.address, iteration,
                RuntimeError(f"dominated the diverged rounds ({verdict})"),
                "divergence quarantine", reason="divergence",
            )
            if m is not None:
                m.registry.counter("clients_quarantined").inc()
                m.log(
                    "client_quarantined", client=client_id,
                    round=iteration, reason=verdict,
                )
        if self.guardian is not None:
            self.guardian.note_rollback()
        self.logger.warning(
            "round %d: DIVERGENCE (%s) — rolled back to %s, quarantined "
            "%s", iteration, verdict,
            f"checkpointed round {restored_round}"
            if restored_round is not None else "last finite state",
            quarantined or "nobody",
        )
        if m is not None:
            m.registry.counter("divergence_rollbacks").inc()
            event = dict(round=iteration, reason=verdict)
            if restored_round is not None:
                event["restored_round"] = int(restored_round)
            m.log("divergence_rollback", **event)
        return restored

    # ---- model-quality plane (README "Model-quality observability") --------
    def _ensure_quality_monitor(self):
        """Lazily construct the TopicQualityMonitor on the first averaged
        round the plane is enabled for — the global vocabulary (needed for
        id2token) only exists after consensus, and loading the reference
        corpus before the federation even forms would front-load a failure
        the operator cannot see yet."""
        if self.quality_every <= 0:
            return None
        if self._quality_mon is None:
            from gfedntm_tpu.eval.monitor import (
                TopicQualityMonitor,
                load_reference_corpus,
            )

            ref = (
                load_reference_corpus(self.quality_ref)
                if self.quality_ref else None
            )
            if ref is None:
                self.logger.warning(
                    "quality monitoring is on without --quality_ref: NPMI "
                    "coherence (and the coherence guard) are disabled; "
                    "diversity and drift still run"
                )
            kwargs = dict(self.quality_monitor_kwargs)
            if self.dp.enabled and "noise_floor" not in kwargs:
                # DP noise jitters every quality round's coherence; give
                # the collapse guard an additive NPMI slack so the noise
                # floor cannot read as decay (operators override via
                # quality_monitor_kwargs; a genuine collapse still fires
                # — the slack is additive, not a disable).
                kwargs["noise_floor"] = DP_GUARD_NOISE_FLOOR
            self._quality_mon = TopicQualityMonitor(
                every=self.quality_every,
                id2token=self.global_vocab.id2token,
                ref_tokens=ref,
                topn=self.quality_topn,
                history=self.quality_history,
                metrics=self.metrics,
                logger=self.logger,
                **kwargs,
            )
        return self._quality_mon

    def _observe_contributions(self, iteration: int, snapshots,
                               average: dict[str, np.ndarray]) -> None:
        """Per-client contribution analytics over the admitted cohort:
        cosine of each update to the accepted aggregate update plus the
        pairwise cohort-similarity summary. ``average`` must be the
        aggregate the cohort actually produced — NOT a rollback
        re-broadcast (cosine to a restored checkpoint's delta would make
        every honest client look adversarial). On the device backend the
        stats reuse the round's stacked ``[N, D]`` plane (one extra
        sharded matmul); the numpy path is the oracle
        (``aggregation.contribution_stats``)."""
        if len(snapshots) == 0:
            return
        client_ids = [c for c, _w, _l in self._round_accepted]
        if isinstance(snapshots, list):
            from gfedntm_tpu.federation.aggregation import contribution_stats

            cos, norms, pair_mean, pair_min = contribution_stats(
                [s for _w, s in snapshots], self._current_global(), average,
            )
        else:  # device backend: a StackedRound
            cos, norms, pair_mean, pair_min = (
                snapshots.engine.contribution_stats(snapshots, average)
            )
        self.contributions.observe_round(
            iteration, client_ids, cos, norms, pair_mean, pair_min,
        )

    def _quality_step(
        self, iteration: int, snapshots, average: dict[str, np.ndarray],
        accepted_average: "dict[str, np.ndarray] | None" = None,
    ) -> dict[str, np.ndarray]:
        """One round's model-quality pass, run AFTER the aggregate is
        computed and BEFORE it is broadcast: contribution analytics every
        averaged round, topic coherence/diversity/drift on the
        ``quality_every`` cadence, and — with ``quality_guard`` — the
        coherence-collapse verdict routed through the same rollback path
        as a loss divergence (the returned average is then the restored
        state). ``accepted_average`` is the aggregate the cohort itself
        produced: when a loss-guardian rollback already swapped
        ``average`` for a restored checkpoint this round, contributions
        are still measured against what the clients converged on, while
        the quality monitor observes the broadcast (restored) state.
        Entirely inert when ``quality_every`` is 0. Observation failures
        are contained: telemetry must never kill the round loop (same
        stance as checkpointing)."""
        if self.quality_every <= 0:
            return average
        m = self.metrics
        try:
            self._observe_contributions(
                iteration, snapshots,
                accepted_average if accepted_average is not None
                else average,
            )
        except Exception:
            self.logger.exception(
                "round %d: contribution analytics failed", iteration
            )
            if m is not None:
                m.registry.counter("quality_errors").inc()
        monitor = None
        try:
            monitor = self._ensure_quality_monitor()
        except Exception:
            # An unreadable reference corpus must be loud but not fatal:
            # disable the monitor (leave contributions running) instead
            # of failing every round's average.
            self.logger.exception(
                "quality monitor construction failed; disabling the "
                "topic-quality plane (contribution analytics stay on)"
            )
            self.quality_ref = None
            if m is not None:
                m.registry.counter("quality_errors").inc()
        if monitor is None or not monitor.should_run(iteration):
            return average
        try:
            monitor.observe(iteration, average)
        except Exception:
            self.logger.exception(
                "round %d: quality observation failed", iteration
            )
            if m is not None:
                m.registry.counter("quality_errors").inc()
            return average
        if self.quality_guard and monitor.collapsed:
            restored = self._divergence_rollback(
                iteration, COHERENCE_COLLAPSE
            )
            if restored is not None:
                # Only a rollback that actually restored state re-anchors
                # the monitor. With nothing to restore (no checkpoint),
                # the collapsed streak stays open and the verdict keeps
                # firing — loud every quality round, like the loss
                # guardian's no-checkpoint path — instead of re-seeding
                # the EWMA at the collapsed coherence and going quiet.
                monitor.note_rollback()
                return restored
        return average

    def _skip_below_quorum(self, iteration: int, got: int, membership: int,
                           quorum: int, what: str) -> None:
        """Log/count one skipped round, then wait out a backoff tick."""
        self.logger.warning(
            "round %d below quorum (%d/%d %s, need %d): skipping average",
            iteration, got, membership, what, quorum,
        )
        if self.metrics is not None:
            self.metrics.registry.counter("quorum_skipped_rounds").inc()
            self.metrics.log(
                "quorum_skip", round=iteration, got=got, needed=quorum,
            )
        self._stopping.wait(self.round_backoff_s)

    def _size_codec_caches(self) -> None:
        """Size both codec reference caches at training start.

        Cohort/async/push recipients sync at different rounds, so uplink
        deltas may reference broadcasts much older than the sync default
        cache depth — size the caches to the rotation period (every
        client is re-polled within ~N/K aggregations in expectation) so
        ``codec_ref_miss`` stays 0 — but CAP them at
        ``codec_ref_cache_max``: the auto-size is O(N) at fixed K, and
        server memory must not scale with the population (ISSUE 11).
        Past the cap, a long-unsampled client costs one self-contained
        push / one loud ReferenceMismatch heal instead of cache
        growth."""
        if self.pacing.policy == "sync" or self.wire_codec.identity:
            return
        fan = max(self.pacing.cohort_size, self.pacing.buffer_size, 1)
        sized = max(
            self._uplink_dec.max_refs,
            4 * math.ceil(max(1, len(self.federation)) / fan),
        )
        capped = min(sized, max(1, self.codec_ref_cache_max))
        if capped < sized:
            self.logger.info(
                "codec reference cache capped at %d (rotation-aware "
                "size would be %d): long-unsampled clients degrade to "
                "self-contained pushes", capped, sized,
            )
        self._uplink_dec.max_refs = capped
        self._downlink_enc.max_views = capped

    def _run_training(self) -> None:
        # Recovery grace clock starts when training actually resumes (the
        # resume-ready quorum was just met) — not at restore time, which
        # may long predate the first reconnect.
        if self.federation.awaiting_reconnect():
            self._recovery_deadline = (
                time.monotonic() + self.reconnect_grace_s
            )
        if self.metrics is not None:
            # One trace identity per training run: every round span inherits
            # it (via the logger) and every poll/push advertises it, so the
            # N per-node JSONL streams merge into one tree.
            self.trace_id = (
                getattr(self.metrics, "trace_id", None) or new_trace_id()
            )
            self.metrics.trace_id = self.trace_id
            self.metrics.log(
                "trace_started", trace_id=self.trace_id,
                round=self.global_iterations,
            )
        try:
            self._training_loop()
        except Exception:  # pragma: no cover - defensive
            self.logger.exception("federated training loop failed")
        finally:
            if self.profiler is not None:
                self.profiler.close()
            # Snapshot in the failure path too: a crashed run's metrics.jsonl
            # must still carry its cumulative RPC/codec/step-time state —
            # those are exactly the runs telemetry exists to debug.
            if self.metrics is not None:
                self.metrics.snapshot_registry(rounds=self.global_iterations)
            self._stopping.set()
            self.training_done.set()

    def _training_loop(self) -> None:
        stubs: dict[int, tuple[str, Any, rpc.ServiceStub]] = {}
        # The round control plane is a pacing engine (README "Federation
        # pacing"): sync is the historical barrier verbatim; cohort/async
        # sample or buffer. The poll pool is persistent and bounded —
        # sized by the engine (a K-cohort never needs more than K
        # threads), created once for the whole training run.
        self._engine = pacing.make_engine(self, self.pacing)
        self._size_codec_caches()
        pool = ThreadPoolExecutor(
            max_workers=self._engine.pool_workers(self.poll_workers)
        )
        self.logger.info(
            "starting federated training (%s pacing): total weight %.0f",
            self.pacing.spec_id, self.federation.total_weight(),
        )
        try:
            self._engine.run(stubs, pool)
        finally:
            if not self._aborted.is_set():
                self._stop_broadcast(stubs)
                self._finalize()
                self._mark_journal_finished()
            pool.shutdown(wait=False)
            for _addr, channel, _stub in stubs.values():
                channel.close()

    def _stop_broadcast(self, stubs: dict) -> None:
        # Stop broadcast + server-side artifact (server.py:523-551); every
        # ready client gets the broadcast, stub created if need be, each
        # attempt retried with backoff — a client that misses it would
        # otherwise sit on its liveness watchdog timeout. _stopping goes up
        # first: any ReadyForTraining from here on is answered code=1
        # rather than being left waiting for polls.
        self._stopping.set()
        stop = pb.Aggregate(stop=True)
        for rec in self.federation.get_clients():
            if not rec.ready_for_training:
                continue
            stub = self._stub_for(stubs, rec)
            if stub is None:
                continue
            try:
                # The stub routes through retry_policy, so the broadcast is
                # retried with backoff before being given up on.
                stub.ApplyAggregate(stop)
            except Exception as exc:
                self.logger.warning(
                    "stop broadcast to client %d failed: %s",
                    rec.client_id, exc,
                )

    def _finalize(self) -> None:
        """Write the aggregated global model (betas only — the server has no
        corpus; ``get_topics_in_server``, ``federated_model.py:183-197``)."""
        if self.template is None or self.last_average is None:
            return
        from gfedntm_tpu.federated.stepper import FederatedStepper

        stepper = FederatedStepper(self.template, self.grads_to_share)
        stepper.set_gradients(self.last_average)
        self.global_betas = stepper.get_topics_in_server(self.save_dir)
        self.logger.info(
            "federated training done after %d global iterations",
            self.global_iterations,
        )
